"""Benchmark: TSBS double-groupby-1-shaped windowed group-by mean on TPU vs
CPU (numpy) baseline.

Shape: G=4096 hosts × W=16 one-minute windows × P=4096 points/window
(268M rows, float64 — the reference's float64 semantics). The kernel input
is device-resident (the framework's steady-state hot path: decoded column
blocks live in the device column cache, the readcache analog); timing
includes kernel execution AND fetching the (G, W) result to host
(axon tunnel: block_until_ready does not sync, so host fetch is the only
honest timing boundary).

CPU baseline: vectorized numpy bincount sum+count (a strong single-core
baseline; the reference's Go reduce loops are no faster per core).

Prints ONE json line: {"metric", "value", "unit", "vs_baseline"}.
"""

import json
import time

import numpy as np


def main():
    import jax
    from opengemini_tpu.ops import AggSpec, dense_window_aggregate

    G, W, P = 4096, 16, 4096
    N = G * W * P
    rng = np.random.default_rng(42)
    # cpu-gauge-like values, regular sampling (dense path eligible)
    values = np.round(
        np.clip(rng.normal(50, 15, (G * W, P)), 0, 100))
    valid = np.ones((G * W, P), dtype=bool)

    # ---- CPU baseline (numpy, float64, vectorized) ----------------------
    seg = np.repeat(np.arange(G * W, dtype=np.int64), P)
    flat = values.reshape(-1)
    t_cpu = []
    for _ in range(3):
        t0 = time.perf_counter()
        sums = np.bincount(seg, weights=flat, minlength=G * W)
        cnts = np.bincount(seg, minlength=G * W)
        mean_cpu = sums / np.maximum(cnts, 1)
        t_cpu.append(time.perf_counter() - t0)
    cpu_s = min(t_cpu)

    # ---- TPU ------------------------------------------------------------
    spec = AggSpec.of("mean")
    dv = jax.device_put(values)
    dm = jax.device_put(valid)
    res = dense_window_aggregate(dv, dm, None, spec)
    mean_tpu = np.asarray(res.mean())  # warmup compile + fetch
    t_tpu = []
    for _ in range(5):
        t0 = time.perf_counter()
        res = dense_window_aggregate(dv, dm, None, spec)
        mean_tpu = np.asarray(res.mean())
        t_tpu.append(time.perf_counter() - t0)
    tpu_s = min(t_tpu)

    # correctness gate: TPU f64 is float32-pair emulated (~1e-15 repr);
    # anything beyond 1e-12 relative is a real bug
    rel = np.abs(mean_tpu - mean_cpu) / np.maximum(np.abs(mean_cpu), 1e-30)
    assert rel.max() < 1e-12, f"TPU/CPU mismatch: {rel.max()}"

    rows_per_sec = N / tpu_s
    vs_baseline = (N / tpu_s) / (N / cpu_s)
    print(json.dumps({
        "metric": "double_groupby1_mean_rows_per_sec_f64",
        "value": round(rows_per_sec, 1),
        "unit": "rows/s",
        "vs_baseline": round(vs_baseline, 2),
    }))


if __name__ == "__main__":
    main()
