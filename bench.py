"""Benchmark: TSBS double-groupby-1-shaped windowed group-by mean on TPU vs
CPU (numpy) baseline.

Shape: G=4096 hosts × W=16 windows × P=8192 points/window = 537M rows
(float64 — the reference's float64 semantics) per query; a stream of K=8
such queries is pipelined on the device (server steady state: dispatches
overlap, so the per-call axon-tunnel latency floor (~90ms) amortizes),
and every query's (G, W) result grid is delivered to the host in one
stacked readback at the end. Input is device-resident (the framework's
steady-state hot path: decoded column blocks live in the device column
cache, the readcache analog) with no validity mask — the decoder knows
these blocks carry no nulls, so the kernel is pure VPU reductions.

CPU baseline: vectorized numpy bincount sum+count — a strong single-core
baseline for generic segment aggregation (the reference's Go reduce loops
are no faster per core). Measured once per query shape and scaled by K
(it is exactly linear; running it K times would add minutes for no
information).

Prints ONE json line: {"metric", "value", "unit", "vs_baseline"}.
"""

import json
import time

import numpy as np


def main():
    import jax
    import jax.numpy as jnp

    from opengemini_tpu.ops import AggSpec, dense_window_aggregate

    G, W, P, K = 4096, 16, 8192, 8
    N = G * W * P
    rng = np.random.default_rng(42)
    # cpu-gauge-like values, regular sampling (dense path eligible)
    values = np.round(
        np.clip(rng.normal(50, 15, (G * W, P)), 0, 100))

    # ---- CPU baseline (numpy, float64, vectorized) ----------------------
    seg = np.repeat(np.arange(G * W, dtype=np.int64), P)
    flat = values.reshape(-1)
    t_cpu = []
    for _ in range(2):
        t0 = time.perf_counter()
        sums = np.bincount(seg, weights=flat, minlength=G * W)
        cnts = np.bincount(seg, minlength=G * W)
        mean_cpu = sums / np.maximum(cnts, 1)
        t_cpu.append(time.perf_counter() - t0)
    cpu_s = min(t_cpu) * K          # K identical queries, linear
    del seg, flat

    # ---- TPU ------------------------------------------------------------
    spec = AggSpec.of("mean")

    @jax.jit
    def query_step(v):
        return dense_window_aggregate(v, None, None, spec).mean()

    stack = jax.jit(lambda rs: jnp.stack(rs))
    dv = jax.device_put(values)
    np.asarray(query_step(dv))      # warmup compile + fetch
    t_tpu = []
    for _ in range(3):
        t0 = time.perf_counter()
        rs = [query_step(dv) for _ in range(K)]
        out = np.asarray(stack(rs))   # all K result grids to host
        t_tpu.append(time.perf_counter() - t0)
    tpu_s = min(t_tpu)
    mean_tpu = out[-1]

    # correctness: bit-identical to the f64 CPU reference. Exactness here
    # is BY CONSTRUCTION, not luck: values are integral (np.round, ≤100),
    # so every partial sum is an exact f64 integer regardless of
    # reduction order (CPU sequential vs XLA tree), and P is a power of
    # two so the mean division is exact. This mirrors TSBS cpu gauges
    # (integral percentages). Non-integral data needs the fixed-order
    # reduction documented in SURVEY.md §7 before this gate applies.
    assert mean_tpu.shape == (G * W,)
    if not np.array_equal(mean_tpu, mean_cpu):
        md = np.max(np.abs(mean_tpu - mean_cpu))
        raise SystemExit(f"MISMATCH vs CPU reference: max delta {md}")

    rows_per_s = N * K / tpu_s
    print(json.dumps({
        "metric": "double_groupby1_mean_rows_per_sec_f64",
        "value": round(rows_per_s, 1),
        "unit": "rows/s",
        "vs_baseline": round(cpu_s / tpu_s, 2)}))


if __name__ == "__main__":
    main()
