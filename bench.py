"""End-to-end benchmark: TSBS-shaped data stored in the engine, queried
through the full path (parse → scan plan → segment decode → device
kernel → merge/finalize), TPU backend vs the same engine on CPU.

Structure (round-5 rework, VERDICT r4 #1: the benchmark artifact must
land EVERY round):
  * the parent process is a jax-free ORCHESTRATOR under an explicit
    time budget (OG_BENCH_BUDGET_S); every phase runs in its own
    sequential subprocess, so at most one live TPU tunnel client
    exists at any moment;
  * the HEADLINE phase (BASELINE configs 1-2) runs FIRST and its JSON
    line prints immediately; auxiliary phases (colstore config 3, prom
    rate config 4, the ≥500M-point scale record) each run only if the
    remaining budget fits a conservative estimate, and a failed or
    skipped auxiliary prints a '#' comment, never an error exit;
  * the headline line is RE-PRINTED LAST, so a driver that parses the
    final JSON line of stdout always finds the headline even when
    auxiliaries were skipped — and if the run is killed mid-phase the
    already-printed headline still stands;
  * SIGTERM/SIGINT kill live children and clean every /dev/shm
    tempdir (r4's timeout leaked a 1.5GB dataset).

Correctness gate: CPU and TPU runs must produce IDENTICAL result rows
over NON-integral float gauges — the reproducible-sum limbs
(ops/exactsum.py) make sums/means bit-identical across backends and
topologies (and equal to math.fsum).

Prints one JSON line per completed phase; the LAST line is always the
headline {"metric", "value", "unit", "vs_baseline", ...}.
"""

import argparse
import hashlib
import json
import math
import os
import shutil
import signal
import subprocess
import sys
import tempfile
import threading
import time
import uuid

import numpy as np

from opengemini_tpu.utils import knobs

HOSTS = int(knobs.get("OG_BENCH_HOSTS"))
HOURS = float(knobs.get("OG_BENCH_HOURS"))
STEP_S = 10
# TSBS double-groupby-1 (BASELINE config 2): mean of one metric over 12h
# GROUP BY time(1h), hostname — the headline shape
QUERY = ("SELECT mean(usage_user) FROM cpu WHERE time >= 0 AND "
         f"time < {int(HOURS * 3600)}s GROUP BY time(1h), hostname")
# secondary: per-minute windows AND per-host grouping — a 60× larger
# result grid (11.5M cells at 16k hosts). Served by the big-grid
# lattice route (ops/blockagg._kernel_lattice). NOTE the shape is
# transfer/materialize-bound, not compute-bound: ~3s of the e2e is
# host-side row assembly + digesting 11.5M result rows, which the
# CPU-pinned baseline shares 1:1, so the achievable ratio here is
# bounded near (cpu_kernel + shared) / (tpu_kernel + pull + shared)
# ≈ 1.3-2 on the measured 70MB/s tunnel link — the headline 1h shape
# (192k cells) is where the 100×-class device win lives
QUERY_1M = ("SELECT mean(usage_user) FROM cpu WHERE time >= 0 AND "
            f"time < {int(HOURS * 3600)}s GROUP BY time(1m), hostname")
# BASELINE config 1 verbatim: SELECT mean(usage_user) GROUP BY
# time(1m) — per-minute windows, NO per-host grouping (720 cells).
# Wide windows route to the scatter-free prefix kernel
QUERY_CFG1 = ("SELECT mean(usage_user) FROM cpu WHERE time >= 0 AND "
              f"time < {int(HOURS * 3600)}s GROUP BY time(1m)")
# answer-sized D2H shapes (PR 12): the heavy grid with ORDER BY+LIMIT
# — the device top-k cut ships only k×groups winner cells instead of
# the 11.5M-cell grid — and the percentile shape, finalized as order
# statistics over device-resident sorted-sample planes
QUERY_1M_TOPK = QUERY_1M + " ORDER BY time DESC LIMIT 5"
QUERY_PCTL = ("SELECT percentile(usage_user, 95) FROM cpu WHERE "
              f"time >= 0 AND time < {int(HOURS * 3600)}s "
              "GROUP BY time(5m), hostname")
# packed-space predicates (round 18): the headline 1h cut with a field
# residual — the smoke sweep runs it under every config (including the
# OG_PACKED_PREDICATE=0 hatch pair) on both lattice routes; the
# measured selectivity gate builds its own time-ramped measurement
# because the normal-distributed cpu gauge never lets a segment
# envelope exclude a realistic threshold
QUERY_PRED = ("SELECT mean(usage_user) FROM cpu WHERE usage_user >= 50"
              f" AND time >= 0 AND time < {int(HOURS * 3600)}s "
              "GROUP BY time(1h), hostname")

# ---------------------------------------------------------------- util

_TMPDIRS: list = []
_CHILDREN: list = []


def _register_tmp(path: str) -> None:
    _TMPDIRS.append(path)


def _cleanup() -> None:
    import shutil
    # graceful first: children own their /dev/shm tempdirs and clean
    # them from their OWN signal handlers — a SIGKILL would leak them
    for p in list(_CHILDREN):
        try:
            p.terminate()
        except Exception:
            pass
    for p in list(_CHILDREN):
        try:
            p.wait(timeout=8)
        except Exception:
            try:
                p.kill()
            except Exception:
                pass
    for d in list(_TMPDIRS):
        shutil.rmtree(d, ignore_errors=True)


def _on_signal(signum, frame):
    _cleanup()
    sys.stdout.flush()
    raise SystemExit(128 + signum)


def run_child(args: list, timeout: float, env=None) -> tuple:
    """Popen-based child runner: tracked for signal cleanup, killed on
    timeout. Returns (rc, stdout, stderr)."""
    p = subprocess.Popen(
        args, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
        text=True, env=env,
        cwd=os.path.dirname(os.path.abspath(__file__)))
    _CHILDREN.append(p)
    try:
        out, err = p.communicate(timeout=timeout)
        return p.returncode, out, err
    except subprocess.TimeoutExpired:
        # graceful: the child's own SIGTERM handler cleans its
        # /dev/shm tempdirs; SIGKILL would leak them. rc 124 (the
        # shell `timeout` convention) — NOT a signal number, so the
        # crash gate can tell a parent-imposed timeout apart from a
        # child that genuinely died to its own SIGKILL failpoint
        p.terminate()
        try:
            out, err = p.communicate(timeout=10)
        except subprocess.TimeoutExpired:
            p.kill()
            out, err = p.communicate()
        return 124, out, err
    finally:
        _CHILDREN.remove(p)


def _pipeline_depth() -> int:
    """The depth the executor will actually use — same parser as
    opengemini_tpu/ops/pipeline.py, so the benchmark artifact cannot
    claim a path the queries didn't take (a raw int() here diverged on
    malformed values)."""
    from opengemini_tpu.ops.pipeline import pipeline_depth
    return pipeline_depth()


def _cpu_env() -> dict:
    # identical engine/code, JAX pinned to host CPU. The axon
    # sitecustomize registers the TPU-tunnel PJRT plugin whenever
    # PALLAS_AXON_POOL_IPS is set, even under JAX_PLATFORMS=cpu, and a
    # concurrent tunnel handshake can wedge against a live TPU session
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("PALLAS_AXON_POOL_IPS", None)
    return env


def _digest_series(res: dict) -> tuple:
    dig = hashlib.sha256()
    cells = 0
    for s in sorted(res.get("series", []),
                    key=lambda s: json.dumps(s.get("tags", {}),
                                             sort_keys=True)):
        dig.update(json.dumps(s.get("tags", {}),
                              sort_keys=True).encode())
        for r in s["values"]:
            dig.update(repr(tuple(r)).encode())   # FULL row, every col
            cells += 1
    return dig.hexdigest(), cells


# ---------------------------------------------------- headline (1-2)

def build_dataset(data_dir: str, hosts: int = None,
                  wal_sync: bool = False) -> tuple:
    """Ingest TSBS devops-cpu-shaped data (HOSTS hosts ≙ BASELINE
    config 2, double-groupby-1) through the bulk record-writer path and
    flush to TSSP files. Returns (rows written, ingest seconds).
    ``wal_sync=True`` makes every ingest batch fsync-acknowledged —
    the crash gate's child uses it so a SIGKILL mid-flush may lose
    NOTHING (the dataset is fully deterministic, so the post-restart
    digest must equal the no-crash reference bit for bit)."""
    from opengemini_tpu.storage import Engine, EngineOptions

    if hosts is None:
        hosts = HOSTS
    points = int(HOURS * 3600 / STEP_S)
    rng = np.random.default_rng(42)
    eng = Engine(data_dir, EngineOptions(shard_duration=1 << 62,
                                         wal_sync=wal_sync))
    eng.create_database("bench")
    n = 0
    t0 = time.perf_counter()
    times = np.arange(points, dtype=np.int64) * (STEP_S * 10**9)
    for h in range(hosts):
        tags = {"hostname": f"host_{h}", "region": f"r{h % 4}"}
        # NON-integral cpu gauges: the exact-sum limbs carry the
        # bit-identical guarantee
        vals = np.round(np.clip(rng.normal(50, 15, points), 0, 100), 2)
        n += eng.write_record("bench", "cpu", tags, times,
                              {"usage_user": vals})
    for s in eng.database("bench").all_shards():
        s.flush()
    eng.close()
    t_ing = time.perf_counter() - t0
    print(f"# ingest: {n} rows in {t_ing:.1f}s", file=sys.stderr)
    return n, t_ing


def run_query_phase(data_dir: str, runs: int,
                    extras: bool = True) -> dict:
    """Open the stored dataset, run all three query shapes end-to-end
    `runs` times (after warmup), return best wall times + digests."""
    from opengemini_tpu.query import QueryExecutor, parse_query
    from opengemini_tpu.storage import Engine, EngineOptions

    eng = Engine(data_dir, EngineOptions(shard_duration=1 << 62))
    ex = QueryExecutor(eng)
    out = {}
    big = None
    est_err = {}
    from opengemini_tpu.query import scheduler as qsched
    from opengemini_tpu.query.manager import QueryManager
    qm = QueryManager()
    from opengemini_tpu.ops import compileaudit as _ca
    warm_compiles = {}
    for key, qtext in (("1h", QUERY), ("1m", QUERY_1M),
                       ("cfg1", QUERY_CFG1),
                       ("1m-topk", QUERY_1M_TOPK),
                       ("pctl", QUERY_PCTL)):
        (stmt,) = parse_query(qtext)
        res = ex.execute(stmt, "bench")      # warmup: compile + caches
        if "error" in res:
            raise SystemExit(f"query error: {res['error']}")
        times = []
        # compile audit: the timed loop is the warm steady state — any
        # compile inside it is a hot-loop retrace stealing wall from
        # the measurement (and from every production dashboard repeat)
        _mark = _ca.AUDITOR.mark()
        for _ in range(runs):
            t0 = time.perf_counter()
            res = ex.execute(stmt, "bench")
            times.append(time.perf_counter() - t0)
        warm_compiles[key] = _ca.AUDITOR.total_since(_mark)
        dig, n_cells = _digest_series(res)
        out[key] = {"best_s": min(times), "digest": dig,
                    "cells": n_cells}
        if key == "1m":
            big = res        # reused by the serialize measurement
        # device observatory: grade the admission estimator against a
        # measured (ctx-instrumented, untimed) run of the same shape —
        # feeds the scheduler's estimate-error histograms + per-class
        # EWMA bias, and the per-shape ratios land in the headline JSON
        cost = qsched.estimate_request_cost(ex, [stmt], "bench")
        cctx = qm.attach(qtext, "bench")
        t0 = time.perf_counter()
        ex.execute(stmt, "bench", ctx=cctx)
        dev_ms = (time.perf_counter() - t0) * 1e3
        qm.detach(cctx)
        qsched.get_scheduler().record_actual(
            cost, cells=cctx.actual_cells, pull_bytes=cctx.d2h_bytes,
            device_ms=cctx.device_ns / 1e6 or dev_ms,
            hbm_peak=cctx.hbm_peak)
        est_err[key] = {
            "est_cells": cost.cells,
            "actual_cells": cctx.actual_cells,
            "cells_ratio": round(cctx.actual_cells
                                 / max(1, cost.cells), 4),
            "est_pull_bytes": cost.pull_bytes,
            "actual_pull_bytes": cctx.d2h_bytes,
            "hbm_peak_bytes": cctx.hbm_peak}
    # answer-sized D2H (PR 12): the device top-k cut must shrink the
    # heavy shape's pull to winner cells ONLY, bit-identical to the
    # full-grid escape hatch — measured per-query gauge, not a guess —
    # and the percentile shape must route through the device
    # order-statistic finalize (counter-proven). All figures are
    # per-query deltas/gauges, not cumulative process counters.
    if not extras:
        eng.close()
        return out
    from opengemini_tpu.ops.devstats import DEVICE_STATS as _DSTK
    (stmt_tk,) = parse_query(QUERY_1M_TOPK)
    knobs.set_env("OG_DEVICE_TOPK", "0")
    try:
        ref_tk = ex.execute(stmt_tk, "bench")
        tk_off_b = _DSTK["last_query_d2h_bytes"]
    finally:
        knobs.del_env("OG_DEVICE_TOPK")
    tk_c0 = _DSTK["topk_cells_pulled"]
    got_tk = ex.execute(stmt_tk, "bench")
    tk_on_b = _DSTK["last_query_d2h_bytes"]
    (stmt_pc,) = parse_query(QUERY_PCTL)
    knobs.set_env("OG_DEVICE_SKETCH", "0")
    try:
        ref_pc = ex.execute(stmt_pc, "bench")
    finally:
        knobs.del_env("OG_DEVICE_SKETCH")
    sk0 = _DSTK["sketch_dev_grids"]
    sk_h0 = _DSTK["sketch_plane_hits"]
    got_pc = ex.execute(stmt_pc, "bench")
    out["answer_sized_d2h"] = {
        "topk_bit_identical": got_tk == ref_tk,
        "topk_d2h_bytes_off": int(tk_off_b),
        "topk_d2h_bytes_on": int(tk_on_b),
        "topk_d2h_shrink_x": round(tk_off_b / max(tk_on_b, 1), 1),
        "topk_cells_pulled": int(_DSTK["topk_cells_pulled"] - tk_c0),
        "pctl_bit_identical": got_pc == ref_pc,
        "sketch_dev_grids": int(_DSTK["sketch_dev_grids"] - sk0),
        "sketch_plane_hits": int(_DSTK["sketch_plane_hits"] - sk_h0),
    }
    # per-phase wall times from EXPLAIN ANALYZE: plan / dispatch /
    # kernel+pull / fold / finalize of the 1h shape. With the streaming
    # pipeline the device_pull span OVERLAPS the others (it opens at
    # the first background pull), so sum(phases) > query wall is the
    # overlap proof, and pull_bytes / pull wall gives the effective
    # link throughput next to it
    (est,) = parse_query("EXPLAIN ANALYZE " + QUERY)
    res = ex.execute(est, "bench")
    out.update(_parse_phases(res))
    # heavy-shape phases: the ORDER BY+LIMIT variant carries the new
    # device_finalize/device_topk sub-phases (both declared in
    # devstats.QUERY_PHASE_NS, so the PR 7 phase-drift gate covers
    # their span names) — reported separately so the answer-sized cut
    # is attributable next to the full-grid phases above
    (est_h,) = parse_query("EXPLAIN ANALYZE " + QUERY_1M_TOPK)
    res_h = ex.execute(est_h, "bench")
    ph_h = _parse_phases(res_h)
    out["phases_ms_heavy"] = ph_h.get("phases_ms", {})
    out["pull_bytes_heavy"] = ph_h.get("pull_bytes", 0)
    # compressed-domain execution (round 14): the H2D diet on the 1m
    # heavy shape — cold slab build with the device decode stage
    # (compressed DFOR payloads cross the link, expansion + limb
    # decomposition run in-kernel) vs the OG_DEVICE_DECODE=0 host
    # build (dense f64 planes cross). Per-query deltas off the
    # transfer manifest, not cumulative counters; the warm repeat
    # after evicting ONLY the decoded tier proves the compressed HBM
    # tier rebuild (zero slab-site H2D).
    import opengemini_tpu.ops.devicecache as _dcq
    from opengemini_tpu.ops import compileaudit as _caq
    from opengemini_tpu.ops.device_decode import DECODE_STATS as _DDQ
    from opengemini_tpu.ops.devstats import QUERY_PHASE_NS as _QPN
    (stmt_1m,) = parse_query(QUERY_1M)

    res_off, cd_off_b = _cold_build_h2d(
        lambda: ex.execute(stmt_1m, "bench"), decode_on=False)
    d0 = _QPN["device_decode_ns"]
    res_on, cd_on_b = _cold_build_h2d(
        lambda: ex.execute(stmt_1m, "bench"), decode_on=True)
    cd_decode_ms = (_QPN["device_decode_ns"] - d0) / 1e6
    comp_bytes = _dcq.compressed_cache().stats()["bytes"]
    slab_bytes = _dcq.global_cache().stats()["bytes"]
    # warm rebuild from the compressed tier: decoded planes evicted
    # (the relief ladder's first rung), payloads stay resident
    hits0 = _DDQ["compressed_hits"]
    _dcq.global_cache().purge()
    _dcq.host_cache().purge()
    m0 = _caq.manifest_snapshot()
    res_rb = ex.execute(stmt_1m, "bench")
    m1 = _caq.manifest_snapshot()
    rb_slab_b = sum(m1[f"h2d_{s}_bytes"] - m0[f"h2d_{s}_bytes"]
                    for s in ("slab", "limbs", "dfor", "payload"))
    dig_on, _c = _digest_series(res_on)
    dig_off, _c = _digest_series(res_off)
    dig_rb, _c = _digest_series(res_rb)
    out["compressed_domain"] = {
        "h2d_bytes_on": int(cd_on_b),
        "h2d_bytes_off": int(cd_off_b),
        "h2d_shrink_x": round(cd_off_b / max(cd_on_b, 1), 1),
        "bit_identical": dig_on == dig_off == dig_rb,
        "device_decode_ms": round(cd_decode_ms, 3),
        "compressed_tier_bytes": int(comp_bytes),
        "decoded_slab_bytes": int(slab_bytes),
        "residency_density_x": round(slab_bytes / max(comp_bytes, 1),
                                     1),
        "compressed_rebuild_hits": int(_DDQ["compressed_hits"]
                                       - hits0),
        "rebuild_slab_h2d_bytes": int(rb_slab_b),
        "dfor_blocks": int(_DDQ["dfor_blocks"]),
        "host_heals": int(_DDQ["host_heals"]),
    }
    # packed-space predicates (round 18): selectivity sweep on the 1h
    # cut — thresholds at the ~50%/1%/0.1% quantiles of the N(50,15)
    # gauge — reporting the rows that EXPAND out of packed space
    # (pushdown_lanes_expanded) packed-on vs the OG_PACKED_PREDICATE=0
    # expand-then-filter hatch (which decodes every stored row on the
    # scan route), the decode-phase wall, and per-threshold digest
    # equality. The 3x-shrink assertion lives in the smoke gate, whose
    # ramp measurement gives envelopes a real chance to skip — here
    # the numbers are honest observations on TSBS data
    pp = {}
    for tag, thr in (("50pct", 50.0), ("1pct", 84.9),
                     ("0.1pct", 96.3)):
        qp = ("SELECT mean(usage_user) FROM cpu WHERE usage_user >= "
              f"{thr!r} AND time >= 0 AND time < "
              f"{int(HOURS * 3600)}s GROUP BY time(1h), hostname")
        (stmt_pp,) = parse_query(qp)
        _dcq.global_cache().purge()
        _dcq.host_cache().purge()
        l0 = _DDQ["pushdown_lanes_expanded"]
        d0 = _QPN["device_decode_ns"]
        res_pp = ex.execute(stmt_pp, "bench")
        lanes_on = _DDQ["pushdown_lanes_expanded"] - l0
        pp_dec_ms = (_QPN["device_decode_ns"] - d0) / 1e6
        knobs.set_env("OG_PACKED_PREDICATE", "0")
        try:
            _dcq.global_cache().purge()
            _dcq.host_cache().purge()
            res_pph = ex.execute(stmt_pp, "bench")
        finally:
            knobs.del_env("OG_PACKED_PREDICATE")
        # the hatch is the row-wise scan route: it decodes every
        # stored row in range before filtering (no slabs, no lanes
        # counter) — that row count is its side of the comparison
        lanes_off = HOSTS * int(HOURS * 3600 / STEP_S)
        dig_pp, _c = _digest_series(res_pp)
        dig_pph, _c = _digest_series(res_pph)
        pp[tag] = {"lanes_on": int(lanes_on),
                   "lanes_off": int(lanes_off),
                   "decode_ms": round(pp_dec_ms, 3),
                   "digest": dig_pp[:16],
                   "bit_identical": dig_pp == dig_pph}
    pp["segments_skipped"] = int(_DDQ["pushdown_segments_skipped"])
    pp["blocks_masked"] = int(_DDQ["pushdown_blocks_masked"])
    out["packed_predicate"] = pp
    # serialize phase: stream the 11.5M-cell 1m result (kept from the
    # timing loop — no extra execution) through the chunked encoder
    # (http/serializer — what the HTTP layer emits); measured here
    # because EXPLAIN ANALYZE spans end at the executor
    from opengemini_tpu.http.serializer import iter_results_json
    t0 = time.perf_counter()
    n_ser = sum(len(p) for p in iter_results_json(
        {"results": [dict(big, statement_id=0)]}))
    out.setdefault("phases_ms", {})["serialize"] = round(
        (time.perf_counter() - t0) * 1e3, 3)
    out["serialized_bytes"] = n_ser
    # histogram-derived tails (flight-recorder histograms): the timing
    # loop above fed the per-phase and D2H-pull distributions — p50/p99
    # say what the counters' means hide (one bad pull vs a slow link)
    from opengemini_tpu.utils.stats import histogram_summaries
    hs = histogram_summaries()
    out["hist_p50_p99"] = {
        grp + "." + k[:-4]: [g[k], g[k[:-4] + "_p99"]]
        for grp in ("query_phase", "device")
        for g in [hs.get(grp, {})]
        for k in sorted(g) if k.endswith("_p50")}
    # device observatory: process-wide tracked-HBM high-watermark
    # (device cache + host mirror + in-flight pipeline buffers) and
    # the calibration state the instrumented runs above produced —
    # estimate-error ratios per shape + the learned per-class bias
    from opengemini_tpu.ops import hbm as _hbm
    out["hbm_peak_mb"] = round(
        _hbm.LEDGER.snapshot(events=False)["total_hwm_bytes"] / 1e6, 3)
    calib = qsched.get_scheduler().calibration_snapshot()
    out["estimate_error"] = {
        "shapes": est_err,
        "classes": {n: c for n, c in calib["classes"].items()
                    if c["n"] > 0},
        "error_hist": calib["error_hist"]}
    # compile-cache + transfer audit (PR 11): warm-loop compiles per
    # shape (0 = the jit caches served every timed run), total
    # compiles/duplicates this process, and the manifest-vs-devstats
    # + pipeline-ledger attribution checks
    _cac = _ca.compileaudit_collector()
    out["compile_audit"] = {
        "warm_compiles": warm_compiles,
        "compiles_total": _cac["compiles_total"],
        "duplicate_compiles": _cac["duplicate_compiles"],
        "kernels_distinct": _cac["kernels_distinct"]}
    xman = _ca.manifest_cross_check()
    out["xfer_audit"] = {
        "manifest_ok": xman["ok"],
        "ledger_checks": xman["ledger"]["checks"],
        "ledger_mismatches": xman["ledger"]["mismatches"],
        "h2d_bytes": xman["h2d"]["manifest"],
        "d2h_bytes": xman["d2h"]["manifest"]}
    eng.close()
    return out


def _manifest_h2d_total() -> int:
    """Total H2D bytes across every transfer-manifest site."""
    from opengemini_tpu.ops import compileaudit
    m = compileaudit.manifest_snapshot()
    return sum(v for k, v in m.items()
               if k.startswith("h2d_") and k.endswith("_bytes"))


def _cold_build_h2d(runner, decode_on: bool):
    """The compressed-domain measurement protocol, shared by the
    headline ``compressed_domain`` block and the smoke gate so the
    two can never measure different things: purge the decoded AND
    compressed device tiers, run ``runner`` cold (with
    OG_DEVICE_DECODE pinned off when requested), return (runner
    result, exact H2D byte delta off the transfer manifest)."""
    import opengemini_tpu.ops.devicecache as _dch
    _dch.global_cache().purge()
    _dch.compressed_cache().purge()
    if not decode_on:
        knobs.set_env("OG_DEVICE_DECODE", "0")
    b0 = _manifest_h2d_total()
    try:
        out = runner()
    finally:
        if not decode_on:
            knobs.del_env("OG_DEVICE_DECODE")
    return out, _manifest_h2d_total() - b0


def _parse_phases(res: dict) -> dict:
    import re
    phases = {}
    pull_bytes = 0
    streamed = 0
    for row in res.get("series", [{}])[0].get("values", []):
        line = row[0].strip()
        name, _, rest = line.partition(":")
        if "ms" in rest:
            phases[name] = float(rest.split("ms")[0].strip())
        if name == "device_pull":
            m = re.search(r"pull_bytes=(\d+)", rest)
            if m:
                pull_bytes = int(m.group(1))
            m = re.search(r"streamed=(\d+)", rest)
            if m:
                streamed = int(m.group(1))
    out = {"phases_ms": phases, "pull_bytes": pull_bytes,
           "streamed_launches": streamed}
    pull_ms = phases.get("device_pull", 0.0)
    out["pull_gbps"] = round(pull_bytes / 1e9 / (pull_ms / 1e3), 3) \
        if pull_ms > 0 else 0.0
    # overlap proof: children phase wall vs the root query span
    out["phase_sum_ms"] = round(sum(phases.values()), 3)
    return out


def kernel_micro() -> float:
    """Device-resident dense-kernel throughput (rows/s) — the
    steady-state ceiling when blocks live in the device column cache."""
    import jax
    import jax.numpy as jnp
    from opengemini_tpu.ops import AggSpec, dense_window_aggregate

    G, W, P, K = 4096, 16, 4096, 4
    rng = np.random.default_rng(1)
    values = np.round(np.clip(rng.normal(50, 15, (G * W, P)), 0, 100))
    spec = AggSpec.of("mean")

    @jax.jit
    def step(v):
        return dense_window_aggregate(v, None, None, spec).mean()

    stack = jax.jit(lambda rs: jnp.stack(rs))
    dv = jax.device_put(values)
    np.asarray(step(dv))
    best = np.inf
    for _ in range(3):
        t0 = time.perf_counter()
        out = np.asarray(stack([step(dv) for _ in range(K)]))
        best = min(best, time.perf_counter() - t0)
    assert out.shape == (K, G * W)
    return G * W * P * K / best


def http_roundtrip(data_dir: str) -> tuple:
    """One warm query over HTTP. Returns (ms, trace_info): the timed
    request rides the flight recorder (X-OG-Trace forces the sample
    WITHOUT touching OG_TRACE_SAMPLE, so the timed run itself stays on
    the default path) and trace_info carries the merged tree's id, the
    Chrome trace-event export path, and the span names seen — the
    headline JSON's proof that HTTP → scheduler → executor phases →
    pipeline lanes landed in ONE tree."""
    import urllib.request
    import urllib.parse
    from opengemini_tpu.http.server import HttpServer
    from opengemini_tpu.storage import Engine, EngineOptions

    eng = Engine(data_dir, EngineOptions(shard_duration=1 << 62))
    srv = HttpServer(eng, port=0)
    srv.start()
    trace_info = {}
    try:
        url = (f"http://127.0.0.1:{srv.port}/query?db=bench&q="
               + urllib.parse.quote(QUERY))
        urllib.request.urlopen(url, timeout=600).read()   # warm
        t0 = time.perf_counter()
        urllib.request.urlopen(url, timeout=600).read()
        ms = (time.perf_counter() - t0) * 1000
        # traced replay of the same warm query (forced sample), then
        # pull its tree + Chrome export back out of the recorder
        req = urllib.request.Request(url, headers={
            "X-OG-Trace": uuid.uuid4().hex[:16]})
        resp = urllib.request.urlopen(req, timeout=600)
        resp.read()
        tid = resp.headers.get("X-OG-Trace-Id", "")
        if tid:
            base = f"http://127.0.0.1:{srv.port}/debug/trace?id={tid}"
            tree = json.loads(urllib.request.urlopen(
                base, timeout=60).read())
            chrome = urllib.request.urlopen(
                base + "&format=chrome", timeout=60).read()
            path = os.path.join(tempfile.gettempdir(),
                                f"og_trace_{tid}.json")
            with open(path, "wb") as f:
                f.write(chrome)

            def _names(d, acc):
                acc.add(d["name"])
                for c in d["children"]:
                    _names(c, acc)
                return acc

            trace_info = {
                "trace_id": tid, "trace_path": path,
                "trace_span_names":
                    sorted(_names(tree.get("spans", {
                        "name": "?", "children": []}), set())),
                "trace_overlap_ns": tree.get("spans", {}).get(
                    "fields", {}).get("overlap_ns", 0)}
        return ms, trace_info
    finally:
        srv.stop()
        eng.close()


def headline_phase(runs: int, cpu_timeout: float) -> dict:
    """BASELINE configs 1-2 end-to-end: build, CPU-pinned subprocess
    baseline, TPU run in THIS process, digest gate, kernel micro +
    HTTP latency."""
    shm = "/dev/shm" if os.path.isdir("/dev/shm") else None
    with tempfile.TemporaryDirectory(prefix="og-bench-", dir=shm) as td:
        _register_tmp(td)
        n_rows, t_ing = build_dataset(td)
        # restart-to-serving cost (PR 10): reopen the freshly built
        # data dir with eager shard open — orphan sweep, schema and
        # file loads (v3 checksum verification included), WAL replay
        # — the recovery_ms headline
        from opengemini_tpu.storage import Engine, EngineOptions
        t_r0 = time.perf_counter()
        Engine(td, EngineOptions(shard_duration=1 << 62,
                                 lazy_shard_open=False)).close()
        recovery_ms = (time.perf_counter() - t_r0) * 1e3
        rc, out, err = run_child(
            [sys.executable, os.path.abspath(__file__), "--phase",
             "query", "--data", td, "--runs", str(runs)],
            timeout=cpu_timeout, env=_cpu_env())
        if rc != 0:
            raise SystemExit(f"cpu phase failed rc={rc}: {err[-2000:]}")
        cpu = json.loads(out.strip().splitlines()[-1])
        tpu = run_query_phase(td, runs)
        for key in ("1h", "1m", "cfg1", "1m-topk", "pctl"):
            if cpu[key]["digest"] != tpu[key]["digest"]:
                raise SystemExit(
                    f"MISMATCH [{key}]: cpu {cpu[key]['digest'][:16]} "
                    f"!= tpu {tpu[key]['digest'][:16]}")
        try:
            kernel_rps = kernel_micro()
        except Exception as e:
            print(f"# kernel_micro failed: {e}", file=sys.stderr)
            kernel_rps = 0.0
        try:
            http_ms, trace_info = http_roundtrip(td)
        except Exception as e:
            print(f"# http_roundtrip failed: {e}", file=sys.stderr)
            http_ms, trace_info = 0.0, {}
    e2e_rps = n_rows / tpu["1h"]["best_s"]
    # honest speedups only (round 17 satellite): on a CPU-only host
    # the "TPU" process runs the same backend as the pinned baseline
    # subprocess, so a vs_baseline ratio is process-setup noise dressed
    # up as a speedup — label the run cpu_only and refuse the ratios
    import jax as _jx
    backend = _jx.devices()[0].platform
    cpu_only = backend == "cpu"

    def _vs(c: float, t: float):
        return None if cpu_only else round(c / t, 3)
    return {
        "metric": "tsbs_double_groupby1_mean_e2e_rows_per_sec",
        "value": round(e2e_rps, 1),
        "unit": "rows/s",
        "backend_platform": backend,
        "cpu_only": cpu_only,
        "vs_baseline": _vs(cpu["1h"]["best_s"], tpu["1h"]["best_s"]),
        "rows": n_rows,
        "hosts": HOSTS,
        "result_cells": tpu["1h"]["cells"],
        "e2e_query_s": round(tpu["1h"]["best_s"], 4),
        "cpu_query_s": round(cpu["1h"]["best_s"], 4),
        "e2e_1m_rows_per_sec": round(n_rows / tpu["1m"]["best_s"], 1),
        "vs_baseline_1m": _vs(cpu["1m"]["best_s"],
                              tpu["1m"]["best_s"]),
        "e2e_1m_s": round(tpu["1m"]["best_s"], 4),
        "cpu_1m_s": round(cpu["1m"]["best_s"], 4),
        "result_cells_1m": tpu["1m"]["cells"],
        "e2e_cfg1_s": round(tpu["cfg1"]["best_s"], 4),
        "cpu_cfg1_s": round(cpu["cfg1"]["best_s"], 4),
        "vs_baseline_cfg1": _vs(cpu["cfg1"]["best_s"],
                                tpu["cfg1"]["best_s"]),
        # answer-sized D2H (PR 12): ORDER BY+LIMIT heavy shape (device
        # top-k cut) and the percentile shape (device order-statistic
        # finalize), each digest-gated against the CPU baseline above
        "e2e_1m_topk_s": round(tpu["1m-topk"]["best_s"], 4),
        "cpu_1m_topk_s": round(cpu["1m-topk"]["best_s"], 4),
        "vs_baseline_1m_topk": _vs(cpu["1m-topk"]["best_s"],
                                   tpu["1m-topk"]["best_s"]),
        "e2e_pctl_s": round(tpu["pctl"]["best_s"], 4),
        "cpu_pctl_s": round(cpu["pctl"]["best_s"], 4),
        "vs_baseline_pctl": _vs(cpu["pctl"]["best_s"],
                                tpu["pctl"]["best_s"]),
        "answer_sized_d2h": tpu.get("answer_sized_d2h", {}),
        # compressed-domain execution (round 14): the H2D diet on the
        # 1m heavy shape — device decode on vs off, compressed HBM
        # tier residency/rebuild, decode-stage wall split
        "compressed_domain": tpu.get("compressed_domain", {}),
        # packed-space predicates (round 18): selectivity sweep of
        # the 1h cut — expand-lane counts on vs hatch, decode wall,
        # per-threshold digest equality
        "packed_predicate": tpu.get("packed_predicate", {}),
        "phases_ms_heavy": tpu.get("phases_ms_heavy", {}),
        "bit_identical": True,
        "ingest_rows_per_sec": round(n_rows / max(t_ing, 1e-9), 1),
        "ingest_s": round(t_ing, 1),
        # storage crash consistency (PR 10): cold restart of the
        # built data dir to first-query-serving (recovery contract
        # work: orphan sweep + open-time verification + WAL replay)
        "recovery_ms": round(recovery_ms, 1),
        "kernel_rows_per_sec": round(kernel_rps, 1),
        "http_query_ms": round(http_ms, 1),
        "phases_ms": tpu.get("phases_ms", {}),
        "phase_sum_ms": tpu.get("phase_sum_ms", 0.0),
        "pull_bytes": tpu.get("pull_bytes", 0),
        "pull_gbps": tpu.get("pull_gbps", 0.0),
        "streamed_launches": tpu.get("streamed_launches", 0),
        "pipeline_depth": _pipeline_depth(),
        # flight recorder (PR 7): histogram-derived [p50, p99] per
        # phase/D2H metric, plus the headline query's recorded trace
        # (id + exported Chrome timeline path + merged span names)
        "hist_p50_p99": tpu.get("hist_p50_p99", {}),
        # device observatory (PR 8): tracked-HBM high-watermark and
        # the admission estimator graded against measured actuals
        "hbm_peak_mb": tpu.get("hbm_peak_mb", 0.0),
        "estimate_error": tpu.get("estimate_error", {}),
        # compile-cache + transfer audit (PR 11): zero warm-loop
        # recompiles and byte-exact transfer attribution, measured on
        # the same runs that produced the headline numbers
        "compile_audit": tpu.get("compile_audit", {}),
        "xfer_audit": tpu.get("xfer_audit", {}),
        **trace_info}


# ------------------------------------------- colstore (config 3)

CS_HOSTS = int(knobs.get("OG_BENCH_CS_HOSTS"))
CS_HOURS = 1.0
CS_FIELDS = [f"usage_{k}" for k in
             ("user", "system", "idle", "nice", "iowait", "irq",
              "softirq", "steal", "guest", "guest_nice")]
# VERDICT r4 weak #5: the old time(1h) shape produced ONE result cell,
# answered from fragment metadata without decoding. Per-minute windows
# per host force the ColumnStoreReader scan: fragments decode, the
# sparse index prunes, and the result grid is 120k cells
CS_QUERY = ("SELECT " + ", ".join(f"max({f})" for f in CS_FIELDS)
            + f" FROM cpu WHERE time >= 0 AND "
              f"time < {int(CS_HOURS * 3600)}s "
              "GROUP BY time(1m), hostname")


def colstore_query_phase(data_dir: str, runs: int) -> dict:
    from opengemini_tpu.query import QueryExecutor, parse_query
    from opengemini_tpu.storage import Engine, EngineOptions
    eng = Engine(data_dir, EngineOptions(shard_duration=1 << 62))
    ex = QueryExecutor(eng)
    (stmt,) = parse_query(CS_QUERY)
    res = ex.execute(stmt, "bench")
    if "error" in res:
        raise SystemExit(f"colstore query error: {res['error']}")
    times = []
    for _ in range(runs):
        t0 = time.perf_counter()
        res = ex.execute(stmt, "bench")
        times.append(time.perf_counter() - t0)
    dig, cells = _digest_series(res)
    eng.close()
    return {"best_s": min(times), "digest": dig, "cells": cells}


def colstore_phase(cpu_timeout: float) -> dict:
    """BASELINE config 3 (high-cpu-all shape): max() across 10 cpu
    fields on the COLUMN-STORE engine, per-minute per-host windows —
    the fragment-decode scan path. Reports e2e throughput AND
    vs_baseline (same engine pinned to CPU, digests compared)."""
    from opengemini_tpu.storage import Engine, EngineOptions

    points = int(CS_HOURS * 3600 / STEP_S)
    rng = np.random.default_rng(7)
    with tempfile.TemporaryDirectory(
            prefix="og-csbench-",
            dir="/dev/shm" if os.path.isdir("/dev/shm") else None) as td:
        _register_tmp(td)
        eng = Engine(td, EngineOptions(shard_duration=1 << 62))
        eng.create_columnstore("bench", "cpu", ["hostname"],
                               {"hostname": "bloom"})
        t0 = time.perf_counter()
        n = 0
        times = np.arange(points, dtype=np.int64) * (STEP_S * 10**9)
        batch = []
        for h in range(CS_HOSTS):
            vals = np.round(np.clip(
                rng.normal(50, 15, (len(CS_FIELDS), points)), 0, 100),
                2)
            batch.append(("cpu", {"hostname": f"host_{h}"}, times,
                          {f: vals[j]
                           for j, f in enumerate(CS_FIELDS)}))
            if len(batch) >= 500:
                n += eng.write_record_batch("bench", batch)
                batch = []
        if batch:
            n += eng.write_record_batch("bench", batch)
        eng.flush_all()
        eng.close()
        t_ing = time.perf_counter() - t0

        rc, out, err = run_child(
            [sys.executable, os.path.abspath(__file__), "--phase",
             "csquery", "--data", td, "--runs", "3"],
            timeout=cpu_timeout, env=_cpu_env())
        if rc != 0:
            raise SystemExit(f"cs cpu phase failed: {err[-1500:]}")
        cpu = json.loads(out.strip().splitlines()[-1])
        tpu = colstore_query_phase(td, 3)
        if cpu["digest"] != tpu["digest"]:
            raise SystemExit(
                f"COLSTORE MISMATCH: {cpu['digest'][:16]} != "
                f"{tpu['digest'][:16]}")
    return {"metric": "tsbs_high_cpu_all_colstore_rows_per_sec",
            "value": round(n / tpu["best_s"], 1), "unit": "rows/s",
            "rows": n, "fields": len(CS_FIELDS), "hosts": CS_HOSTS,
            "ingest_rows_per_sec": round(n / t_ing, 1),
            "e2e_query_s": round(tpu["best_s"], 4),
            "cpu_query_s": round(cpu["best_s"], 4),
            "vs_baseline": round(cpu["best_s"] / tpu["best_s"], 3),
            "bit_identical": True,
            "result_cells": tpu["cells"]}


# ----------------------------------------------- prom rate (config 4)

PROM_SERIES = int(knobs.get("OG_BENCH_PROM_SERIES"))
PROM_MINUTES = 10


def _prom_build(data_dir: str) -> int:
    """PROM_SERIES counter series, PROM_MINUTES at 10s resolution,
    written through the bulk record path (remote-write mapping:
    value field, labels as tags)."""
    from opengemini_tpu.storage import Engine, EngineOptions
    NS = 10**9
    points = PROM_MINUTES * 60 // STEP_S
    eng = Engine(data_dir, EngineOptions(shard_duration=1 << 62))
    eng.create_database("prom")
    rng = np.random.default_rng(5)
    times = (np.arange(points, dtype=np.int64) * STEP_S + STEP_S) * NS
    n = 0
    t0 = time.perf_counter()
    batch = []
    for s in range(PROM_SERIES):
        # counters: cumulative sums of positive increments, occasional
        # reset to exercise the reset-corrected rate
        inc = rng.uniform(0.5, 2.0, points)
        v = np.cumsum(inc)
        if s % 97 == 0:
            v[points // 2:] -= v[points // 2] - 0.1
        batch.append(("node_cpu_seconds_total",
                      {"instance": f"i{s}", "cpu": str(s % 64)},
                      times, {"value": np.round(v, 3)}))
        if len(batch) >= 2000:
            n += eng.write_record_batch("prom", batch)
            batch = []
    if batch:
        n += eng.write_record_batch("prom", batch)
    eng.flush_all()
    eng.close()
    print(f"# prom ingest: {n} rows in {time.perf_counter()-t0:.1f}s",
          file=sys.stderr)
    return n


def prom_query_phase(data_dir: str, runs: int) -> dict:
    """rate(node_cpu_seconds_total[5m]) range query over the stored
    series (BASELINE config 4, RangeVectorCursor role)."""
    from opengemini_tpu.promql import PromEngine
    from opengemini_tpu.storage import Engine, EngineOptions
    NS = 10**9
    eng = Engine(data_dir, EngineOptions(shard_duration=1 << 62))
    pe = PromEngine(eng, "prom")
    start = 6 * 60 * NS
    end = PROM_MINUTES * 60 * NS
    step = 120 * NS
    q = "rate(node_cpu_seconds_total[5m])"
    res = pe.query_range(q, start, end, step)        # warm
    times = []
    for _ in range(runs):
        t0 = time.perf_counter()
        res = pe.query_range(q, start, end, step)
        times.append(time.perf_counter() - t0)
    dig = hashlib.sha256()
    cells = 0
    for s in sorted(res, key=lambda s: json.dumps(s["metric"],
                                                  sort_keys=True)):
        dig.update(json.dumps(s["metric"], sort_keys=True).encode())
        for t, v in s["values"]:
            dig.update(repr((t, v)).encode())
            cells += 1
    eng.close()
    return {"best_s": min(times), "digest": dig.hexdigest(),
            "cells": cells, "series": len(res),
            "phases": getattr(pe, "last_phases", {})}


def prom_phase(cpu_timeout: float) -> dict:
    # the rate/increase pipeline is HOST-exact by design: the device
    # bucket-state fold runs in the TPU's f32-pair-emulated f64 and
    # drifts from the CPU backend's real f64 on fractional counters
    # (the digest gate caught it at 1M series), so BOTH sides pin the
    # host fold — the measurement is the end-to-end prom path
    # (scan, fold, eval, format), not a device kernel
    knobs.set_env("OG_PROM_DEVICE_MIN_ROWS", 1 << 62)
    shm = "/dev/shm" if os.path.isdir("/dev/shm") else None
    with tempfile.TemporaryDirectory(prefix="og-prom-", dir=shm) as td:
        _register_tmp(td)
        n = _prom_build(td)
        env = _cpu_env()
        env["OG_PROM_DEVICE_MIN_ROWS"] = str(1 << 62)
        rc, out, err = run_child(
            [sys.executable, os.path.abspath(__file__), "--phase",
             "promquery", "--data", td, "--runs", "2"],
            timeout=cpu_timeout, env=env)
        if rc != 0:
            raise SystemExit(f"prom cpu phase failed: {err[-1500:]}")
        cpu = json.loads(out.strip().splitlines()[-1])
        tpu = prom_query_phase(td, 2)
        if cpu["digest"] != tpu["digest"]:
            raise SystemExit(
                f"PROM MISMATCH: {cpu['digest'][:16]} != "
                f"{tpu['digest'][:16]}")
    return {"metric": "prom_rate_range_rows_per_sec",
            "value": round(n / tpu["best_s"], 1), "unit": "rows/s",
            "rows": n, "series": tpu["series"],
            "result_cells": tpu["cells"],
            "e2e_query_s": round(tpu["best_s"], 4),
            "cpu_query_s": round(cpu["best_s"], 4),
            "vs_baseline": round(cpu["best_s"] / tpu["best_s"], 3),
            "bit_identical": True,
            "phases": tpu["phases"],
            # honest bottleneck note (VERDICT r5 item 3 contract): the
            # prom path keeps rate/increase arithmetic in host IEEE
            # f64 for cross-backend bit-identity (device f64 is
            # f32-pair emulated), so both backends share the
            # scan+fold+format cost and the ratio stays near 1 on
            # realistic shapes; the device bucket-state path exists
            # (PROM_DEVICE_MIN_ROWS) but its 15-plane state pull
            # exceeds the tunnel link's budget at high cardinality
            "note": "host-exact rate semantics; ratio bounded by "
                    "shared scan+format cost"}


# -------------------------------------------------- scale (≥500M pts)

SCALE_ROWS = int(knobs.get("OG_BENCH_SCALE_ROWS"))
SCALE_WINDOW_H = 12


def scale_query(points: int) -> str:
    """Double-groupby-1 over the most recent 12h of the scale dataset
    (dashboards query recent windows; the full 500M-row span exceeds a
    single v5e's HBM — multi-chip shards own slices in production)."""
    t_hi = points * STEP_S
    t_lo = t_hi - SCALE_WINDOW_H * 3600
    return ("SELECT mean(usage_user) FROM cpu WHERE "
            f"time >= {t_lo}s AND time < {t_hi}s "
            "GROUP BY time(1h), hostname")


def scale_query_phase(data_dir: str, runs: int) -> dict:
    from opengemini_tpu.query import QueryExecutor, parse_query
    from opengemini_tpu.storage import Engine, EngineOptions
    eng = Engine(data_dir, EngineOptions(shard_duration=1 << 62))
    ex = QueryExecutor(eng)
    points = -(-SCALE_ROWS // HOSTS)
    (stmt,) = parse_query(scale_query(points))
    res = ex.execute(stmt, "bench")
    if "error" in res:
        raise SystemExit(f"scale query error: {res['error']}")
    times = []
    for _ in range(runs):
        t0 = time.perf_counter()
        res = ex.execute(stmt, "bench")
        times.append(time.perf_counter() - t0)
    dig, cells = _digest_series(res)
    eng.close()
    return {"best_s": min(times), "all_s": [round(t, 4) for t in times],
            "digest": dig, "cells": cells}


def scale_phase(cpu_timeout: float) -> dict:
    """≥500M-point record (BASELINE.json '1B pts' bar): full-range
    ingest through the bulk writer, then the headline query shape over
    the recent window — planner/caches must survive 7x the headline
    data with warm repeats stable (no eviction collapse)."""
    from opengemini_tpu.storage import Engine, EngineOptions

    points = -(-SCALE_ROWS // HOSTS)
    shm = "/dev/shm" if os.path.isdir("/dev/shm") else None
    with tempfile.TemporaryDirectory(prefix="og-scale-", dir=shm) as td:
        _register_tmp(td)
        eng = Engine(td, EngineOptions(shard_duration=1 << 62))
        eng.create_database("bench")
        rng = np.random.default_rng(9)
        times = np.arange(points, dtype=np.int64) * (STEP_S * 10**9)
        t0 = time.perf_counter()
        n = 0
        batch = []
        for h in range(HOSTS):
            vals = np.round(np.clip(
                rng.normal(50, 15, points), 0, 100), 2)
            batch.append(("cpu", {"hostname": f"host_{h}",
                                  "region": f"r{h % 4}"},
                          times, {"usage_user": vals}))
            if len(batch) >= 250:
                n += eng.write_record_batch("bench", batch)
                batch = []
        if batch:
            n += eng.write_record_batch("bench", batch)
        eng.flush_all()
        eng.close()
        t_ing = time.perf_counter() - t0
        print(f"# scale ingest: {n} rows in {t_ing:.0f}s",
              file=sys.stderr)

        rc, out, err = run_child(
            [sys.executable, os.path.abspath(__file__), "--phase",
             "scalequery", "--data", td, "--runs", "3"],
            timeout=cpu_timeout, env=_cpu_env())
        if rc != 0:
            raise SystemExit(f"scale cpu phase failed: {err[-1500:]}")
        cpu = json.loads(out.strip().splitlines()[-1])
        tpu = scale_query_phase(td, 3)
        if cpu["digest"] != tpu["digest"]:
            raise SystemExit(
                f"SCALE MISMATCH: {cpu['digest'][:16]} != "
                f"{tpu['digest'][:16]}")
        # warm stability: the slowest warm repeat must stay within 2x
        # of the best (eviction collapse would rebuild stacks per run)
        spread = max(tpu["all_s"]) / max(tpu["best_s"], 1e-9)
    return {"metric": "tsbs_scale_recent_window_rows_per_sec",
            "value": round(n / tpu["best_s"], 1), "unit": "rows/s",
            "rows_total": n,
            "window_rows": HOSTS * SCALE_WINDOW_H * 3600 // STEP_S,
            "hosts": HOSTS,
            "ingest_rows_per_sec": round(n / t_ing, 1),
            "e2e_query_s": round(tpu["best_s"], 4),
            "warm_runs_s": tpu["all_s"],
            "warm_spread": round(spread, 2),
            "cpu_query_s": round(cpu["best_s"], 4),
            "vs_baseline": round(cpu["best_s"] / tpu["best_s"], 3),
            "bit_identical": True,
            "result_cells": tpu["cells"]}


# -------------------------------------------------- perf smoke (CPU)

def crash_child_phase(data_dir: str, site: str, skip: int) -> None:
    """perf_smoke crash-gate CHILD: rebuild the deterministic bench
    dataset with fsync-acknowledged (wal_sync) ingest while ONE
    ``crash``-action failpoint is armed at a storage durability
    boundary — the SIGKILL lands mid-flush, and the parent then
    proves the restarted engine serves the no-crash digest. Requires
    OG_CRASH_OK=1 in the environment."""
    from opengemini_tpu.utils import failpoint

    failpoint.enable(site, "crash", skip=skip)
    build_dataset(data_dir, wal_sync=True)
    # reaching here means the site never fired — the parent treats
    # any exit other than death-by-SIGKILL as a gate failure
    raise SystemExit(7)


def smoke_phase() -> dict:
    """CPU streaming-equivalence gate (scripts/perf_smoke.sh): a tiny
    dataset runs every query shape through the streaming pipeline AND
    the single-barrier fallback, on both lattice fold routes (device /
    host) with the lattice route force-enabled — any result-cell
    disagreement is fatal. Phase output (phases_ms, pull_bytes) prints
    alongside so CI logs show the pipeline working."""
    import opengemini_tpu.query.executor as E
    from opengemini_tpu.query import QueryExecutor, parse_query
    from opengemini_tpu.storage import Engine, EngineOptions

    # the smoke sweeps exercise the DEVICE execution layer with
    # repeated statements across config flips — the serving-layer
    # result cache would satisfy the repeats from host memory, masking
    # the very configs under test and zeroing the measured-transfer
    # gates (its own digest gate is bench.py --phase rcgate)
    knobs.set_env("OG_RESULT_CACHE", "0")
    shm = "/dev/shm" if os.path.isdir("/dev/shm") else None
    checked = 0
    with tempfile.TemporaryDirectory(prefix="og-smoke-", dir=shm) as td:
        _register_tmp(td)
        n_rows, _t_ing = build_dataset(td)
        eng = Engine(td, EngineOptions(shard_duration=1 << 62))
        ex = QueryExecutor(eng)

        last_res = {}

        def run(qtext):
            (stmt,) = parse_query(qtext)
            # the trace-on config executes with a live span tree bound
            # (what the HTTP layer does for a sampled request) — the
            # digest compare below is the "results byte-identical with
            # tracing on vs off" gate
            if knobs.get_raw("OG_TRACE_SAMPLE") == "1":
                from opengemini_tpu.utils import tracing
                root = tracing.new_trace("query")
                with tracing.bind(root, tracing.new_trace_id()):
                    res = ex.execute(stmt, "bench", span=root)
                root.end_ns = time.perf_counter_ns()
                tracing.annotate_overlap(root)
                last_res["root"] = root
            else:
                res = ex.execute(stmt, "bench")
            if "error" in res:
                raise SystemExit(f"smoke query error: {res['error']}")
            last_res["res"] = res
            return _digest_series(res)

        # ------------------------------------ recompile-budget gate
        # compile auditor (ops/compileaudit.py): every bench shape
        # runs COLD (total compiles must fit the per-shape budget
        # declared next to the knob registry, utils/knobs.py
        # RECOMPILE_BUDGETS) then WARM (a repeat of the same shape
        # recompiling ANYTHING is the hot-loop retrace class that
        # erased the r05 1m win — budget is zero, always)
        from opengemini_tpu.ops import compileaudit as _ca
        if not _ca.AUDITOR.installed():
            raise SystemExit("SMOKE MISMATCH: compile auditor not "
                             "installed (OG_COMPILE_AUDIT=0 in the "
                             "smoke environment?)")
        recompile_report = {}
        for key, qtext in (("1h", QUERY), ("1m", QUERY_1M),
                           ("cfg1", QUERY_CFG1),
                           ("1m-topk", QUERY_1M_TOPK),
                           ("pctl", QUERY_PCTL)):
            mark = _ca.AUDITOR.mark()
            run(qtext)
            cold = _ca.AUDITOR.since(mark)
            rep = _ca.check_recompile_budget(key, sum(cold.values()))
            if not rep["ok"]:
                detail = "\n".join(f"  {n}x {k}" for k, n in
                                   sorted(cold.items()))
                raise SystemExit(
                    f"RECOMPILE BUDGET BREACH [{key} cold]: "
                    f"{rep['compiles']} compiles > budget "
                    f"{rep['budget']} — either a kernel variant "
                    "exploded into per-value shape classes (fix it) "
                    "or a reviewed budget bump belongs in "
                    "utils/knobs.py RECOMPILE_BUDGETS:\n" + detail)
            mark = _ca.AUDITOR.mark()
            run(qtext)
            warm = _ca.AUDITOR.since(mark)
            if warm:
                raise SystemExit(
                    f"RECOMPILE BUDGET BREACH [{key} warm]: a repeat "
                    f"of the same shape recompiled {warm} — "
                    "a shape-deriving arg is not static or a jit "
                    "wrapper is rebuilt per call (oglint R9 / "
                    "ops/compileaudit.py)")
            recompile_report[key] = {"cold": rep["compiles"],
                                     "budget": rep["budget"]}
        configs = [("stream", {"OG_PIPELINE_DEPTH": "4"}),
                   ("barrier", {"OG_PIPELINE_DEPTH": "0"}),
                   ("stream-hostfold", {"OG_PIPELINE_DEPTH": "4",
                                        "OG_LATTICE_DEVICE_FOLD": "0"}),
                   ("barrier-hostfold", {"OG_PIPELINE_DEPTH": "0",
                                         "OG_LATTICE_DEVICE_FOLD": "0"}),
                   # result-path equivalence (PR 3): parallel finalize
                   # + native row assembly vs the serial/python route
                   # must agree on every cell of every shape
                   ("finalize-serial", {"OG_PIPELINE_DEPTH": "4",
                                        "OG_FINALIZE_WORKERS": "0"}),
                   ("finalize-pool", {"OG_PIPELINE_DEPTH": "4",
                                      "OG_FINALIZE_WORKERS": "8"}),
                   # D2H diet gate: the device finalize epilogue +
                   # op-aware plane pruning (default on in the configs
                   # above) vs the byte-identical legacy transport
                   # (OG_DEVICE_FINALIZE=0) — every cell of every
                   # shape, streamed AND single-barrier, including the
                   # scaled-down 1m heavy shape and (second sweep) the
                   # forced lattice route
                   ("devfinal-off", {"OG_PIPELINE_DEPTH": "4",
                                     "OG_DEVICE_FINALIZE": "0"}),
                   ("devfinal-off-barrier",
                    {"OG_PIPELINE_DEPTH": "0",
                     "OG_DEVICE_FINALIZE": "0"}),
                   # tracing gate (PR 7): a sampled query carries a
                   # full span tree through the executor + pipeline —
                   # every result cell must match the untraced runs,
                   # on the streamed AND single-barrier routes
                   ("trace-on", {"OG_PIPELINE_DEPTH": "4",
                                 "OG_TRACE_SAMPLE": "1"}),
                   ("trace-on-barrier", {"OG_PIPELINE_DEPTH": "0",
                                         "OG_TRACE_SAMPLE": "1"}),
                   # device observatory gate (PR 8): with the
                   # utilization sampler ticking fast in the
                   # background (the ledger itself is always on),
                   # every result cell must match the untraced runs —
                   # streamed AND single-barrier
                   ("observatory", {"OG_PIPELINE_DEPTH": "4",
                                    "OG_DEVUTIL_MS": "10"}),
                   ("observatory-barrier", {"OG_PIPELINE_DEPTH": "0",
                                            "OG_DEVUTIL_MS": "10"}),
                   # answer-sized D2H gate (PR 12): the device ORDER
                   # BY/LIMIT cut and the order-statistic finalize
                   # (default on in every config above) vs their
                   # byte-identical escape hatches — every cell of
                   # every shape, streamed AND single-barrier
                   ("topk-off", {"OG_PIPELINE_DEPTH": "4",
                                 "OG_DEVICE_TOPK": "0"}),
                   ("sketch-off", {"OG_PIPELINE_DEPTH": "4",
                                   "OG_DEVICE_SKETCH": "0"}),
                   ("topk-sketch-off-barrier",
                    {"OG_PIPELINE_DEPTH": "0",
                     "OG_DEVICE_TOPK": "0",
                     "OG_DEVICE_SKETCH": "0"}),
                   # compressed-domain gate (round 14): device decode
                   # of DFOR/CONST slab payloads vs the byte-identical
                   # host-decode escape hatch (OG_DEVICE_DECODE=0) —
                   # every cell of every shape, streamed AND single-
                   # barrier. The sweep loop purges the device+
                   # compressed caches for these configs so the host
                   # path actually REBUILDS the slabs it compares
                   ("device-decode-off", {"OG_PIPELINE_DEPTH": "4",
                                          "OG_DEVICE_DECODE": "0"}),
                   ("device-decode-off-barrier",
                    {"OG_PIPELINE_DEPTH": "0",
                     "OG_DEVICE_DECODE": "0"}),
                   # whole-plan fused gate (round 17): the one-dispatch
                   # fused program (default on in every config above,
                   # engaging on the forced-lattice sweep below) vs the
                   # byte-identical staged chain (OG_FUSED_PLAN=0) —
                   # every cell of every shape, streamed AND single-
                   # barrier; the measured launch-count collapse is
                   # gated separately after the sweeps
                   ("fused-off", {"OG_PIPELINE_DEPTH": "4",
                                  "OG_FUSED_PLAN": "0"}),
                   ("fused-off-barrier", {"OG_PIPELINE_DEPTH": "0",
                                          "OG_FUSED_PLAN": "0"}),
                   # packed-predicate gate (round 18): packed-space
                   # residual evaluation (default on, engaging on the
                   # 1h-pred shape below) vs the byte-identical
                   # expand-then-filter hatch (OG_PACKED_PREDICATE=0)
                   # — every cell of every shape, streamed AND single-
                   # barrier, both lattice routes; the measured
                   # selectivity/shrink gate runs separately after the
                   # sweeps
                   ("packed-off", {"OG_PIPELINE_DEPTH": "4",
                                   "OG_PACKED_PREDICATE": "0"}),
                   ("packed-off-barrier",
                    {"OG_PIPELINE_DEPTH": "0",
                     "OG_PACKED_PREDICATE": "0"})]
        from opengemini_tpu.ops import hbm as _hbm
        # force the block path + lattice route so the smoke covers the
        # shapes the streaming pipeline actually rewires (originals
        # saved: the chaos gate below needs the BLOCK route back after
        # the forced-lattice sweep clobbers these)
        E.BLOCK_MIN_RATIO = 0
        _blk_cells0 = E.BLOCK_MAX_CELLS
        _blk_packed0 = E.BLOCK_MIN_RATIO_PACKED
        shape_refs = {}          # no-crash digests for the crash gate
        for forced_lattice in (False, True):
            if forced_lattice:
                E.BLOCK_MAX_CELLS = 8
                E.BLOCK_MIN_RATIO_PACKED = 0
            for key, qtext in (("1h", QUERY), ("1m", QUERY_1M),
                               ("cfg1", QUERY_CFG1),
                               ("1m-topk", QUERY_1M_TOPK),
                               ("pctl", QUERY_PCTL),
                               ("1h-pred", QUERY_PRED)):
                ref = None
                for cname, env in configs:
                    for k, v in env.items():
                        os.environ[k] = v
                    if "OG_DEVICE_DECODE" in env:
                        # force a cold host-stage rebuild: warm slabs
                        # (device-decoded by the earlier configs)
                        # would mask a decode-stage divergence
                        import opengemini_tpu.ops.devicecache as _dcp
                        _dcp.global_cache().purge()
                        _dcp.compressed_cache().purge()
                    if "OG_DEVUTIL_MS" in env:
                        _hbm.sampler().start()
                    try:
                        dig, cells = run(qtext)
                    finally:
                        if "OG_DEVUTIL_MS" in env:
                            _hbm.sampler().stop()
                    checked += cells
                    if ref is None:
                        ref = (cname, dig)
                    elif dig != ref[1]:
                        raise SystemExit(
                            f"SMOKE MISMATCH [{key} lattice="
                            f"{forced_lattice}]: {cname} {dig[:16]} != "
                            f"{ref[0]} {ref[1][:16]}")
                    for k in env:
                        os.environ.pop(k, None)
                if not forced_lattice:
                    shape_refs[key] = ref[1]
        # the observatory sweep must leave the HBM ledger exactly
        # reconciled with the caches it mirrors, with the utilization
        # ring populated from the background sampler
        cross = _hbm.cross_check()
        if not cross["ok"]:
            raise SystemExit(f"SMOKE MISMATCH: HBM ledger diverged "
                             f"from its sources: {cross}")
        n_samples = len(_hbm.sampler().samples())
        if n_samples == 0:
            raise SystemExit("SMOKE MISMATCH: utilization sampler "
                             "produced no samples at OG_DEVUTIL_MS=10")
        # ------------------------------- answer-sized D2H gate (PR 12)
        # the forced-lattice sweep left the tiny cell cap — restore
        # the block route so the shrink measurement reflects it
        E.BLOCK_MAX_CELLS = _blk_cells0
        E.BLOCK_MIN_RATIO_PACKED = _blk_packed0
        from opengemini_tpu.ops.devstats import DEVICE_STATS as _DSM
        knobs.set_env("OG_DEVICE_TOPK", "0")
        try:
            run(QUERY_1M_TOPK)
            tk_off_b = _DSM["last_query_d2h_bytes"]
        finally:
            knobs.del_env("OG_DEVICE_TOPK")
        run(QUERY_1M_TOPK)
        tk_on_b = _DSM["last_query_d2h_bytes"]
        topk_shrink = tk_off_b / max(tk_on_b, 1)
        if topk_shrink < 2.0:
            raise SystemExit(
                f"SMOKE MISMATCH: device topk cut shrank D2H only "
                f"{topk_shrink:.2f}x ({tk_off_b}B -> {tk_on_b}B) — "
                "the winner cut is not engaging on the heavy shape")
        sk_g0 = _DSM["sketch_dev_grids"]
        run(QUERY_PCTL)
        sketch_grids = _DSM["sketch_dev_grids"] - sk_g0
        if sketch_grids <= 0:
            raise SystemExit(
                "SMOKE MISMATCH: percentile shape did not route "
                "through the device order-statistic finalize "
                "(sketch_dev_grids unchanged)")
        # --------------------------- compressed-domain gate (round 14)
        # measured H2D diet on the heavy shape: cold slab build with
        # device decode (compressed payloads cross the link) vs the
        # OG_DEVICE_DECODE=0 host build (dense planes cross) — the
        # manifest attributes every byte, so the ratio is exact
        import opengemini_tpu.ops.devicecache as _dcs
        from opengemini_tpu.ops.device_decode import (
            DECODE_STATS as _DDS)

        (dd_dig_off, _c1), dd_off_b = _cold_build_h2d(
            lambda: run(QUERY_1M), decode_on=False)
        (dd_dig_on, _c2), dd_on_b = _cold_build_h2d(
            lambda: run(QUERY_1M), decode_on=True)
        if dd_dig_on != dd_dig_off:
            raise SystemExit("SMOKE MISMATCH: device decode changed "
                             "heavy-shape bytes")
        dd_shrink = dd_off_b / max(dd_on_b, 1)
        if dd_shrink < 3.0:
            raise SystemExit(
                f"SMOKE MISMATCH: device decode shrank cold-build "
                f"H2D only {dd_shrink:.2f}x ({dd_off_b}B -> "
                f"{dd_on_b}B) — the compressed-domain stage is not "
                "engaging on the heavy shape")
        # seeded OOM + transient at the new device.decode.launch
        # failpoint: the ladder must heal PER BLOCK through the host
        # stage — digests unchanged, heal counter proven, ledger exact
        from opengemini_tpu.utils import failpoint as _fpd
        dd_heals0 = _DDS["host_heals"]
        for _mode, _hits in (("oom", 2), ("transient", 3)):
            _dcs.global_cache().purge()
            _dcs.compressed_cache().purge()
            _fpd.seed(13)
            _fpd.enable("device.decode.launch", _mode, maxhits=_hits)
            try:
                dig, _cells = run(QUERY_1M)
            finally:
                _fpd.disable("device.decode.launch")
            if dig != dd_dig_on:
                raise SystemExit(
                    f"SMOKE MISMATCH: decode-launch {_mode} "
                    "injection changed heavy-shape bytes")
        dd_heals = _DDS["host_heals"] - dd_heals0
        if dd_heals <= 0:
            raise SystemExit(
                "SMOKE MISMATCH: decode-launch injections never "
                "reached the per-block host heal")
        cross = _hbm.cross_check()
        if not cross["ok"]:
            raise SystemExit(
                f"SMOKE MISMATCH: HBM ledger diverged after the "
                f"decode-heal gate: {cross}")
        from opengemini_tpu.ops import devicefault as _dfd
        _dfd.reset_breakers()
        # f32 fast tier (OG_F32_TIER): NOT bit-identical by design —
        # gated on tolerance against the f64 path, on the dense-window
        # route (block cache off so dense groups actually form), and
        # the Pallas kernel must actually have run
        def _series_cells(res):
            out = {}
            for se in res.get("series", []):
                key = json.dumps(se.get("tags", {}), sort_keys=True)
                out[key] = se["values"]
            return out
        # block cache off so the scan DECODES; the 1m windows
        # straddle segments, so pre-agg metadata can't answer and the
        # decoded segments assemble into dense (S, P) groups — the
        # dashboard-class route the tier serves
        knobs.set_env("OG_DEVICE_CACHE_MB", "0")
        f32_max_err = 0.0
        f32_cells = 0
        try:
            run(QUERY_1M)
            ref_f = _series_cells(last_res["res"])
            knobs.set_env("OG_F32_TIER", "1")
            f32_l0 = _DSM["f32_tier_launches"]
            run(QUERY_1M)
            got_f = _series_cells(last_res["res"])
            f32_launches = _DSM["f32_tier_launches"] - f32_l0
        finally:
            knobs.del_env("OG_F32_TIER")
            knobs.del_env("OG_DEVICE_CACHE_MB")
        if f32_launches <= 0:
            raise SystemExit("SMOKE MISMATCH: OG_F32_TIER=1 ran zero "
                             "Pallas fast-tier launches on the dense "
                             "1m shape")
        if set(ref_f) != set(got_f):
            raise SystemExit("SMOKE MISMATCH: f32 tier changed the "
                             "series set")
        for key, rrows in ref_f.items():
            grows = got_f[key]
            if len(rrows) != len(grows):
                raise SystemExit(
                    f"SMOKE MISMATCH: f32 tier changed row count for "
                    f"{key}: {len(rrows)} != {len(grows)}")
            for rr, gr in zip(rrows, grows):
                if rr[0] != gr[0]:
                    raise SystemExit("SMOKE MISMATCH: f32 tier moved "
                                     f"a row time: {rr} vs {gr}")
                for a, b in zip(rr[1:], gr[1:]):
                    if (a is None) != (b is None):
                        raise SystemExit(
                            f"SMOKE MISMATCH: f32 tier changed cell "
                            f"presence: {rr} vs {gr}")
                    if a is None:
                        continue
                    err = abs(a - b) / max(abs(a), 1e-9)
                    f32_max_err = max(f32_max_err, err)
                    f32_cells += 1
                    if err > 1e-4:
                        raise SystemExit(
                            f"SMOKE MISMATCH: f32 tier drifted "
                            f"{err:.2e} > 1e-4 at {key} {rr} vs {gr}")
        # streaming-serializer golden gate: the chunked emit (with the
        # bounded-queue overlap thread) must be byte-identical to
        # json.dumps of the same document
        from opengemini_tpu.http.serializer import (iter_results_json,
                                                    stream_chunks)
        doc = {"results": [dict(last_res["res"], statement_id=0)]}
        want = json.dumps(doc).encode() + b"\n"
        got = b"".join(stream_chunks(iter_results_json(doc)))
        if got != want:
            raise SystemExit("SMOKE MISMATCH: streaming serializer "
                             "diverged from json.dumps")
        # the last trace-on run's tree must export as loadable Chrome
        # trace-event JSON with sane (non-negative, in-root) timestamps
        from opengemini_tpu.utils import tracing
        trec = tracing.TraceRecord(
            trace_id="smoke", kind="query", text=QUERY, db="bench",
            start_wall=time.time(), duration_ns=0,
            root=last_res["root"])
        cdoc = json.loads(tracing.chrome_json(trec))
        xs = [e for e in cdoc["traceEvents"] if e["ph"] == "X"]
        if not xs or any(e["ts"] < 0 or e["dur"] < 0 for e in xs):
            raise SystemExit("SMOKE MISMATCH: chrome trace export "
                             "empty or non-monotonic")
        # tracing overhead gate: best-of-N wall of the 1h shape with a
        # live span tree vs without must stay within
        # OG_SMOKE_TRACE_OVERHEAD_PCT (default 3%) — with a small
        # absolute slack so a sub-ms CI jitter can't flap the gate
        (stmt_1h,) = parse_query(QUERY)
        n_overhead = 7

        def best_wall(span_on):
            best = float("inf")
            for _ in range(n_overhead):
                t0 = time.perf_counter()
                if span_on:
                    root = tracing.new_trace("query")
                    with tracing.bind(root, tracing.new_trace_id()):
                        ex.execute(stmt_1h, "bench", span=root)
                    root.end_ns = time.perf_counter_ns()
                else:
                    ex.execute(stmt_1h, "bench")
                best = min(best, time.perf_counter() - t0)
            return best

        best_wall(False)                     # warm both code paths
        t_off = best_wall(False)
        t_on = best_wall(True)
        overhead_pct = (t_on - t_off) / max(t_off, 1e-9) * 100
        limit = float(knobs.get("OG_SMOKE_TRACE_OVERHEAD_PCT"))
        if overhead_pct > limit and (t_on - t_off) > 2e-3:
            raise SystemExit(
                f"SMOKE MISMATCH: tracing overhead {overhead_pct:.2f}%"
                f" (on {t_on * 1e3:.2f}ms vs off {t_off * 1e3:.2f}ms)"
                f" exceeds {limit}%")
        # observatory overhead gate (PR 8): fast-ticking utilization
        # sampler + per-query ctx attribution + calibration recording
        # vs the plain path, same best-of-N + pct/2ms-slack mechanism
        # as the tracing gate above (t_off is the same plain baseline)
        from opengemini_tpu.query import scheduler as qsched
        from opengemini_tpu.query.manager import QueryManager
        qm_oh = QueryManager()
        cost_oh = qsched.estimate_request_cost(ex, [stmt_1h], "bench")

        def best_wall_obs():
            best = float("inf")
            for _ in range(n_overhead):
                t0 = time.perf_counter()
                cctx = qm_oh.attach(QUERY, "bench")
                ex.execute(stmt_1h, "bench", ctx=cctx)
                qm_oh.detach(cctx)
                qsched.get_scheduler().record_actual(
                    cost_oh, cells=cctx.actual_cells,
                    pull_bytes=cctx.d2h_bytes,
                    device_ms=cctx.device_ns / 1e6,
                    hbm_peak=cctx.hbm_peak)
                best = min(best, time.perf_counter() - t0)
            return best

        knobs.set_env("OG_DEVUTIL_MS", "10")
        _hbm.sampler().start()
        try:
            best_wall_obs()                  # warm the observatory path
            t_obs = best_wall_obs()
        finally:
            _hbm.sampler().stop()
            knobs.del_env("OG_DEVUTIL_MS")
        obs_pct = (t_obs - t_off) / max(t_off, 1e-9) * 100
        obs_limit = float(knobs.get("OG_SMOKE_OBS_OVERHEAD_PCT"))
        if obs_pct > obs_limit and (t_obs - t_off) > 2e-3:
            raise SystemExit(
                f"SMOKE MISMATCH: observatory overhead {obs_pct:.2f}%"
                f" (on {t_obs * 1e3:.2f}ms vs off {t_off * 1e3:.2f}ms)"
                f" exceeds {obs_limit}%")
        # --------------------------- fused whole-plan gate (round 17)
        # measured launch collapse: on the forced-lattice heavy shape a
        # WARM repeat through the fused route must answer in <= 2
        # device launches (the staged chain pays ~6), recompile nothing
        # (the shape class is pinned in ops/fused._PROGRAMS), agree
        # byte-for-byte with the OG_FUSED_PLAN=0 staged escape hatch,
        # and heal a seeded launch fault at device.fused.launch back to
        # the staged chain for that query only — digest unchanged,
        # fused_fallbacks moving, HBM ledger still reconciled
        from opengemini_tpu.ops import devicefault as _dfu
        from opengemini_tpu.utils import failpoint as _fpu
        E.BLOCK_MAX_CELLS = 8
        E.BLOCK_MIN_RATIO_PACKED = 0
        fused_heals = 0
        try:
            fu0 = _DSM["fused_launches"]
            ref_fu, _fc = run(QUERY_1M)      # warms slabs + shape class
            if _DSM["fused_launches"] <= fu0:
                raise SystemExit(
                    "FUSED GATE: the forced-lattice heavy shape never "
                    "dispatched a fused program (fused_launches flat) "
                    "— the route probe is not engaging")
            mark = _ca.AUDITOR.mark()
            kl0 = _DSM["kernel_launches"]
            dig_w, _fc = run(QUERY_1M)       # warm fused repeat
            fused_warm_launches = _DSM["kernel_launches"] - kl0
            warm_fu = _ca.AUDITOR.since(mark)
            if warm_fu:
                raise SystemExit(
                    f"FUSED GATE: warm fused repeat recompiled "
                    f"{warm_fu} — a shape-deriving value leaked out of "
                    "the shape-class key (query/plancache.py)")
            if dig_w != ref_fu:
                raise SystemExit("FUSED GATE: warm fused repeat "
                                 "changed bytes")
            if not 0 < fused_warm_launches <= 2:
                raise SystemExit(
                    f"FUSED GATE: warm heavy shape took "
                    f"{fused_warm_launches} device launches through "
                    "the fused route (budget <= 2; staged chain ~6)")
            knobs.set_env("OG_FUSED_PLAN", "0")
            try:
                dig_off, _fc = run(QUERY_1M)
            finally:
                knobs.del_env("OG_FUSED_PLAN")
            if dig_off != ref_fu:
                raise SystemExit(
                    "FUSED GATE: OG_FUSED_PLAN=0 changed bytes — the "
                    "fused and staged routes must be bit-identical")
            # per-query heal: retries disabled, and TWO seeded OOM hits
            # (an OOM always earns one pressure-ladder retry) exhaust
            # the ladder so the executor re-runs the group through the
            # staged lattice chain
            knobs.set_env("OG_DEVICE_RETRY", "0")
            _fpu.seed(17)
            hb0 = _DSM["fused_fallbacks"]
            _fpu.enable("device.fused.launch", "oom", maxhits=2)
            dig_h, _fc = run(QUERY_1M)
            fired_fu = not _fpu.active("device.fused.launch")
            _fpu.disable("device.fused.launch")
            if not fired_fu:
                raise SystemExit(
                    "FUSED GATE: device.fused.launch failpoint never "
                    "fired — the fused route is not the dispatch path")
            fused_heals = _DSM["fused_fallbacks"] - hb0
            if fused_heals <= 0:
                raise SystemExit(
                    "FUSED GATE: seeded fused-launch OOM produced no "
                    "staged heal (fused_fallbacks flat)")
            if dig_h != ref_fu:
                raise SystemExit(
                    f"FUSED GATE: healed query changed bytes: "
                    f"{dig_h[:16]} != {ref_fu[:16]}")
            cross = _hbm.cross_check()
            if not cross["ok"]:
                raise SystemExit(f"FUSED GATE: HBM ledger diverged "
                                 f"across the fused heal: {cross}")
        finally:
            _fpu.disable_all()
            _dfu.reset_breakers()
            knobs.del_env("OG_DEVICE_RETRY")
            knobs.del_env("OG_FUSED_PLAN")
            E.BLOCK_MAX_CELLS = _blk_cells0
            E.BLOCK_MIN_RATIO_PACKED = _blk_packed0
        # --------------- packed-predicate selectivity gate (round 18)
        # measured lane diet: a predicate must cut the rows that ever
        # EXPAND out of packed space, not merely filter them after. A
        # time-ramped measurement (decimal-scaled values climbing 0.01
        # per point) gives every 4096-row segment a tight DFOR
        # envelope, so a selective threshold classifies most segments
        # "none" and they never stage — pushdown_lanes_expanded under
        # the packed route vs the OG_PACKED_PREDICATE=0 hatch is the
        # shrink. Digests must agree per threshold (cold AND warm,
        # the warm repeat recompiling nothing), and a seeded fault at
        # the mask-launch site (device.pushdown.eval) must heal per
        # batch to the host expand-then-filter mask, byte-identical,
        # with the HBM ledger still reconciled after
        import opengemini_tpu.ops.devicecache as _dcr
        from opengemini_tpu.ops.device_decode import DECODE_STATS as _DDS
        rp_pts, rp_hosts = 1 << 16, 2
        rp_times = np.arange(rp_pts, dtype=np.int64) * 10**9
        rp_vals = np.round(np.arange(rp_pts, dtype=np.float64) * 0.01,
                           2)
        rp_max = float(rp_vals[-1])
        for h in range(rp_hosts):
            eng.write_record("bench", "ramp",
                             {"hostname": f"host_{h}"}, rp_times,
                             {"v": rp_vals})
        for s in eng.database("bench").all_shards():
            s.flush()

        def _ramp_q(thr):
            return (f"SELECT sum(v), count(v), mean(v) FROM ramp "
                    f"WHERE v >= {thr!r} AND time >= 0 AND time < "
                    f"{rp_pts}s GROUP BY time(1h), hostname")

        def _purge_planes():
            # comparable cold builds: the hatch's pred-free slab key
            # may be warm from an earlier run (and vice versa)
            _dcr.global_cache().purge()
            _dcr.host_cache().purge()

        pd_sel = {}
        pd_heals = 0
        try:
            sk0 = _DDS["pushdown_segments_skipped"]
            for tag, frac in (("50pct", 0.5), ("1pct", 0.01),
                              ("0.1pct", 0.001)):
                qtext = _ramp_q(round(rp_max * (1.0 - frac), 2))
                _purge_planes()
                l0 = _DDS["pushdown_lanes_expanded"]
                dig_on, _pc = run(qtext)
                lanes_on = _DDS["pushdown_lanes_expanded"] - l0
                mark = _ca.AUDITOR.mark()
                dig_w, _pc = run(qtext)          # warm packed repeat
                if _ca.AUDITOR.since(mark):
                    raise SystemExit(
                        f"PACKED GATE [{tag}]: warm packed repeat "
                        "recompiled — a predicate value leaked into a "
                        "shape-deriving traced argument")
                knobs.set_env("OG_PACKED_PREDICATE", "0")
                try:
                    _purge_planes()
                    dig_off, _pc = run(qtext)
                finally:
                    knobs.del_env("OG_PACKED_PREDICATE")
                # the hatch takes the row-wise scan route — no block
                # slabs, no lanes counter — and decodes EVERY stored
                # row in range before filtering: that row count is
                # the expand-then-filter side of the shrink
                lanes_off = rp_pts * rp_hosts
                if not dig_on == dig_w == dig_off:
                    raise SystemExit(
                        f"PACKED GATE [{tag}]: packed route changed "
                        f"bytes: cold {dig_on[:16]} warm {dig_w[:16]}"
                        f" hatch {dig_off[:16]}")
                pd_sel[tag] = {"lanes_on": int(lanes_on),
                               "lanes_off": int(lanes_off)}
            pd_skipped = _DDS["pushdown_segments_skipped"] - sk0
            if pd_skipped <= 0:
                raise SystemExit(
                    "PACKED GATE: no segment envelope classified "
                    '"none" across the selectivity sweep — the skip-'
                    "before-stage path is dead")
            sel = pd_sel["0.1pct"]
            pd_shrink = sel["lanes_off"] / max(sel["lanes_on"], 1)
            if pd_shrink < 3.0:
                raise SystemExit(
                    f"PACKED GATE: 0.1% selectivity expanded "
                    f"{sel['lanes_on']} lanes vs {sel['lanes_off']} "
                    f"under the hatch — shrink {pd_shrink:.1f}x < 3x")
            # per-batch heal: a persistent transient at the mask
            # launch exhausts its retries and the builder re-derives
            # THAT batch's survivor mask on host (expand-then-filter)
            # — a fresh threshold forces the cold build that actually
            # launches
            thr_heal = round(rp_max * 0.61, 2)
            _fpu.seed(18)
            h0 = _DDS["pushdown_heals"]
            _fpu.enable("device.pushdown.eval", "transient")
            try:
                dig_h, _pc = run(_ramp_q(thr_heal))
            finally:
                _fpu.disable("device.pushdown.eval")
                _dfu.reset_breakers()
            pd_heals = _DDS["pushdown_heals"] - h0
            if pd_heals <= 0:
                raise SystemExit(
                    "PACKED GATE: seeded device.pushdown.eval fault "
                    "produced no per-batch heal (pushdown_heals flat)")
            knobs.set_env("OG_PACKED_PREDICATE", "0")
            try:
                _purge_planes()
                dig_hh, _pc = run(_ramp_q(thr_heal))
            finally:
                knobs.del_env("OG_PACKED_PREDICATE")
            if dig_h != dig_hh:
                raise SystemExit(
                    f"PACKED GATE: healed query changed bytes: "
                    f"{dig_h[:16]} != hatch {dig_hh[:16]}")
            cross = _hbm.cross_check()
            if not cross["ok"]:
                raise SystemExit(f"PACKED GATE: HBM ledger diverged "
                                 f"across the pushdown heal: {cross}")
        finally:
            _fpu.disable_all()
            _dfu.reset_breakers()
            knobs.del_env("OG_PACKED_PREDICATE")
        # ------------------------------------------------ chaos gate
        # device fault domain (PR 9): one seeded device-fault schedule
        # per bench shape — OOM + transient + hang injections across
        # the launch/pull/fill sites — must leave every digest equal
        # to its fault-free reference and the HBM ledger exactly
        # reconciled (zero drift), with the breakers healed after
        from opengemini_tpu.ops import devicefault as _df
        from opengemini_tpu.utils import failpoint as _fp
        _df.reset_breakers()
        chaos_injected = 0
        knobs.set_env("OG_DEVICE_HANG_S", "0.5")
        knobs.set_env("OG_DEVICE_RETRY_BACKOFF_MS", "1")
        knobs.set_env("OG_DEVICE_BREAKER_COOLDOWN_S", "0.05")
        _CHAOS_SCHEDULE = [
            ("device.block.launch", "oom"),
            ("device.block.launch", "transient"),
            ("device.lattice.launch", "transient"),
            ("device.finalize.launch", "oom"),
            ("pipeline.submit", "transient"),
            ("pipeline.pull", "oom"),
            ("pipeline.pull", "hang"),
            ("pipeline.unpack", "transient"),
            ("blockagg.lattice_fold", "oom"),
        ]
        # the staged-chain sites above (device.lattice.launch,
        # blockagg.lattice_fold) sit INSIDE the fused program's fault
        # domain with OG_FUSED_PLAN on — the fused route would answer
        # the cfg1 slice in one dispatch and those failpoints would
        # never fire; the schedule pins the staged chain (the fused
        # route's own seeded-fault coverage is the gate above)
        knobs.set_env("OG_FUSED_PLAN", "0")
        try:
            _fp.seed(9)
            # the forced-lattice sweep left BLOCK_MAX_CELLS=8 — put
            # the block route back or its launch sites never fire and
            # the recovery cycle below can never trip the breaker
            E.BLOCK_MAX_CELLS = _blk_cells0
            E.BLOCK_MIN_RATIO_PACKED = _blk_packed0
            led_before = {
                t: v["bytes"] for t, v in _hbm.LEDGER.snapshot(
                    events=False)["tiers"].items()}
            # one seeded schedule per shape: the 9-entry site/mode
            # matrix rotates across the 3 shapes (3 injections each,
            # every site exercised once per smoke) — an OOM rung
            # evicts the WHOLE device-cache tier by design, so running
            # all 9 on every shape would triple the cold-rebuild cost
            # for no added coverage. The cfg1 slice carries both
            # lattice sites, so that shape runs under the forced
            # lattice route; EVERY injection must actually fire
            for si, (key, qtext) in enumerate((
                    ("1h", QUERY), ("1m", QUERY_1M),
                    ("cfg1", QUERY_CFG1))):
                if key == "cfg1":
                    E.BLOCK_MAX_CELLS = 8
                    E.BLOCK_MIN_RATIO_PACKED = 0
                ref, _cells = run(qtext)
                for site, mode in _CHAOS_SCHEDULE[si::3]:
                    arg = 700 if mode == "hang" else None
                    _fp.enable(site, mode, arg, maxhits=1)
                    dig, cells = run(qtext)
                    fired = not _fp.active(site)
                    _fp.disable(site)
                    if not fired:
                        raise SystemExit(
                            f"CHAOS MISMATCH [{key}]: failpoint "
                            f"{site} never fired — the fault schedule "
                            "no longer reaches its device route")
                    chaos_injected += 1
                    if dig != ref:
                        raise SystemExit(
                            f"CHAOS MISMATCH [{key}]: {site}/{mode} "
                            f"changed bytes: {dig[:16]} != {ref[:16]}")
                cross = _hbm.cross_check()
                if not cross["ok"]:
                    raise SystemExit(
                        f"CHAOS MISMATCH [{key}]: ledger diverged "
                        f"after the fault schedule: {cross}")
            E.BLOCK_MAX_CELLS = _blk_cells0
            E.BLOCK_MIN_RATIO_PACKED = _blk_packed0
            led_after = {
                t: v["bytes"] for t, v in _hbm.LEDGER.snapshot(
                    events=False)["tiers"].items()}
            if led_after["pipeline"] != led_before["pipeline"]:
                raise SystemExit(
                    f"CHAOS MISMATCH: pipeline-tier ledger drifted "
                    f"{led_before['pipeline']} -> "
                    f"{led_after['pipeline']} across the storms")
            # fault_recovery_ms: the breaker-trip → half-open probe →
            # restore cycle, measured end to end on the 1h shape (a
            # persistent fault trips the 'block' route to its host
            # fallback; disarming lets the next query probe it closed)
            knobs.set_env("OG_DEVICE_RETRY", "0")
            _fp.enable("device.block.launch", "oom")
            t_trip0 = time.perf_counter()
            for _ in range(50):
                run(QUERY)          # host-fallback answers, breaker
                if _df.breaker_for("block").is_open:
                    break
            else:
                raise SystemExit(
                    "CHAOS MISMATCH: persistent device.block.launch "
                    "OOM never tripped the block breaker (route not "
                    "exercised?)")
            _fp.disable("device.block.launch")
            for _ in range(200):
                time.sleep(0.01)    # cooldown, then the probe query
                run(QUERY)
                if not _df.breaker_for("block").is_open:
                    break
            else:
                raise SystemExit(
                    "CHAOS MISMATCH: block breaker never recovered "
                    "after the fault cleared")
            fault_recovery_ms = (time.perf_counter() - t_trip0) * 1e3
            knobs.del_env("OG_DEVICE_RETRY")
            dfc = _df.devicefault_collector()
            if not (dfc["breaker_trips"] >= 1
                    and dfc["breaker_recoveries"] >= 1
                    and dfc["route_fallbacks"] >= 1):
                raise SystemExit(
                    f"CHAOS MISMATCH: recovery cycle not observable "
                    f"in the fault counters: {dfc}")
        finally:
            _fp.disable_all()
            _df.reset_breakers()
            for k in ("OG_DEVICE_HANG_S", "OG_DEVICE_RETRY_BACKOFF_MS",
                      "OG_DEVICE_BREAKER_COOLDOWN_S",
                      "OG_DEVICE_RETRY", "OG_FUSED_PLAN"):
                knobs.del_env(k)
        # ------------------------------------------------ crash gate
        # storage crash consistency (PR 10): one SIGKILL/restart cycle
        # per bench shape — a crashchild subprocess rebuilds the
        # deterministic dataset with fsync-acked ingest and dies
        # MID-FLUSH at a rotating durability boundary; the restarted
        # engine (eager open = orphan sweep + WAL replay, then a flush
        # to steady state) must serve the shape's digest bit-identical
        # to the no-crash reference, with zero orphan .tmp files,
        # across TWO restarts
        crash_cycles = 0
        crash_recovery_ms = 0.0
        for key, qtext, site in (
                ("1h", QUERY, "tssp.finalize.crash_pre_rename"),
                ("1m", QUERY_1M, "shard.flush.crash_commit"),
                ("cfg1", QUERY_CFG1, "wal.switch.crash")):
            cdir = os.path.join(td, f"crash_{key}")
            cenv = dict(os.environ)
            cenv["OG_CRASH_OK"] = "1"
            rc, _out, err = run_child(
                [sys.executable, os.path.abspath(__file__), "--phase",
                 "crashchild", "--data", cdir, "--crash-site", site],
                timeout=300, env=cenv)
            if rc != -signal.SIGKILL:
                raise SystemExit(
                    f"CRASH GATE [{key}]: child armed at {site} "
                    f"exited rc={rc} instead of dying to SIGKILL: "
                    f"{err[-1500:]}")
            for restart in (1, 2):
                t_r0 = time.perf_counter()
                eng_c = Engine(cdir, EngineOptions(
                    shard_duration=1 << 62, lazy_shard_open=False))
                rec_ms = (time.perf_counter() - t_r0) * 1e3
                eng_c.flush_all()
                (stmt_c,) = parse_query(qtext)
                res_c = QueryExecutor(eng_c).execute(stmt_c, "bench")
                eng_c.close()
                if "error" in res_c:
                    raise SystemExit(
                        f"CRASH GATE [{key}]: post-restart query "
                        f"error: {res_c['error']}")
                dig_c, _cells_c = _digest_series(res_c)
                if dig_c != shape_refs[key]:
                    raise SystemExit(
                        f"CRASH GATE [{key}]: restart #{restart} "
                        f"after {site} serves {dig_c[:16]} != "
                        f"no-crash reference "
                        f"{shape_refs[key][:16]}")
                orphans = [os.path.join(dp, fn)
                           for dp, _dn, fns in os.walk(cdir)
                           for fn in fns if fn.endswith(".tmp")]
                if orphans:
                    raise SystemExit(
                        f"CRASH GATE [{key}]: orphan .tmp survived "
                        f"restart #{restart}: {orphans}")
                if restart == 1:
                    crash_recovery_ms = max(crash_recovery_ms, rec_ms)
            crash_cycles += 1
            shutil.rmtree(cdir, ignore_errors=True)
        # -------------------------------- transfer-manifest gate
        # after every sweep, storm and crash cycle: the per-site
        # manifest must still equal the devstats transfer totals to
        # the byte, every streamed pull must have matched its HBM-
        # ledger booking, and no (kernel, signature) may have
        # compiled twice anywhere in the smoke
        xman = _ca.manifest_cross_check()
        if not xman["ok"]:
            raise SystemExit(
                f"TRANSFER MANIFEST MISMATCH: {json.dumps(xman)} — "
                "a transfer path moved bytes outside the "
                "record_h2d/record_d2h funnel (oglint R10 / "
                "ops/compileaudit.py)")
        if xman["ledger"]["checks"] <= 0:
            raise SystemExit("TRANSFER MANIFEST MISMATCH: zero "
                             "pipeline ledger cross-checks ran — the "
                             "streamed pull path was never exercised")
        _ca_counters = _ca.compileaudit_collector()
        if _ca_counters["duplicate_compiles"] > 0:
            raise SystemExit(
                f"RECOMPILE BUDGET BREACH: "
                f"{_ca_counters['duplicate_compiles']} duplicate "
                "(kernel, signature) compiles across the smoke — a "
                "jit cache is being dropped or re-wrapped: "
                f"{[e for e in _ca.AUDITOR.snapshot()['recent'] if e['dup']]}")
        (est,) = parse_query("EXPLAIN ANALYZE " + QUERY)
        phases = _parse_phases(ex.execute(est, "bench"))
        eng.close()
    return {"metric": "perf_smoke_streaming_equivalence",
            "value": 1, "unit": "pass", "rows": n_rows,
            "cells_checked": checked,
            "configs": [c for c, _e in configs],
            "trace_overhead_pct": round(overhead_pct, 2),
            "trace_e2e_off_ms": round(t_off * 1e3, 2),
            "trace_e2e_on_ms": round(t_on * 1e3, 2),
            "obs_overhead_pct": round(obs_pct, 2),
            "obs_e2e_on_ms": round(t_obs * 1e3, 2),
            "obs_ledger_reconciled": 1 if cross["ok"] else 0,
            "obs_util_samples": n_samples,
            # device fault domain gate (PR 9)
            "chaos_injections": chaos_injected,
            "chaos_ledger_ok": 1,
            "fault_recovery_ms": round(fault_recovery_ms, 1),
            # storage crash gate (PR 10)
            "crash_cycles": crash_cycles,
            "crash_digest_ok": 1,
            "crash_orphans": 0,
            "crash_recovery_ms": round(crash_recovery_ms, 1),
            # compressed-domain gate (round 14)
            "dd_h2d_shrink_x": round(dd_shrink, 1),
            "dd_h2d_bytes_on": int(dd_on_b),
            "dd_h2d_bytes_off": int(dd_off_b),
            "dd_decode_heals": int(dd_heals),
            # answer-sized D2H gate (PR 12)
            "topk_d2h_shrink_x": round(topk_shrink, 1),
            "topk_d2h_bytes_on": int(tk_on_b),
            "topk_d2h_bytes_off": int(tk_off_b),
            "sketch_dev_grids": int(sketch_grids),
            "f32_tier_launches": int(f32_launches),
            "f32_max_rel_err": float(f"{f32_max_err:.3e}"),
            "f32_checked_cells": int(f32_cells),
            # whole-plan fused gate (round 17)
            "fused_launches": int(_DSM["fused_launches"]),
            "fused_warm_launches": int(fused_warm_launches),
            "fused_heals": int(fused_heals),
            # packed-predicate gate (round 18)
            "pd_lane_shrink_x": round(pd_shrink, 1),
            "pd_selectivity": pd_sel,
            "pd_segments_skipped": int(pd_skipped),
            "pd_heals": int(pd_heals),
            # compile-cache + transfer audit gates (PR 11)
            "recompile_budget_ok": 1,
            "recompile_budget": recompile_report,
            "warm_compiles": 0,
            "compiles_total": _ca_counters["compiles_total"],
            "duplicate_compiles": 0,
            "xfer_manifest_ok": 1,
            "xfer_ledger_checks": xman["ledger"]["checks"],
            "xfer_h2d_bytes": xman["h2d"]["manifest"],
            "xfer_d2h_bytes": xman["d2h"]["manifest"],
            **phases}


# --------------------------------- concurrent serving (scheduler gate)

# ------------------------------------------------ result-cache gate


def rcgate_phase() -> dict:
    """Result-cache correctness + warm-hit gate (perf_smoke): on every
    bench shape, cache-on digests must equal the OG_RESULT_CACHE=0
    reference on a COLD pass, a WARM pass (served from cache), and a
    POST-WRITE pass (a point written into the cached range must
    invalidate — the staleness contract), and the measured warm-hit
    wall must shrink vs the cache-off wall."""
    from opengemini_tpu.query import QueryExecutor, parse_query
    from opengemini_tpu.query import resultcache as _rc
    from opengemini_tpu.storage import Engine, EngineOptions
    from opengemini_tpu.storage.rows import PointRow

    shm = "/dev/shm" if os.path.isdir("/dev/shm") else None
    shapes = (("1h", QUERY), ("1m", QUERY_1M), ("cfg1", QUERY_CFG1))
    out: dict = {"metric": "resultcache_gate", "value": 1,
                 "unit": "bool", "shapes": [k for k, _q in shapes]}
    with tempfile.TemporaryDirectory(prefix="og-rc-", dir=shm) as td:
        _register_tmp(td)
        n_rows, _ = build_dataset(td)
        eng = Engine(td, EngineOptions(shard_duration=1 << 62))
        ex = QueryExecutor(eng)
        stmts = {k: parse_query(q)[0] for k, q in shapes}

        def run(k, best_of=1):
            best, dig = None, None
            for _ in range(best_of):
                t0 = time.perf_counter()
                res = ex.execute(stmts[k], "bench")
                dt = time.perf_counter() - t0
                if "error" in res:
                    raise SystemExit(
                        f"rcgate query error [{k}]: {res['error']}")
                if best is None or dt < best:
                    best = dt
                dig = _digest_series(res)[0]
            return dig, best * 1000

        try:
            # cold references, cache OFF (also warms jit compiles so
            # the shrink measurement below is compile-free)
            knobs.set_env("OG_RESULT_CACHE", "0")
            ref = {k: run(k)[0] for k, _q in shapes}
            off_ms = {k: run(k, best_of=2)[1] for k, _q in shapes}
            # cache ON: cold pass fills, warm pass serves
            knobs.set_env("OG_RESULT_CACHE", "1")
            h0 = _rc.RC_STATS["hits"]
            for k, _q in shapes:
                d, _ms = run(k)
                if d != ref[k]:
                    raise SystemExit(f"RC MISMATCH cold [{k}]")
            warm_ms = {}
            for k, _q in shapes:
                d, ms = run(k, best_of=3)
                if d != ref[k]:
                    raise SystemExit(f"RC MISMATCH warm [{k}]")
                warm_ms[k] = ms
            warm_hits = _rc.RC_STATS["hits"] - h0
            if warm_hits < len(shapes):
                raise SystemExit(
                    f"rcgate: expected >= {len(shapes)} warm hits, "
                    f"saw {warm_hits}")
            # measured warm-hit shrink on the heaviest shape
            shrink = {k: round(off_ms[k] / max(warm_ms[k], 1e-6), 2)
                      for k, _q in shapes}
            # post-write invalidation: a point INSIDE every cached
            # range (t=5m) — cache-on must match a fresh cache-off
            # recompute immediately, never the stale entry
            inv0 = _rc.RC_STATS["invalidations_epoch"]
            eng.write_points("bench", [PointRow(
                "cpu", {"hostname": "host_0", "region": "r0"},
                {"usage_user": 99.25}, 300 * 10**9)])
            for s in eng.database("bench").all_shards():
                s.flush()
            knobs.set_env("OG_RESULT_CACHE", "0")
            ref2 = {k: run(k)[0] for k, _q in shapes}
            knobs.set_env("OG_RESULT_CACHE", "1")
            for k, _q in shapes:
                d, _ms = run(k)
                if d != ref2[k]:
                    raise SystemExit(f"RC MISMATCH post-write [{k}]")
                if d == ref[k]:
                    raise SystemExit(
                        f"rcgate [{k}]: post-write digest equals the "
                        "pre-write one — the write was not observed")
            out.update(
                rows=n_rows,
                rc_digest_ok=1,
                rc_warm_hits=int(warm_hits),
                rc_invalidations=int(
                    _rc.RC_STATS["invalidations_epoch"] - inv0),
                rc_warm_shrink_x=shrink,
                rc_warm_shrink_min_x=min(shrink.values()),
                rc_off_ms={k: round(v, 2)
                           for k, v in off_ms.items()},
                rc_warm_ms={k: round(v, 2)
                            for k, v in warm_ms.items()})
        finally:
            knobs.del_env("OG_RESULT_CACHE")
            eng.close()
    return out


# the concurrent phase serves from a smaller host count than the
# headline: admission ORDER is what's measured, not scan throughput
CONC_HOSTS = int(knobs.get_raw("OG_BENCH_CONC_HOSTS") or min(HOSTS, 1000))
CONC_DASH = 16


def concurrent_phase() -> dict:
    """Concurrent-serving mode (device query scheduler acceptance): 16
    dashboard queries + 1 heavy query through the full HTTP path with
    ONE device slot, so admission ordering is the measured variable.
    Runs twice — scheduler on (deadline-aware weighted-fair queue) and
    OG_SCHED=0 (legacy counting-gate path) — reporting concurrent_qps
    and dashboard p99_ms for both. Correctness gate: EVERY response
    (warmups across all three bench shapes + all concurrent responses)
    must be bit-identical to the serial reference digest."""
    import urllib.parse
    import urllib.request
    from opengemini_tpu.http.server import HttpServer
    from opengemini_tpu.query import QueryExecutor, parse_query
    from opengemini_tpu.storage import Engine, EngineOptions
    from opengemini_tpu.utils.config import Config

    # admission ORDERING is the measured variable: with the result
    # cache on, warm dashboards resolve in host memory and the
    # scheduler-vs-gate contrast vanishes — cache-on serving has its
    # own phase (--phase sustained)
    knobs.set_env("OG_RESULT_CACHE", "0")
    shm = "/dev/shm" if os.path.isdir("/dev/shm") else None
    with tempfile.TemporaryDirectory(prefix="og-conc-", dir=shm) as td:
        _register_tmp(td)
        n_rows, _t_ing = build_dataset(td, hosts=CONC_HOSTS)
        eng = Engine(td, EngineOptions(shard_duration=1 << 62))
        ex = QueryExecutor(eng)
        serial = {}
        for key, qtext in (("1h", QUERY), ("1m", QUERY_1M),
                           ("cfg1", QUERY_CFG1)):
            (stmt,) = parse_query(qtext)
            res = ex.execute(stmt, "bench")
            if "error" in res:
                raise SystemExit(f"serial ref error [{key}]: "
                                 f"{res['error']}")
            serial[key] = _digest_series(res)[0]

        def run_mode(sched_on: bool) -> dict:
            knobs.set_env("OG_SCHED", "1" if sched_on else "0")
            cfg = Config()
            cfg.data.max_concurrent_queries = 1
            cfg.data.max_queued_queries = 64
            cfg.data.query_timeout_ns = 0       # the phase is the budget
            srv = HttpServer(eng, port=0, config=cfg)
            srv.start()
            # generous slot waits: the point is ordering, not shedding
            from opengemini_tpu.query.scheduler import get_scheduler
            get_scheduler().configure(timeout_s=600.0)
            srv.resources.queries.timeout_s = 600.0
            try:
                def fetch(qtext):
                    url = (f"http://127.0.0.1:{srv.port}/query?db=bench"
                           "&q=" + urllib.parse.quote(qtext))
                    t0 = time.perf_counter()
                    body = urllib.request.urlopen(url,
                                                  timeout=600).read()
                    dt_ms = (time.perf_counter() - t0) * 1000
                    res = json.loads(body)["results"][0]
                    if "error" in res:
                        raise SystemExit(
                            f"concurrent query error "
                            f"(sched={sched_on}): {res['error']}")
                    return dt_ms, _digest_series(res)[0]

                for key, qtext in (("1h", QUERY), ("1m", QUERY_1M),
                                   ("cfg1", QUERY_CFG1)):   # warm
                    _dt, dig = fetch(qtext)
                    if dig != serial[key]:
                        raise SystemExit(
                            f"CONCURRENT MISMATCH warm [{key}] "
                            f"sched={sched_on}")
                lat_dash: list = []
                lat_heavy: list = []
                errs: list = []
                lk = threading.Lock()

                def worker(qtext, key, sink):
                    try:
                        dt, dig = fetch(qtext)
                        with lk:
                            sink.append(dt)
                            if dig != serial[key]:
                                errs.append(f"digest mismatch [{key}]")
                    except BaseException as e:   # SystemExit included
                        with lk:
                            errs.append(str(e))

                # 4 dashboards in flight, then the heavy query, then 12
                # more dashboards arriving behind it: the FIFO gate
                # parks the 12 behind the monster; the weighted-fair
                # queue lets every dashboard jump it
                threads = [threading.Thread(
                    target=worker, args=(QUERY_CFG1, "cfg1", lat_dash))
                    for _ in range(4)]
                threads.append(threading.Thread(
                    target=worker, args=(QUERY_1M, "1m", lat_heavy)))
                threads += [threading.Thread(
                    target=worker, args=(QUERY_CFG1, "cfg1", lat_dash))
                    for _ in range(CONC_DASH - 4)]
                t_w0 = time.perf_counter()
                for t in threads:
                    t.start()
                    time.sleep(0.02)    # deterministic arrival order
                for t in threads:
                    t.join()
                wall = time.perf_counter() - t_w0
                if errs:
                    raise SystemExit(
                        f"concurrent phase failed (sched={sched_on}): "
                        f"{errs[:3]}")
                lat_dash.sort()
                p99_i = min(len(lat_dash) - 1,
                            int(math.ceil(0.99 * len(lat_dash))) - 1)
                return {"concurrent_qps":
                        round((CONC_DASH + 1) / wall, 2),
                        "p99_ms": round(lat_dash[p99_i], 1),
                        "mean_dash_ms": round(
                            sum(lat_dash) / len(lat_dash), 1),
                        "heavy_ms": round(lat_heavy[0], 1),
                        "wall_s": round(wall, 2)}
            finally:
                srv.stop()
                knobs.del_env("OG_SCHED")

        sched = run_mode(True)
        base = run_mode(False)
        eng.close()
    return {"metric": "concurrent_serving_dashboard_p99_ms",
            "value": sched["p99_ms"], "unit": "ms",
            "hosts": CONC_HOSTS, "rows": n_rows,
            "dashboards": CONC_DASH, "heavy_queries": 1,
            "concurrent_qps": sched["concurrent_qps"],
            "p99_ms": sched["p99_ms"],
            "baseline_qps": base["concurrent_qps"],
            "baseline_p99_ms": base["p99_ms"],
            "p99_speedup": round(
                base["p99_ms"] / max(sched["p99_ms"], 1e-9), 3),
            "heavy_ms": sched["heavy_ms"],
            "baseline_heavy_ms": base["heavy_ms"],
            "bit_identical": True}


# ------------------------------------------------- sustained serving


def sustained_phase() -> dict:
    """Open-loop sustained multi-tenant load (ROADMAP item 5): a fixed
    arrival-rate schedule of mixed dashboard/heavy requests over the
    full HTTP path — requests launch at their scheduled instant
    whether or not earlier ones finished, so latency includes every
    queueing effect (the closed-loop PR 4 burst hides them). Runs
    cache-on and OG_RESULT_CACHE=0; every response digest-gates
    against the serial reference. Reports offered/achieved qps,
    dashboard p50/p99, heavy p99, shed counts, cache hit ratio, and a
    closed-loop warm-burst capacity ratio on the PR 4 concurrent
    shape (the >= 10x acceptance metric)."""
    import urllib.error
    import urllib.parse
    import urllib.request
    from opengemini_tpu.http.server import HttpServer
    from opengemini_tpu.query import QueryExecutor, parse_query
    from opengemini_tpu.query import resultcache as _rc
    from opengemini_tpu.storage import Engine, EngineOptions
    from opengemini_tpu.utils.config import Config

    rate = float(knobs.get("OG_BENCH_SUST_QPS"))
    n_reqs = int(knobs.get("OG_BENCH_SUST_REQS"))
    n_workers = int(knobs.get("OG_BENCH_SUST_WORKERS"))
    heavy_pct = float(knobs.get("OG_BENCH_SUST_HEAVY_PCT"))
    heavy_every = max(2, int(round(100.0 / max(heavy_pct, 0.01)))) \
        if heavy_pct > 0 else 1 << 30
    slo_ms = float(knobs.get("OG_BENCH_SUST_SLO_MS"))
    dash_shapes = (("cfg1", QUERY_CFG1), ("1h", QUERY))
    tenants = ("dash-a", "dash-b", "dash-c", "analytics")

    shm = "/dev/shm" if os.path.isdir("/dev/shm") else None
    with tempfile.TemporaryDirectory(prefix="og-sust-", dir=shm) as td:
        _register_tmp(td)
        n_rows, _t = build_dataset(td, hosts=CONC_HOSTS)
        eng = Engine(td, EngineOptions(shard_duration=1 << 62))
        ex = QueryExecutor(eng)
        serial = {}
        knobs.set_env("OG_RESULT_CACHE", "0")
        for key, qtext in dash_shapes + (("1m", QUERY_1M),):
            (stmt,) = parse_query(qtext)
            res = ex.execute(stmt, "bench")
            if "error" in res:
                raise SystemExit(f"sustained serial ref error "
                                 f"[{key}]: {res['error']}")
            serial[key] = _digest_series(res)[0]
        knobs.del_env("OG_RESULT_CACHE")

        def run_mode(cache_on: bool) -> dict:
            knobs.set_env("OG_RESULT_CACHE", "1" if cache_on else "0")
            cfg = Config()
            cfg.data.max_concurrent_queries = 4
            cfg.data.max_queued_queries = 256
            cfg.data.query_timeout_ns = 0
            srv = HttpServer(eng, port=0, config=cfg)
            srv.start()
            from opengemini_tpu.query.scheduler import get_scheduler
            get_scheduler().configure(timeout_s=600.0)
            srv.resources.queries.timeout_s = 600.0
            rc0 = dict(_rc.RC_STATS)
            try:
                def fetch(key, qtext, tenant):
                    url = (f"http://127.0.0.1:{srv.port}/query?db="
                           "bench&q=" + urllib.parse.quote(qtext))
                    req = urllib.request.Request(
                        url, headers={"X-OG-Tenant": tenant})
                    body = urllib.request.urlopen(
                        req, timeout=600).read()
                    res = json.loads(body)["results"][0]
                    if "error" in res:
                        raise SystemExit(
                            f"sustained query error [{key}]: "
                            f"{res['error']}")
                    if _digest_series(res)[0] != serial[key]:
                        raise SystemExit(
                            f"SUSTAINED MISMATCH [{key}] "
                            f"cache_on={cache_on}")

                # warm pass: compiles + (on-mode) cache fill — the
                # acceptance metric is with the cache WARM
                for key, qtext in dash_shapes + (("1m", QUERY_1M),):
                    fetch(key, qtext, "warmup")

                # ---- closed-loop warm burst (PR 4 concurrent shape:
                # 16 dashboards + 1 heavy) — capacity, not SLO
                lat_b: list = []
                errs: list = []
                lk = threading.Lock()

                def burst_worker(key, qtext, tenant):
                    try:
                        t0 = time.perf_counter()
                        fetch(key, qtext, tenant)
                        with lk:
                            lat_b.append(
                                (time.perf_counter() - t0) * 1e3)
                    except BaseException as e:
                        with lk:
                            errs.append(str(e))

                bt = [threading.Thread(
                    target=burst_worker,
                    args=("cfg1", QUERY_CFG1,
                          tenants[i % 3])) for i in range(CONC_DASH)]
                bt.append(threading.Thread(
                    target=burst_worker,
                    args=("1m", QUERY_1M, "analytics")))
                t_b0 = time.perf_counter()
                for t in bt:
                    t.start()
                    time.sleep(0.005)   # don't overrun the listen
                    # backlog: a SYN drop retransmits after ~1s and
                    # poisons the capacity measure on localhost
                for t in bt:
                    t.join()
                burst_wall = time.perf_counter() - t_b0
                if errs:
                    raise SystemExit(
                        f"sustained burst failed: {errs[:3]}")
                burst_qps = (CONC_DASH + 1) / burst_wall

                # ---- open-loop schedule
                lat_dash: list = []
                lat_heavy: list = []
                sheds = [0]
                idx = [0]
                t0 = time.perf_counter()

                def worker():
                    while True:
                        with lk:
                            if errs:       # fail fast, don't skew
                                return     # the survivors' numbers
                            i = idx[0]
                            if i >= n_reqs:
                                return
                            idx[0] += 1
                        target = t0 + i / rate
                        now = time.perf_counter()
                        if now < target:
                            time.sleep(target - now)
                        heavy = (i % heavy_every) == heavy_every - 1
                        key, qtext = ("1m", QUERY_1M) if heavy else \
                            dash_shapes[i % len(dash_shapes)]
                        tenant = "analytics" if heavy else \
                            tenants[i % 3]
                        try:
                            fetch(key, qtext, tenant)
                        except urllib.error.HTTPError as e:
                            if e.code in (429, 503):
                                with lk:
                                    sheds[0] += 1
                                continue
                            with lk:
                                errs.append(f"HTTP {e.code} [{key}]")
                            continue
                        except BaseException as e:  # SystemExit incl:
                            # threading.excepthook swallows it — the
                            # digest gate must fail the PHASE, not
                            # silently kill one worker
                            with lk:
                                errs.append(str(e) or repr(e))
                            continue
                        done = time.perf_counter()
                        with lk:
                            (lat_heavy if heavy
                             else lat_dash).append(
                                (done - target) * 1e3)

                ws = [threading.Thread(target=worker)
                      for _ in range(n_workers)]
                for w in ws:
                    w.start()
                for w in ws:
                    w.join()
                wall = time.perf_counter() - t0
                if errs:
                    raise SystemExit(
                        f"sustained open-loop failed: {errs[:3]}")

                def pct(lst, p):
                    if not lst:
                        return 0.0
                    lst = sorted(lst)
                    i = min(len(lst) - 1,
                            int(math.ceil(p * len(lst))) - 1)
                    return round(lst[max(0, i)], 1)

                rc = _rc.RC_STATS
                served = (rc["hits"] - rc0["hits"]
                          + rc["partial_hits"] - rc0["partial_hits"])
                asked = served + rc["misses"] - rc0["misses"]
                return {
                    "offered_qps": round(rate, 1),
                    "achieved_qps": round(
                        (len(lat_dash) + len(lat_heavy)) / wall, 1),
                    "completed": len(lat_dash) + len(lat_heavy),
                    "shed": sheds[0],
                    "p50_ms": pct(lat_dash, 0.50),
                    "p99_ms": pct(lat_dash, 0.99),
                    "heavy_p99_ms": pct(lat_heavy, 0.99),
                    "burst_qps": round(burst_qps, 2),
                    "burst_p99_ms": pct(lat_b, 0.99),
                    "cache_hit_ratio": round(served / asked, 4)
                    if asked else 0.0,
                    "wall_s": round(wall, 2)}
            finally:
                srv.stop()
                knobs.del_env("OG_RESULT_CACHE")

        on = run_mode(True)
        off = run_mode(False)
        eng.close()
    out = {"metric": "sustained_dashboard_p99_ms",
           "value": on["p99_ms"], "unit": "ms",
           "hosts": CONC_HOSTS, "rows": n_rows,
           "requests": n_reqs, "workers": n_workers,
           "heavy_every": heavy_every,
           "sustained": on, "sustained_cache_off": off,
           "qps_x_warm_burst": round(
               on["burst_qps"] / max(off["burst_qps"], 1e-9), 2),
           "p99_x": round(
               off["p99_ms"] / max(on["p99_ms"], 1e-9), 2),
           "bit_identical": True}
    if slo_ms > 0:
        out["slo_ms"] = slo_ms
        out["slo_ok"] = bool(on["p99_ms"] <= slo_ms)
    return out



def ingest_phase() -> dict:
    """Flight-ingest line-rate gate (ROADMAP PR 20): the columnar
    fast lane — Arrow RecordBatch → batch_to_columns →
    Engine.write_record_batch over an uncompressed scatter-gather WAL
    — measured open-loop in-process (no gRPC socket, so the number is
    the storage lane itself), against the r08 row-wise baseline
    (1,366,408.7 rows/s on this container). Also measured: the
    row-wise hatch (same batches through batch_to_rows →
    write_points) for the lane multiple, a cross-lane digest parity
    gate (columnar vs hatch must serve bit-identical query results),
    and one fsync-acknowledged group-commit cycle with
    OG_INGEST_WORKERS concurrent writers proving fsyncs coalesce."""
    import numpy as np
    try:
        import pyarrow as pa
    except Exception as e:                        # pragma: no cover
        return {"skipped": f"pyarrow unavailable: {e}"}
    from opengemini_tpu.query import QueryExecutor, parse_query
    from opengemini_tpu.services.arrowflight import (batch_to_columns,
                                                     batch_to_rows)
    from opengemini_tpu.storage import Engine, EngineOptions
    from opengemini_tpu.storage.wal import WAL_STATS

    BASELINE = 1366408.7                 # r08 row-wise rows/s
    BR = 65536
    n_batches = max(2, int(knobs.get("OG_BENCH_INGEST_BATCHES")))
    rng = np.random.default_rng(20)
    host = pa.array([f"h{j}" for j in rng.integers(0, 32, BR)]) \
        .dictionary_encode()
    region = pa.array([f"r{j}" for j in rng.integers(0, 4, BR)]) \
        .dictionary_encode()
    t0 = 1_700_000_000_000_000_000

    def mk(i):
        times = pa.array(t0 + i * BR * 1000 + np.arange(BR) * 1000,
                         type=pa.int64())
        return pa.RecordBatch.from_arrays(
            [host, region, times,
             pa.array(rng.random(BR)), pa.array(rng.random(BR)),
             pa.array(rng.integers(0, 1000, BR))],
            names=["host", "region", "time",
                   "usage", "load", "count"])

    batches = [mk(i) for i in range(n_batches)]
    tags = ["host", "region"]
    shm = "/dev/shm" if os.path.isdir("/dev/shm") else None
    opts = dict(wal_compression="none", flush_bytes=1 << 40,
                shard_duration=1 << 62)

    def ingest_columnar(eng, sub=None):
        rows = 0
        for b in batches[:sub]:
            groups = batch_to_columns(b, tags)
            eng.write_record_batch(
                "bench", [("cpu",) + g for g in groups])
            rows += b.num_rows
        return rows

    def ingest_hatch(eng, sub):
        rows = 0
        for b in batches[:sub]:
            pts = batch_to_rows(b, "cpu", tags)
            eng.write_points("bench", pts)
            rows += len(pts)
        return rows

    out = {"batch_rows": BR, "batches": n_batches}

    # ---- columnar lane: best-of-3 single-writer reps -------------
    best = 0.0
    with tempfile.TemporaryDirectory(prefix="og-ing-", dir=shm) as td:
        _register_tmp(td)
        eng = Engine(td, EngineOptions(**opts))
        eng.create_database("bench")
        ingest_columnar(eng, 2)          # warmup: import/alloc paths
        import gc as _gc
        _gc.collect()
        for _ in range(5):
            t = time.perf_counter()
            rows = ingest_columnar(eng)
            best = max(best, rows / (time.perf_counter() - t))
        eng.close()
    out["ingest_rows_per_sec"] = round(best, 1)
    out["baseline_rows_per_sec"] = BASELINE
    out["ingest_x_baseline"] = round(best / BASELINE, 2)

    # ---- row hatch + cross-lane digest parity --------------------
    sub = min(2, n_batches)              # hatch is ~25x slower
    qs = [("SELECT count(usage), sum(count) FROM cpu WHERE time >= 0 "
           "GROUP BY host"),
          ("SELECT mean(load) FROM cpu WHERE time >= 0 "
           "GROUP BY region")]

    def digests(ing):
        with tempfile.TemporaryDirectory(prefix="og-ing-",
                                         dir=shm) as td:
            _register_tmp(td)
            eng = Engine(td, EngineOptions(**opts))
            eng.create_database("bench")
            t = time.perf_counter()
            rows = ing(eng)
            rps = rows / (time.perf_counter() - t)
            ex = QueryExecutor(eng)
            digs = []
            for q in qs:
                (stmt,) = parse_query(q)
                res = ex.execute(stmt, "bench")
                if "error" in res:
                    raise SystemExit(
                        f"ingest parity query error: {res['error']}")
                digs.append(_digest_series(res)[0])
            eng.close()
            return rps, digs

    hatch_rps, hatch_digs = digests(lambda e: ingest_hatch(e, sub))
    col_rps, col_digs = digests(lambda e: ingest_columnar(e, sub))
    out["row_hatch_rows_per_sec"] = round(hatch_rps, 1)
    out["columnar_x_hatch"] = round(best / max(hatch_rps, 1e-9), 2)
    out["lanes_bit_identical"] = col_digs == hatch_digs
    if col_digs != hatch_digs:
        raise SystemExit("ingest parity FAILED: columnar and row-wise "
                         "lanes served different query digests")

    # ---- group commit under fsync-acknowledged load --------------
    workers = max(1, int(knobs.get("OG_INGEST_WORKERS")))
    knobs.set_env("OG_WAL_GROUP_COMMIT_US", "2000")
    try:
        with tempfile.TemporaryDirectory(prefix="og-ing-",
                                         dir=shm) as td:
            _register_tmp(td)
            eng = Engine(td, EngineOptions(wal_sync=True, **opts))
            eng.create_database("bench")
            gc0 = int(WAL_STATS.get("group_commits", 0))
            fr0 = int(WAL_STATS.get("writes", 0))
            import concurrent.futures as cf
            t = time.perf_counter()
            with cf.ThreadPoolExecutor(workers) as pool:
                futs = [pool.submit(
                    eng.write_record_batch, "bench",
                    [("cpu",) + g for g in batch_to_columns(b, tags)])
                    for b in batches[:8]]
                rows = 0
                for f in futs:
                    f.result()
                rows = sum(b.num_rows for b in batches[:8])
            dt = time.perf_counter() - t
            out["group_commit"] = {
                "workers": workers,
                "rows_per_sec_fsync": round(rows / dt, 1),
                "frames": int(WAL_STATS.get("writes", 0)) - fr0,
                "fsyncs": int(WAL_STATS.get("group_commits", 0)) - gc0,
            }
            eng.close()
    finally:
        knobs.del_env("OG_WAL_GROUP_COMMIT_US")
    return out


# --------------------------------------------------------------- main

# conservative wall-clock estimates (s) used to gate auxiliaries; a
# phase only starts if the remaining budget covers its estimate
EST_PROM = int(knobs.get("OG_BENCH_EST_PROM"))
EST_CS = int(knobs.get("OG_BENCH_EST_CS"))
EST_CONC = int(knobs.get("OG_BENCH_EST_CONC"))
EST_SUST = int(knobs.get("OG_BENCH_EST_SUST"))
# measured at full 500M rows: ingest 211s + a CPU-pinned baseline
# pass that alone exceeds 35 minutes — the phase needs ~50 min and
# only runs under a generous driver budget (the gate skips it
# honestly otherwise; OG_BENCH_SCALE_ROWS shrinks it for smoke runs)
EST_SCALE = int(knobs.get("OG_BENCH_EST_SCALE"))
EST_ING = int(knobs.get("OG_BENCH_EST_INGEST"))
# r04/r05 hit the DRIVER's external kill (rc 124) with the old 3300s
# budget: the orchestrator's own gating only bounds phase STARTS, so
# the total can overshoot the budget by a phase. 1800s keeps headline
# + one auxiliary comfortably inside typical external timeouts; raise
# OG_BENCH_BUDGET_S under a generous driver
BUDGET_S = float(knobs.get("OG_BENCH_BUDGET_S"))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--phase",
                    choices=["query", "csquery", "promquery",
                             "scalequery", "headline", "csfull",
                             "promfull", "scalefull", "smoke",
                             "concurrent", "crashchild", "rcgate",
                             "sustained", "ingest"],
                    default=None)
    ap.add_argument("--data", default=None)
    ap.add_argument("--runs", type=int, default=3)
    ap.add_argument("--crash-site", default=None,
                    help="crashchild: failpoint site to arm as crash")
    ap.add_argument("--crash-skip", type=int, default=0,
                    help="crashchild: passes to let through unfired")
    args = ap.parse_args()

    signal.signal(signal.SIGTERM, _on_signal)
    signal.signal(signal.SIGINT, _on_signal)
    import atexit
    atexit.register(_cleanup)

    if args.phase in ("query", "csquery", "promquery", "scalequery",
                      "headline", "csfull", "promfull", "scalefull",
                      "smoke", "concurrent"):
        # perf phases measure the stored-data DEVICE path on repeated
        # statements — the serving-layer result cache would turn warm
        # repeats into host-memory lookups and the numbers would
        # measure the cache, not the kernels. Cache-on serving is
        # measured by --phase sustained; its digest gate by --phase
        # rcgate (both manage the knob themselves).
        knobs.set_env("OG_RESULT_CACHE", "0")

    if args.phase == "query":
        # CPU-baseline child: digests + best_s only — the answer-sized
        # D2H measurement block and the EXPLAIN sweeps run once, in
        # the in-process (device) run whose JSON actually reports them
        print(json.dumps(run_query_phase(args.data, args.runs,
                                         extras=False)))
        return
    if args.phase == "csquery":
        print(json.dumps(colstore_query_phase(args.data, args.runs)))
        return
    if args.phase == "promquery":
        print(json.dumps(prom_query_phase(args.data, args.runs)))
        return
    if args.phase == "scalequery":
        print(json.dumps(scale_query_phase(args.data, args.runs)))
        return
    if args.phase == "smoke":
        print(json.dumps(smoke_phase()))
        return
    if args.phase == "crashchild":
        crash_child_phase(args.data, args.crash_site, args.crash_skip)
        return
    if args.phase == "concurrent":
        print(json.dumps(concurrent_phase()))
        return
    if args.phase == "rcgate":
        print(json.dumps(rcgate_phase()))
        return
    if args.phase == "sustained":
        print(json.dumps(sustained_phase()))
        return
    if args.phase == "ingest":
        print(json.dumps(ingest_phase()))
        return
    if args.phase == "headline":
        print(json.dumps(headline_phase(
            args.runs, cpu_timeout=BUDGET_S * 0.8)))
        return
    if args.phase == "csfull":
        print(json.dumps(colstore_phase(cpu_timeout=EST_CS * 2)))
        return
    if args.phase == "promfull":
        print(json.dumps(prom_phase(cpu_timeout=EST_PROM * 2)))
        return
    if args.phase == "scalefull":
        print(json.dumps(scale_phase(cpu_timeout=EST_SCALE * 2)))
        return

    # ---- orchestrator: jax-free parent, one TPU child at a time ----
    t0 = time.monotonic()

    def remaining() -> float:
        return BUDGET_S - (time.monotonic() - t0)

    def run_phase(name: str, timeout: float):
        rc, out, err = run_child(
            [sys.executable, os.path.abspath(__file__), "--phase",
             name], timeout=timeout)
        for ln in err.splitlines():
            if ln.startswith("#"):
                print(ln, file=sys.stderr)
        if rc != 0 or not out.strip():
            print(f"# phase {name} failed rc={rc}: {err[-600:]}",
                  file=sys.stderr)
            return None
        return out.strip().splitlines()[-1]

    # headline gets the biggest share, but its budget is CLAMPED inside
    # the orchestrator's own (the old open-ended timeout let the total
    # overshoot BUDGET_S and the DRIVER's outer kill hit with rc 124 —
    # BENCH_r04/r05; every stage now has a hard sub-budget and the
    # process exits 0 with whatever stages finished)
    headline = run_phase("headline",
                         timeout=max(min(remaining() - 90, BUDGET_S),
                                     120))
    if headline is None:
        print("# headline phase failed — exiting 0 with no benchmark "
              "line", file=sys.stderr)
        return
    print(headline, flush=True)          # lands even if killed later

    for name, est in (("ingest", EST_ING),
                      ("concurrent", EST_CONC),
                      ("sustained", EST_SUST),
                      ("promfull", EST_PROM),
                      ("csfull", EST_CS), ("scalefull", EST_SCALE)):
        if remaining() < est + 120:
            print(f"# skipped {name}: {remaining():.0f}s left < "
                  f"{est}s estimate", file=sys.stderr)
            continue
        # per-stage budget: a runaway auxiliary is killed at twice its
        # estimate or the remaining orchestrator budget, whichever is
        # tighter — its '#' failure comment prints, the run continues
        line = run_phase(name, timeout=max(
            min(remaining() - 60, est * 2), 60))
        if line:
            print(line, flush=True)
            # the driver parses the LAST JSON line: re-assert the
            # headline after every auxiliary so a kill at ANY point
            # leaves the headline last on stdout
            print(headline, flush=True)

    print(headline, flush=True)


if __name__ == "__main__":
    main()
