"""End-to-end benchmark: TSBS-shaped data stored in the engine, queried
through the full path (parse → scan plan → segment decode → device
kernel → merge/finalize), TPU backend vs the same engine on CPU.

Round-2 rework (VERDICT r1 weak #1): the headline number is measured
over STORED TSSP data through QueryExecutor — parse, index scan, chunk
metas, decode, H2D, kernel, finalize all included. The baseline is the
SAME engine with the JAX backend pinned to single-node CPU (subprocess
with JAX_PLATFORMS=cpu) — i.e. the north star's "TPU execution backend
vs CPU iterator path" comparison on identical code and data
(BASELINE.json configs 1-2 shape).

Correctness gate: the CPU and TPU runs must produce IDENTICAL result
rows over NON-integral float gauges — the reproducible-sum limbs
(ops/exactsum.py) make sums/means bit-identical across backends and
topologies (and equal to math.fsum).

Prints ONE json line: {"metric", "value", "unit", "vs_baseline", ...}.
Extra keys: kernel-only throughput (device-resident dense kernel) and
one HTTP round-trip latency.
"""

import argparse
import hashlib
import json
import os
import subprocess
import sys
import tempfile
import time

import numpy as np

HOSTS = int(os.environ.get("OG_BENCH_HOSTS", "16000"))
HOURS = float(os.environ.get("OG_BENCH_HOURS", "12"))
STEP_S = 10
# TSBS double-groupby-1 (BASELINE config 2): mean of one metric over 12h
# GROUP BY time(1h), hostname — 4k hosts
QUERY = ("SELECT mean(usage_user) FROM cpu WHERE time >= 0 AND "
         f"time < {int(HOURS * 3600)}s GROUP BY time(1h), hostname")
# secondary: per-minute windows AND per-host grouping — a 60× larger
# result grid than the headline (11.5M cells at 16k hosts), stressing
# the merge/materialize stages. Transfer-bound on the tunnel link: the
# exact per-cell sum state is ≥ ~16B/cell ≈ 180MB against a measured
# 10-30MB/s D2H, so this shape stays on the host paths by design
QUERY_1M = ("SELECT mean(usage_user) FROM cpu WHERE time >= 0 AND "
            f"time < {int(HOURS * 3600)}s GROUP BY time(1m), hostname")
# BASELINE config 1 verbatim: SELECT mean(usage_user) GROUP BY
# time(1m) — per-minute windows, NO per-host grouping (720 cells).
# Wide windows route to the scatter-free prefix kernel
QUERY_CFG1 = ("SELECT mean(usage_user) FROM cpu WHERE time >= 0 AND "
              f"time < {int(HOURS * 3600)}s GROUP BY time(1m)")


def build_dataset(data_dir: str) -> int:
    """Ingest TSBS devops-cpu-shaped data (4k hosts ≙ BASELINE config 2,
    double-groupby-1) through the bulk record-writer path and flush to
    TSSP files. Returns rows written."""
    from opengemini_tpu.storage import Engine, EngineOptions

    points = int(HOURS * 3600 / STEP_S)
    rng = np.random.default_rng(42)
    eng = Engine(data_dir, EngineOptions(shard_duration=1 << 62))
    eng.create_database("bench")
    n = 0
    t0 = time.perf_counter()
    times = np.arange(points, dtype=np.int64) * (STEP_S * 10**9)
    for h in range(HOSTS):
        tags = {"hostname": f"host_{h}", "region": f"r{h % 4}"}
        # NON-integral cpu gauges: the exact-sum limbs carry the
        # bit-identical guarantee (round 1 relied on integral values)
        vals = np.round(np.clip(rng.normal(50, 15, points), 0, 100), 2)
        n += eng.write_record("bench", "cpu", tags, times,
                              {"usage_user": vals})
    for s in eng.database("bench").all_shards():
        s.flush()
    eng.close()
    print(f"# ingest: {n} rows in {time.perf_counter() - t0:.1f}s",
          file=sys.stderr)
    return n


def run_query_phase(data_dir: str, runs: int) -> dict:
    """Open the stored dataset, run both query shapes end-to-end `runs`
    times (after warmup), return best wall times + result digests."""
    from opengemini_tpu.query import QueryExecutor, parse_query
    from opengemini_tpu.storage import Engine, EngineOptions

    eng = Engine(data_dir, EngineOptions(shard_duration=1 << 62))
    ex = QueryExecutor(eng)
    out = {}
    for key, qtext in (("1h", QUERY), ("1m", QUERY_1M),
                       ("cfg1", QUERY_CFG1)):
        (stmt,) = parse_query(qtext)
        res = ex.execute(stmt, "bench")      # warmup: compile + caches
        if "error" in res:
            raise SystemExit(f"query error: {res['error']}")
        times = []
        for _ in range(runs):
            t0 = time.perf_counter()
            res = ex.execute(stmt, "bench")
            times.append(time.perf_counter() - t0)
        dig = hashlib.sha256()
        n_cells = 0
        for s in sorted(res.get("series", []),
                        key=lambda s: json.dumps(s.get("tags", {}),
                                                 sort_keys=True)):
            dig.update(json.dumps(s.get("tags", {}),
                                  sort_keys=True).encode())
            for r in s["values"]:
                dig.update(repr((r[0], r[1])).encode())
                n_cells += 1
        out[key] = {"best_s": min(times), "digest": dig.hexdigest(),
                    "cells": n_cells}
    # per-phase wall times from EXPLAIN ANALYZE (VERDICT r2 next #2):
    # plan / dispatch / kernel+pull / fold / finalize of the 1h shape
    (est,) = parse_query("EXPLAIN ANALYZE " + QUERY)
    res = ex.execute(est, "bench")
    phases = {}
    for row in res.get("series", [{}])[0].get("values", []):
        line = row[0].strip()
        name, _, rest = line.partition(":")
        if "ms" in rest:
            phases[name] = float(rest.split("ms")[0].strip())
    out["phases_ms"] = phases
    eng.close()
    return out


CS_HOSTS = int(os.environ.get("OG_BENCH_CS_HOSTS", "2000"))
CS_HOURS = 1.0
CS_FIELDS = [f"usage_{k}" for k in
             ("user", "system", "idle", "nice", "iowait", "irq",
              "softirq", "steal", "guest", "guest_nice")]
CS_QUERY = ("SELECT " + ", ".join(f"max({f})" for f in CS_FIELDS)
            + f" FROM cpu WHERE time >= 0 AND "
              f"time < {int(CS_HOURS * 3600)}s GROUP BY time(1h)")


def colstore_query_phase(data_dir: str, runs: int) -> dict:
    """Query loop over a built colstore dataset (runs in-process for
    the TPU pass and in a JAX_PLATFORMS=cpu subprocess for the
    baseline — identical code both ways)."""
    from opengemini_tpu.query import QueryExecutor, parse_query
    from opengemini_tpu.storage import Engine, EngineOptions
    eng = Engine(data_dir, EngineOptions(shard_duration=1 << 62))
    ex = QueryExecutor(eng)
    (stmt,) = parse_query(CS_QUERY)
    res = ex.execute(stmt, "bench")
    if "error" in res:
        raise SystemExit(f"colstore query error: {res['error']}")
    times = []
    for _ in range(runs):
        t0 = time.perf_counter()
        res = ex.execute(stmt, "bench")
        times.append(time.perf_counter() - t0)
    dig = hashlib.sha256()
    for s in sorted(res.get("series", []),
                    key=lambda s: json.dumps(s.get("tags", {}),
                                             sort_keys=True)):
        for r in s["values"]:
            dig.update(repr(tuple(r)).encode())
    cells = sum(len(s["values"]) for s in res.get("series", []))
    eng.close()
    return {"best_s": min(times), "digest": dig.hexdigest(),
            "cells": cells}


def colstore_phase() -> dict:
    """BASELINE config 3 (high-cpu-all shape): max() across 10 cpu
    fields on the COLUMN-STORE engine, grouped hourly — exercises
    storage/colstore.py + sparse-index scan (ColumnStoreReader role).
    Reports e2e throughput AND vs_baseline (same engine pinned to
    CPU, digests compared)."""
    from opengemini_tpu.storage import Engine, EngineOptions

    points = int(CS_HOURS * 3600 / STEP_S)
    rng = np.random.default_rng(7)
    with tempfile.TemporaryDirectory(
            prefix="og-csbench-",
            dir="/dev/shm" if os.path.isdir("/dev/shm") else None) as td:
        eng = Engine(td, EngineOptions(shard_duration=1 << 62))
        eng.create_columnstore("bench", "cpu", ["hostname"],
                               {"hostname": "bloom"})
        t0 = time.perf_counter()
        n = 0
        times = np.arange(points, dtype=np.int64) * (STEP_S * 10**9)
        batch = []
        for h in range(CS_HOSTS):
            vals = np.round(np.clip(
                rng.normal(50, 15, (len(CS_FIELDS), points)), 0, 100),
                2)
            batch.append(("cpu", {"hostname": f"host_{h}"}, times,
                          {f: vals[j]
                           for j, f in enumerate(CS_FIELDS)}))
            if len(batch) >= 500:
                n += eng.write_record_batch("bench", batch)
                batch = []
        if batch:
            n += eng.write_record_batch("bench", batch)
        eng.flush_all()
        eng.close()
        t_ing = time.perf_counter() - t0

        env = dict(os.environ, JAX_PLATFORMS="cpu")
        env.pop("PALLAS_AXON_POOL_IPS", None)
        out = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--phase",
             "csquery", "--data", td, "--runs", "3"],
            capture_output=True, text=True, env=env, timeout=1800,
            cwd=os.path.dirname(os.path.abspath(__file__)))
        if out.returncode != 0:
            raise SystemExit(
                f"cs cpu phase failed: {out.stderr[-1500:]}")
        cpu = json.loads(out.stdout.strip().splitlines()[-1])
        tpu = colstore_query_phase(td, 3)
        if cpu["digest"] != tpu["digest"]:
            raise SystemExit(
                f"COLSTORE MISMATCH: {cpu['digest'][:16]} != "
                f"{tpu['digest'][:16]}")
    return {"metric": "tsbs_high_cpu_all_colstore_rows_per_sec",
            "value": round(n / tpu["best_s"], 1), "unit": "rows/s",
            "rows": n, "fields": len(CS_FIELDS), "hosts": CS_HOSTS,
            "ingest_rows_per_sec": round(n / t_ing, 1),
            "e2e_query_s": round(tpu["best_s"], 4),
            "cpu_query_s": round(cpu["best_s"], 4),
            "vs_baseline": round(cpu["best_s"] / tpu["best_s"], 3),
            "bit_identical": True,
            "result_cells": tpu["cells"]}


SCALE_ROWS = int(os.environ.get("OG_BENCH_SCALE_ROWS", "500000000"))
SCALE_WINDOW_H = 12


def scale_query(points: int) -> str:
    """Double-groupby-1 over the most recent 12h of the scale dataset
    (dashboards query recent windows; the full 500M-row span exceeds a
    single v5e's HBM — multi-chip shards own slices in production)."""
    t_hi = points * STEP_S
    t_lo = t_hi - SCALE_WINDOW_H * 3600
    return ("SELECT mean(usage_user) FROM cpu WHERE "
            f"time >= {t_lo}s AND time < {t_hi}s "
            "GROUP BY time(1h), hostname")


def scale_query_phase(data_dir: str, runs: int) -> dict:
    from opengemini_tpu.query import QueryExecutor, parse_query
    from opengemini_tpu.storage import Engine, EngineOptions
    eng = Engine(data_dir, EngineOptions(shard_duration=1 << 62))
    ex = QueryExecutor(eng)
    points = -(-SCALE_ROWS // HOSTS)
    (stmt,) = parse_query(scale_query(points))
    res = ex.execute(stmt, "bench")
    if "error" in res:
        raise SystemExit(f"scale query error: {res['error']}")
    times = []
    for _ in range(runs):
        t0 = time.perf_counter()
        res = ex.execute(stmt, "bench")
        times.append(time.perf_counter() - t0)
    dig = hashlib.sha256()
    cells = 0
    for s in sorted(res.get("series", []),
                    key=lambda s: json.dumps(s.get("tags", {}),
                                             sort_keys=True)):
        dig.update(json.dumps(s.get("tags", {}),
                              sort_keys=True).encode())
        for r in s["values"]:
            dig.update(repr((r[0], r[1])).encode())
            cells += 1
    eng.close()
    return {"best_s": min(times), "all_s": [round(t, 4) for t in times],
            "digest": dig.hexdigest(), "cells": cells}


def scale_phase() -> dict:
    """≥500M-point record (BASELINE.json '1B pts' bar): full-range
    ingest through the bulk writer, then the headline query shape over
    the recent window — planner/caches must survive 7x the headline
    data with warm repeats stable (no eviction collapse)."""
    from opengemini_tpu.storage import Engine, EngineOptions

    points = -(-SCALE_ROWS // HOSTS)
    shm = "/dev/shm" if os.path.isdir("/dev/shm") else None
    with tempfile.TemporaryDirectory(prefix="og-scale-", dir=shm) as td:
        eng = Engine(td, EngineOptions(shard_duration=1 << 62))
        eng.create_database("bench")
        rng = np.random.default_rng(9)
        times = np.arange(points, dtype=np.int64) * (STEP_S * 10**9)
        t0 = time.perf_counter()
        n = 0
        batch = []
        for h in range(HOSTS):
            vals = np.round(np.clip(
                rng.normal(50, 15, points), 0, 100), 2)
            batch.append(("cpu", {"hostname": f"host_{h}",
                                  "region": f"r{h % 4}"},
                          times, {"usage_user": vals}))
            if len(batch) >= 250:
                n += eng.write_record_batch("bench", batch)
                batch = []
        if batch:
            n += eng.write_record_batch("bench", batch)
        eng.flush_all()
        eng.close()
        t_ing = time.perf_counter() - t0
        print(f"# scale ingest: {n} rows in {t_ing:.0f}s",
              file=sys.stderr)

        env = dict(os.environ, JAX_PLATFORMS="cpu")
        env.pop("PALLAS_AXON_POOL_IPS", None)
        out = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--phase",
             "scalequery", "--data", td, "--runs", "2"],
            capture_output=True, text=True, env=env, timeout=5400,
            cwd=os.path.dirname(os.path.abspath(__file__)))
        if out.returncode != 0:
            raise SystemExit(
                f"scale cpu phase failed: {out.stderr[-1500:]}")
        cpu = json.loads(out.stdout.strip().splitlines()[-1])
        tpu = scale_query_phase(td, 3)
        if cpu["digest"] != tpu["digest"]:
            raise SystemExit(
                f"SCALE MISMATCH: {cpu['digest'][:16]} != "
                f"{tpu['digest'][:16]}")
        # warm stability: the slowest warm repeat must stay within 2x
        # of the best (eviction collapse would rebuild stacks per run)
        spread = max(tpu["all_s"]) / max(tpu["best_s"], 1e-9)
    return {"metric": "tsbs_scale_recent_window_rows_per_sec",
            "value": round(n / tpu["best_s"], 1), "unit": "rows/s",
            "rows_total": n,
            "window_rows": HOSTS * SCALE_WINDOW_H * 3600 // STEP_S,
            "hosts": HOSTS,
            "ingest_rows_per_sec": round(n / t_ing, 1),
            "e2e_query_s": round(tpu["best_s"], 4),
            "warm_runs_s": tpu["all_s"],
            "warm_spread": round(spread, 2),
            "cpu_query_s": round(cpu["best_s"], 4),
            "vs_baseline": round(cpu["best_s"] / tpu["best_s"], 3),
            "bit_identical": True,
            "result_cells": tpu["cells"]}


def kernel_micro() -> float:
    """Device-resident dense-kernel throughput (rows/s) — the
    steady-state ceiling when blocks live in the device column cache."""
    import jax
    import jax.numpy as jnp
    from opengemini_tpu.ops import AggSpec, dense_window_aggregate

    G, W, P, K = 4096, 16, 4096, 4
    rng = np.random.default_rng(1)
    values = np.round(np.clip(rng.normal(50, 15, (G * W, P)), 0, 100))
    spec = AggSpec.of("mean")

    @jax.jit
    def step(v):
        return dense_window_aggregate(v, None, None, spec).mean()

    stack = jax.jit(lambda rs: jnp.stack(rs))
    dv = jax.device_put(values)
    np.asarray(step(dv))
    best = np.inf
    for _ in range(3):
        t0 = time.perf_counter()
        out = np.asarray(stack([step(dv) for _ in range(K)]))
        best = min(best, time.perf_counter() - t0)
    assert out.shape == (K, G * W)
    return G * W * P * K / best


def http_roundtrip(data_dir: str) -> float:
    """One warm query over HTTP (ms)."""
    import urllib.request
    import urllib.parse
    from opengemini_tpu.http.server import HttpServer
    from opengemini_tpu.storage import Engine, EngineOptions

    eng = Engine(data_dir, EngineOptions(shard_duration=1 << 62))
    srv = HttpServer(eng, port=0)
    srv.start()
    try:
        url = (f"http://127.0.0.1:{srv.port}/query?db=bench&q="
               + urllib.parse.quote(QUERY))
        urllib.request.urlopen(url, timeout=600).read()   # warm
        t0 = time.perf_counter()
        urllib.request.urlopen(url, timeout=600).read()
        return (time.perf_counter() - t0) * 1000
    finally:
        srv.stop()
        eng.close()


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--phase",
                    choices=["query", "csquery", "scalequery",
                             "scalefull"],
                    default=None)
    ap.add_argument("--data", default=None)
    ap.add_argument("--runs", type=int, default=3)
    args = ap.parse_args()

    if args.phase == "query":
        print(json.dumps(run_query_phase(args.data, args.runs)))
        return
    if args.phase == "csquery":
        print(json.dumps(colstore_query_phase(args.data, args.runs)))
        return
    if args.phase == "scalequery":
        print(json.dumps(scale_query_phase(args.data, args.runs)))
        return
    if args.phase == "scalefull":
        print(json.dumps(scale_phase()))
        return

    shm = "/dev/shm" if os.path.isdir("/dev/shm") else None
    # the ≥500M-point scale record runs FIRST in an ISOLATED process:
    # it needs the whole HBM for its window stacks, and this parent
    # has not initialized its own TPU client yet (two live tunnel
    # clients wedge; a shared one exhausts HBM across phases —
    # observed RESOURCE_EXHAUSTED when scale ran after the headline)
    scale_line = None
    if SCALE_ROWS > 0:
        # auxiliary metric: never let it cost the headline line
        try:
            out = subprocess.run(
                [sys.executable, os.path.abspath(__file__), "--phase",
                 "scalefull"],
                capture_output=True, text=True, timeout=5400,
                cwd=os.path.dirname(os.path.abspath(__file__)))
            if out.returncode == 0 and out.stdout.strip():
                scale_line = out.stdout.strip().splitlines()[-1]
            else:
                print(f"# scale phase failed: {out.stderr[-800:]}",
                      file=sys.stderr)
        except Exception as e:
            print(f"# scale phase failed: {e!r}", file=sys.stderr)
    with tempfile.TemporaryDirectory(prefix="og-bench-", dir=shm) as td:
        n_rows = build_dataset(td)

        # CPU baseline: identical engine/code, JAX pinned to host CPU.
        # PALLAS_AXON_POOL_IPS must be ABSENT: the axon sitecustomize
        # registers the TPU-tunnel PJRT plugin whenever it is set, even
        # under JAX_PLATFORMS=cpu, and a concurrent tunnel handshake
        # can wedge against the parent's live TPU session.
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        env.pop("PALLAS_AXON_POOL_IPS", None)
        out = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--phase", "query",
             "--data", td, "--runs", str(args.runs)],
            capture_output=True, text=True, env=env, timeout=5400,
            cwd=os.path.dirname(os.path.abspath(__file__)))
        if out.returncode != 0:
            raise SystemExit(f"cpu phase failed: {out.stderr[-2000:]}")
        cpu = json.loads(out.stdout.strip().splitlines()[-1])

        # TPU run (this process inherits the real device)
        tpu = run_query_phase(td, args.runs)

        for key in ("1h", "1m", "cfg1"):
            if cpu[key]["digest"] != tpu[key]["digest"]:
                raise SystemExit(
                    f"MISMATCH [{key}]: cpu {cpu[key]['digest'][:16]} "
                    f"!= tpu {tpu[key]['digest'][:16]}")

        # auxiliary metrics must never cost us the headline line;
        # drop the query phase's resident stacks first (HBM headroom)
        try:
            from opengemini_tpu.ops import devicecache as _dc
            _dc._CACHE = None
            _dc._HOST_CACHE = None
            import gc
            gc.collect()
        except Exception:
            pass
        try:
            print(json.dumps(colstore_phase()))   # BASELINE config 3
        except Exception as e:
            print(f"# colstore phase failed: {e}", file=sys.stderr)
        if scale_line:
            print(scale_line)                     # >=500M-point record
        try:
            kernel_rps = kernel_micro()
        except Exception as e:
            print(f"# kernel_micro failed: {e}", file=sys.stderr)
            kernel_rps = 0.0
        try:
            http_ms = http_roundtrip(td)
        except Exception as e:
            print(f"# http_roundtrip failed: {e}", file=sys.stderr)
            http_ms = 0.0

    e2e_rps = n_rows / tpu["1h"]["best_s"]
    print(json.dumps({
        "metric": "tsbs_double_groupby1_mean_e2e_rows_per_sec",
        "value": round(e2e_rps, 1),
        "unit": "rows/s",
        "vs_baseline": round(cpu["1h"]["best_s"] / tpu["1h"]["best_s"],
                             3),
        "rows": n_rows,
        "hosts": HOSTS,
        "result_cells": tpu["1h"]["cells"],
        "e2e_query_s": round(tpu["1h"]["best_s"], 4),
        "cpu_query_s": round(cpu["1h"]["best_s"], 4),
        "e2e_1m_rows_per_sec": round(n_rows / tpu["1m"]["best_s"], 1),
        "vs_baseline_1m": round(cpu["1m"]["best_s"]
                                / tpu["1m"]["best_s"], 3),
        "e2e_cfg1_s": round(tpu["cfg1"]["best_s"], 4),
        "cpu_cfg1_s": round(cpu["cfg1"]["best_s"], 4),
        "vs_baseline_cfg1": round(cpu["cfg1"]["best_s"]
                                  / tpu["cfg1"]["best_s"], 3),
        "bit_identical": True,
        "kernel_rows_per_sec": round(kernel_rps, 1),
        "http_query_ms": round(http_ms, 1),
        "phases_ms": tpu.get("phases_ms", {})}))


if __name__ == "__main__":
    main()
