"""PromQL parser (role of the reference's promql2influxql transpiler front
end, lib/util/lifted/promql2influxql/ — here PromQL evaluates natively
against the TPU kernels instead of transpiling to InfluxQL).

Supported grammar:
    <expr> := number | 'str' | <vector> | fn(<expr>...) |
              agg [by|without (labels)] (<expr>[, param]) |
              <expr> binop <expr> | (-)<expr> | (<expr>)
    <vector> := metric_name[{matchers}][[range]][offset dur]
    matchers: label =|!=|=~|!~ "value"
    binops: + - * / % ^ == != > < >= <= (with optional `bool`)
    aggs: sum avg min max count topk bottomk
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field


class PromParseError(Exception):
    pass


_DUR = re.compile(r"^(\d+)(ms|s|m|h|d|w|y)")
_DUR_NS = {"ms": 10**6, "s": 10**9, "m": 60 * 10**9, "h": 3600 * 10**9,
           "d": 86400 * 10**9, "w": 7 * 86400 * 10**9,
           "y": 365 * 86400 * 10**9}

AGG_OPS = {"sum", "avg", "min", "max", "count", "topk", "bottomk",
           "group", "stddev", "stdvar", "quantile", "count_values"}

RANGE_FUNCS = {"rate", "irate", "increase", "delta", "idelta",
               "avg_over_time", "sum_over_time", "min_over_time",
               "max_over_time", "count_over_time", "last_over_time",
               "first_over_time", "resets", "changes",
               "stddev_over_time", "stdvar_over_time",
               "present_over_time", "absent_over_time",
               "quantile_over_time", "deriv", "predict_linear"}

SCALAR_FUNCS = {"abs", "ceil", "floor", "round", "exp", "ln", "log2",
                "log10", "sqrt", "clamp_min", "clamp_max", "clamp",
                "scalar", "timestamp", "sgn", "sort", "sort_desc",
                "absent", "vector", "time", "pi", "histogram_quantile",
                "label_replace", "label_join", "minute", "hour",
                "day_of_week", "day_of_month", "day_of_year", "month",
                "year", "days_in_month", "sin", "cos", "tan", "asin",
                "acos", "atan", "sinh", "cosh", "tanh", "deg", "rad"}


@dataclass
class NumberLit:
    value: float


@dataclass
class StringLit:
    value: str


@dataclass
class Matcher:
    name: str
    op: str        # = != =~ !~
    value: str


@dataclass
class VectorSelector:
    name: str = ""
    matchers: list[Matcher] = field(default_factory=list)
    range_ns: int = 0          # 0 = instant selector
    offset_ns: int = 0
    # @-modifier: pin evaluation to an absolute time (unix-seconds
    # literal) or to the query range bound (`@ start()` / `@ end()`)
    at_ns: int | None = None
    at_anchor: str | None = None     # "start" | "end"


@dataclass
class Subquery:
    """<expr>[range:step] — evaluate the inner expression as a range
    vector at `step` resolution (0 = engine default, matching the
    upstream promqltest 1m interval); consumable by every range
    function. Reference: PromSubquery/PromSubCalls
    (engine/executor/logic_plan.go PromSubquery,
    lib/util/lifted/promql2influxql range-function transpile).

    Known divergence from upstream: an inner expression step that
    evaluates to NaN (0/0, sqrt of a negative, …) is treated as AN
    ABSENT SAMPLE, not a NaN-valued sample — the engine's SeriesMatrix
    uses NaN as its missing marker. count_over_time over such steps
    undercounts relative to Prometheus."""
    expr: object = None
    range_ns: int = 0
    step_ns: int = 0
    offset_ns: int = 0
    at_ns: int | None = None
    at_anchor: str | None = None


@dataclass
class FuncCall:
    func: str
    args: list = field(default_factory=list)


@dataclass
class Aggregation:
    op: str
    expr: object = None
    grouping: list[str] = field(default_factory=list)
    without: bool = False
    param: object = None       # topk/bottomk k


@dataclass
class BinaryOp:
    op: str
    lhs: object = None
    rhs: object = None
    bool_mode: bool = False
    # vector matching: on(l…)/ignoring(l…) restrict the match key;
    # group_left/group_right allow many-to-one with extra labels
    # copied from the "one" side
    match_on: list[str] | None = None    # None = full label match
    match_ignoring: bool = False
    group_side: str | None = None        # "left" | "right"
    group_labels: list[str] = field(default_factory=list)


def parse_duration(s: str) -> int:
    total = 0
    pos = 0
    while pos < len(s):
        m = _DUR.match(s[pos:])
        if not m:
            raise PromParseError(f"bad duration {s!r}")
        total += int(m.group(1)) * _DUR_NS[m.group(2)]
        pos += m.end()
    if total == 0:
        raise PromParseError(f"bad duration {s!r}")
    return total


class _P:
    def __init__(self, text: str):
        self.s = text
        self.i = 0

    def ws(self):
        while self.i < len(self.s) and self.s[self.i] in " \t\n":
            self.i += 1

    def peek(self, n=1) -> str:
        return self.s[self.i:self.i + n]

    def eat(self, tok: str) -> bool:
        self.ws()
        if self.s.startswith(tok, self.i):
            self.i += len(tok)
            return True
        return False

    def expect(self, tok: str):
        if not self.eat(tok):
            raise PromParseError(
                f"expected {tok!r} at {self.i}: ...{self.s[self.i:self.i+20]!r}")

    def ident(self) -> str:
        self.ws()
        m = re.match(r"[a-zA-Z_:][a-zA-Z0-9_:]*", self.s[self.i:])
        if not m:
            raise PromParseError(f"expected identifier at {self.i}")
        self.i += m.end()
        return m.group()

    def string(self) -> str:
        self.ws()
        q = self.peek()
        if q not in "'\"`":
            raise PromParseError(f"expected string at {self.i}")
        self.i += 1
        out = []
        while self.i < len(self.s):
            c = self.s[self.i]
            if c == "\\" and q != "`" and self.i + 1 < len(self.s):
                nxt = self.s[self.i + 1]
                out.append({"n": "\n", "t": "\t", "\\": "\\",
                            q: q}.get(nxt, "\\" + nxt))
                self.i += 2
                continue
            if c == q:
                self.i += 1
                return "".join(out)
            out.append(c)
            self.i += 1
        raise PromParseError("unterminated string")

    def duration_tok(self) -> int:
        self.ws()
        m = re.match(r"[0-9]+[a-z]+(?:[0-9]+[a-z]+)*", self.s[self.i:])
        if not m:
            raise PromParseError(f"expected duration at {self.i}")
        self.i += m.end()
        return parse_duration(m.group())

    # ---- grammar ---------------------------------------------------------

    def parse_expr(self, min_prec=0):
        lhs = self.parse_unary()
        PREC = {"or": 1, "and": 2, "unless": 2,
                "==": 3, "!=": 3, ">": 3, "<": 3, ">=": 3, "<=": 3,
                "+": 4, "-": 4, "*": 5, "/": 5, "%": 5, "^": 6}
        while True:
            self.ws()
            op = None
            for cand in ("==", "!=", ">=", "<=", "or", "and", "unless",
                         ">", "<", "+", "-", "*", "/", "%", "^"):
                if self.s.startswith(cand, self.i):
                    # word ops need a word boundary
                    if cand.isalpha():
                        end = self.i + len(cand)
                        if end < len(self.s) and (self.s[end].isalnum()
                                                  or self.s[end] == "_"):
                            continue
                    op = cand
                    break
            if op is None or PREC[op] < min_prec:
                return lhs
            self.i += len(op)
            bool_mode = False
            self.ws()
            if self._kw_at("bool"):
                self.i += 4
                bool_mode = True
            match_on = None
            match_ignoring = False
            group_side = None
            group_labels: list[str] = []
            self.ws()
            for kw in ("ignoring", "on"):
                if self._modifier_at(kw):
                    self.i += len(kw)
                    match_on = self._label_list()
                    match_ignoring = kw == "ignoring"
                    break
            self.ws()
            for kw in ("group_left", "group_right"):
                if self._kw_at(kw):
                    self.i += len(kw)
                    group_side = kw[len("group_"):]
                    self.ws()
                    if self.peek() == "(":
                        group_labels = self._label_list()
                    break
            if group_side and match_on is None:
                raise PromParseError(
                    f"group_{group_side} requires on() or ignoring()")
            # ^ is right-assoc, others left
            nxt = PREC[op] + (0 if op == "^" else 1)
            rhs = self.parse_expr(nxt)
            lhs = BinaryOp(op, lhs, rhs, bool_mode,
                           match_on=match_on,
                           match_ignoring=match_ignoring,
                           group_side=group_side,
                           group_labels=group_labels)

    def _kw_at(self, kw: str) -> bool:
        """True if `kw` sits at the cursor with a word boundary after
        it (shared by every keyword/modifier scan)."""
        if not self.s.startswith(kw, self.i):
            return False
        j = self.i + len(kw)
        return j >= len(self.s) or not (self.s[j].isalnum()
                                        or self.s[j] == "_")

    def _modifier_at(self, kw: str) -> bool:
        """True if `kw` sits at the cursor followed by '(' (so a
        metric named `on` is still usable as an operand)."""
        if not self.s.startswith(kw, self.i):
            return False
        j = self.i + len(kw)
        while j < len(self.s) and self.s[j].isspace():
            j += 1
        return j < len(self.s) and self.s[j] == "("

    def _label_list(self) -> list[str]:
        self.ws()
        self.expect("(")
        out: list[str] = []
        self.ws()
        while self.peek() != ")":
            out.append(self.ident())
            self.ws()
            if self.peek() == ",":
                self.expect(",")
                self.ws()
        self.expect(")")
        return out

    def parse_unary(self):
        self.ws()
        if self.eat("-"):
            # upstream precedence: ^ binds TIGHTER than unary minus
            # (-2^2 == -(2^2) == -4), so the operand parses at the
            # power level
            e = self.parse_expr(6)
            if isinstance(e, NumberLit):
                return NumberLit(-e.value)
            return BinaryOp("*", NumberLit(-1.0), e)
        if self.eat("+"):
            return self.parse_unary()
        return self.parse_postfix()

    def parse_postfix(self):
        e = self.parse_primary()
        while True:
            self.ws()
            if self.peek() == "[":
                self.expect("[")
                rng = self.duration_tok()
                self.ws()
                if self.peek() == ":":
                    # subquery: <expr>[range:step]
                    self.expect(":")
                    self.ws()
                    sstep = 0
                    if self.peek() != "]":
                        sstep = self.duration_tok()
                    self.expect("]")
                    e = Subquery(expr=e, range_ns=rng, step_ns=sstep)
                    continue
                if not isinstance(e, VectorSelector) or e.range_ns:
                    raise PromParseError("range on non-selector")
                e.range_ns = rng
                self.expect("]")
                continue
            if self.s.startswith("offset", self.i):
                self.i += len("offset")
                if not isinstance(e, (VectorSelector, Subquery)):
                    raise PromParseError("offset on non-selector")
                e.offset_ns = self.duration_tok()
                continue
            if self.peek() == "@":
                self.expect("@")
                if not isinstance(e, (VectorSelector, Subquery)):
                    raise PromParseError("@ modifier on non-selector")
                self.ws()
                if self.s.startswith("start()", self.i):
                    self.i += len("start()")
                    e.at_anchor = "start"
                elif self.s.startswith("end()", self.i):
                    self.i += len("end()")
                    e.at_anchor = "end"
                else:
                    m = re.match(
                        r"-?[0-9]*\.?[0-9]+(?:[eE][+-]?[0-9]+)?",
                        self.s[self.i:])
                    if not m:
                        raise PromParseError(
                            "@ expects a unix timestamp, start() or "
                            "end()")
                    self.i += m.end()
                    e.at_ns = int(round(float(m.group()) * 1e9))
                continue
            return e

    def parse_primary(self):
        self.ws()
        if self.i >= len(self.s):
            raise PromParseError("unexpected end of query")
        c = self.s[self.i]
        if c == "(":
            self.expect("(")
            e = self.parse_expr()
            self.expect(")")
            return e
        if c in "'\"`":
            return StringLit(self.string())
        m = re.match(r"[0-9]*\.?[0-9]+(?:[eE][+-]?[0-9]+)?",
                     self.s[self.i:])
        if m and (c.isdigit() or c == "."):
            # could be a duration-like bare number? numbers are seconds
            self.i += m.end()
            return NumberLit(float(m.group()))
        if c == "{":
            vs = VectorSelector()
            self._matchers(vs)
            return vs
        name = self.ident()
        self.ws()
        # aggregation operators are case-insensitive keywords upstream
        # (functions stay case-sensitive); `SUM(...)` must aggregate,
        # but a bare `SUM` with no parens is a metric selector
        if name.lower() in AGG_OPS and self.peek() in ("(", "b", "w",
                                                       "B", "W"):
            return self._aggregation(name.lower())
        if self.peek() == "(":
            self.expect("(")
            args = []
            self.ws()
            if not self.eat(")"):
                args.append(self.parse_expr())
                while self.eat(","):
                    args.append(self.parse_expr())
                self.expect(")")
            return FuncCall(name, args)
        vs = VectorSelector(name=name)
        self.ws()
        if self.peek() == "{":
            self._matchers(vs)
        return vs

    def _matchers(self, vs: VectorSelector):
        self.expect("{")
        self.ws()
        if self.eat("}"):
            return
        while True:
            lname = self.ident()
            self.ws()
            for op in ("=~", "!~", "!=", "="):
                if self.eat(op):
                    break
            else:
                raise PromParseError(f"bad matcher op at {self.i}")
            val = self.string()
            if lname == "__name__" and op == "=":
                vs.name = val
            else:
                vs.matchers.append(Matcher(lname, op, val))
            self.ws()
            if self.eat("}"):
                return
            self.expect(",")

    def _aggregation(self, op: str) -> Aggregation:
        agg = Aggregation(op)
        self.ws()

        def _grp_kw():
            # BY/WITHOUT are case-insensitive keywords upstream
            low = self.s[self.i:self.i + 7].lower()
            if low.startswith("without"):
                return "without"
            if low.startswith("by"):
                return "by"
            return None

        # prefix grouping: sum by (a,b) (expr)
        kw = _grp_kw()
        if kw:
            agg.without = kw == "without"
            self.i += len(kw)
            agg.grouping = self._label_list()
        self.expect("(")
        first = self.parse_expr()
        if self.eat(","):
            agg.param = first
            agg.expr = self.parse_expr()
        else:
            agg.expr = first
        self.expect(")")
        # suffix grouping
        self.ws()
        kw = _grp_kw()
        if kw:
            agg.without = kw == "without"
            self.i += len(kw)
            agg.grouping = self._label_list()
        return agg

    def _label_list(self) -> list[str]:
        self.expect("(")
        out = []
        self.ws()
        if self.eat(")"):
            return out
        out.append(self.ident())
        while self.eat(","):
            out.append(self.ident())
        self.expect(")")
        return out


def parse_promql(text: str):
    p = _P(text)
    e = p.parse_expr()
    p.ws()
    if p.i != len(p.s):
        raise PromParseError(
            f"unexpected trailing input at {p.i}: {p.s[p.i:p.i+20]!r}")
    return e
