"""PromQL evaluation engine over the storage engine + TPU prom kernels.

Role of the reference's PromQL path (transpiler + prom cursors + prom
transforms, SURVEY §3.3) — evaluated natively: selectors scan the series
index, samples become per-(series, step-bucket) BucketStates on device
(ops/prom.py), range functions fold bucket windows, aggregations reduce
across the series axis.

Data model: a prom metric is a measurement whose float samples live in the
``value`` field (the openGemini prom remote-write mapping); labels are tags.

Bucket alignment: internal bucket width = gcd(step, range/lookback) so
windows land exactly on bucket edges (capped at _MAX_FOLD shifted-copy
merges; beyond that the range rounds up to a step multiple — documented
approximation).
"""

from __future__ import annotations

import math
from dataclasses import dataclass

import numpy as np

from ..index import TagFilter
from ..utils import get_logger
from ..ops import prom as K
from .parser import (Aggregation, BinaryOp, FuncCall, Matcher, NumberLit,
                     PromParseError, StringLit, VectorSelector,
                     RANGE_FUNCS, parse_promql)

log = get_logger(__name__)

DEFAULT_LOOKBACK_NS = 5 * 60 * 10**9
_MAX_FOLD = 128
VALUE_FIELD = "value"


@dataclass
class SeriesMatrix:
    """Evaluation intermediate: S series × B eval steps; NaN = no sample."""
    labels: list[dict]            # per-series label sets (incl. __name__)
    values: np.ndarray            # (S, B) float64
    metric_dropped: bool = False  # set after functions/aggregations

    def drop_metric(self) -> "SeriesMatrix":
        labels = [{k: v for k, v in ls.items() if k != "__name__"}
                  for ls in self.labels]
        return SeriesMatrix(labels, self.values, True)


class PromQLError(Exception):
    pass


class PromEngine:
    def __init__(self, engine, db: str = "prometheus"):
        self.engine = engine
        self.db = db

    # ---------------------------------------------------------------- api

    def query_instant(self, text: str, t_ns: int,
                      lookback_ns: int = DEFAULT_LOOKBACK_NS) -> list[dict]:
        """Returns prom API 'vector' result list."""
        expr = parse_promql(text)
        res = self._eval(expr, t_ns, t_ns, 10**9, lookback_ns)
        if isinstance(res, float):
            return [{"metric": {}, "value": [t_ns / 1e9, _fmt(res)]}]
        out = []
        for ls, row in zip(res.labels, res.values):
            v = row[-1]
            if not np.isnan(v):
                out.append({"metric": ls, "value": [t_ns / 1e9, _fmt(v)]})
        return out

    def query_range(self, text: str, start_ns: int, end_ns: int,
                    step_ns: int,
                    lookback_ns: int = DEFAULT_LOOKBACK_NS) -> list[dict]:
        """Returns prom API 'matrix' result list."""
        expr = parse_promql(text)
        if step_ns <= 0:
            raise PromQLError("step must be positive")
        nsteps = int((end_ns - start_ns) // step_ns) + 1
        if nsteps > 11000:
            raise PromQLError("exceeded maximum resolution of 11,000 points")
        res = self._eval(expr, start_ns, end_ns, step_ns, lookback_ns)
        ts = [(start_ns + i * step_ns) / 1e9 for i in range(nsteps)]
        if isinstance(res, float):
            return [{"metric": {},
                     "values": [[t, _fmt(res)] for t in ts]}]
        out = []
        for ls, row in zip(res.labels, res.values):
            vals = [[ts[i], _fmt(row[i])] for i in range(nsteps)
                    if not np.isnan(row[i])]
            if vals:
                out.append({"metric": ls, "values": vals})
        return out

    # ---------------------------------------------------- metadata api

    def _db_obj(self):
        try:
            return self.engine.database(self.db)
        except Exception:
            return None

    def labels(self) -> list[str]:
        names = set()
        db = self._db_obj()
        if db:
            for s in db.all_shards():
                for m in s.measurements():
                    names.update(s.index.tag_keys(m))
        return sorted(names | {"__name__"})

    def label_values(self, name: str) -> list[str]:
        vals = set()
        db = self._db_obj()
        if db:
            for s in db.all_shards():
                for m in s.measurements():
                    if name == "__name__":
                        vals.add(m)
                    else:
                        vals.update(s.index.tag_values(m, name))
        return sorted(vals)

    def series(self, selectors: list[str]) -> list[dict]:
        """prom /api/v1/series: label sets matching any selector."""
        db = self._db_obj()
        seen = set()
        out = []
        for sel in selectors:
            expr = parse_promql(sel)
            if not isinstance(expr, VectorSelector) or expr.range_ns:
                raise PromQLError(
                    f"match[] must be an instant vector selector: {sel!r}")
            if db is None:
                continue
            filters = [TagFilter(m.name, m.value, m.op)
                       for m in expr.matchers]
            msts = ([expr.name] if expr.name else
                    sorted({m for s in db.all_shards()
                            for m in s.measurements()}))
            for mst in msts:
                for s in db.all_shards():
                    for sid in s.index.series_ids(mst, filters).tolist():
                        key = (mst,) + tuple(sorted(
                            s.index.tags_of(sid).items()))
                        if key in seen:
                            continue
                        seen.add(key)
                        ls = dict(key[1:])
                        ls["__name__"] = mst
                        out.append(ls)
        return out

    # ------------------------------------------------------------- eval

    def _eval(self, expr, start_ns, end_ns, step_ns, lookback_ns):
        """Returns SeriesMatrix or python float (scalar)."""
        if isinstance(expr, NumberLit):
            return float(expr.value)
        if isinstance(expr, StringLit):
            raise PromQLError("string literal is not a valid expression "
                              "result")
        if isinstance(expr, VectorSelector):
            if expr.range_ns:
                raise PromQLError(
                    "range vector selector must be wrapped in a function")
            return self._eval_selector_instant(expr, start_ns, end_ns,
                                               step_ns, lookback_ns)
        if isinstance(expr, FuncCall):
            return self._eval_func(expr, start_ns, end_ns, step_ns,
                                   lookback_ns)
        if isinstance(expr, Aggregation):
            inner = self._eval(expr.expr, start_ns, end_ns, step_ns,
                               lookback_ns)
            if isinstance(inner, float):
                raise PromQLError(f"{expr.op} expects a vector")
            return _aggregate(expr, inner)
        if isinstance(expr, BinaryOp):
            return self._eval_binop(expr, start_ns, end_ns, step_ns,
                                    lookback_ns)
        raise PromQLError(f"unsupported expression {type(expr).__name__}")

    # ---- selectors -------------------------------------------------------

    def _gather(self, vs: VectorSelector, t_min: int, t_max: int):
        """Scan storage: matching series → flat sorted arrays + per-series
        labels. Returns (labels, values, times, series_row_ids)."""
        if not vs.name:
            raise PromQLError("selector requires a metric name")
        filters = [TagFilter(m.name, m.value, m.op) for m in vs.matchers]
        try:
            db = self.engine.database(self.db)
        except Exception:
            return [], np.zeros(0), np.zeros(0, np.int64), np.zeros(
                0, np.int64)
        shards = db.shards_overlapping(t_min, t_max)
        # label-set → row list (same series may span shards)
        by_labels: dict[tuple, list] = {}
        for s in shards:
            for sid in s.index.series_ids(vs.name, filters).tolist():
                rec = s.read_series(vs.name, sid, [VALUE_FIELD],
                                    t_min, t_max)
                if rec is None or rec.num_rows == 0:
                    continue
                col = rec.column(VALUE_FIELD)
                if col is None or col.values is None:
                    continue
                tags = s.index.tags_of(sid)
                key = tuple(sorted(tags.items()))
                by_labels.setdefault(key, []).append(
                    (rec.times, col.values.astype(np.float64), col.valid))
        labels = []
        vparts, tparts, sparts = [], [], []
        for si, (key, parts) in enumerate(sorted(by_labels.items())):
            ls = dict(key)
            ls["__name__"] = vs.name
            labels.append(ls)
            ts = np.concatenate([p[0] for p in parts])
            v = np.concatenate([p[1] for p in parts])
            m = np.concatenate([p[2] for p in parts])
            order = np.argsort(ts, kind="stable")
            ts, v, m = ts[order], v[order], m[order]
            keep = m
            vparts.append(v[keep])
            tparts.append(ts[keep])
            sparts.append(np.full(int(keep.sum()), si, dtype=np.int64))
        if not labels:
            return [], np.zeros(0), np.zeros(0, np.int64), np.zeros(
                0, np.int64)
        return (labels, np.concatenate(vparts), np.concatenate(tparts),
                np.concatenate(sparts))

    def _window_states(self, vs: VectorSelector, start_ns, end_ns, step_ns,
                       window_ns):
        """Shared selector machinery: (labels, BucketState (S, nsteps),
        window_end_times (nsteps,)). Window = (t_i - window, t_i]."""
        nsteps = int((end_ns - start_ns) // step_ns) + 1
        off = vs.offset_ns
        if nsteps == 1:
            # single eval point: one bucket of exactly the window width
            bs, k, stride = window_ns, 1, 1
        else:
            # bucket width: gcd so window edges align; cap fold size
            bs = math.gcd(step_ns, window_ns)
            k = window_ns // bs
            if k > _MAX_FOLD:
                bs = step_ns
                k = -(-window_ns // bs)  # ceil: rounds window UP to grid
            if k > _MAX_FOLD:
                raise PromQLError(
                    f"window {window_ns/1e9:.0f}s at step "
                    f"{step_ns/1e9:.0f}s needs {k} merge folds "
                    f"(max {_MAX_FOLD}); use a larger step")
        stride = step_ns // bs if nsteps > 1 else 1
        # bucket right-edges at origin + (j+1)*bs; eval t_i at bucket
        # index k-1 + i*stride  relative to origin = start - window
        origin = start_ns - off - (k * bs)
        t_lo = origin + 1
        t_hi = end_ns - off
        labels, values, times, series = self._gather(vs, t_lo, t_hi)
        S = len(labels)
        if S == 0:
            return [], None, None
        nb = k + (nsteps - 1) * stride
        bucket = (times - origin - 1) // bs
        seg = np.where((bucket >= 0) & (bucket < nb),
                       series * nb + bucket, S * nb)
        st = K.bucket_states(values, np.ones(len(values), bool), times,
                             seg, series, S * nb)
        st = K.BucketState(*[np.asarray(x).reshape(S, nb) for x in st])
        win = K.fold_windows(st, int(k))
        # slice eval positions: indices k-1, k-1+stride, ...
        sel = (k - 1) + stride * np.arange(nsteps)
        win = K.BucketState(*[np.asarray(x)[:, sel] for x in win])
        ends = (start_ns - off + step_ns * np.arange(nsteps)).astype(
            np.int64)
        return labels, win, np.broadcast_to(ends, (S, nsteps))

    def _eval_selector_instant(self, vs, start_ns, end_ns, step_ns,
                               lookback_ns) -> SeriesMatrix:
        labels, win, _ends = self._window_states(
            vs, start_ns, end_ns, step_ns, lookback_ns)
        if win is None:
            return SeriesMatrix([], np.zeros((0, 1)))
        vals = np.asarray(K.over_time_value(win, "last_over_time"))
        return SeriesMatrix(labels, vals)

    # ---- functions -------------------------------------------------------

    def _eval_func(self, fc: FuncCall, start_ns, end_ns, step_ns,
                   lookback_ns):
        f = fc.func
        if f in RANGE_FUNCS:
            if len(fc.args) != 1 or not isinstance(fc.args[0],
                                                   VectorSelector):
                raise PromQLError(f"{f}() expects a range vector selector")
            vs = fc.args[0]
            if not vs.range_ns:
                raise PromQLError(f"{f}() expects a range like {f}(x[5m])")
            labels, win, ends = self._window_states(
                vs, start_ns, end_ns, step_ns, vs.range_ns)
            if win is None:
                return SeriesMatrix([], np.zeros((0, 1)))
            if f in ("rate", "increase", "delta"):
                kind = f if f != "increase" else "increase"
                vals = np.asarray(K.prom_rate(win, ends, vs.range_ns,
                                              kind))
            elif f in ("irate", "idelta"):
                labels, vals = self._irate(vs, start_ns, end_ns, step_ns, f)
            elif f == "resets" or f == "changes":
                raise PromQLError(f"{f}() not implemented yet")
            else:
                vals = np.asarray(K.over_time_value(win, f))
            return SeriesMatrix(labels, vals).drop_metric()
        if f == "scalar":
            inner = self._eval(fc.args[0], start_ns, end_ns, step_ns,
                               lookback_ns)
            if isinstance(inner, float):
                return inner
            if len(inner.labels) == 1:
                m = inner.values[0]
                return SeriesMatrix([{}], m.reshape(1, -1), True)
            nsteps = int((end_ns - start_ns) // step_ns) + 1
            return SeriesMatrix([{}], np.full((1, nsteps), np.nan), True)
        if f in ("abs", "ceil", "floor", "exp", "ln", "log2", "log10",
                 "sqrt", "round"):
            inner = self._eval(fc.args[0], start_ns, end_ns, step_ns,
                               lookback_ns)
            if isinstance(inner, float):
                inner = SeriesMatrix([{}], np.array([[inner]]), True)
            fn = {"abs": np.abs, "ceil": np.ceil, "floor": np.floor,
                  "exp": np.exp, "ln": np.log, "log2": np.log2,
                  "log10": np.log10, "sqrt": np.sqrt,
                  "round": np.round}[f]
            with np.errstate(all="ignore"):
                return SeriesMatrix(inner.labels, fn(inner.values),
                                    inner.metric_dropped).drop_metric()
        if f in ("clamp_min", "clamp_max"):
            inner = self._eval(fc.args[0], start_ns, end_ns, step_ns,
                               lookback_ns)
            lim = self._eval(fc.args[1], start_ns, end_ns, step_ns,
                             lookback_ns)
            if not isinstance(lim, float):
                raise PromQLError(f"{f} limit must be a scalar")
            op = np.maximum if f == "clamp_min" else np.minimum
            return SeriesMatrix(inner.labels, op(inner.values, lim),
                                inner.metric_dropped).drop_metric()
        raise PromQLError(f"unsupported function {f}()")

    def _irate(self, vs, start_ns, end_ns, step_ns, f):
        """Dedicated per-eval-point last-two-samples pass (bucket
        granularity can't express 'previous sample')."""
        nsteps = int((end_ns - start_ns) // step_ns) + 1
        off = vs.offset_ns
        labels_all = None
        cols = []
        # evaluate per step: segments = (series, this one window)
        t_los = [start_ns - off + i * step_ns - vs.range_ns
                 for i in range(nsteps)]
        labels, values, times, series = self._gather(
            vs, min(t_los) + 1, end_ns - off)
        if not labels:
            return [], np.zeros((0, nsteps))
        S = len(labels)
        out = np.full((S, nsteps), np.nan)
        for i in range(nsteps):
            t_i = start_ns - off + i * step_ns
            m = (times > t_i - vs.range_ns) & (times <= t_i)
            if not m.any():
                continue
            seg = np.where(m, series, S)
            last, prev, lt, pt, cnt = K.irate_states(
                values, m, times, seg, S)
            out[:, i] = np.asarray(K.prom_irate_value(
                np.asarray(last), np.asarray(prev), np.asarray(lt),
                np.asarray(pt), np.asarray(cnt),
                "idelta" if f == "idelta" else "irate"))
        return labels, out

    # ---- binary ops ------------------------------------------------------

    def _eval_binop(self, b: BinaryOp, start_ns, end_ns, step_ns,
                    lookback_ns):
        lhs = self._eval(b.lhs, start_ns, end_ns, step_ns, lookback_ns)
        rhs = self._eval(b.rhs, start_ns, end_ns, step_ns, lookback_ns)
        if isinstance(lhs, float) and isinstance(rhs, float):
            return _scalar_op(b.op, lhs, rhs)
        if isinstance(lhs, float):
            return SeriesMatrix(
                rhs.labels, _vec_op(b.op, lhs, rhs.values, b.bool_mode,
                                    scalar_left=True),
                rhs.metric_dropped)._maybe_drop(b)
        if isinstance(rhs, float):
            return SeriesMatrix(
                lhs.labels, _vec_op(b.op, lhs.values, rhs, b.bool_mode),
                lhs.metric_dropped)._maybe_drop(b)
        # vector-vector: one-to-one on full label match (sans __name__)
        def key(ls):
            return tuple(sorted((k, v) for k, v in ls.items()
                                if k != "__name__"))
        rmap = {key(ls): i for i, ls in enumerate(rhs.labels)}
        labels, rows = [], []
        for i, ls in enumerate(lhs.labels):
            j = rmap.get(key(ls))
            if j is None:
                continue
            rows.append(_vec_op(b.op, lhs.values[i:i+1],
                                rhs.values[j:j+1], b.bool_mode))
            labels.append({k: v for k, v in ls.items() if k != "__name__"})
        if not rows:
            nsteps = lhs.values.shape[1] if lhs.values.size else 1
            return SeriesMatrix([], np.zeros((0, nsteps)), True)
        return SeriesMatrix(labels, np.vstack(rows), True)


def _fmt(v: float) -> str:
    if np.isnan(v):
        return "NaN"
    if np.isinf(v):
        return "+Inf" if v > 0 else "-Inf"
    return repr(float(v))


def _scalar_op(op, a, b):
    import operator
    with np.errstate(all="ignore"):
        fns = {"+": operator.add, "-": operator.sub, "*": operator.mul,
               "/": lambda x, y: x / y if y != 0 else math.inf * (1 if x > 0 else -1) if x != 0 else math.nan,
               "%": lambda x, y: math.fmod(x, y) if y != 0 else math.nan,
               "^": operator.pow,
               "==": lambda x, y: 1.0 if x == y else 0.0,
               "!=": lambda x, y: 1.0 if x != y else 0.0,
               ">": lambda x, y: 1.0 if x > y else 0.0,
               "<": lambda x, y: 1.0 if x < y else 0.0,
               ">=": lambda x, y: 1.0 if x >= y else 0.0,
               "<=": lambda x, y: 1.0 if x <= y else 0.0}
        if op not in fns:
            raise PromQLError(f"unsupported scalar op {op}")
        return float(fns[op](a, b))


def _vec_op(op, a, b, bool_mode, scalar_left=False):
    with np.errstate(all="ignore"):
        if op in ("+", "-", "*", "/", "%", "^"):
            fns = {"+": np.add, "-": np.subtract, "*": np.multiply,
                   "/": np.divide, "%": np.fmod, "^": np.power}
            return fns[op](a, b)
        cmp = {"==": np.equal, "!=": np.not_equal, ">": np.greater,
               "<": np.less, ">=": np.greater_equal,
               "<=": np.less_equal}[op]
        mask = cmp(a, b)
        vals = a if not scalar_left else np.broadcast_to(
            b, np.shape(mask)).astype(float)
        if bool_mode:
            out = np.where(np.isnan(vals), np.nan,
                           mask.astype(np.float64))
            return out
        return np.where(mask, vals, np.nan)


SeriesMatrix._maybe_drop = lambda self, b: (
    self.drop_metric() if b.op in ("+", "-", "*", "/", "%", "^",)
    or b.bool_mode else self)


def _aggregate(agg: Aggregation, inner: SeriesMatrix) -> SeriesMatrix:
    S, B = inner.values.shape if inner.values.size else (0, 1)
    if S == 0:
        return SeriesMatrix([], np.zeros((0, B)), True)
    groups: dict[tuple, list[int]] = {}
    out_labels: dict[tuple, dict] = {}
    for i, ls in enumerate(inner.labels):
        if agg.without:
            kept = {k: v for k, v in ls.items()
                    if k not in agg.grouping and k != "__name__"}
        elif agg.grouping:
            kept = {k: ls[k] for k in agg.grouping if k in ls}
        else:
            kept = {}
        key = tuple(sorted(kept.items()))
        groups.setdefault(key, []).append(i)
        out_labels[key] = kept
    keys = sorted(groups)
    vals = inner.values
    out = np.full((len(keys), B), np.nan)
    for gi, key in enumerate(keys):
        rows = vals[groups[key]]
        has = ~np.all(np.isnan(rows), axis=0)
        with np.errstate(all="ignore"):
            if agg.op == "sum":
                r = np.nansum(rows, axis=0)
            elif agg.op == "avg":
                r = np.nanmean(rows, axis=0)
            elif agg.op == "min":
                r = np.nanmin(np.where(np.isnan(rows), np.inf, rows),
                              axis=0)
            elif agg.op == "max":
                r = np.nanmax(np.where(np.isnan(rows), -np.inf, rows),
                              axis=0)
            elif agg.op == "count":
                r = np.sum(~np.isnan(rows), axis=0).astype(np.float64)
            elif agg.op == "group":
                r = np.ones(B)
            elif agg.op in ("stddev", "stdvar"):
                r = np.nanvar(rows, axis=0)
                if agg.op == "stddev":
                    r = np.sqrt(r)
            else:
                raise PromQLError(f"unsupported aggregation {agg.op}")
        out[gi] = np.where(has, r, np.nan)
    return SeriesMatrix([out_labels[k] for k in keys], out, True)
