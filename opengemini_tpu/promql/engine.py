"""PromQL evaluation engine over the storage engine + TPU prom kernels.

Role of the reference's PromQL path (transpiler + prom cursors + prom
transforms, SURVEY §3.3) — evaluated natively: selectors scan the series
index, samples become per-(series, step-bucket) BucketStates on device
(ops/prom.py), range functions fold bucket windows, aggregations reduce
across the series axis.

Data model: a prom metric is a measurement whose float samples live in the
``value`` field (the openGemini prom remote-write mapping); labels are tags.

Bucket alignment: internal bucket width = gcd(step, range/lookback) so
windows land exactly on bucket edges (capped at _MAX_FOLD shifted-copy
merges; beyond that the range rounds up to a step multiple — documented
approximation).
"""

from __future__ import annotations

import math
from dataclasses import dataclass

import numpy as np

from ..index import TagFilter
from ..utils import get_logger, knobs
from ..ops import prom as K
from .parser import (Aggregation, BinaryOp, FuncCall, Matcher, NumberLit,
                     PromParseError, StringLit, Subquery, VectorSelector,
                     RANGE_FUNCS, parse_promql)

# subquery default resolution when [range:] omits the step — upstream
# promqltest's default evaluation interval
DEFAULT_SUBQUERY_STEP_NS = 60 * 10**9


def _pin_at_anchors(expr, start_ns: int, end_ns: int) -> None:
    """Resolve `@ start()` / `@ end()` anchors against the TOP-LEVEL
    query range, in place, before evaluation (upstream semantics: the
    anchors always mean the outer query bounds, even on selectors
    nested inside subqueries, whose inner evaluation runs on its own
    sample grid)."""
    if isinstance(expr, (VectorSelector, Subquery)):
        if expr.at_anchor == "start":
            expr.at_ns, expr.at_anchor = start_ns, None
        elif expr.at_anchor == "end":
            expr.at_ns, expr.at_anchor = end_ns, None
        if isinstance(expr, Subquery):
            _pin_at_anchors(expr.expr, start_ns, end_ns)
        return
    if isinstance(expr, FuncCall):
        for a in expr.args:
            _pin_at_anchors(a, start_ns, end_ns)
    elif isinstance(expr, Aggregation):
        _pin_at_anchors(expr.expr, start_ns, end_ns)
        if expr.param is not None:
            _pin_at_anchors(expr.param, start_ns, end_ns)
    elif isinstance(expr, BinaryOp):
        _pin_at_anchors(expr.lhs, start_ns, end_ns)
        _pin_at_anchors(expr.rhs, start_ns, end_ns)

log = get_logger(__name__)

DEFAULT_LOOKBACK_NS = 5 * 60 * 10**9
_MAX_FOLD = 128

# rows below this fold on host (numpy): the device bucket kernel pulls
# 15 state arrays, each paying a full transfer round trip on tunnel-
# attached chips — raise/lower for directly-attached hardware
PROM_DEVICE_MIN_ROWS = int(knobs.get("OG_PROM_DEVICE_MIN_ROWS"))
# rows per device launch in the chunked fold: bounds the kernel's
# working set (inputs + 15-plane segment grid); an unchunked 60M-row
# launch crashed the tunnel-attached v5e's worker
PROM_DEVICE_CHUNK_ROWS = int(knobs.get("OG_PROM_DEVICE_CHUNK_ROWS"))
VALUE_FIELD = "value"


@dataclass
class SeriesMatrix:
    """Evaluation intermediate: S series × B eval steps; NaN = no sample."""
    labels: list[dict]            # per-series label sets (incl. __name__)
    values: np.ndarray            # (S, B) float64
    metric_dropped: bool = False  # set after functions/aggregations

    def drop_metric(self) -> "SeriesMatrix":
        labels = [{k: v for k, v in ls.items() if k != "__name__"}
                  for ls in self.labels]
        return SeriesMatrix(labels, self.values, True)


@dataclass
class ScalarSteps:
    """A scalar that varies per eval step — prom 'scalar' type in a range
    query (time(), scalar(v)). Plain python floats stay floats."""
    values: np.ndarray            # (B,) float64


class PromQLError(Exception):
    pass


class PromEngine:
    def __init__(self, engine, db: str = "prometheus"):
        self.engine = engine
        self.db = db
        from collections import OrderedDict
        self._plan_cache: OrderedDict = OrderedDict()
        # per-plan label assembly cache: (present-bitmap, labels, remap)
        self._label_cache: OrderedDict = OrderedDict()

    def _flat_residues(self, ft, mst: str, t_min, t_max):
        """Generic decode of the bulk scan's residues: memtable records
        and merged (overlapping-source) series."""
        times_l, vals_l, valid_l, gid_l = [], [], [], []

        def add(gid, rec):
            c = rec.column(VALUE_FIELD)
            if c is None or c.values is None or rec.num_rows == 0:
                return
            times_l.append(rec.times)
            vals_l.append(c.values.astype(np.float64, copy=False))
            valid_l.append(c.valid)
            gid_l.append(np.full(rec.num_rows, gid, dtype=np.int64))

        for gid, rec in ft.mem:
            add(gid, rec)
        for gid, _r, sp, _x in ft.slow:
            rec = sp.shard.read_series(mst, sp.sid, [VALUE_FIELD],
                                       t_min, t_max)
            if rec is not None:
                add(gid, rec)
        if not times_l:
            z = np.zeros(0, dtype=np.int64)
            return z, np.zeros(0), np.zeros(0, bool), z
        return (np.concatenate(times_l), np.concatenate(vals_l),
                np.concatenate(valid_l), np.concatenate(gid_l))

    # ---------------------------------------------------------------- api

    def query_instant(self, text: str, t_ns: int,
                      lookback_ns: int = DEFAULT_LOOKBACK_NS) -> list[dict]:
        """Returns prom API 'vector' result list."""
        expr = parse_promql(text)
        _pin_at_anchors(expr, t_ns, t_ns)
        res = self._eval(expr, t_ns, t_ns, 10**9, lookback_ns)
        if isinstance(res, ScalarSteps):
            res = float(res.values[-1])
        if isinstance(res, float):
            return [{"metric": {}, "value": [t_ns / 1e9, _fmt(res)]}]
        # vectorized assembly: one NaN mask + one tolist, then a plain
        # comprehension (a per-series np.isnan scalar call costs ~2us
        # — 2s of the 1M-series rate query)
        vals = np.asarray(res.values)[:, -1]
        kept = np.nonzero(~np.isnan(vals))[0]
        fv = vals[kept].tolist()
        t = t_ns / 1e9
        labels = res.labels
        return [{"metric": labels[i], "value": [t, _fmt(v)]}
                for i, v in zip(kept.tolist(), fv)]

    def query_range(self, text: str, start_ns: int, end_ns: int,
                    step_ns: int,
                    lookback_ns: int = DEFAULT_LOOKBACK_NS) -> list[dict]:
        """Returns prom API 'matrix' result list."""
        expr = parse_promql(text)
        if step_ns <= 0:
            raise PromQLError("step must be positive")
        nsteps = int((end_ns - start_ns) // step_ns) + 1
        if nsteps > 11000:
            raise PromQLError("exceeded maximum resolution of 11,000 points")
        _pin_at_anchors(expr, start_ns, end_ns)
        import time as _time
        _t0 = _time.perf_counter()
        res = self._eval(expr, start_ns, end_ns, step_ns, lookback_ns)
        # phase record for observability/bench (scan+fold+eval vs the
        # matrix formatting below)
        self.last_phases = {"eval_s": round(_time.perf_counter() - _t0,
                                            4)}
        _t0 = _time.perf_counter()
        ts = [(start_ns + i * step_ns) / 1e9 for i in range(nsteps)]
        if isinstance(res, float):
            return [{"metric": {},
                     "values": [[t, _fmt(res)] for t in ts]}]
        if isinstance(res, ScalarSteps):
            return [{"metric": {},
                     "values": [[ts[i], _fmt(res.values[i])]
                                for i in range(nsteps)
                                if not np.isnan(res.values[i])]}]
        out = []
        notnan = ~np.isnan(np.asarray(res.values))
        rows = np.asarray(res.values).tolist()
        for ls, row, m in zip(res.labels, rows, notnan):
            vals = [[ts[i], _fmt(row[i])]
                    for i in np.nonzero(m)[0].tolist()]
            if vals:
                out.append({"metric": ls, "values": vals})
        self.last_phases["format_s"] = round(
            _time.perf_counter() - _t0, 4)
        return out

    # ---------------------------------------------------- metadata api

    def _db_obj(self):
        try:
            return self.engine.database(self.db)
        except Exception:
            return None

    def labels(self) -> list[str]:
        names = set()
        db = self._db_obj()
        if db:
            for s in db.all_shards():
                for m in s.measurements():
                    names.update(s.index.tag_keys(m))
        return sorted(names | {"__name__"})

    def label_values(self, name: str) -> list[str]:
        vals = set()
        db = self._db_obj()
        if db:
            for s in db.all_shards():
                for m in s.measurements():
                    if name == "__name__":
                        vals.add(m)
                    else:
                        vals.update(s.index.tag_values(m, name))
        return sorted(vals)

    def series(self, selectors: list[str]) -> list[dict]:
        """prom /api/v1/series: label sets matching any selector."""
        db = self._db_obj()
        seen = set()
        out = []
        for sel in selectors:
            expr = parse_promql(sel)
            if not isinstance(expr, VectorSelector) or expr.range_ns:
                raise PromQLError(
                    f"match[] must be an instant vector selector: {sel!r}")
            if db is None:
                continue
            filters = [TagFilter(m.name, m.value, m.op)
                       for m in expr.matchers]
            msts = ([expr.name] if expr.name else
                    sorted({m for s in db.all_shards()
                            for m in s.measurements()}))
            for mst in msts:
                for s in db.all_shards():
                    for sid in s.index.series_ids(mst, filters).tolist():
                        key = (mst,) + tuple(sorted(
                            s.index.tags_of(sid).items()))
                        if key in seen:
                            continue
                        seen.add(key)
                        ls = dict(key[1:])
                        ls["__name__"] = mst
                        out.append(ls)
        return out

    # ------------------------------------------------------------- eval

    def _eval(self, expr, start_ns, end_ns, step_ns, lookback_ns):
        """Returns SeriesMatrix or python float (scalar)."""
        if isinstance(expr, NumberLit):
            return float(expr.value)
        if isinstance(expr, StringLit):
            raise PromQLError("string literal is not a valid expression "
                              "result")
        if isinstance(expr, Subquery):
            raise PromQLError(
                "subquery result must be wrapped in a range function")
        if isinstance(expr, VectorSelector):
            if expr.range_ns:
                raise PromQLError(
                    "range vector selector must be wrapped in a function")
            return self._eval_selector_instant(expr, start_ns, end_ns,
                                               step_ns, lookback_ns)
        if isinstance(expr, FuncCall):
            return self._eval_func(expr, start_ns, end_ns, step_ns,
                                   lookback_ns)
        if isinstance(expr, Aggregation):
            inner = self._eval(expr.expr, start_ns, end_ns, step_ns,
                               lookback_ns)
            if isinstance(inner, (float, ScalarSteps)):
                raise PromQLError(f"{expr.op} expects a vector")
            nsteps = int((end_ns - start_ns) // step_ns) + 1
            param = None
            if expr.op in ("topk", "bottomk", "quantile"):
                if expr.param is None:
                    raise PromQLError(f"{expr.op} requires a parameter")
                param = self._scalar_arg(expr.param, start_ns, end_ns,
                                         step_ns, lookback_ns, nsteps)
            elif expr.op == "count_values":
                if not isinstance(expr.param, StringLit):
                    raise PromQLError(
                        "count_values requires a string label name")
                param = expr.param.value
            return _aggregate(expr, inner, param)
        if isinstance(expr, BinaryOp):
            return self._eval_binop(expr, start_ns, end_ns, step_ns,
                                    lookback_ns)
        raise PromQLError(f"unsupported expression {type(expr).__name__}")

    # ---- selectors -------------------------------------------------------

    def _subquery_samples(self, sq: Subquery, t_lo: int, t_hi: int,
                          lookback_ns: int = DEFAULT_LOOKBACK_NS):
        """Evaluate a subquery's inner expression on its own step grid
        and flatten the result into the same (labels, values, times,
        series_row_ids) shape `_gather` produces — everything
        downstream (bucket fold, rate extrapolation, host passes) is
        source-agnostic. Sample times sit on absolute multiples of the
        subquery step (upstream alignment semantics)."""
        sub_step = sq.step_ns or DEFAULT_SUBQUERY_STEP_NS
        first = -(-t_lo // sub_step) * sub_step          # ceil
        last = (t_hi // sub_step) * sub_step
        empty = ([], np.zeros(0), np.zeros(0, np.int64),
                 np.zeros(0, np.int64))
        if last < first:
            return empty
        inner = self._eval(sq.expr, first, last, sub_step, lookback_ns)
        if isinstance(inner, (float, ScalarSteps)):
            raise PromQLError("subquery requires an instant-vector "
                              "inner expression")
        if not inner.labels:
            return empty
        vm = np.asarray(inner.values, dtype=np.float64)
        m = vm.shape[1]
        tgrid = first + sub_step * np.arange(m, dtype=np.int64)
        present = ~np.isnan(vm)
        # drop series with no samples in range (downstream anchors
        # index the first sample of every series)
        keep = present.any(axis=1)
        if not keep.any():
            return empty
        vm = vm[keep]
        present = present[keep]
        labels = [ls for ls, k in zip(inner.labels, keep) if k]
        sidx, col = np.nonzero(present)        # row-major: sorted by
        return (labels, vm[sidx, col],         # (series, time)
                tgrid[col], sidx.astype(np.int64))

    def _gather(self, vs: VectorSelector, t_min: int, t_max: int):
        """Scan storage: matching series → flat sorted arrays + per-series
        labels. Returns (labels, values, times, series_row_ids).

        Batched: tagset grouping is one vectorized index pass (each
        distinct label set is a group) and decode goes through the
        row-store scan plan + pooled segment decode (query/scan.py) —
        the round-2 per-series read_series loop cost ~170µs/series of
        pure Python at 1M-series scale."""
        if not vs.name:
            # bare selector with __name__ matchers: expand to the union
            # of matching measurements (upstream {__name__=~"..."}).
            name_ms = [m for m in vs.matchers if m.name == "__name__"]
            if not name_ms:
                raise PromQLError("selector requires a metric name")
            import re as _re
            from dataclasses import replace as _rep
            rest = [m for m in vs.matchers if m.name != "__name__"]
            db = self._db_obj()
            msts: set = set()
            if db:
                for s in db.all_shards():
                    msts.update(s.measurements())

            def name_ok(nm: str) -> bool:
                for m in name_ms:
                    if m.op == "=":
                        ok = nm == m.value
                    elif m.op == "!=":
                        ok = nm != m.value
                    elif m.op == "=~":
                        ok = _re.fullmatch(m.value, nm) is not None
                    else:
                        ok = _re.fullmatch(m.value, nm) is None
                    if not ok:
                        return False
                return True

            parts = [self._gather(_rep(vs, name=nm, matchers=rest),
                                  t_min, t_max)
                     for nm in sorted(msts) if name_ok(nm)]
            parts = [p for p in parts if p[0]]
            if not parts:
                return ([], np.zeros(0), np.zeros(0, np.int64),
                        np.zeros(0, np.int64))
            labels: list = []
            va, ta, ga = [], [], []
            for ls, v, t, g in parts:
                ga.append(g + len(labels))
                labels.extend(ls)
                va.append(v)
                ta.append(t)
            return (labels, np.concatenate(va), np.concatenate(ta),
                    np.concatenate(ga))
        filters = [TagFilter(m.name, m.value, m.op) for m in vs.matchers]
        try:
            db = self.engine.database(self.db)
        except Exception:
            return [], np.zeros(0), np.zeros(0, np.int64), np.zeros(
                0, np.int64)
        shards = db.shards_overlapping(t_min, t_max)
        empty = ([], np.zeros(0), np.zeros(0, np.int64),
                 np.zeros(0, np.int64))
        tag_keys: list[str] = sorted(
            {k for s in shards for k in s.index.tag_keys(vs.name)})
        from ..query.scan import (bulk_flat_scan, decode_pool,
                                  materialize_scan, plan_rowstore_scan)
        # content-keyed plan cache (executor-style): warm dashboards
        # skip tagset grouping AND the chunk-meta walk — at 1M series
        # those cost ~26s of Python per query
        filt_key = tuple(sorted((m.name, m.op, m.value)
                                for m in vs.matchers))
        plan_key = (vs.name, filt_key, t_min, t_max,
                    tuple((s.serial,
                           tuple(r.serial
                                 for r in s._files.get(vs.name, ())),
                           s.mem.mutations) for s in shards))
        hit = self._plan_cache.get(plan_key)
        if hit is not None:
            self._plan_cache.move_to_end(plan_key)
            global_groups, plan = hit
        else:
            global_groups = {}
            per_shard = []
            for s in shards:
                ts = s.index.group_by_tagsets(vs.name, tag_keys,
                                              filters)
                pairs = []
                for key, sids in ts:
                    gi = global_groups.setdefault(key,
                                                  len(global_groups))
                    pairs.extend((int(sid), gi) for sid in sids)
                per_shard.append((s, pairs))
            plan = plan_rowstore_scan(per_shard, vs.name, t_min, t_max)
            self._plan_cache[plan_key] = (global_groups, plan)
            while len(self._plan_cache) > 8:
                self._plan_cache.popitem(last=False)
        G = len(global_groups)
        if G == 0 or not plan.has_rows:
            return empty
        flat = bulk_flat_scan(
            plan, vs.name, VALUE_FIELD, t_min, t_max,
            decode_fallback=lambda ft: self._flat_residues(
                ft, vs.name, t_min, t_max))
        if flat is not None:
            times, vals, valid, gids = flat
            keep = valid
            vals = vals[keep]
            times = times[keep]
            gids = gids[keep]
        else:
            scanres = materialize_scan(
                plan, vs.name, [VALUE_FIELD], t_min, t_max, 0, 2**62,
                1, G, allow_preagg=False, allow_dense=False,
                pool=decode_pool())
            got = scanres.fields.get(VALUE_FIELD)
            if got is None or scanres.n_rows == 0:
                return empty
            vals, valid = got
            times = scanres.times
            gids = scanres.gids
            keep = valid
            vals = vals.astype(np.float64, copy=False)[keep]
            times = times[keep]
            gids = gids[keep]
        if len(vals) == 0:
            return empty
        # drop label sets with no surviving rows and RENUMBER densely,
        # labels sorted by label tuple (prom output order); the single
        # lexsort below establishes the kernel's series-then-time order.
        # The label-dict assembly (~3us/series) caches on the plan
        # entry: warm dashboards over unchanged storage reuse it
        present = np.zeros(G, dtype=bool)
        present[gids] = True
        pkey = present.tobytes()
        aux = self._label_cache.get(plan_key)
        if aux is not None and aux[0] == pkey:
            labels, remap = aux[1], aux[2]
        else:
            key_of = [None] * G
            for key, gi in global_groups.items():
                key_of[gi] = key
            order_g = sorted((gi for gi in range(G) if present[gi]),
                             key=lambda gi: key_of[gi])
            remap = np.full(G, -1, dtype=np.int64)
            labels = []
            for new_gi, gi in enumerate(order_g):
                remap[gi] = new_gi
                ls = {k: v for k, v in zip(tag_keys, key_of[gi]) if v}
                ls["__name__"] = vs.name
                labels.append(ls)
            self._label_cache[plan_key] = (pkey, labels, remap)
            while len(self._label_cache) > 8:
                self._label_cache.popitem(last=False)
        gids = remap[gids]
        order = np.lexsort((times, gids))
        return (labels, vals[order], times[order], gids[order])

    def _window_states(self, vs: VectorSelector, start_ns, end_ns, step_ns,
                       window_ns, lookback_ns=DEFAULT_LOOKBACK_NS):
        """Shared selector machinery: (labels, BucketState (S, nsteps),
        window_end_times (nsteps,)). Window = (t_i - window, t_i]."""
        nsteps = int((end_ns - start_ns) // step_ns) + 1
        if vs.at_ns is not None:
            # @-pinned selector: ONE evaluation at the pinned time,
            # tiled across the query grid. Pinning here (not at the
            # function level) keeps sibling scalar arguments on the
            # outer grid.
            from dataclasses import replace as _rep
            labels, win, ends, origin, anchor = self._window_states(
                _rep(vs, at_ns=None), vs.at_ns, vs.at_ns, step_ns,
                window_ns, lookback_ns)
            if win is None or nsteps == 1:
                return labels, win, ends, origin, anchor
            win = K.BucketState(*[np.repeat(np.asarray(x), nsteps,
                                            axis=1) for x in win])
            return (labels, win, np.repeat(ends, nsteps, axis=1),
                    origin, anchor)
        off = vs.offset_ns
        if nsteps == 1:
            # single eval point: one bucket of exactly the window width
            bs, k, stride = window_ns, 1, 1
        else:
            # bucket width: gcd so window edges align; cap fold size
            bs = math.gcd(step_ns, window_ns)
            k = window_ns // bs
            if k > _MAX_FOLD:
                bs = step_ns
                k = -(-window_ns // bs)  # ceil: rounds window UP to grid
            if k > _MAX_FOLD:
                raise PromQLError(
                    f"window {window_ns/1e9:.0f}s at step "
                    f"{step_ns/1e9:.0f}s needs {k} merge folds "
                    f"(max {_MAX_FOLD}); use a larger step")
        stride = step_ns // bs if nsteps > 1 else 1
        # bucket right-edges at origin + (j+1)*bs; eval t_i at bucket
        # index k-1 + i*stride  relative to origin = start - window
        origin = start_ns - off - (k * bs)
        t_lo = origin + 1
        t_hi = end_ns - off
        if isinstance(vs, Subquery):
            labels, values, times, series = self._subquery_samples(
                vs, t_lo, t_hi, lookback_ns)
        else:
            labels, values, times, series = self._gather(vs, t_lo, t_hi)
        S = len(labels)
        if S == 0:
            return [], None, None, origin, None
        # per-series value anchor (first sample) shifts the second-order
        # sums in the kernel — large-magnitude gauges would otherwise
        # cancel catastrophically in variance/regression
        anchor = values[np.searchsorted(series, np.arange(S))]
        nb = k + (nsteps - 1) * stride
        bucket = (times - origin - 1) // bs
        # bucketed shapes: row count and series count both pad so the
        # jit cache recurs across queries/data sizes (an unpadded 1M-
        # series query would recompile the fused kernel per shape —
        # measured 15s of XLA compile per distinct S)
        from ..ops.segment_agg import pad_bucket
        S_pad = pad_bucket(S, minimum=64)
        n = len(values)
        n_pad = pad_bucket(n)
        st = None
        if (n_pad >= PROM_DEVICE_MIN_ROWS
                and n_pad > PROM_DEVICE_CHUNK_ROWS):
            # very large folds run in SERIES CHUNKS before any full-
            # length padding is built: until aggregation every state is
            # per-series, so chunk states concatenate exactly. One
            # unchunked 60M-row launch allocated input copies + a
            # 15-plane segment grid past the tunnel-attached chip's
            # HBM and CRASHED the TPU worker (observed at 1M series).
            # None → a single series exceeds the chunk cap (cannot
            # split: states for one series would need merging, not
            # concatenation) — the host fold below handles any size
            st = self._bucket_states_chunked(
                values, times, series, bucket, n, nb, S, origin,
                anchor)
        if st is None:
            seg = np.where((bucket >= 0) & (bucket < nb),
                           series * nb + bucket, S_pad * nb)
            valid = np.ones(n_pad, dtype=bool)
            if n_pad != n:
                valid[n:] = False
                pad = n_pad - n
                values = np.pad(values, (0, pad))
                times = np.pad(times, (0, pad))
                series = np.pad(series, (0, pad),
                                constant_values=S_pad - 1)
                seg = np.pad(seg, (0, pad),
                             constant_values=S_pad * nb)
            anchor_rows = np.pad(anchor[series[:n]], (0, n_pad - n)) \
                if n_pad != n else anchor[series]
            if (n_pad < PROM_DEVICE_MIN_ROWS
                    or n_pad > PROM_DEVICE_CHUNK_ROWS):
                # host fold: on tunnel-attached chips the device
                # kernel's 15 pulled state arrays each pay a full
                # transfer round trip; realistic prom shapes (high
                # cardinality, few rows per series) fold faster in
                # numpy. Also the safety net for folds too big to
                # launch whole and unchunkable (one giant series)
                st = K.bucket_states_host(values, valid, times, seg,
                                          series, S_pad * nb,
                                          origin_t=origin,
                                          value_anchor=anchor_rows)
            else:
                import jax
                st = K.bucket_states(values, valid, times, seg,
                                     series, S_pad * nb,
                                     origin_t=origin,
                                     value_anchor=anchor_rows)
                st = K.BucketState(
                    *jax.device_get(tuple(st)))    # ONE pull
            st = K.BucketState(*[np.asarray(x).reshape(S_pad, nb)[:S]
                                 for x in st])
        win = K.fold_windows_host(st, int(k))
        # slice eval positions: indices k-1, k-1+stride, ...
        sel = (k - 1) + stride * np.arange(nsteps)
        win = K.BucketState(*[np.asarray(x)[:, sel] for x in win])
        ends = (start_ns - off + step_ns * np.arange(nsteps)).astype(
            np.int64)
        return (labels, win, np.broadcast_to(ends, (S, nsteps)), origin,
                anchor.reshape(S, 1))

    def _bucket_states_chunked(self, values, times, series, bucket,
                               n: int, nb: int, S: int, origin: int,
                               anchor) -> "K.BucketState":
        """Device bucket-state fold in bounded series chunks (rows are
        series-sorted from _gather): each chunk re-bases series ids to
        a local range, runs the same jitted kernel on a bounded
        segment grid, and the per-chunk states concatenate along the
        series axis — identical to the one-launch result. ``n`` is the
        TRUE row count (callers may hand padded arrays; pad rows are
        never sliced — each chunk re-pads itself). Returns None when a
        single series exceeds the chunk cap (caller: host fold)."""
        import jax

        from ..ops.segment_agg import pad_bucket
        rows_cap = PROM_DEVICE_CHUNK_ROWS
        # chunk boundaries on series edges (first row of each series);
        # the sentinel n entry lets the search return S for the final
        # chunk instead of always splitting the last series off
        firsts = np.concatenate([
            np.searchsorted(series[:n], np.arange(S)),
            np.array([n], dtype=np.int64)])
        spans: list = []
        s0 = 0
        while s0 < S:
            s1 = int(np.searchsorted(
                firsts, firsts[s0] + rows_cap, side="right")) - 1
            s1 = min(max(s1, s0 + 1), S)
            if int(firsts[s1]) - int(firsts[s0]) > rows_cap:
                # a single series wider than the cap cannot chunk
                # (its states would need merging, not concatenation):
                # signal the caller to take the host fold
                return None
            spans.append((s0, s1, int(firsts[s0]), int(firsts[s1])))
            s0 = s1
        # UNIFORM padded shapes across chunks: one jit compile serves
        # every launch (per-chunk shapes cost ~15s of XLA compile each)
        sc_pad = pad_bucket(max(s1 - s0 for s0, s1, _r0, _r1 in spans),
                            minimum=64)
        nc_pad = pad_bucket(max(r1 - r0 for _s0, _s1, r0, r1 in spans))
        parts: list = []
        for s0, s1, r0, r1 in spans:
            sc, nc = s1 - s0, r1 - r0
            pad = nc_pad - nc
            vals_c = np.pad(values[r0:r1], (0, pad))
            times_c = np.pad(times[r0:r1], (0, pad))
            ser_c = np.pad(series[r0:r1] - s0, (0, pad),
                           constant_values=sc_pad - 1)
            bkt_c = bucket[r0:r1]
            seg_c = np.pad(
                np.where((bkt_c >= 0) & (bkt_c < nb),
                         (series[r0:r1] - s0) * nb + bkt_c,
                         sc_pad * nb),
                (0, pad), constant_values=sc_pad * nb)
            valid_c = np.ones(nc_pad, dtype=bool)
            if pad:
                valid_c[nc:] = False
            anchor_c = np.pad(anchor[s0:s1][ser_c[:nc]], (0, pad))
            stc = K.bucket_states(vals_c, valid_c, times_c, seg_c,
                                  ser_c, sc_pad * nb, origin_t=origin,
                                  value_anchor=anchor_c)
            stc = K.BucketState(*jax.device_get(tuple(stc)))
            parts.append(K.BucketState(
                *[np.asarray(x).reshape(sc_pad, nb)[:sc]
                  for x in stc]))
        return K.BucketState(*[np.concatenate(
            [getattr(p, f) for p in parts], axis=0)
            for f in K.BucketState._fields])

    def _eval_selector_instant(self, vs, start_ns, end_ns, step_ns,
                               lookback_ns) -> SeriesMatrix:
        # @-pinning happens inside _window_states (selector level)
        labels, win, _ends, _origin, _anchor = self._window_states(
            vs, start_ns, end_ns, step_ns, lookback_ns)
        if win is None:
            return SeriesMatrix([], np.zeros((0, 1)))
        vals = np.asarray(K.over_time_value(win, "last_over_time"))
        return SeriesMatrix(labels, vals)

    # ---- functions -------------------------------------------------------

    def _scalar_arg(self, e, start_ns, end_ns, step_ns, lookback_ns,
                    nsteps) -> np.ndarray:
        """Evaluate an argument that must be a scalar → per-step row."""
        v = self._eval(e, start_ns, end_ns, step_ns, lookback_ns)
        if isinstance(v, float):
            return np.full(nsteps, v)
        if isinstance(v, ScalarSteps):
            return v.values
        raise PromQLError("expected a scalar argument")

    def _eval_func(self, fc: FuncCall, start_ns, end_ns, step_ns,
                   lookback_ns):
        f = fc.func
        nsteps = int((end_ns - start_ns) // step_ns) + 1
        step_ts = (start_ns + step_ns * np.arange(nsteps)) / 1e9

        def scal(e):
            return self._scalar_arg(e, start_ns, end_ns, step_ns,
                                    lookback_ns, nsteps)

        def vec(e) -> SeriesMatrix:
            v = self._eval(e, start_ns, end_ns, step_ns, lookback_ns)
            if isinstance(v, (float, ScalarSteps)):
                raise PromQLError(f"{f}() expects an instant vector")
            return v

        if f in RANGE_FUNCS:
            return self._eval_range_func(fc, start_ns, end_ns, step_ns,
                                         nsteps, lookback_ns)
        if f == "time":
            if fc.args:
                raise PromQLError("time() takes no arguments")
            return ScalarSteps(step_ts.copy())
        if f == "pi":
            return float(np.pi)
        if f == "vector":
            if len(fc.args) != 1:
                raise PromQLError("vector() expects 1 argument")
            row = scal(fc.args[0])
            return SeriesMatrix([{}], row.reshape(1, -1), True)
        if f == "scalar":
            inner = self._eval(fc.args[0], start_ns, end_ns, step_ns,
                               lookback_ns)
            if isinstance(inner, float):
                return inner
            if isinstance(inner, ScalarSteps):
                return inner
            if len(inner.labels) == 1:
                return ScalarSteps(inner.values[0].copy())
            return ScalarSteps(np.full(nsteps, np.nan))
        if f in _ELEMENTWISE:
            if f == "round" and len(fc.args) == 2:
                # round(v, to_nearest): round to the nearest multiple
                # (upstream promql round's optional second argument)
                near = scal(fc.args[1])
                inner = self._eval(fc.args[0], start_ns, end_ns,
                                   step_ns, lookback_ns)
                with np.errstate(all="ignore"):
                    fn2 = (lambda x: np.floor(
                        np.asarray(x) / near + 0.5) * near)
                    if isinstance(inner, float):
                        out = fn2(inner)
                        # `near` may vary per step (range query):
                        # a scalar inner then yields per-step scalars
                        return (float(out) if np.ndim(out) == 0
                                else ScalarSteps(np.asarray(out)))
                    if isinstance(inner, ScalarSteps):
                        return ScalarSteps(fn2(inner.values))
                    return SeriesMatrix(
                        [{k: v for k, v in ls.items()
                          if k != "__name__"}
                         for ls in inner.labels],
                        fn2(inner.values), True)
            if len(fc.args) != 1:
                raise PromQLError(f"{f}() expects 1 argument")
            inner = self._eval(fc.args[0], start_ns, end_ns, step_ns,
                               lookback_ns)
            fn = _ELEMENTWISE[f]
            with np.errstate(all="ignore"):
                if isinstance(inner, float):
                    return float(fn(inner))
                if isinstance(inner, ScalarSteps):
                    return ScalarSteps(fn(inner.values))
                return SeriesMatrix(inner.labels, fn(inner.values),
                                    inner.metric_dropped).drop_metric()
        if f in ("clamp_min", "clamp_max", "clamp"):
            inner = vec(fc.args[0])
            with np.errstate(all="ignore"):
                if f == "clamp":
                    if len(fc.args) != 3:
                        raise PromQLError("clamp(v, min, max) expected")
                    lo, hi = scal(fc.args[1]), scal(fc.args[2])
                    vals = np.clip(inner.values, lo, np.maximum(lo, hi))
                    vals = np.where(lo <= hi, vals, np.nan)
                else:
                    lim = scal(fc.args[1])
                    op = np.maximum if f == "clamp_min" else np.minimum
                    vals = op(inner.values, lim)
            return SeriesMatrix(inner.labels, vals,
                                inner.metric_dropped).drop_metric()
        if f in ("sort", "sort_desc"):
            inner = vec(fc.args[0])
            key = inner.values[:, -1] if inner.values.size else \
                np.zeros(0)
            key = np.where(np.isnan(key), -np.inf, key)
            order = np.argsort(-key if f == "sort_desc" else key,
                               kind="stable")
            return SeriesMatrix([inner.labels[i] for i in order],
                                inner.values[order],
                                inner.metric_dropped)
        if f == "timestamp":
            arg = fc.args[0] if fc.args else None
            if isinstance(arg, VectorSelector) and not arg.range_ns:
                labels, win, _e, _o, _a = self._window_states(
                    arg, start_ns, end_ns, step_ns, lookback_ns)
                if win is None:
                    return SeriesMatrix([], np.zeros((0, nsteps)), True)
                vals = np.where(np.asarray(win.count) > 0,
                                np.asarray(win.last_t) / 1e9, np.nan)
                return SeriesMatrix(labels, vals).drop_metric()
            inner = vec(arg)
            vals = np.where(np.isnan(inner.values), np.nan, step_ts)
            return SeriesMatrix(inner.labels, vals,
                                inner.metric_dropped).drop_metric()
        if f == "absent":
            inner = self._eval(fc.args[0], start_ns, end_ns, step_ns,
                               lookback_ns)
            if isinstance(inner, (float, ScalarSteps)):
                raise PromQLError("absent() expects an instant vector")
            present = (~np.isnan(inner.values)).any(axis=0) \
                if inner.values.size else np.zeros(nsteps, bool)
            vals = np.where(present, np.nan, 1.0).reshape(1, -1)
            ls = _absent_labels(fc.args[0])
            return SeriesMatrix([ls], vals, True)
        if f == "histogram_quantile":
            if len(fc.args) != 2:
                raise PromQLError("histogram_quantile(φ, vector) expected")
            q = scal(fc.args[0])
            inner = vec(fc.args[1])
            return _histogram_quantile(q, inner, nsteps)
        if f == "label_replace":
            if len(fc.args) != 5:
                raise PromQLError("label_replace(v, dst, repl, src, "
                                  "regex) expected")
            inner = vec(fc.args[0])
            dst, repl, src, regex = (_str_arg(a, f) for a in fc.args[1:])
            return _label_replace(inner, dst, repl, src, regex)
        if f == "label_join":
            if len(fc.args) < 3:
                raise PromQLError("label_join(v, dst, sep, src...) "
                                  "expected")
            inner = vec(fc.args[0])
            dst, sep = _str_arg(fc.args[1], f), _str_arg(fc.args[2], f)
            srcs = [_str_arg(a, f) for a in fc.args[3:]]
            out = []
            for ls in inner.labels:
                ls = dict(ls)
                val = sep.join(ls.get(s, "") for s in srcs)
                if val:
                    ls[dst] = val
                else:
                    ls.pop(dst, None)
                out.append(ls)
            return SeriesMatrix(out, inner.values, inner.metric_dropped)
        if f in _TIME_COMPONENT:
            if fc.args:
                inner = self._eval(fc.args[0], start_ns, end_ns, step_ns,
                                   lookback_ns)
            else:
                inner = ScalarSteps(step_ts.copy())
            comp = _TIME_COMPONENT[f]
            if isinstance(inner, float):
                return float(_calendar(np.array([inner]), comp)[0])
            if isinstance(inner, ScalarSteps):
                return SeriesMatrix([{}],
                                    _calendar(inner.values,
                                              comp).reshape(1, -1), True)
            vals = _calendar(inner.values, comp)
            return SeriesMatrix(inner.labels, vals,
                                inner.metric_dropped).drop_metric()
        raise PromQLError(f"unsupported function {f}()")

    def _eval_range_func(self, fc: FuncCall, start_ns, end_ns, step_ns,
                         nsteps, lookback_ns):
        f = fc.func
        # locate the range-vector argument; side scalars per function
        q_row = t_pred = None
        if f == "quantile_over_time":
            if len(fc.args) != 2:
                raise PromQLError("quantile_over_time(φ, v[d]) expected")
            q_row = self._scalar_arg(fc.args[0], start_ns, end_ns,
                                     step_ns, lookback_ns, nsteps)
            vs = fc.args[1]
        elif f == "predict_linear":
            if len(fc.args) != 2:
                raise PromQLError("predict_linear(v[d], t) expected")
            vs = fc.args[0]
            t_pred = self._scalar_arg(fc.args[1], start_ns, end_ns,
                                      step_ns, lookback_ns, nsteps)
        else:
            if len(fc.args) != 1:
                raise PromQLError(f"{f}() expects a range vector selector")
            vs = fc.args[0]
        if not isinstance(vs, (VectorSelector, Subquery)) \
                or not vs.range_ns:
            raise PromQLError(f"{f}() expects a range like {f}(x[5m])")

        if f in ("irate", "idelta"):
            labels, vals = self._irate(vs, start_ns, end_ns, step_ns, f,
                                       lookback_ns)
            return SeriesMatrix(labels, vals).drop_metric()
        if f == "quantile_over_time":
            labels, vals = self._quantile_over_time(
                vs, q_row, start_ns, end_ns, step_ns, nsteps,
                lookback_ns)
            return SeriesMatrix(labels, vals).drop_metric()

        labels, win, ends, origin, anchor = self._window_states(
            vs, start_ns, end_ns, step_ns, vs.range_ns, lookback_ns)
        if win is None:
            if f == "absent_over_time":
                return SeriesMatrix([_absent_labels(vs)],
                                    np.ones((1, nsteps)), True)
            return SeriesMatrix([], np.zeros((0, nsteps)), True)
        if f in ("rate", "increase", "delta"):
            vals = np.asarray(K.prom_rate(win, ends, vs.range_ns, f))
        elif f == "deriv":
            end_rel = (ends - origin) / 1e9
            slope, _ic = K.prom_linreg(win, end_rel, anchor)
            vals = np.asarray(slope)
        elif f == "predict_linear":
            end_rel = (ends - origin) / 1e9
            slope, icept = K.prom_linreg(win, end_rel, anchor)
            # prom anchors the intercept at the EVAL timestamp, which for
            # an offset selector is `offset` past the window end
            vals = (np.asarray(icept)
                    + np.asarray(slope) * (t_pred + vs.offset_ns / 1e9))
        elif f == "absent_over_time":
            present = (np.asarray(win.count) > 0).any(axis=0)
            vals = np.where(present, np.nan, 1.0).reshape(1, -1)
            return SeriesMatrix([_absent_labels(vs)], vals, True)
        else:
            vals = np.asarray(K.over_time_value(win, f, anchor))
        if f in ("last_over_time", "first_over_time"):
            # upstream keeps the metric name for the value-selecting
            # *_over_time functions (they return a raw sample)
            return SeriesMatrix(labels, vals)
        return SeriesMatrix(labels, vals).drop_metric()

    def _host_pass(self, vs: VectorSelector, start_ns, end_ns, step_ns,
                   nsteps, lookback_ns=DEFAULT_LOOKBACK_NS):
        """Raw gather + per-step window masks, for functions whose state
        is not monoid-able into fixed-size buckets (irate's last-two
        samples, exact window quantiles). Window = (t_i - range, t_i],
        offset-adjusted. Returns (labels, values, times, series, masks)
        where masks yields (step index, row mask)."""
        if vs.at_ns is not None:
            # @-pinned: every step evaluates at the pinned time
            from dataclasses import replace as _rep
            at = vs.at_ns
            labels, values, times, series, _m = self._host_pass(
                _rep(vs, at_ns=None), at, at, step_ns, 1, lookback_ns)
            off = vs.offset_ns
            mask = (times > at - off - vs.range_ns) & (times <= at - off)

            def masks_pinned():
                if mask.any():
                    for i in range(nsteps):
                        yield i, mask
            return labels, values, times, series, masks_pinned
        off = vs.offset_ns
        if isinstance(vs, Subquery):
            labels, values, times, series = self._subquery_samples(
                vs, start_ns - off - vs.range_ns + 1, end_ns - off,
                lookback_ns)
        else:
            labels, values, times, series = self._gather(
                vs, start_ns - off - vs.range_ns + 1, end_ns - off)

        def masks():
            for i in range(nsteps):
                t_i = start_ns - off + i * step_ns
                m = (times > t_i - vs.range_ns) & (times <= t_i)
                if m.any():
                    yield i, m
        return labels, values, times, series, masks

    def _quantile_over_time(self, vs, q_row, start_ns, end_ns, step_ns,
                            nsteps, lookback_ns=DEFAULT_LOOKBACK_NS):
        labels, values, times, series, masks = self._host_pass(
            vs, start_ns, end_ns, step_ns, nsteps, lookback_ns)
        if not labels:
            return [], np.zeros((0, nsteps))
        S = len(labels)
        out = np.full((S, nsteps), np.nan)
        for i, m in masks():
            q = q_row[i]
            for si in np.unique(series[m]):
                v = values[m & (series == si)]
                out[si, i] = _prom_quantile(q, v)
        return labels, out

    def _irate(self, vs, start_ns, end_ns, step_ns, f,
               lookback_ns=DEFAULT_LOOKBACK_NS):
        """Dedicated per-eval-point last-two-samples pass (bucket
        granularity can't express 'previous sample')."""
        nsteps = int((end_ns - start_ns) // step_ns) + 1
        labels, values, times, series, masks = self._host_pass(
            vs, start_ns, end_ns, step_ns, nsteps, lookback_ns)
        if not labels:
            return [], np.zeros((0, nsteps))
        S = len(labels)
        out = np.full((S, nsteps), np.nan)
        for i, m in masks():
            seg = np.where(m, series, S)
            last, prev, lt, pt, cnt = (
                K.irate_states_host(values, m, times, seg, S)
                if len(values) < PROM_DEVICE_MIN_ROWS
                else K.irate_states(values, m, times, seg, S))
            out[:, i] = np.asarray(K.prom_irate_value(
                np.asarray(last), np.asarray(prev), np.asarray(lt),
                np.asarray(pt), np.asarray(cnt),
                "idelta" if f == "idelta" else "irate"))
        return labels, out

    # ---- binary ops ------------------------------------------------------

    def _eval_binop(self, b: BinaryOp, start_ns, end_ns, step_ns,
                    lookback_ns):
        lhs = self._eval(b.lhs, start_ns, end_ns, step_ns, lookback_ns)
        rhs = self._eval(b.rhs, start_ns, end_ns, step_ns, lookback_ns)
        l_sc = isinstance(lhs, (float, ScalarSteps))
        r_sc = isinstance(rhs, (float, ScalarSteps))
        if b.op in ("and", "or", "unless"):
            if l_sc or r_sc:
                raise PromQLError(
                    f"set operator {b.op} requires vector operands")
            if b.group_side is not None:
                raise PromQLError(
                    "no grouping allowed for set operations")
            return _set_op(b.op, lhs, rhs, _binop_key(b))
        if l_sc and r_sc:
            if isinstance(lhs, float) and isinstance(rhs, float):
                return _scalar_op(b.op, lhs, rhs)
            lr = lhs.values if isinstance(lhs, ScalarSteps) else lhs
            rr = rhs.values if isinstance(rhs, ScalarSteps) else rhs
            with np.errstate(all="ignore"):
                out = _vec_op(b.op, np.asarray(lr, dtype=np.float64),
                              rr, True)  # scalar cmp is always 0/1
            return ScalarSteps(np.broadcast_to(
                out, np.broadcast_shapes(np.shape(lr), np.shape(rr))
            ).astype(np.float64).reshape(-1))
        if l_sc:
            lv = lhs.values if isinstance(lhs, ScalarSteps) else lhs
            return SeriesMatrix(
                rhs.labels, _vec_op(b.op, lv, rhs.values, b.bool_mode,
                                    scalar_left=True),
                rhs.metric_dropped)._maybe_drop(b)
        if r_sc:
            rv = rhs.values if isinstance(rhs, ScalarSteps) else rhs
            return SeriesMatrix(
                lhs.labels, _vec_op(b.op, lhs.values, rv, b.bool_mode),
                lhs.metric_dropped)._maybe_drop(b)
        # vector-vector matching: one-to-one on the match key (full
        # label set, or on()/ignoring()); many-to-one with
        # group_left/group_right. Filtering comparisons (no bool) pass
        # LHS samples through UNCHANGED, metric name included (upstream
        # semantics); arithmetic and bool-mode drop the name.
        keyf = _binop_key(b)
        keep_name = b.op in ("==", "!=", ">", "<", ">=", "<=") \
            and not b.bool_mode
        nsteps_out = lhs.values.shape[1] if lhs.values.size else (
            rhs.values.shape[1] if rhs.values.size else 1)
        if b.group_side is not None:
            many, one = ((lhs, rhs) if b.group_side == "left"
                         else (rhs, lhs))
            # filtering comparisons (no bool) keep the many side's
            # samples and metric name (upstream filter semantics; for
            # group_right the compared lhs value is the 'one' side,
            # so the name drops)
            keep_name = keep_name and b.group_side == "left"
            omap: dict = {}
            for j, ls in enumerate(one.labels):
                k = keyf(ls)
                if k in omap:
                    raise PromQLError(
                        "many-to-one matching: duplicate series on "
                        "the 'one' side of the match")
                omap[k] = j
            labels, rows = [], []
            seen_out: set = set()
            for i, ls in enumerate(many.labels):
                j = omap.get(keyf(ls))
                if j is None:
                    continue
                mrow = many.values[i:i + 1]
                orow = one.values[j:j + 1]
                lv, rv = ((mrow, orow) if b.group_side == "left"
                          else (orow, mrow))
                rows.append(_vec_op(b.op, lv, rv, b.bool_mode))
                out_ls = (dict(ls) if keep_name else
                          {k: v for k, v in ls.items()
                           if k != "__name__"})
                for g in b.group_labels:
                    if g in one.labels[j]:
                        out_ls[g] = one.labels[j][g]
                    else:
                        out_ls.pop(g, None)
                okey = tuple(sorted(out_ls.items()))
                if okey in seen_out:
                    raise PromQLError(
                        "multiple matches for labels: grouped labels "
                        "must ensure unique output series")
                seen_out.add(okey)
                labels.append(out_ls)
            if not rows:
                return SeriesMatrix([], np.zeros((0, nsteps_out)), True)
            return SeriesMatrix(labels, np.vstack(rows), not keep_name)
        rmap: dict = {}
        for j, ls in enumerate(rhs.labels):
            k = keyf(ls)
            if k in rmap and b.match_on is not None:
                raise PromQLError(
                    "found duplicate series for the match group on "
                    "the right side; use group_left/group_right")
            rmap[k] = j
        seen_l: set = set()
        labels, rows = [], []
        for i, ls in enumerate(lhs.labels):
            k = keyf(ls)
            j = rmap.get(k)
            if j is None:
                continue
            if k in seen_l:
                raise PromQLError(
                    "found duplicate series for the match group on "
                    "the left side; use group_left/group_right")
            seen_l.add(k)
            rows.append(_vec_op(b.op, lhs.values[i:i+1],
                                rhs.values[j:j+1], b.bool_mode))
            if keep_name:
                labels.append(dict(ls))
            elif b.match_on is None:
                labels.append({k2: v for k2, v in ls.items()
                               if k2 != "__name__"})
            else:
                # on()/ignoring(): result carries the match-group labels
                labels.append(dict(k))
        if not rows:
            return SeriesMatrix([], np.zeros((0, nsteps_out)), True)
        return SeriesMatrix(labels, np.vstack(rows), not keep_name)


with np.errstate(all="ignore"):
    _ELEMENTWISE = {
        "abs": np.abs, "ceil": np.ceil, "floor": np.floor,
        "exp": np.exp, "ln": np.log, "log2": np.log2,
        "log10": np.log10, "sqrt": np.sqrt, "round": np.round,
        "sgn": np.sign, "sin": np.sin, "cos": np.cos, "tan": np.tan,
        "asin": np.arcsin, "acos": np.arccos, "atan": np.arctan,
        "sinh": np.sinh, "cosh": np.cosh, "tanh": np.tanh,
        "deg": np.degrees, "rad": np.radians,
    }

_TIME_COMPONENT = {"minute": "minute", "hour": "hour",
                   "day_of_week": "dow", "day_of_month": "dom",
                   "day_of_year": "doy", "month": "month",
                   "year": "year", "days_in_month": "dim"}


def _calendar(vals: np.ndarray, comp: str) -> np.ndarray:
    """UTC calendar components of float-second timestamps (prom time
    functions); NaN-preserving."""
    out = np.full(vals.shape, np.nan)
    ok = ~np.isnan(vals)
    if not ok.any():
        return out
    secs = np.floor(vals[ok]).astype(np.int64)
    if comp == "minute":
        r = (secs // 60) % 60
    elif comp == "hour":
        r = (secs // 3600) % 24
    elif comp == "dow":
        r = (secs // 86400 + 4) % 7       # epoch was a Thursday
    else:
        d = secs.astype("datetime64[s]").astype("datetime64[D]")
        M = d.astype("datetime64[M]")
        Y = d.astype("datetime64[Y]")
        if comp == "dom":
            r = (d - M).astype(np.int64) + 1
        elif comp == "doy":
            r = (d - Y.astype("datetime64[D]")).astype(np.int64) + 1
        elif comp == "month":
            r = (M - Y).astype(np.int64) + 1
        elif comp == "year":
            r = Y.astype(np.int64) + 1970
        else:  # days in month
            r = ((M + 1).astype("datetime64[D]")
                 - M.astype("datetime64[D]")).astype(np.int64)
    out[ok] = r.astype(np.float64)
    return out


def _prom_quantile(q: float, vals: np.ndarray) -> float:
    """Prom quantile semantics (promql/quantile.go): linear interpolation
    between order statistics; out-of-range φ → ±Inf."""
    if np.isnan(q):
        return np.nan
    if q < 0:
        return -np.inf
    if q > 1:
        return np.inf
    if len(vals) == 0:
        return np.nan
    return float(np.quantile(vals, q, method="linear"))


def _absent_labels(e) -> dict:
    """absent()/absent_over_time() result labels: the equality matchers
    of the selector argument (metric name excluded)."""
    if isinstance(e, VectorSelector):
        return {m.name: m.value for m in e.matchers if m.op == "="}
    return {}


def _str_arg(e, fname: str) -> str:
    if not isinstance(e, StringLit):
        raise PromQLError(f"{fname}() expects a string literal here")
    return e.value


def _label_replace(inner: SeriesMatrix, dst: str, repl: str, src: str,
                   regex: str) -> SeriesMatrix:
    import re as _re
    try:
        pat = _re.compile(r"^(?:" + regex + r")$")
    except _re.error as e:
        raise PromQLError(f"label_replace: bad regex: {e}")
    # $1 / ${name} → python backreferences
    py_repl = _re.sub(r"\$(\d+)", r"\\\1", repl)
    py_repl = _re.sub(r"\$\{(\w+)\}", r"\\g<\1>", py_repl)
    out = []
    for ls in inner.labels:
        ls = dict(ls)
        m = pat.match(ls.get(src, ""))
        if m:
            try:
                val = m.expand(py_repl)
            except _re.error as e:
                raise PromQLError(f"label_replace: bad replacement: {e}")
            if val:
                ls[dst] = val
            else:
                ls.pop(dst, None)
        out.append(ls)
    return SeriesMatrix(out, inner.values, inner.metric_dropped)


def _histogram_quantile(q_row: np.ndarray, inner: SeriesMatrix,
                        nsteps: int) -> SeriesMatrix:
    """promql/quantile.go bucketQuantile over le-labelled cumulative
    buckets, grouped by the remaining labels."""
    groups: dict[tuple, list[tuple[float, int]]] = {}
    out_labels: dict[tuple, dict] = {}
    for i, ls in enumerate(inner.labels):
        le = ls.get("le")
        if le is None:
            continue
        try:
            ub = float("inf") if le in ("+Inf", "inf", "Inf") else float(le)
        except ValueError:
            continue
        kept = {k: v for k, v in ls.items()
                if k not in ("le", "__name__")}
        key = tuple(sorted(kept.items()))
        groups.setdefault(key, []).append((ub, i))
        out_labels[key] = kept
    keys = sorted(groups)
    out = np.full((len(keys), nsteps), np.nan)
    for gi, key in enumerate(keys):
        blist = sorted(groups[key])
        les = np.array([b[0] for b in blist])
        if len(les) < 2 or not np.isinf(les[-1]):
            continue  # prom requires an +Inf bucket
        rows = inner.values[[b[1] for b in blist]]     # (NB, nsteps)
        counts = np.maximum.accumulate(
            np.nan_to_num(rows, nan=0.0), axis=0)      # enforce monotone
        total = counts[-1]
        for si in range(nsteps):
            q = q_row[si]
            if np.isnan(q) or total[si] <= 0 \
                    or np.all(np.isnan(rows[:, si])):
                continue
            if q < 0:
                out[gi, si] = -np.inf
                continue
            if q > 1:
                out[gi, si] = np.inf
                continue
            rank = q * total[si]
            b = int(np.argmax(counts[:, si] >= rank))
            if b == len(les) - 1:
                out[gi, si] = les[-2]
                continue
            if b == 0 and les[0] <= 0:
                out[gi, si] = les[0]
                continue
            lo = 0.0 if b == 0 else les[b - 1]
            hi = les[b]
            prev = 0.0 if b == 0 else counts[b - 1, si]
            cnt = counts[b, si] - prev
            if cnt <= 0:
                out[gi, si] = hi
                continue
            out[gi, si] = lo + (hi - lo) * (rank - prev) / cnt
    return SeriesMatrix([out_labels[k] for k in keys], out, True)


_POS_INF = float("inf")
_NEG_INF = float("-inf")


def _fmt(v: float) -> str:
    # plain-float comparisons, not np.isnan/np.isinf: the per-scalar
    # numpy calls cost ~2us each and this runs once per output value
    v = float(v)
    if v != v:
        return "NaN"
    if v == _POS_INF:
        return "+Inf"
    if v == _NEG_INF:
        return "-Inf"
    # upstream prints integral floats without the trailing .0 (the
    # count_values label "300", not "300.0")
    iv = int(v)
    if v == iv and -1e15 < v < 1e15:
        return str(iv)
    return repr(v)


def _scalar_op(op, a, b):
    import operator
    with np.errstate(all="ignore"):
        fns = {"+": operator.add, "-": operator.sub, "*": operator.mul,
               "/": lambda x, y: x / y if y != 0 else math.inf * (1 if x > 0 else -1) if x != 0 else math.nan,
               "%": lambda x, y: math.fmod(x, y) if y != 0 else math.nan,
               "^": operator.pow,
               "==": lambda x, y: 1.0 if x == y else 0.0,
               "!=": lambda x, y: 1.0 if x != y else 0.0,
               ">": lambda x, y: 1.0 if x > y else 0.0,
               "<": lambda x, y: 1.0 if x < y else 0.0,
               ">=": lambda x, y: 1.0 if x >= y else 0.0,
               "<=": lambda x, y: 1.0 if x <= y else 0.0}
        if op not in fns:
            raise PromQLError(f"unsupported scalar op {op}")
        return float(fns[op](a, b))


def _vec_op(op, a, b, bool_mode, scalar_left=False):
    with np.errstate(all="ignore"):
        if op in ("+", "-", "*", "/", "%", "^"):
            fns = {"+": np.add, "-": np.subtract, "*": np.multiply,
                   "/": np.divide, "%": np.fmod, "^": np.power}
            return fns[op](a, b)
        cmp = {"==": np.equal, "!=": np.not_equal, ">": np.greater,
               "<": np.less, ">=": np.greater_equal,
               "<=": np.less_equal}[op]
        mask = cmp(a, b)
        vals = a if not scalar_left else np.broadcast_to(
            b, np.shape(mask)).astype(float)
        if bool_mode:
            out = np.where(np.isnan(vals), np.nan,
                           mask.astype(np.float64))
            return out
        return np.where(mask, vals, np.nan)


SeriesMatrix._maybe_drop = lambda self, b: (
    self.drop_metric() if b.op in ("+", "-", "*", "/", "%", "^",)
    or b.bool_mode else self)


def _lkey(ls: dict) -> tuple:
    return tuple(sorted((k, v) for k, v in ls.items() if k != "__name__"))


def _binop_key(b):
    """Match-key function for a binary op: full label set (sans
    __name__), on(...) labels only, or all-but-ignoring(...)."""
    if b.match_on is None:
        return _lkey
    if b.match_ignoring:
        drop = set(b.match_on) | {"__name__"}
        return lambda ls: tuple(sorted((k, v) for k, v in ls.items()
                                       if k not in drop))
    want = set(b.match_on)
    return lambda ls: tuple(sorted((k, v) for k, v in ls.items()
                                   if k in want))


def _set_op(op: str, lhs: SeriesMatrix, rhs: SeriesMatrix,
            key=_lkey) -> SeriesMatrix:
    """Prom set operators: per-step sample-presence logic over the
    match key (full label set sans __name__, or on()/ignoring()).
    Set ops are MANY-TO-MANY: presence on the other side is the OR
    over every series sharing the key. Labels of surviving series keep
    their metric name (prom keeps lhs elements as-is)."""
    rgroups: dict[tuple, list[int]] = {}
    for j, ls in enumerate(rhs.labels):
        rgroups.setdefault(key(ls), []).append(j)

    def r_present(k):
        """(nsteps,) bool: any rhs series with this key has a sample."""
        js = rgroups.get(k)
        if not js:
            return None
        return ~np.isnan(rhs.values[js]).all(axis=0)

    labels: list[dict] = []
    rows: list[np.ndarray] = []
    if op == "and":
        for i, ls in enumerate(lhs.labels):
            pres = r_present(key(ls))
            if pres is None:
                continue
            labels.append(ls)
            rows.append(np.where(pres, lhs.values[i], np.nan))
    elif op == "unless":
        for i, ls in enumerate(lhs.labels):
            pres = r_present(key(ls))
            labels.append(ls)
            rows.append(lhs.values[i] if pres is None else
                        np.where(pres, np.nan, lhs.values[i]))
    else:  # or
        lgroups: dict[tuple, list[int]] = {}
        for i, ls in enumerate(lhs.labels):
            lgroups.setdefault(key(ls), []).append(i)
        for i, ls in enumerate(lhs.labels):
            labels.append(ls)
            rows.append(lhs.values[i])
        lfull = {_lkey(ls): i for i, ls in enumerate(lhs.labels)}
        for j, ls in enumerate(rhs.labels):
            li = lgroups.get(key(ls))
            if li is None:
                labels.append(ls)
                rows.append(rhs.values[j])
                continue
            # per-step: the rhs element appears only at steps where NO
            # lhs element with the same key has a sample
            lhs_present = ~np.isnan(lhs.values[li]).all(axis=0)
            masked = np.where(lhs_present, np.nan, rhs.values[j])
            fi = lfull.get(_lkey(ls))
            if fi is not None and len(li) == 1 and li[0] == fi:
                # identical full label set: merge into the lhs row
                # (one series per label set in the output; lhs rows
                # occupy indices 0..S_lhs-1 in emission order)
                rows[fi] = np.where(np.isnan(rows[fi]), masked,
                                    rows[fi])
            elif not np.all(np.isnan(masked)):
                labels.append(ls)
                rows.append(masked)
    nsteps = (lhs.values.shape[1] if lhs.values.size else
              (rhs.values.shape[1] if rhs.values.size else 1))
    if not rows:
        return SeriesMatrix([], np.zeros((0, nsteps)), True)
    vals = np.vstack(rows)
    keep = ~np.all(np.isnan(vals), axis=1)
    return SeriesMatrix([ls for ls, k in zip(labels, keep) if k],
                        vals[keep], lhs.metric_dropped)


def _aggregate(agg: Aggregation, inner: SeriesMatrix,
               param=None) -> SeriesMatrix:
    S, B = inner.values.shape if inner.values.size else (0, 1)
    if S == 0:
        return SeriesMatrix([], np.zeros((0, B)), True)
    groups: dict[tuple, list[int]] = {}
    out_labels: dict[tuple, dict] = {}
    for i, ls in enumerate(inner.labels):
        if agg.without:
            kept = {k: v for k, v in ls.items()
                    if k not in agg.grouping and k != "__name__"}
        elif agg.grouping:
            kept = {k: ls[k] for k in agg.grouping if k in ls}
        else:
            kept = {}
        key = tuple(sorted(kept.items()))
        groups.setdefault(key, []).append(i)
        out_labels[key] = kept
    keys = sorted(groups)
    vals = inner.values

    if agg.op in ("topk", "bottomk"):
        # per-step selection WITHIN each group; original series (and their
        # metric names) survive — prom keeps input labels for topk/bottomk
        out = np.full((S, B), np.nan)
        sign = -1.0 if agg.op == "topk" else 1.0
        for key in keys:
            idx = np.array(groups[key])
            sub = vals[idx]                       # (R, B)
            rank = np.argsort(
                np.argsort(np.where(np.isnan(sub), np.inf,
                                    sign * sub), axis=0, kind="stable"),
                axis=0)
            k_row = np.maximum(np.nan_to_num(param, nan=0.0), 0)
            keep = (rank < k_row[None, :]) & ~np.isnan(sub)
            out[idx] = np.where(keep, sub, np.nan)
        alive = ~np.all(np.isnan(out), axis=1)
        return SeriesMatrix(
            [ls for ls, a in zip(inner.labels, alive) if a],
            out[alive], inner.metric_dropped)

    if agg.op == "count_values":
        # one output series per (group, distinct value); the value lands
        # in the `param` label
        rows_out: dict[tuple, np.ndarray] = {}
        label_out: dict[tuple, dict] = {}
        for key in keys:
            sub = vals[groups[key]]
            uniq = np.unique(sub[~np.isnan(sub)])
            for u in uniq:
                cnt = np.sum(sub == u, axis=0).astype(np.float64)
                cnt = np.where(cnt > 0, cnt, np.nan)
                ls = dict(out_labels[key])
                ls[param] = _fmt(u)
                k2 = tuple(sorted(ls.items()))
                prev = rows_out.get(k2)
                if prev is not None:
                    # distinct groups can collapse onto one output label
                    # set (param label shadows a grouped label): sum them
                    tot = np.nansum(np.vstack([prev, cnt]), axis=0)
                    cnt = np.where(np.isnan(prev) & np.isnan(cnt),
                                   np.nan, tot)
                rows_out[k2] = cnt
                label_out[k2] = ls
        ks = sorted(rows_out)
        if not ks:
            return SeriesMatrix([], np.zeros((0, B)), True)
        return SeriesMatrix([label_out[k] for k in ks],
                            np.vstack([rows_out[k] for k in ks]), True)

    out = np.full((len(keys), B), np.nan)
    for gi, key in enumerate(keys):
        rows = vals[groups[key]]
        has = ~np.all(np.isnan(rows), axis=0)
        with np.errstate(all="ignore"):
            if agg.op == "sum":
                r = np.nansum(rows, axis=0)
            elif agg.op == "avg":
                r = np.nanmean(rows, axis=0)
            elif agg.op == "min":
                r = np.nanmin(np.where(np.isnan(rows), np.inf, rows),
                              axis=0)
            elif agg.op == "max":
                r = np.nanmax(np.where(np.isnan(rows), -np.inf, rows),
                              axis=0)
            elif agg.op == "count":
                r = np.sum(~np.isnan(rows), axis=0).astype(np.float64)
            elif agg.op == "group":
                r = np.ones(B)
            elif agg.op in ("stddev", "stdvar"):
                r = np.nanvar(rows, axis=0)
                if agg.op == "stddev":
                    r = np.sqrt(r)
            elif agg.op == "quantile":
                r = np.array([_prom_quantile(
                    param[j], rows[~np.isnan(rows[:, j]), j])
                    for j in range(B)])
            else:
                raise PromQLError(f"unsupported aggregation {agg.op}")
        out[gi] = np.where(has, r, np.nan)
    return SeriesMatrix([out_labels[k] for k in keys], out, True)
