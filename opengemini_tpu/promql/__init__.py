from .parser import parse_promql, PromParseError
from .engine import PromEngine
