from .catalog import Catalog, RetentionPolicy, DownsamplePolicy, StreamTask
