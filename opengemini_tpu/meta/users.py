"""User catalog + authentication.

Role of the reference's user management: users live in the meta catalog
(`lib/util/lifted/influx/meta/data.go` Users, raft-replicated;
`meta_client.go` CreateUser/DropUser/UpdateUser/Authenticate) and the
httpd layer enforces them when `[http] auth-enabled = true`
(handler.go authenticate middleware; credentials via Basic auth or the
u/p query params, influx 1.x style).

Passwords are stored PBKDF2-HMAC-SHA256 (salted, 100k rounds) in a small
json file under the data dir (single node) — the cluster meta store
replicates the same records through raft like any catalog object.

Division of labor vs meta/catalog.py's user records: THIS module is the
node-local authentication engine behind the HTTP layer (hashing,
verification cache, admin flag). The catalog's users/grant/authorized
methods model raft-replicated per-database privileges (reference
meta.Data user ACLs) consumed by cluster-side authorization — the two
deliberately stay separate the way the reference splits httpd auth from
meta ACL storage."""

from __future__ import annotations

import hashlib
import hmac
import json
import os
import secrets
import threading
from dataclasses import dataclass

_ROUNDS = 100_000


def _hash(password: str, salt: bytes) -> bytes:
    return hashlib.pbkdf2_hmac("sha256", password.encode(), salt, _ROUNDS)


@dataclass
class User:
    name: str
    admin: bool = False
    privileges: dict = None          # db -> READ | WRITE | ALL


class UserStore:
    """CREATE USER / DROP USER / SET PASSWORD / authenticate. The first
    user created must be an admin (reference rule: first user bootstraps
    auth)."""

    def __init__(self, path: str | None = None):
        self.path = path
        self._lock = threading.Lock()
        self._users: dict[str, dict] = {}
        self._verified: dict[str, bytes] = {}   # auth fast-path cache
        if path and os.path.exists(path):
            with open(path) as f:
                self._users = json.load(f)

    def _persist(self) -> None:
        if not self.path:
            return
        tmp = self.path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(self._users, f)
        os.replace(tmp, self.path)

    def __len__(self) -> int:
        return len(self._users)

    def create_user(self, name: str, password: str,
                    admin: bool = False) -> None:
        with self._lock:
            if name in self._users:
                raise ValueError(f"user already exists: {name}")
            if not self._users and not admin:
                raise ValueError(
                    "the first user must be created WITH ALL PRIVILEGES")
            salt = secrets.token_bytes(16)
            self._users[name] = {
                "salt": salt.hex(),
                "hash": _hash(password, salt).hex(),
                "admin": bool(admin),
                "privileges": {}}
            self._persist()

    def drop_user(self, name: str) -> None:
        with self._lock:
            if name not in self._users:
                raise ValueError(f"user not found: {name}")
            u = self._users[name]
            if u["admin"] and sum(1 for x in self._users.values()
                                  if x["admin"]) == 1:
                raise ValueError("cannot drop the last admin user")
            del self._users[name]
            self._verified.pop(name, None)
            self._persist()

    def set_password(self, name: str, password: str) -> None:
        with self._lock:
            if name not in self._users:
                raise ValueError(f"user not found: {name}")
            salt = secrets.token_bytes(16)
            self._users[name].update(
                salt=salt.hex(), hash=_hash(password, salt).hex())
            self._verified.pop(name, None)
            self._persist()

    def authenticate(self, name: str, password: str) -> User | None:
        with self._lock:
            u = self._users.get(name)
            cached = self._verified.get(name)
        if u is None:
            # constant-ish time: still hash to avoid user-enum timing
            _hash(password, b"\x00" * 16)
            return None
        # per-request PBKDF2 would burn ~50ms/request: after one full
        # check, remember a fast digest of the presented password
        # (invalidated on set_password/drop_user)
        fast = hashlib.sha256(password.encode()
                              + bytes.fromhex(u["salt"])).digest()
        if cached is not None and hmac.compare_digest(cached, fast):
            return User(name, u["admin"])
        if hmac.compare_digest(_hash(password, bytes.fromhex(u["salt"])),
                               bytes.fromhex(u["hash"])):
            with self._lock:
                self._verified[name] = fast
            return User(name, u["admin"])
        return None

    def users(self) -> list[User]:
        with self._lock:
            return [User(n, u["admin"], dict(u.get("privileges", {})))
                    for n, u in sorted(self._users.items())]

    # ---- per-database privileges (reference GRANT/REVOKE semantics:
    # influxql/parser.go:636,715; enforced by httpd) -------------------

    def grant(self, name: str, db: str | None, privilege: str) -> None:
        """GRANT READ|WRITE|ALL ON db, or admin when db is None."""
        with self._lock:
            u = self._users.get(name)
            if u is None:
                raise ValueError(f"user not found: {name}")
            if db is None:
                u["admin"] = True
            else:
                u.setdefault("privileges", {})[db] = privilege.upper()
            self._persist()

    def revoke(self, name: str, db: str | None,
               privilege: str) -> None:
        """REVOKE on db narrows or removes the db privilege; with db
        None (REVOKE ALL PRIVILEGES FROM u) clears admin (influx 1.x
        rule: the user keeps per-db grants)."""
        with self._lock:
            u = self._users.get(name)
            if u is None:
                raise ValueError(f"user not found: {name}")
            if db is None:
                if u["admin"] and sum(1 for x in self._users.values()
                                      if x["admin"]) == 1:
                    raise ValueError(
                        "cannot revoke admin from the last admin user")
                u["admin"] = False
            else:
                privs = u.setdefault("privileges", {})
                cur = privs.get(db)
                want = privilege.upper()
                if cur is None:
                    pass
                elif want == "ALL" or cur == want:
                    privs.pop(db, None)
                elif cur == "ALL":
                    # ALL minus READ leaves WRITE and vice versa
                    privs[db] = "WRITE" if want == "READ" else "READ"
            self._persist()

    def grants(self, name: str) -> dict:
        with self._lock:
            u = self._users.get(name)
            if u is None:
                raise ValueError(f"user not found: {name}")
            return dict(u.get("privileges", {}))

    def authorized(self, user, db: str, need: str) -> bool:
        """Does `user` hold `need` (READ or WRITE) on `db`?"""
        if user is None:
            return False
        if user.admin:
            return True
        with self._lock:
            u = self._users.get(user.name)
        if u is None:
            return False
        p = u.get("privileges", {}).get(db, "")
        return p == "ALL" or p == need.upper()


def execute_user_statement(store: "UserStore", stmt) -> dict:
    """Shared executor for CREATE USER / DROP USER / SET PASSWORD /
    SHOW USERS — the single implementation behind both the single-node
    QueryExecutor and the HTTP layer's cluster-facade path."""
    from ..query.ast import (CreateUserStatement, DropUserStatement,
                             GrantStatement, RevokeStatement,
                             SetPasswordStatement, ShowGrantsStatement)
    if store is None:
        return {"error": "user management is not available"}
    try:
        if isinstance(stmt, CreateUserStatement):
            store.create_user(stmt.name, stmt.password, stmt.admin)
        elif isinstance(stmt, DropUserStatement):
            store.drop_user(stmt.name)
        elif isinstance(stmt, SetPasswordStatement):
            store.set_password(stmt.name, stmt.password)
        elif isinstance(stmt, GrantStatement):
            store.grant(stmt.user, stmt.on_db, stmt.privilege)
        elif isinstance(stmt, RevokeStatement):
            store.revoke(stmt.user, stmt.on_db, stmt.privilege)
        elif isinstance(stmt, ShowGrantsStatement):
            rows = [[db, p] for db, p in
                    sorted(store.grants(stmt.user).items())]
            return {"series": [
                {"name": "", "columns": ["database", "privilege"],
                 "values": rows}]}
        else:                                  # SHOW USERS
            return {"series": [
                {"name": "", "columns": ["user", "admin"],
                 "values": [[u.name, u.admin] for u in store.users()]}]}
    except ValueError as e:
        return {"error": str(e)}
    return {}
