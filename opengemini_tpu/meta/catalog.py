"""Meta catalog: databases, retention policies, users, downsample policies,
stream tasks, continuous queries, subscriptions.

Role of the reference's ts-meta store (app/ts-meta/meta/store.go over
hashicorp-raft with the data model of lib/util/lifted/influx/meta/data.go).
Single-node deployment persists the catalog as JSON with atomic replace and
fsync; the cluster deployment replicates the same state machine over the
raft log in parallel/cluster (every mutation here is a deterministic apply
of a command dict, so the raft FSM reuses these methods directly).
"""

from __future__ import annotations

import hashlib
import json
import os
import secrets
import threading
from dataclasses import asdict, dataclass, field

from ..utils import get_logger
from ..utils.errors import (ErrDatabaseNotFound,
                            ErrRetentionPolicyNotFound, GeminiError)

log = get_logger(__name__)

INF = 0  # duration 0 = infinite retention (influx semantics)


@dataclass
class RetentionPolicy:
    name: str = "autogen"
    duration_ns: int = INF
    shard_group_duration_ns: int = 7 * 24 * 3600 * 10**9
    replica_n: int = 1
    default: bool = True


@dataclass
class DownsamplePolicy:
    """Rewrite data older than `age_ns` at `interval_ns` resolution
    (reference UpdateDownSampleInfo engine_downsample.go:120; DDL shape
    CreateDownSampleStatement influxql/ast.go:7745)."""
    rp: str
    age_ns: int
    interval_ns: int
    calls: dict = field(default_factory=lambda: {"float": "mean",
                                                 "integer": "sum"})
    duration_ns: int = 0             # retention of downsampled data


@dataclass
class StreamTask:
    """Ingest-time windowed aggregation (reference app/ts-store/stream
    tag_task/time_task). Tasks without group_tags run the time-task fast
    path (one accumulator per window); tasks with group_tags are the
    tag-task shape. ``condition`` filters source rows (tag equality map,
    reference task filters); late rows below the watermark are dropped
    and counted (reference lateness policy)."""
    name: str
    src_measurement: str
    dest_measurement: str
    interval_ns: int
    group_tags: list = field(default_factory=list)
    calls: dict = field(default_factory=dict)   # field -> agg func
    delay_ns: int = 0
    condition: dict = field(default_factory=dict)   # tag -> required value


@dataclass
class ContinuousQuery:
    name: str
    query: str              # full SELECT ... INTO ... text
    every_ns: int
    offset_ns: int = 0
    last_run_ns: int = 0


@dataclass
class Subscription:
    name: str
    db: str
    mode: str               # ALL | ANY
    destinations: list = field(default_factory=list)
    rp: str = "autogen"


class Catalog:
    def __init__(self, path: str | None = None):
        self.path = path
        self._lock = threading.RLock()
        self.databases: dict[str, dict] = {}
        self.users: dict[str, dict] = {}
        self.subscriptions: dict[str, Subscription] = {}
        if path and os.path.exists(path):
            self._load()

    # ---- persistence -----------------------------------------------------

    def _load(self) -> None:
        with open(self.path, "r", encoding="utf-8") as f:
            raw = json.load(f)
        self.databases = raw.get("databases", {})
        self.users = raw.get("users", {})
        self.subscriptions = {
            k: Subscription(**v)
            for k, v in raw.get("subscriptions", {}).items()}

    def save(self) -> None:
        if not self.path:
            return
        with self._lock:
            blob = json.dumps(
                {"databases": self.databases, "users": self.users,
                 "subscriptions": {k: asdict(v) for k, v in
                                   self.subscriptions.items()}},
                indent=1)
            tmp = self.path + ".tmp"
            with open(tmp, "w", encoding="utf-8") as f:
                f.write(blob)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, self.path)

    # ---- databases / RPs -------------------------------------------------

    def create_database(self, name: str,
                        rp: RetentionPolicy | None = None) -> None:
        with self._lock:
            if name not in self.databases:
                rp = rp or RetentionPolicy()
                self.databases[name] = {
                    "retention_policies": {rp.name: asdict(rp)},
                    "default_rp": rp.name,
                    "downsample_policies": [],
                    "stream_tasks": {},
                    "continuous_queries": {},
                }
            self.save()

    def drop_database(self, name: str) -> None:
        with self._lock:
            self.databases.pop(name, None)
            self.save()

    def database(self, name: str) -> dict:
        db = self.databases.get(name)
        if db is None:
            raise ErrDatabaseNotFound(f"database not found: {name}")
        return db

    def retention_policy(self, db: str, rp: str | None = None
                         ) -> RetentionPolicy:
        d = self.database(db)
        rp = rp or d["default_rp"]
        raw = d["retention_policies"].get(rp)
        if raw is None:
            raise ErrRetentionPolicyNotFound(
                f"retention policy not found: {rp}")
        return RetentionPolicy(**raw)

    def create_retention_policy(self, db: str, rp: RetentionPolicy,
                                make_default: bool = False) -> None:
        with self._lock:
            d = self.database(db)
            d["retention_policies"][rp.name] = asdict(rp)
            if make_default or rp.default:
                d["default_rp"] = rp.name
            self.save()

    def alter_retention_policy(self, db: str, name: str, *,
                               duration_ns: int | None = None,
                               shard_group_duration_ns: int | None = None,
                               replica_n: int | None = None,
                               make_default: bool = False) -> None:
        with self._lock:
            d = self.database(db)
            raw = d["retention_policies"].get(name)
            if raw is None:
                raise ErrRetentionPolicyNotFound(
                    f"retention policy not found: {name}")
            if duration_ns is not None:
                raw["duration_ns"] = duration_ns
            if shard_group_duration_ns is not None:
                raw["shard_group_duration_ns"] = shard_group_duration_ns
            if replica_n is not None:
                raw["replica_n"] = replica_n
            if make_default:
                d["default_rp"] = name
            self.save()

    def drop_retention_policy(self, db: str, name: str) -> None:
        with self._lock:
            d = self.database(db)
            d["retention_policies"].pop(name, None)
            if d["default_rp"] == name:
                rps = list(d["retention_policies"])
                d["default_rp"] = rps[0] if rps else ""
            self.save()

    # ---- downsample / stream / CQ ---------------------------------------

    def add_downsample_policy(self, db: str, p: DownsamplePolicy) -> None:
        with self._lock:
            self.database(db)["downsample_policies"].append(asdict(p))
            self.save()

    def downsample_policies(self, db: str) -> list[DownsamplePolicy]:
        return [DownsamplePolicy(**p)
                for p in self.database(db).get("downsample_policies", [])]

    def drop_downsample_policies(self, db: str,
                                 rp: str | None = None) -> int:
        """DROP DOWNSAMPLE ON db[.rp]: remove all (or one rp's)
        policies; returns how many were removed."""
        with self._lock:
            pols = self.database(db).get("downsample_policies", [])
            keep = [p for p in pols
                    if rp is not None and p.get("rp") != rp]
            removed = len(pols) - len(keep)
            self.database(db)["downsample_policies"] = keep
            self.save()
        return removed

    def register_stream(self, db: str, task: StreamTask) -> None:
        with self._lock:
            self.database(db)["stream_tasks"][task.name] = asdict(task)
            self.save()

    def drop_stream(self, db: str, name: str) -> None:
        with self._lock:
            self.database(db)["stream_tasks"].pop(name, None)
            self.save()

    def stream_tasks(self, db: str) -> list[StreamTask]:
        return [StreamTask(**t)
                for t in self.database(db).get("stream_tasks",
                                               {}).values()]

    def register_cq(self, db: str, cq: ContinuousQuery) -> None:
        with self._lock:
            self.database(db)["continuous_queries"][cq.name] = asdict(cq)
            self.save()

    def drop_cq(self, db: str, name: str) -> None:
        with self._lock:
            self.database(db)["continuous_queries"].pop(name, None)
            self.save()

    def continuous_queries(self, db: str) -> list[ContinuousQuery]:
        return [ContinuousQuery(**c)
                for c in self.database(db).get("continuous_queries",
                                               {}).values()]

    def set_cq_last_run(self, db: str, name: str, t_ns: int) -> None:
        with self._lock:
            cqs = self.database(db)["continuous_queries"]
            if name in cqs:
                cqs[name]["last_run_ns"] = t_ns
                self.save()

    # ---- users (reference meta users + httpd auth) ----------------------

    def create_user(self, name: str, password: str,
                    admin: bool = False) -> None:
        with self._lock:
            salt = secrets.token_hex(8)
            self.users[name] = {
                "salt": salt,
                "hash": _hash_pw(password, salt),
                "admin": admin,
                "privileges": {},   # db -> READ|WRITE|ALL
            }
            self.save()

    def drop_user(self, name: str) -> None:
        with self._lock:
            self.users.pop(name, None)
            self.save()

    def authenticate(self, name: str, password: str) -> bool:
        u = self.users.get(name)
        if u is None:
            return False
        return secrets.compare_digest(u["hash"],
                                      _hash_pw(password, u["salt"]))

    def grant(self, user: str, db: str, privilege: str) -> None:
        with self._lock:
            u = self.users.get(user)
            if u is None:
                raise GeminiError(f"user not found: {user}")
            u["privileges"][db] = privilege.upper()
            self.save()

    def authorized(self, user: str, db: str, need: str) -> bool:
        u = self.users.get(user)
        if u is None:
            return False
        if u.get("admin"):
            return True
        p = u["privileges"].get(db, "")
        return p == "ALL" or p == need.upper()

    # ---- subscriptions ---------------------------------------------------

    def create_subscription(self, sub: Subscription) -> None:
        with self._lock:
            self.subscriptions[f"{sub.db}:{sub.name}"] = sub
            self.save()

    def drop_subscription(self, db: str, name: str) -> None:
        with self._lock:
            self.subscriptions.pop(f"{db}:{name}", None)
            self.save()

    def subscriptions_for(self, db: str) -> list[Subscription]:
        return [s for s in self.subscriptions.values() if s.db == db]


def _hash_pw(pw: str, salt: str) -> str:
    return hashlib.pbkdf2_hmac("sha256", pw.encode(), salt.encode(),
                               10_000).hex()
