"""oglint engine: file walking, pragma suppression, rule protocol.

Rules are module-level objects with ``rule_id`` ("R1".."R6"), a
``codes`` doc map and ``check(ctx) -> list[Violation]``. Each gets a
``FileCtx`` per scanned file (parsed AST + source + per-line pragma
set) plus, after all files are parsed, one ``finish(repo)`` pass for
cross-file rules (counter registries, README drift).
"""

from __future__ import annotations

import ast
import os
import re
import tokenize
from dataclasses import dataclass, field

# directories never scanned (tests are exercised code, not hot-path
# invariant surface — and the lint fixtures live there on purpose)
_SKIP_DIRS = {".git", "__pycache__", "tests", ".claude", "node_modules",
              "related"}

_PRAGMA_RE = re.compile(r"#\s*oglint:\s*(disable=([A-Za-z0-9_,]+)"
                        r"|skip-file)")


@dataclass(order=True)
class Violation:
    path: str
    line: int
    code: str
    msg: str = field(compare=False)

    def __str__(self):
        return f"{self.path}:{self.line}: {self.code} {self.msg}"


class FileCtx:
    """One parsed file: AST, raw source and pragma suppressions."""

    def __init__(self, root: str, path: str):
        self.root = root
        self.path = path                       # repo-relative, posix
        self.abspath = os.path.join(root, path)
        with open(self.abspath, "rb") as f:
            raw = f.read()
        self.source = raw.decode("utf-8", errors="replace")
        self.lines = self.source.splitlines()
        self.tree = ast.parse(self.source, filename=path)
        self.skip_file = False
        # line → set of disabled rule prefixes ("R1", "R103", ...)
        self.disabled: dict[int, set] = {}
        self._scan_pragmas(raw)

    def _scan_pragmas(self, raw: bytes) -> None:
        """Tokenize for comments (string literals containing 'oglint:'
        must not suppress anything)."""
        import io
        try:
            toks = tokenize.tokenize(io.BytesIO(raw).readline)
            for tok in toks:
                if tok.type != tokenize.COMMENT:
                    continue
                m = _PRAGMA_RE.search(tok.string)
                if not m:
                    continue
                if m.group(1) == "skip-file":
                    self.skip_file = True
                    continue
                rules = {r.strip().upper()
                         for r in m.group(2).split(",") if r.strip()}
                self.disabled.setdefault(tok.start[0], set()).update(
                    rules)
        except tokenize.TokenError:
            pass

    def suppressed(self, line: int, code: str) -> bool:
        dis = self.disabled.get(line)
        if not dis:
            return False
        # "R1" disables every R1xx code; "R103" only itself
        return any(code.startswith(d) for d in dis)


class Rule:
    rule_id = "R?"
    codes: dict[str, str] = {}

    def check(self, ctx: FileCtx) -> list[Violation]:  # per file
        return []

    def finish(self, repo: "Repo") -> list[Violation]:  # cross-file
        return []


class Repo:
    """All parsed files plus shared lookups rules build during check()
    and consume in finish()."""

    def __init__(self, root: str, ctxs: list[FileCtx]):
        self.root = root
        self.ctxs = ctxs
        self.shared: dict = {}


def collect_files(root: str, paths: list[str] | None = None) -> list[str]:
    """Repo-relative paths of every scannable .py file. ``paths``
    restricts to explicit files/dirs (still repo-relative)."""
    if paths:
        out = []
        for p in paths:
            a = os.path.join(root, p)
            if os.path.isdir(a):
                out.extend(collect_files(root, [
                    os.path.join(p, f) for f in sorted(os.listdir(a))]))
            elif p.endswith(".py"):
                out.append(p.replace(os.sep, "/"))
        return out
    out = []
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = sorted(d for d in dirnames
                             if d not in _SKIP_DIRS
                             and not d.startswith("."))
        rel = os.path.relpath(dirpath, root)
        for f in sorted(filenames):
            if not f.endswith(".py"):
                continue
            p = f if rel == "." else os.path.join(rel, f)
            out.append(p.replace(os.sep, "/"))
    return out


def default_rules() -> list[Rule]:
    from .counter_rule import CounterRule
    from .deadline_rule import DeadlineRule
    from .durability_rule import DurabilityRule
    from .fault_rule import FaultRule
    from .jit_rule import JitRule
    from .knob_rule import KnobRule
    from .launch_rule import LaunchRule
    from .lockrank_rule import LockRankRule
    from .trace_rule import TraceRule
    from .transfer_rule import TransferRule
    return [TransferRule(), KnobRule(), DeadlineRule(),
            LockRankRule(), TraceRule(), CounterRule(),
            FaultRule(), DurabilityRule(), JitRule(), LaunchRule()]


def run_lint(root: str, rules: list[Rule] | None = None,
             paths: list[str] | None = None) -> list[Violation]:
    """Run ``rules`` (default: all ten classes) over the repo at
    ``root``; returns sorted, pragma-filtered violations."""
    rules = rules if rules is not None else default_rules()
    ctxs = []
    violations: list[Violation] = []
    for p in collect_files(root, paths):
        try:
            ctx = FileCtx(root, p)
        except (SyntaxError, OSError) as e:
            violations.append(Violation(p, getattr(e, "lineno", 0) or 0,
                                        "R000", f"unparseable: {e}"))
            continue
        if ctx.skip_file:
            continue
        ctxs.append(ctx)
    repo = Repo(root, ctxs)
    for ctx in ctxs:
        for rule in rules:
            for v in rule.check(ctx):
                if not ctx.suppressed(v.line, v.code):
                    violations.append(v)
    for rule in rules:
        for v in rule.finish(repo):
            ctx = next((c for c in ctxs if c.path == v.path), None)
            if ctx is None or not ctx.suppressed(v.line, v.code):
                violations.append(v)
    return sorted(violations)


# ------------------------------------------------------- AST helpers

def dotted(node: ast.AST) -> str:
    """'a.b.c' for Name/Attribute chains, '' otherwise."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    if isinstance(node, ast.Call):
        # __import__("os").environ
        f = node.func
        if isinstance(f, ast.Name) and f.id == "__import__" \
                and node.args and isinstance(node.args[0], ast.Constant):
            parts.append(str(node.args[0].value))
            return ".".join(reversed(parts))
    return ""


def const_str(node: ast.AST) -> str | None:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def walk_calls(tree: ast.AST):
    for node in ast.walk(tree):
        if isinstance(node, ast.Call):
            yield node
