"""R5 — trace purity: no host state inside jit-traced code.

Functions reachable from a ``jax.jit`` site execute at TRACE time:
an ``os.environ`` read there bakes the value of the first trace into
the compiled kernel forever (a flipped knob silently does nothing —
or worse, does something on the next cache miss); a lock acquire or
RNG call runs once per compilation, not per execution, which is
near-impossible to reason about. The "Control Flow Duplication for
Columnar Arrays" reference (PAPERS.md) makes the same demand of
columnar kernels: host-side control flow stays OUT of the kernel.

Detection rides the shared reachability walker (``lint/jitwalk.py``,
also used by R9): jit roots are functions decorated ``@jax.jit`` /
``@functools.partial(jax.jit, ...)``, passed to a ``jax.jit(...)``
call by name, or Pallas kernels passed to ``pl.pallas_call(...)``;
the rule then walks same-module functions a root calls by name
(one-module transitive closure — cross-module helpers are ops-layer
jnp code in practice).

Code R501 flags, inside traced code: environment reads (including
``knobs.get``), lock use (``threading.*``/``.acquire``), RNG
(``random``/``np.random``), wall clocks (``time.*``), I/O
(``open``/``print``), and writes to module-level state
(``global`` declarations, subscript stores on module-level names).
"""

from __future__ import annotations

import ast

from .core import FileCtx, Rule, Violation, dotted
from .jitwalk import module_assign_names, traced_functions

_SCOPE = ("opengemini_tpu/",)

_BANNED_PREFIXES = ("os.environ", "os.getenv", "knobs.", "_knobs.",
                    "threading.", "random.", "np.random.",
                    "numpy.random.", "time.")
_BANNED_ATTRS = {"acquire", "release"}
_BANNED_NAMES = {"open", "print", "input"}


class TraceRule(Rule):
    rule_id = "R5"
    codes = {"R501": "host state touched inside jit-traced code"}

    def check(self, ctx: FileCtx) -> list[Violation]:
        if not ctx.path.startswith(_SCOPE):
            return []
        if "jax" not in ctx.source:
            return []
        traced = traced_functions(ctx.tree)
        if not traced:
            return []
        module_names = module_assign_names(ctx.tree)
        out = []
        for tf in traced.values():
            out.extend(self._check_fn(ctx, tf.fn, module_names))
        return out

    def _check_fn(self, ctx, fn, module_names) -> list[Violation]:
        out = []
        for node in ast.walk(fn):
            d = ""
            if isinstance(node, (ast.Attribute, ast.Name)):
                d = dotted(node)
            if d and any(d.startswith(p) for p in _BANNED_PREFIXES):
                out.append(self._v(ctx, node, fn, d))
            elif isinstance(node, ast.Call):
                cd = dotted(node.func)
                if isinstance(node.func, ast.Name) \
                        and node.func.id in _BANNED_NAMES:
                    out.append(self._v(ctx, node, fn, node.func.id))
                elif isinstance(node.func, ast.Attribute) \
                        and node.func.attr in _BANNED_ATTRS \
                        and not cd.startswith(("jnp.", "jax.", "lax.")):
                    out.append(self._v(ctx, node, fn, cd or
                                       node.func.attr))
            elif isinstance(node, ast.Global):
                out.append(self._v(ctx, node, fn,
                                   f"global {', '.join(node.names)}"))
            elif isinstance(node, ast.With):
                for item in node.items:
                    cd = dotted(item.context_expr)
                    if isinstance(item.context_expr, ast.Call):
                        cd = dotted(item.context_expr.func)
                    if "lock" in cd.lower():
                        out.append(self._v(ctx, node, fn,
                                           f"lock {cd!r} held"))
            elif isinstance(node, (ast.Assign, ast.AugAssign)):
                tgts = node.targets if isinstance(node, ast.Assign) \
                    else [node.target]
                for t in tgts:
                    if isinstance(t, ast.Subscript) \
                            and isinstance(t.value, ast.Name) \
                            and t.value.id in module_names:
                        out.append(self._v(
                            ctx, node, fn,
                            f"write to module state "
                            f"{t.value.id!r}"))
        # de-dup per line
        seen, uniq = set(), []
        for v in out:
            if v.line not in seen:
                seen.add(v.line)
                uniq.append(v)
        return uniq

    @staticmethod
    def _v(ctx, node, fn, what) -> Violation:
        return Violation(
            ctx.path, node.lineno, "R501",
            f"{what} inside jit-traced {fn.name}() — traced code "
            "runs at compile time; hoist host state out of the "
            "kernel (see lint/trace_rule.py)")
