"""R7 — device-fault classification discipline in ``ops/``.

The device fault domain (ops/devicefault.py) only works if device
errors actually REACH its classifier: a ``RESOURCE_EXHAUSTED`` or
``XlaRuntimeError`` swallowed by a bare ``except Exception: pass``
never retries, never runs the HBM-pressure ladder, never charges a
route breaker — the query silently degrades (or worse, succeeds with
a hole) and the serving plane learns nothing. PR 9's audit routed the
real offenders (the pipeline drain, the multi-field readiness wait)
through ``devicefault.classify``; this rule keeps new code honest.

Scope: ``opengemini_tpu/ops/`` — the device hot path. A ``try`` body
counts as a *device site* when it performs a launch/pull/fill: any
``jax.*``/``jnp.*`` call, or a call whose dotted name mentions
``device_put`` / ``device_get`` / ``block_until_ready`` /
``put_decoded_planes``.

Codes:
- R701: broad ``except Exception`` (or bare ``except:``) around a
  device launch/pull/fill whose handler neither consults
  ``devicefault.classify`` nor re-raises. Fix: classify and re-raise
  device-classed errors (the pipeline drain idiom), or — when
  swallowing is genuinely correct (fail-closed probes, read-only
  diagnostics) — carry a reviewed ``# oglint: disable=R701`` pragma
  saying why.
"""

from __future__ import annotations

import ast

from .core import FileCtx, Rule, Violation, dotted

_SCOPE = ("opengemini_tpu/ops/",)

# dotted-name substrings that mark a try body as a device
# launch/pull/fill site
_DEVICE_MARKERS = ("device_put", "device_get", "block_until_ready",
                   "put_decoded_planes")
_DEVICE_PREFIXES = ("jax.", "jnp.")


def _is_device_call(name: str) -> bool:
    if not name:
        return False
    if name.startswith(_DEVICE_PREFIXES) or name in ("jax", "jnp"):
        return True
    return any(m in name for m in _DEVICE_MARKERS)


def _broad_handler(h: ast.ExceptHandler) -> bool:
    """``except:``, ``except Exception`` or ``except BaseException``
    (bare or aliased, alone or inside a tuple)."""
    t = h.type
    if t is None:
        return True
    names = []
    if isinstance(t, ast.Tuple):
        names = [dotted(e) for e in t.elts]
    else:
        names = [dotted(t)]
    return any(n in ("Exception", "BaseException") for n in names)


def _handler_classifies(h: ast.ExceptHandler) -> bool:
    """Handler consults the classifier or re-raises: either keeps the
    fault ladder in the loop."""
    for node in ast.walk(h):
        if isinstance(node, ast.Raise):
            return True
        if isinstance(node, ast.Call):
            name = dotted(node.func)
            if name.endswith("classify") or "devicefault" in name:
                return True
    return False


class FaultRule(Rule):
    rule_id = "R7"
    codes = {
        "R701": "broad except around a device launch/pull/fill "
                "swallows faults the classifier must see",
    }

    def check(self, ctx: FileCtx) -> list[Violation]:
        if not any(ctx.path.startswith(d) for d in _SCOPE):
            return []
        out = []
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Try):
                continue
            # device site: any launch/pull/fill call inside the TRY
            # BODY (not the handlers — a handler's own cleanup call
            # does not make the guarded region a device site)
            site = None
            for stmt in node.body:
                for sub in ast.walk(stmt):
                    if isinstance(sub, ast.Call):
                        name = dotted(sub.func)
                        if _is_device_call(name):
                            site = name
                            break
                if site:
                    break
            if not site:
                continue
            for h in node.handlers:
                if not _broad_handler(h):
                    continue
                if _handler_classifies(h):
                    continue
                out.append(Violation(
                    ctx.path, h.lineno, "R701",
                    f"broad except around device site {site}(...) "
                    "swallows device faults: route through "
                    "ops.devicefault.classify (re-raise classified "
                    "errors so the retry/pressure/breaker ladder "
                    "runs), or carry a reviewed "
                    "'# oglint: disable=R701' pragma"))
        return out
