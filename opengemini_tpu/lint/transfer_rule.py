"""R1 — transfer discipline on the device hot path.

Every D2H byte must ride an ACCOUNTED transport: the chunked
multi-stream fetch ``ops.pipeline.device_get_parallel`` (which bumps
the devstats d2h counters) or a site that books its own bytes and says
so with a pragma. A bare ``jax.device_get`` or an implicit
``np.asarray`` on a device value silently moves bytes the /metrics
``d2h_bytes`` counter never sees — on a tunnel-attached TPU that
counter IS the capacity-planning ground truth (BENCH r05 attributed
82% of the query phase to pulls from exactly these numbers).

Scope: the hot-path modules (``opengemini_tpu/ops/*`` and
``query/executor.py``), excluding the accounted transport itself
(ops/pipeline.py) and the counter module (ops/devstats.py).

Codes:
- R101: ``jax.device_get(...)`` — use device_get_parallel.
- R102: ``np.asarray``/``np.array`` over an expression containing a
  ``jnp.*``/``jax.*`` call — an implicit device→host transfer fused
  into host code.
- R103: ``np.asarray``/``np.array`` over an expression mentioning a
  device-named value (``*_dev``, ``dev_*``, ``*_device``…) — the
  naming convention the hot path uses for device residents. A site
  that truly accounts its own bytes carries
  ``# oglint: disable=R103`` next to its devstats bump.
"""

from __future__ import annotations

import ast
import re

from .core import FileCtx, Rule, Violation, dotted

_HOT_DIRS = ("opengemini_tpu/ops/",)
_HOT_FILES = ("opengemini_tpu/query/executor.py",)
_EXEMPT = ("opengemini_tpu/ops/pipeline.py",
           "opengemini_tpu/ops/devstats.py")

_DEVICE_NAME = re.compile(r"(^|_)dev(ice)?(_|$)")
_PULLERS = {"np.asarray", "np.array", "numpy.asarray", "numpy.array"}


def _in_scope(path: str) -> bool:
    if path in _EXEMPT:
        return False
    return path in _HOT_FILES or any(path.startswith(d)
                                     for d in _HOT_DIRS)


class TransferRule(Rule):
    rule_id = "R1"
    codes = {
        "R101": "bare jax.device_get (unaccounted D2H)",
        "R102": "np.asarray/np.array over a jax/jnp expression",
        "R103": "np.asarray/np.array over a device-named value",
    }

    def check(self, ctx: FileCtx) -> list[Violation]:
        if not _in_scope(ctx.path):
            return []
        out = []
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            name = dotted(node.func)
            if name in ("jax.device_get",):
                out.append(Violation(
                    ctx.path, node.lineno, "R101",
                    "bare jax.device_get: route the pull through "
                    "ops.pipeline.device_get_parallel so d2h_bytes "
                    "stays truthful"))
                continue
            if name not in _PULLERS or not node.args:
                continue
            arg = node.args[0]
            jaxcall = self._jax_call_in(arg)
            if jaxcall:
                out.append(Violation(
                    ctx.path, node.lineno, "R102",
                    f"implicit transfer: {name}() over device "
                    f"expression {jaxcall}(...) — pull via "
                    "device_get_parallel, then convert on host"))
                continue
            dev = self._device_name_in(arg)
            if dev:
                out.append(Violation(
                    ctx.path, node.lineno, "R103",
                    f"{name}() over device-named value {dev!r} looks "
                    "like an unaccounted D2H pull — use "
                    "device_get_parallel, or book the bytes into "
                    "devstats and mark the site "
                    "'# oglint: disable=R103'"))
        return out

    @staticmethod
    def _jax_call_in(arg: ast.AST) -> str | None:
        for sub in ast.walk(arg):
            if isinstance(sub, ast.Call):
                d = dotted(sub.func)
                if d.startswith(("jnp.", "jax.")) \
                        and d != "jax.device_put":
                    return d
        return None

    @staticmethod
    def _device_name_in(arg: ast.AST) -> str | None:
        for sub in ast.walk(arg):
            if isinstance(sub, ast.Name) and \
                    _DEVICE_NAME.search(sub.id):
                return sub.id
            if isinstance(sub, ast.Attribute) and \
                    _DEVICE_NAME.search(sub.attr):
                return sub.attr
        return None
