"""R6 — counter hygiene: one metric registry, locked increments.

The metric surface (/metrics, /debug/vars, the stats pusher) is built
from module-level counter dicts. Two invariants keep it trustworthy:

1. **One registry.** Every shared counter dict is declared through
   ``utils.stats.register_counters`` and every metric NAME written at
   a bump site must exist in the dict's literal declaration — a typo'd
   key would silently mint a new metric that no dashboard watches
   while the real one stays flat.
2. **Locked read-modify-write.** ``d[k] += n`` on a shared dict is a
   lost-update race under the threaded HTTP/RPC servers and the pull
   pool (PR 4 measured real drops); increments go through
   ``utils.stats.bump`` (which holds COUNTER_LOCK) or hold a lock at
   the site.

Codes:
- R601: module-level ``*_STATS`` dict not registered via
  register_counters.
- R602: bump with a metric name missing from the dict's declaration
  (checked through module-local wrappers and cross-module aliases —
  ``devstats.bump("d2h_bytez")`` is caught).
- R603: unlocked ``+=``/read-modify-write on a registered counter
  dict or a ``self.stats`` attribute.
- R604: module-level ``*_HIST`` histogram dict not registered via
  utils.stats.register_histograms (the flight-recorder histograms
  share the counter registry's one-namespace rule).
- R605: observe() with a bucket/metric label missing from the
  histogram dict's declaration (same wrapper + cross-module alias
  resolution as R602 — a typo'd label would mint an unwatched
  latency series while the dashboards stay flat).
"""

from __future__ import annotations

import ast
import re

from .core import FileCtx, Repo, Rule, Violation, const_str, dotted

_STATS_NAME = re.compile(r"(_STATS|_PHASE_NS)$")
_HIST_NAME = re.compile(r"_HIST$")
_BUMP_FNS = {"bump", "_b", "_bump", "_bump_stat", "_bump_r",
             "_bump_plane"}
_OBSERVE_FNS = {"observe", "_observe", "hobserve"}


def _dict_literal_keys(node: ast.AST) -> set[str] | None:
    if isinstance(node, ast.Call) and node.args:
        # register_counters("name", {...}) / register_histograms(...)
        d = dotted(node.func)
        if (d.endswith("register_counters")
                or d.endswith("register_histograms")) \
                and len(node.args) >= 2:
            node = node.args[1]
    if isinstance(node, ast.Dict):
        keys = set()
        for k in node.keys:
            s = const_str(k)
            if s is None:
                return None          # computed key: can't verify
            keys.add(s)
        return keys
    return None


class _ModuleInfo:
    """Per-file facts gathered in check(), joined in finish()."""

    def __init__(self):
        self.counter_keys: dict[str, set] = {}   # dict name -> keys
        self.registered: set = set()             # dict names registered
        self.hist_dicts: set = set()             # *_HIST dict names
        # wrapper name -> (dict name, key suffix) for one-arg bumpers
        self.wrappers: dict[str, tuple[str, str]] = {}
        # alias -> module basename for `from . import devstats as _ds`
        self.mod_aliases: dict[str, str] = {}
        self.pending: list = []    # (line, alias, fnname, key)


class CounterRule(Rule):
    rule_id = "R6"
    codes = {
        "R601": "counter dict not registered via register_counters",
        "R602": "bump key missing from the counter declaration",
        "R603": "unlocked read-modify-write on a shared counter",
        "R604": "histogram dict not registered via "
                "register_histograms",
        "R605": "observe label missing from the histogram "
                "declaration",
    }

    def check(self, ctx: FileCtx) -> list[Violation]:
        if not ctx.path.startswith("opengemini_tpu/"):
            return []
        info = _ModuleInfo()
        out: list[Violation] = []
        self._collect_decls(ctx, info, out)
        self._collect_wrappers(ctx, info)
        self._collect_aliases(ctx, info)
        self._check_bumps(ctx, info, out)
        self._check_rmw(ctx, info, out)
        repo_key = "counter_rule.modules"
        # stash for the cross-module finish pass
        ctx_mod = ctx.path.rsplit("/", 1)[-1][:-3]
        self._repo_stash.setdefault(repo_key, {})[ctx_mod] = info
        return out

    # check() instances are fresh per run_lint (default_rules()), so
    # instance state is a safe stash between check() and finish()
    def __init__(self):
        self._repo_stash: dict = {}

    # ------------------------------------------------- declarations

    def _collect_decls(self, ctx, info, out) -> None:
        for node in ctx.tree.body:
            tgt = None
            val = None
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name):
                tgt, val = node.targets[0].id, node.value
            elif isinstance(node, ast.AnnAssign) \
                    and isinstance(node.target, ast.Name):
                tgt, val = node.target.id, node.value
            if tgt is None or val is None:
                continue
            is_hist = bool(_HIST_NAME.search(tgt))
            if not is_hist and not _STATS_NAME.search(tgt):
                continue
            if is_hist:
                # a *_HIST dict must register even when its keys are
                # computed (dict comprehension) — check before the
                # literal-keys gate below
                info.hist_dicts.add(tgt)
                is_reg = isinstance(val, ast.Call) and dotted(
                    val.func).endswith("register_histograms")
                if is_reg:
                    info.registered.add(tgt)
                else:
                    out.append(Violation(
                        ctx.path, node.lineno, "R604",
                        f"histogram dict {tgt} must be declared "
                        "through utils.stats.register_histograms() "
                        "so the metric namespace has one registry"))
                keys = _dict_literal_keys(val)
                if keys is not None:
                    info.counter_keys[tgt] = keys
                continue
            keys = _dict_literal_keys(val)
            if keys is None:
                continue
            info.counter_keys[tgt] = keys
            is_reg = isinstance(val, ast.Call) and dotted(
                val.func).endswith("register_counters")
            if is_reg:
                info.registered.add(tgt)
            else:
                out.append(Violation(
                    ctx.path, node.lineno, "R601",
                    f"counter dict {tgt} must be declared through "
                    "utils.stats.register_counters() so the metric "
                    "namespace has one registry"))

    def _collect_wrappers(self, ctx, info) -> None:
        """def bump(key, n=1): _b(DICT, key [+ '_sfx'], n) wrappers —
        and their histogram twins (observe/_observe)."""
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.FunctionDef) or not node.args.args:
                continue
            param = node.args.args[0].arg
            for sub in ast.walk(node):
                if not isinstance(sub, ast.Call) or len(sub.args) < 2:
                    continue
                if dotted(sub.func).split(".")[-1] not in (
                        "bump", "_b", "observe", "_observe"):
                    continue
                if not isinstance(sub.args[0], ast.Name):
                    continue
                dname = sub.args[0].id
                if dname not in info.counter_keys:
                    continue
                karg = sub.args[1]
                if isinstance(karg, ast.Name) and karg.id == param:
                    info.wrappers[node.name] = (dname, "")
                elif isinstance(karg, ast.BinOp) \
                        and isinstance(karg.op, ast.Add) \
                        and isinstance(karg.left, ast.Name) \
                        and karg.left.id == param \
                        and const_str(karg.right) is not None:
                    info.wrappers[node.name] = (dname,
                                                const_str(karg.right))

    def _collect_aliases(self, ctx, info) -> None:
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.ImportFrom) and node.module \
                    and not node.level == 0:
                for a in node.names:
                    info.mod_aliases[a.asname or a.name] = \
                        node.module.rsplit(".", 1)[-1] \
                        if a.name == "*" else a.name
            elif isinstance(node, ast.ImportFrom):
                for a in node.names:
                    info.mod_aliases[a.asname or a.name] = a.name

    # ------------------------------------------------------- bumps

    def _check_bumps(self, ctx, info, out) -> None:
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            d = dotted(node.func)
            base = d.split(".")[-1] if d else ""
            # two-arg form: bump(DICT, "key") / observe(DICT, "key", v)
            if base in (_BUMP_FNS | _OBSERVE_FNS) \
                    and len(node.args) >= 2 \
                    and isinstance(node.args[0], ast.Name):
                dname = node.args[0].id
                key = const_str(node.args[1])
                keys = info.counter_keys.get(dname)
                if keys is not None and key is not None \
                        and key not in keys:
                    hist = dname in info.hist_dicts
                    out.append(Violation(
                        ctx.path, node.lineno,
                        "R605" if hist else "R602",
                        (f"{'label' if hist else 'metric'} {key!r} is "
                         f"not declared in {dname} — typo'd "
                         f"{'histogram labels' if hist else 'counter names'}"
                         " mint unwatched metrics")))
            # one-arg wrapper in the same module: bump("key")
            elif base in info.wrappers and node.args:
                key = const_str(node.args[0])
                if key is None:
                    continue
                dname, sfx = info.wrappers[base]
                if key + sfx not in info.counter_keys[dname]:
                    out.append(Violation(
                        ctx.path, node.lineno,
                        "R605" if dname in info.hist_dicts else "R602",
                        f"metric {key + sfx!r} is not declared in "
                        f"{dname}"))
            # cross-module: alias.bump("key") — resolved in finish()
            elif "." in d and node.args:
                alias, fnname = d.rsplit(".", 1)
                key = const_str(node.args[0])
                if fnname in (_BUMP_FNS | _OBSERVE_FNS) \
                        and key is not None and "." not in alias:
                    mod = info.mod_aliases.get(alias, alias)
                    info.pending.append(
                        (ctx.path, node.lineno, mod, fnname, key))

    def finish(self, repo: Repo) -> list[Violation]:
        mods = self._repo_stash.get("counter_rule.modules", {})
        out = []
        for info in mods.values():
            for path, line, mod, fnname, key in info.pending:
                target = mods.get(mod)
                if target is None:
                    continue
                wrap = target.wrappers.get(fnname)
                if wrap is None:
                    continue
                dname, sfx = wrap
                if key + sfx not in target.counter_keys.get(dname, ()):
                    out.append(Violation(
                        path, line,
                        "R605" if dname in target.hist_dicts
                        else "R602",
                        f"metric {key + sfx!r} is not declared in "
                        f"{mod}.{dname}"))
        return out

    # ------------------------------------------------------- RMW

    def _check_rmw(self, ctx, info, out) -> None:
        lock_depth = [0]

        def walk(node, in_lock: bool):
            if isinstance(node, ast.With):
                held = in_lock or any(
                    "lock" in dotted(i.context_expr).lower()
                    or "LOCK" in dotted(i.context_expr)
                    for i in node.items)
                for child in node.body:
                    walk(child, held)
                return
            if isinstance(node, ast.AugAssign) \
                    and isinstance(node.target, ast.Subscript):
                tv = node.target.value
                shared = (isinstance(tv, ast.Name)
                          and tv.id in info.counter_keys) or \
                         (dotted(tv) == "self.stats")
                if shared and not in_lock:
                    nm = dotted(tv) or getattr(tv, "id", "?")
                    out.append(Violation(
                        ctx.path, node.lineno, "R603",
                        f"unlocked read-modify-write on shared "
                        f"counter {nm}[...] — use utils.stats.bump "
                        "(lost updates under the threaded servers)"))
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef,
                                      ast.AsyncFunctionDef)):
                    walk(child, False)
                else:
                    walk(child, in_lock)

        walk(ctx.tree, False)
        del lock_depth
