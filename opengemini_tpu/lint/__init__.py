"""oglint — repo-specific AST invariant linter (tier-1 gate).

Ten rule classes enforce the conventions the device hot path's
correctness AND performance rest on (see each rule module for the
full contract):

- R1 transfer discipline (``transfer_rule``): D2H pulls in hot-path
  modules ride ``ops.pipeline.device_get_parallel`` or an explicitly
  accounted transport — never bare ``jax.device_get``/implicit
  ``np.asarray`` on device values — so the devstats D2H byte counters
  stay truthful.
- R2 knob registry (``knob_rule``): every ``OG_*`` environment read
  goes through ``utils.knobs``; raw ``os.environ`` reads, unregistered
  knob names and README knob-table drift are errors.
- R3 deadline propagation (``deadline_rule``): cluster RPC call sites
  thread the PR-1 deadline context (``deadline.clamp``) instead of
  hard-coding timeouts; raw sockets live in transport.py only.
- R4 lock ranks (``lockrank_rule``): static half of utils/lockrank.py —
  no blocking calls inside ranked critical sections, no nested
  acquisitions that contradict the declared ranks.
- R5 trace purity (``trace_rule``): functions reachable from
  ``jax.jit`` roots touch no env vars, locks, RNG, wall clocks or
  module state — host-side control flow must stay out of traced code.
- R6 counter hygiene (``counter_rule``): metric names come from the
  ``utils.stats.register_counters`` registry and shared-counter
  read-modify-writes hold the stats lock.
- R7 fault classification (``fault_rule``): broad ``except Exception``
  around device launch/pull/fill sites in ``ops/`` must route through
  ``ops.devicefault.classify`` (or re-raise, or carry a reviewed
  pragma) — a swallowed device fault never retries, never relieves
  HBM pressure and never charges a route breaker.
- R8 rename durability (``durability_rule``): ``os.replace``/
  ``os.rename`` in ``storage/`` must ride
  ``utils.fileops.durable_replace`` (file fsync → rename → parent-dir
  fsync) — a bare rename can roll back after a crash, silently
  unpublishing a TSSP file, manifest or marker.
- R9 jit-boundary hygiene (``jit_rule``): trace-reachable code must
  not host-sync traced values (``.item()``, ``float()``, implicit
  bool, ``np.asarray``), jit roots must declare shape-deriving Python
  args static (a non-static one re-compiles per value), and the f32
  fast paths must not silently promote to emulated f64. Shares R5's
  reachability walker (``jitwalk``); the runtime half is the compile
  auditor (ops/compileaudit.py).
- R10 launch hygiene (``launch_rule``): ``jax.device_put`` / eager
  ``jnp.asarray`` uploads in the hot path must book their bytes
  (``compileaudit.record_h2d`` / ``h2d_bytes``) — the H2D twin of R1,
  cross-checked at runtime by the transfer-manifest audit gate.

Run: ``python scripts/oglint.py`` (or ``python -m opengemini_tpu.lint``).
Suppressions: a trailing ``# oglint: disable=R103`` comment disables
named rules (or rule classes, e.g. ``R1``) for that line; self-tests
cover every rule with failing and passing fixtures
(tests/test_oglint.py + tests/lint_fixtures/).
"""

from __future__ import annotations

from .core import Violation, run_lint  # noqa: F401
from .__main__ import main  # noqa: F401

__all__ = ["Violation", "run_lint", "main"]
