"""R2 — OG_* knob registry discipline.

All ``OG_*`` environment knobs are declared once in
``opengemini_tpu/utils/knobs.py`` (name, type, default, doc, scope)
and read through it. A raw ``os.environ`` read scattered in a module
is exactly how the pre-registry tree ended up with per-launch env
parses in dispatch loops and ~50 undocumented knobs; a knob name not
in the registry is a typo waiting to steer the hot path to a default.

Codes:
- R201: raw environment READ of an OG_* name outside utils/knobs.py
  (os.environ.get / os.getenv / os.environ[...] — including the
  ``__import__("os")`` spelling).
- R202: raw environment WRITE of an OG_* name (os.environ[...] = /
  .pop/.setdefault) — use knobs.set_env/del_env, which keep the
  hot-path parse memo coherent.
- R203: knob-name string passed to knobs.get/get_raw/set_env/del_env
  that is not registered.
- R204: README knob table drifted from the registry (finish pass;
  regenerate with ``python -m opengemini_tpu.lint --knob-table``).
"""

from __future__ import annotations

import ast
import os
import re

from .core import FileCtx, Repo, Rule, Violation, const_str, dotted

_EXEMPT = ("opengemini_tpu/utils/knobs.py",)

_KNOB_FNS = {"get", "get_raw", "set_env", "del_env", "is_registered",
             "invalidate"}

README_BEGIN = "<!-- OGLINT-KNOBS-BEGIN (generated: python -m opengemini_tpu.lint --knob-table) -->"
README_END = "<!-- OGLINT-KNOBS-END -->"


def _og_name(node: ast.AST) -> str | None:
    s = const_str(node)
    if s is not None and s.startswith("OG_"):
        return s
    return None


class KnobRule(Rule):
    rule_id = "R2"
    codes = {
        "R201": "raw os.environ read of an OG_* knob",
        "R202": "raw os.environ write of an OG_* knob",
        "R203": "unregistered knob name",
        "R204": "README knob table drift",
    }

    def check(self, ctx: FileCtx) -> list[Violation]:
        if ctx.path in _EXEMPT:
            return []
        out = []
        for node in ast.walk(ctx.tree):
            out.extend(self._check_node(ctx, node))
        return out

    def _check_node(self, ctx, node) -> list[Violation]:
        out = []
        # reads: os.environ.get("OG_X") / os.getenv("OG_X")
        if isinstance(node, ast.Call):
            d = dotted(node.func)
            if d.endswith(("os.environ.get", "environ.get", "os.getenv")) \
                    and node.args:
                n = _og_name(node.args[0])
                if n:
                    out.append(Violation(
                        ctx.path, node.lineno, "R201",
                        f"raw environment read of {n}: use "
                        "opengemini_tpu.utils.knobs.get()"))
            if d.endswith(("os.environ.pop", "environ.pop",
                           "os.environ.setdefault")) and node.args:
                n = _og_name(node.args[0])
                if n:
                    out.append(Violation(
                        ctx.path, node.lineno, "R202",
                        f"raw environment write of {n}: use "
                        "knobs.del_env()/set_env()"))
            # unregistered names through the registry API
            if d.startswith("knobs.") or d.startswith("_knobs."):
                fn = d.split(".", 1)[1]
                if fn in _KNOB_FNS and node.args:
                    n = _og_name(node.args[0])
                    if n and not self._registered(n):
                        out.append(Violation(
                            ctx.path, node.lineno, "R203",
                            f"knob {n} is not declared in "
                            "utils/knobs.py"))
        # subscript read/write: os.environ["OG_X"]
        if isinstance(node, ast.Subscript):
            d = dotted(node.value)
            if d.endswith("os.environ") or d == "environ":
                n = _og_name(node.slice)
                if n:
                    is_store = isinstance(getattr(node, "ctx", None),
                                          (ast.Store, ast.Del))
                    out.append(Violation(
                        ctx.path, node.lineno,
                        "R202" if is_store else "R201",
                        f"raw environment "
                        f"{'write' if is_store else 'read'} of {n}: "
                        "use knobs."
                        f"{'set_env()' if is_store else 'get()'}"))
        return out

    @staticmethod
    def _registered(name: str) -> bool:
        from ..utils import knobs
        return knobs.is_registered(name)

    # ---------------------------------------------- README drift pass

    def finish(self, repo: Repo) -> list[Violation]:
        readme = os.path.join(repo.root, "README.md")
        if not os.path.exists(readme):
            return []
        text = open(readme, encoding="utf-8").read()
        if README_BEGIN not in text:
            return [Violation(
                "README.md", 1, "R204",
                "README has no generated knob table (expected the "
                f"marker {README_BEGIN!r}); append one via "
                "python -m opengemini_tpu.lint --knob-table")]
        m = re.search(re.escape(README_BEGIN) + r"\n(.*?)"
                      + re.escape(README_END), text, re.S)
        if not m:
            return [Violation("README.md", 1, "R204",
                              "knob table BEGIN marker without END")]
        from ..utils import knobs
        want = knobs.knob_table_md().strip()
        got = m.group(1).strip()
        if want != got:
            line = text[:m.start()].count("\n") + 1
            return [Violation(
                "README.md", line, "R204",
                "README knob table drifted from utils/knobs.py — "
                "regenerate: python -m opengemini_tpu.lint "
                "--knob-table > (paste between markers), or "
                "scripts/oglint.py --fix-readme")]
        return []
