"""R3 — deadline propagation through the cluster RPC plane.

PR 1 introduced the end-to-end deadline context
(``utils.deadline``): the HTTP layer binds a budget and every wait on
the request path clamps by what remains. The transport's own
``call_stream`` clamps internally, but a cluster-layer call site that
hard-codes ``timeout=30.0`` re-introduces a wait the budget cannot
curtail — a dead store node then burns 30s of a 5s request.

Scope: ``opengemini_tpu/cluster/*`` (transport.py is the
implementation and owns its raw sockets/timeouts).

Codes:
- R301: RPC call (``.call``/``.try_call``/``.call_stream``) passing a
  numeric-literal ``timeout=`` — wrap it in ``deadline.clamp(...)``
  (a no-op when no deadline is bound, the curtailed wait otherwise).
- R302: raw ``socket`` use outside transport.py — all wire I/O goes
  through the transport so breakers, stats and deadline clamping
  cannot be bypassed.
"""

from __future__ import annotations

import ast

from .core import FileCtx, Rule, Violation, dotted

_SCOPE = "opengemini_tpu/cluster/"
_IMPL = "opengemini_tpu/cluster/transport.py"
_RPC_METHODS = {"call", "try_call", "call_stream"}


class DeadlineRule(Rule):
    rule_id = "R3"
    codes = {
        "R301": "literal RPC timeout not clamped by the deadline",
        "R302": "raw socket use outside transport.py",
    }

    def check(self, ctx: FileCtx) -> list[Violation]:
        if not ctx.path.startswith(_SCOPE) or ctx.path == _IMPL:
            return []
        out = []
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Call):
                f = node.func
                if isinstance(f, ast.Attribute) \
                        and f.attr in _RPC_METHODS:
                    for kw in node.keywords:
                        if kw.arg == "timeout" and isinstance(
                                kw.value, ast.Constant) and isinstance(
                                kw.value.value, (int, float)):
                            # anchor to the timeout= line itself so a
                            # site pragma sits next to the literal it
                            # excuses (multi-line calls)
                            out.append(Violation(
                                ctx.path, kw.value.lineno, "R301",
                                f"RPC {f.attr}() with literal timeout="
                                f"{kw.value.value}: wrap in "
                                "deadline.clamp(...) so the PR-1 "
                                "request budget curtails the wait"))
            d = dotted(node) if isinstance(
                node, (ast.Attribute, ast.Name)) else ""
            if d == "socket" or d.startswith("socket."):
                out.append(Violation(
                    ctx.path, node.lineno, "R302",
                    "raw socket use outside cluster/transport.py — "
                    "wire I/O must ride the transport (breakers, "
                    "RPC_STATS, deadline clamping)"))
        # de-dup attribute-chain hits on the same line
        seen = set()
        uniq = []
        for v in out:
            key = (v.line, v.code)
            if key not in seen:
                seen.add(key)
                uniq.append(v)
        return uniq
