"""Shared jax.jit reachability walker for the trace-time rule family.

R5 (trace purity) and R9 (jit-boundary hygiene) police the same code
region: every function whose body executes at TRACE time. Both rules
need the same discovery — which functions are jit roots, what the
one-module transitive closure of trace-reachable helpers is, and which
parameters a root declared static — so the walker lives here once
instead of drifting apart in two rule modules.

Roots recognized:
- ``@jax.jit`` / ``@jit`` decorated functions;
- ``@functools.partial(jax.jit, static_argnums=... /
  static_argnames=...)`` decorated functions;
- functions passed by name to an inline ``jax.jit(f, ...)`` /
  ``jax.jit(partial(f, ...))`` call;
- Pallas kernels passed to ``pl.pallas_call(kernel, ...)`` — the
  kernel body is traced exactly like jit code (ops/pallas_agg.py is
  the f32 fast tier this matters for), including kernels built
  through ``functools.partial`` and through kernel FACTORIES
  (``pl.pallas_call(make_kernel(...), ...)`` roots every function
  defined inside ``make_kernel`` — ops/device_decode's DFOR
  bit-unpack kernel is built this way).

Closure: every function lexically reachable from a root by same-module
call-by-name (cross-module helpers are ops-layer jnp code in
practice — the historical R5 contract, unchanged).
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field

from .core import dotted

# _named_jit is ops/blockagg.py's attribution-preserving jit wrapper
# (renames the kernel for the compile auditor, then jax.jit's it) —
# functions passed to it are roots exactly like jax.jit(f).
# _program_jit is ops/fused.py's shape-class twin (round 17): the
# whole-plan fused program builder passes its traced program body
# through it, so R5/R9 cover the fused body like any staged kernel.
_JIT_NAMES = ("jax.jit", "jit", "_named_jit", "_program_jit")
_PALLAS_CALL = ("pl.pallas_call", "pallas.pallas_call", "pallas_call",
                "jax.experimental.pallas.pallas_call")


@dataclass
class TracedFn:
    """One trace-time function: the AST node, whether it is itself a
    jit/pallas root, and the parameter names the root declared static
    (trace-time Python values, exempt from traced-value rules)."""
    fn: ast.FunctionDef
    root: bool = False
    pallas: bool = False
    static: set = field(default_factory=set)


def is_jit_deco(dec: ast.AST) -> bool:
    d = dotted(dec)
    if d in _JIT_NAMES:
        return True
    if isinstance(dec, ast.Call):
        fd = dotted(dec.func)
        if fd in _JIT_NAMES:
            return True
        if fd in ("functools.partial", "partial") and dec.args:
            return dotted(dec.args[0]) in _JIT_NAMES
    return False


def _static_params(fn: ast.FunctionDef, call: ast.Call | None) -> set:
    """Parameter names declared static on a jit root: static_argnames
    (string/tuple-of-strings) and static_argnums (ints mapped onto the
    positional parameter list)."""
    out: set = set()
    if call is None:
        return out
    params = [a.arg for a in fn.args.args]
    for kw in call.keywords:
        if kw.arg == "static_argnames":
            for n in _const_strs(kw.value):
                out.add(n)
        elif kw.arg == "static_argnums":
            for i in _const_ints(kw.value):
                if 0 <= i < len(params):
                    out.add(params[i])
    return out


def _const_strs(node: ast.AST) -> list[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return [node.value]
    if isinstance(node, (ast.Tuple, ast.List)):
        return [e.value for e in node.elts
                if isinstance(e, ast.Constant)
                and isinstance(e.value, str)]
    return []


def _const_ints(node: ast.AST) -> list[int]:
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        return [node.value]
    if isinstance(node, (ast.Tuple, ast.List)):
        return [e.value for e in node.elts
                if isinstance(e, ast.Constant)
                and isinstance(e.value, int)]
    return []


def _jit_call_of(fn: ast.FunctionDef) -> ast.Call | None:
    """The decorator Call carrying static_arg* for a decorated root
    (``functools.partial(jax.jit, ...)`` or ``jax.jit(...)``)."""
    for dec in fn.decorator_list:
        if isinstance(dec, ast.Call) and is_jit_deco(dec):
            return dec
    return None


def traced_functions(tree: ast.AST) -> dict[str, TracedFn]:
    """name → TracedFn for every function in ``tree`` that executes at
    trace time: jit/pallas roots plus the one-module transitive closure
    of functions a traced body calls by name."""
    by_name: dict[str, ast.FunctionDef] = {}
    roots: list[TracedFn] = []
    for node in ast.walk(tree):
        if isinstance(node, ast.FunctionDef):
            by_name.setdefault(node.name, node)
            if any(is_jit_deco(d) for d in node.decorator_list):
                roots.append(TracedFn(
                    node, root=True,
                    static=_static_params(node, _jit_call_of(node))))
    # inline jax.jit(f, ...) / jax.jit(partial(f, ...)) and
    # pl.pallas_call(kernel, ...) roots
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call) or not node.args:
            continue
        fd = dotted(node.func)
        if fd in _JIT_NAMES:
            a = node.args[0]
            if isinstance(a, ast.Call):          # partial(f, ...)
                a = a.args[0] if a.args else a
            nm = dotted(a)
            if nm in by_name:
                roots.append(TracedFn(
                    by_name[nm], root=True,
                    static=_static_params(by_name[nm], node)))
        elif fd in _PALLAS_CALL:
            arg0 = node.args[0]
            nm = dotted(arg0)
            static: set = set()
            if not nm and isinstance(arg0, ast.Call) and \
                    dotted(arg0.func) in ("functools.partial",
                                          "partial") and arg0.args:
                # pl.pallas_call(functools.partial(kernel, P=...)):
                # the bound kwargs are trace-time Python values —
                # static, like jit static_argnames
                nm = dotted(arg0.args[0])
                static = {kw.arg for kw in arg0.keywords
                          if kw.arg is not None}
            elif not nm and isinstance(arg0, ast.Call) and \
                    dotted(arg0.func) in by_name:
                # pl.pallas_call(make_kernel(...), ...) — a kernel
                # FACTORY (ops/device_decode._mk_unpack_kernel): the
                # closure it returns is the traced body, so every
                # function defined INSIDE the factory roots as a
                # pallas kernel, with the factory's parameters static
                # (trace-time constants baked into the closure).
                # Without this, R5/R9 coverage would stop at the
                # factory call and never see the kernel body.
                fac = by_name[dotted(arg0.func)]
                static = {a.arg for a in fac.args.args}
                for sub in ast.walk(fac):
                    if isinstance(sub, ast.FunctionDef) \
                            and sub is not fac:
                        roots.append(TracedFn(sub, root=True,
                                              pallas=True,
                                              static=set(static)))
            if nm in by_name:
                roots.append(TracedFn(by_name[nm], root=True,
                                      pallas=True, static=static))
    if not roots:
        return {}
    traced: dict[str, TracedFn] = {}
    work = list(roots)
    while work:
        tf = work.pop()
        got = traced.get(tf.fn.name)
        if got is not None:
            # a helper later discovered to be a root keeps root status
            got.root = got.root or tf.root
            got.pallas = got.pallas or tf.pallas
            got.static |= tf.static
            continue
        traced[tf.fn.name] = tf
        for sub in ast.walk(tf.fn):
            if isinstance(sub, ast.Call):
                nm = dotted(sub.func)
                if nm in by_name and nm not in traced:
                    work.append(TracedFn(by_name[nm]))
    return traced


def module_assign_names(tree: ast.AST) -> set:
    """Names bound by module-level assignments (shared mutable state a
    traced body must not write)."""
    return {t.id for n in tree.body
            if isinstance(n, ast.Assign)
            for t in n.targets if isinstance(t, ast.Name)}
