"""R8 — rename durability discipline in ``storage/``.

``os.replace``/``os.rename`` alone is not durable on Linux: the rename
is a directory mutation, and until the parent directory is fsynced a
crash can roll it back — a "published" TSSP file, colstore file,
backup manifest or detach marker silently vanishes on restart even
though its bytes were fsynced. PR 10's crash harness
(tests/crashharness.py) SIGKILLs processes at exactly these
boundaries; every publish-by-rename in ``storage/`` must therefore
ride ``utils.fileops.durable_replace`` (file fsync → rename → parent
directory fsync), which is also where the fileops counters live.

Scope: ``opengemini_tpu/storage/`` (plus any future file under it).
Other trees (cluster raft state, logstore, meta) adopt the helper
opportunistically but are not gated — their durability contracts are
weaker by design.

Codes:
- R801: direct ``os.replace``/``os.rename`` call. Fix: route through
  ``utils.fileops.durable_replace`` (or ``durable_write`` for whole
  small files), or — where rename durability is genuinely not needed
  (scratch files inside a directory that is itself swept at open) —
  carry a reviewed ``# oglint: disable=R801`` pragma saying why.
"""

from __future__ import annotations

import ast

from .core import FileCtx, Rule, Violation, dotted

_SCOPE = ("opengemini_tpu/storage/",)
_BANNED = ("os.replace", "os.rename", "os.renames")


class DurabilityRule(Rule):
    rule_id = "R8"
    codes = {
        "R801": "direct os.replace/os.rename in storage/ is not "
                "crash-durable; ride utils.fileops.durable_replace",
    }

    def check(self, ctx: FileCtx) -> list[Violation]:
        if not any(ctx.path.startswith(d) for d in _SCOPE):
            return []
        out = []
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            name = dotted(node.func)
            if name in _BANNED:
                out.append(Violation(
                    ctx.path, node.lineno, "R801",
                    f"{name}(...) publishes by rename without parent-"
                    "directory fsync — a crash can roll the rename "
                    "back after restart. Use utils.fileops."
                    "durable_replace (or durable_write), or carry a "
                    "reviewed '# oglint: disable=R801' pragma"))
        return out
