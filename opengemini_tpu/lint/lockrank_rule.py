"""R4 — static lock-rank and no-blocking-under-lock scan.

Static half of ``utils/lockrank.py`` (the runtime checker catches what
crosses function boundaries; this pass catches what is visible in one
function body — before any test has to hit the interleaving):

- R401: a blocking call inside a ``with``-block on a ranked lock —
  ``time.sleep``, ``Future.result``, ``Thread.join``, ``Event.wait``,
  ``device_get_parallel``, ``block_until_ready``, subprocess/socket
  waits. A ranked critical section on the dispatcher thread that
  sleeps or pulls wedges every queued launch behind it. A Condition
  built ON the held lock is exempt (``wait`` releases it).
- R402: lexically nested ``with`` acquisitions whose declared ranks do
  not strictly increase inward.

The lock-name → rank map mirrors utils/lockrank.py; attribute locks
(``self._lock``) are ranked per owning module. Files outside the lock
web's modules are not scanned.
"""

from __future__ import annotations

import ast

from .core import FileCtx, Rule, Violation, dotted

# per-file rank of `self._lock` (mirrors the RankedLock declarations)
_SELF_LOCK_RANK = {
    "opengemini_tpu/query/scheduler.py": 10,
    "opengemini_tpu/ops/devicecache.py": 20,
    "opengemini_tpu/ops/pipeline.py": 30,
}

# module-level lock names → rank, valid in any scanned file
_NAMED_RANK = {
    "_SCHED_LOCK": 5,
    "_BASE_FILL_LOCKS": 15,
    "_PULL_POOL_LOCK": 25,
    "COUNTER_LOCK": 40,
    "_stats_lock": 41,       # http server's own stats lock (leaf)
}

# Condition variables constructed on the ranked lock they guard:
# cond.wait() RELEASES the lock, so it is not a blocking call under it
_COND_ON_LOCK = {"_dcv"}

_BLOCKING = ("time.sleep", "sleep")
_BLOCKING_ATTRS = {"result", "join", "wait", "block_until_ready",
                   "device_get_parallel", "check_output", "run",
                   "communicate", "recv", "accept", "get"}
# .get() on dicts/caches is ubiquitous and non-blocking; only flag the
# queue-flavored receivers
_GET_RECEIVERS = {"queue", "q", "_dq"}


def _lock_rank(path: str, expr: ast.AST) -> tuple[str, int] | None:
    """(name, rank) when ``with <expr>`` acquires a ranked lock."""
    d = dotted(expr)
    if d == "self._lock" and path in _SELF_LOCK_RANK:
        return d, _SELF_LOCK_RANK[path]
    base = d.split(".")[-1] if d else ""
    if base in _NAMED_RANK:
        return base, _NAMED_RANK[base]
    # _base_fill_lock(...) helper returns a ranked stripe
    if isinstance(expr, ast.Call):
        fd = dotted(expr.func)
        if fd.endswith("_base_fill_lock"):
            return "_BASE_FILL_LOCKS", 15
    return None


class LockRankRule(Rule):
    rule_id = "R4"
    codes = {
        "R401": "blocking call while holding a ranked lock",
        "R402": "nested lock acquisition violates declared ranks",
    }

    def check(self, ctx: FileCtx) -> list[Violation]:
        if ctx.path not in _SELF_LOCK_RANK and not any(
                n in ctx.source for n in _NAMED_RANK):
            return []
        out: list[Violation] = []
        self._walk(ctx, ctx.tree, [], out)
        return out

    def _walk(self, ctx, node, held: list, out: list) -> None:
        """DFS carrying the stack of lexically-held ranked locks."""
        if isinstance(node, ast.With):
            acquired = []
            for item in node.items:
                lk = _lock_rank(ctx.path, item.context_expr)
                if lk is not None:
                    if held and lk[1] <= held[-1][1]:
                        out.append(Violation(
                            ctx.path, node.lineno, "R402",
                            f"acquires {lk[0]!r} (rank {lk[1]}) while "
                            f"holding {held[-1][0]!r} (rank "
                            f"{held[-1][1]}) — ranks must strictly "
                            "increase inward (utils/lockrank.py)"))
                    acquired.append(lk)
            held = held + acquired
            for child in node.body:
                self._walk(ctx, child, held, out)
            return
        if isinstance(node, ast.Call) and held:
            self._check_blocking(ctx, node, held, out)
        # don't descend into nested function definitions: their bodies
        # run later, not under this lock
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef,
                                  ast.AsyncFunctionDef, ast.Lambda)):
                self._walk(ctx, child, [], out)
            else:
                self._walk(ctx, child, held, out)

    def _check_blocking(self, ctx, node, held, out) -> None:
        d = dotted(node.func)
        blocking = d in _BLOCKING
        if not blocking and isinstance(node.func, ast.Attribute):
            attr = node.func.attr
            if attr in _BLOCKING_ATTRS:
                recv = dotted(node.func.value)
                base = recv.split(".")[-1] if recv else ""
                if attr == "get" and base not in _GET_RECEIVERS:
                    return
                if base in _COND_ON_LOCK:
                    return          # cond.wait releases the held lock
                blocking = True
        if blocking:
            out.append(Violation(
                ctx.path, node.lineno, "R401",
                f"blocking call {d or node.func.attr!r} while holding "
                f"ranked lock {held[-1][0]!r} — move it outside the "
                "critical section (a wedged dispatcher blocks every "
                "queued launch)"))
