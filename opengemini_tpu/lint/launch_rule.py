"""R10 — launch hygiene: every H2D upload books its bytes.

The H2D twin of R1's transfer discipline. R1 keeps D2H pulls on the
accounted transport; R10 keeps *uploads* accountable: a bare
``jax.device_put`` / eager ``jnp.asarray`` in the hot path silently
moves bytes the ``h2d_bytes`` counter (and the per-site transfer
manifest, ops/compileaudit.py) never sees — and the transfer-manifest
audit gate cross-checks those counters against the HBM ledger, so an
unbooked upload is not just dark telemetry, it FAILS the runtime gate.
This rule catches the site statically, before a bench run has to.

Contract: a function in scope that uploads
(``jax.device_put(...)``, eager ``jnp.asarray``/``jnp.array`` over
host data) must, in the same function body, book the bytes —
``compileaudit.record_h2d(site, nbytes)`` (the manifest funnel,
preferred), a ``bump("h2d_bytes"|"slab_bytes", ...)`` call, or an HBM
ledger ``account(...)`` — or carry a reviewed
``# oglint: disable=R1001`` pragma next to wherever the booking
actually happens.

Traced functions are exempt (``jnp.asarray`` inside jit code is a
trace op, not a transfer — lint/jitwalk.py decides reachability), as
are the accounted transports themselves (ops/pipeline.py,
ops/devstats.py, ops/compileaudit.py).

Scope: ``opengemini_tpu/ops/*`` + ``query/executor.py`` — the same
hot-path surface as R1.
"""

from __future__ import annotations

import ast

from .core import FileCtx, Rule, Violation, dotted
from .jitwalk import traced_functions

_HOT_DIRS = ("opengemini_tpu/ops/",)
_HOT_FILES = ("opengemini_tpu/query/executor.py",)
_EXEMPT = ("opengemini_tpu/ops/pipeline.py",
           "opengemini_tpu/ops/devstats.py",
           "opengemini_tpu/ops/compileaudit.py")

_UPLOADERS = {"jax.device_put", "jnp.asarray", "jnp.array"}
_BOOK_KEYS = {"h2d_bytes", "slab_bytes"}
# the manifest funnel only — an HBM-ledger `account()` books
# RESIDENCY, not transfer, and must not satisfy this rule
_BOOK_FNS = {"record_h2d"}

# R1002: the manifest site-label sets are CLOSED — every record_h2d /
# record_d2h call must name a LITERAL from them, so the per-site
# attribution can be audited statically and an unknown/variable label
# cannot slip bytes into the manifest under a name the cross-check
# gates never see. MIRROR of ops/compileaudit.{H2D,D2H}_SITES —
# duplicated here so the linter stays jax-import-free; drift between
# the two is pinned by tests/test_oglint.py.
_H2D_SITE_SET = {"slab", "limbs", "planes", "gids", "latcells",
                 "scalars", "pplan", "decode", "dfor", "payload",
                 "mesh", "sketch", "other"}
_D2H_SITE_SET = {"stream", "batch", "segagg", "finalize", "repair",
                 "topk", "decode", "other"}
_FUNNELS = {"record_h2d": _H2D_SITE_SET, "record_d2h": _D2H_SITE_SET}


def _in_scope(path: str) -> bool:
    if path in _EXEMPT:
        return False
    return path in _HOT_FILES or any(path.startswith(d)
                                     for d in _HOT_DIRS)


def _books(fn: ast.AST) -> bool:
    """Does this function body contain an H2D booking call?"""
    for node in ast.walk(fn):
        if not isinstance(node, ast.Call):
            continue
        d = dotted(node.func)
        base = d.split(".")[-1] if d else ""
        if base in _BOOK_FNS:
            return True
        if base in ("bump", "_b", "_bump") and node.args:
            for a in node.args:
                if isinstance(a, ast.Constant) and a.value in _BOOK_KEYS:
                    return True
    return False


class LaunchRule(Rule):
    rule_id = "R10"
    codes = {
        "R1001": "unbooked H2D upload (device_put/jnp.asarray without "
                 "h2d byte accounting)",
        "R1002": "transfer-manifest booking with a non-literal or "
                 "undeclared site label",
    }

    def check(self, ctx: FileCtx) -> list[Violation]:
        if not _in_scope(ctx.path):
            return []
        out = self._check_sites(ctx)
        out.extend(self._check_uploads(ctx))
        return out

    def _check_sites(self, ctx: FileCtx) -> list[Violation]:
        out = []
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            d = dotted(node.func)
            base = d.split(".")[-1] if d else ""
            declared = _FUNNELS.get(base)
            if declared is None:
                continue
            # positional OR keyword form — record_h2d(site=..., ...)
            # must not slip past the closed-set audit
            site = node.args[0] if node.args else next(
                (kw.value for kw in node.keywords
                 if kw.arg == "site"), None)
            if site is None:
                continue
            if not (isinstance(site, ast.Constant)
                    and isinstance(site.value, str)):
                out.append(Violation(
                    ctx.path, node.lineno, "R1002",
                    f"{base}() site label must be a string LITERAL "
                    "from the closed manifest set (a variable label "
                    "defeats static attribution audit); accounted "
                    "transports that thread a caller label live in "
                    "the exempt modules only"))
                continue
            if site.value not in declared:
                out.append(Violation(
                    ctx.path, node.lineno, "R1002",
                    f"{base}() books to undeclared manifest site "
                    f"{site.value!r} — add it to ops/compileaudit."
                    f"{'H2D' if base == 'record_h2d' else 'D2H'}"
                    "_SITES AND the mirror set in lint/launch_rule.py "
                    "in one reviewed change"))
        return out

    def _check_uploads(self, ctx: FileCtx) -> list[Violation]:
        traced = set(traced_functions(ctx.tree))
        # map every node to its enclosing function (innermost)
        out = []
        for fn in self._functions(ctx.tree):
            if fn is not None and fn.name in traced:
                continue
            body = fn if fn is not None else ctx.tree
            for node in self._own_nodes(body):
                if not isinstance(node, ast.Call):
                    continue
                if dotted(node.func) not in _UPLOADERS:
                    continue
                if fn is not None and _books(fn):
                    continue
                where = f"{fn.name}()" if fn is not None \
                    else "module scope"
                out.append(Violation(
                    ctx.path, node.lineno, "R1001",
                    f"{dotted(node.func)} in {where} uploads without "
                    "booking: call compileaudit.record_h2d(site, "
                    "nbytes) (or bump h2d_bytes) in the same function "
                    "so the transfer manifest and h2d counters stay "
                    "truthful — the runtime audit gate cross-checks "
                    "them against the HBM ledger"))
        return out

    @staticmethod
    def _functions(tree: ast.AST):
        """Every FunctionDef plus None for module scope."""
        yield None
        for node in ast.walk(tree):
            if isinstance(node, (ast.FunctionDef,
                                 ast.AsyncFunctionDef)):
                yield node

    @staticmethod
    def _own_nodes(body: ast.AST):
        """Nodes belonging to ``body`` but not to a nested function
        (those are visited as their own scope)."""
        skip_roots = []
        for node in ast.walk(body):
            if node is body:
                continue
            if isinstance(node, (ast.FunctionDef,
                                 ast.AsyncFunctionDef)):
                skip_roots.append(node)
        skipped = set()
        for r in skip_roots:
            for n in ast.walk(r):
                if n is not r:
                    skipped.add(id(n))
        for node in ast.walk(body):
            if node is body or isinstance(
                    node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if id(node) not in skipped:
                yield node
