"""oglint CLI: ``python -m opengemini_tpu.lint`` / scripts/oglint.py.

Modes:
- default: run all ten rule classes over the repo, print violations,
  exit 1 if any (the tier-1/CI gate).
- ``--rules R1,R4``: restrict to named rule classes.
- ``--knob-table``: print the generated README knob table and exit.
- ``--fix-readme``: rewrite the README's generated knob block in
  place from the registry.
- ``--list``: print rule ids + codes.
"""

from __future__ import annotations

import argparse
import os
import re
import sys


def _repo_root() -> str:
    return os.path.abspath(os.path.join(os.path.dirname(__file__),
                                        "..", ".."))


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="oglint", description="repo-specific invariant linter")
    ap.add_argument("paths", nargs="*",
                    help="files/dirs to lint (default: whole repo)")
    ap.add_argument("--root", default=_repo_root())
    ap.add_argument("--rules", default="",
                    help="comma-separated rule ids (R1..R10)")
    ap.add_argument("--knob-table", action="store_true",
                    help="print the generated README knob table")
    ap.add_argument("--fix-readme", action="store_true",
                    help="rewrite README's knob table from the registry")
    ap.add_argument("--list", action="store_true", dest="list_rules")
    args = ap.parse_args(argv)

    from ..utils import knobs
    from .core import default_rules, run_lint
    from .knob_rule import README_BEGIN, README_END

    if args.knob_table:
        print(README_BEGIN)
        print(knobs.knob_table_md())
        print(README_END)
        return 0

    if args.fix_readme:
        path = os.path.join(args.root, "README.md")
        text = open(path, encoding="utf-8").read()
        block = (README_BEGIN + "\n" + knobs.knob_table_md()
                 + "\n" + README_END)
        if README_BEGIN in text:
            text = re.sub(re.escape(README_BEGIN) + r".*?"
                          + re.escape(README_END), block, text,
                          flags=re.S)
        else:
            text = text.rstrip("\n") + "\n\n" + block + "\n"
        open(path, "w", encoding="utf-8").write(text)
        print(f"README knob table rewritten ({path})")
        return 0

    rules = default_rules()
    if args.list_rules:
        for r in rules:
            print(r.rule_id, type(r).__name__)
            for code, desc in r.codes.items():
                print(f"  {code}: {desc}")
        return 0

    if args.rules:
        want = {r.strip().upper() for r in args.rules.split(",")}
        rules = [r for r in rules if r.rule_id in want]
        if not rules:
            print(f"no rules match {args.rules!r}", file=sys.stderr)
            return 2

    vs = run_lint(args.root, rules=rules, paths=args.paths or None)
    for v in vs:
        print(v)
    ran = ",".join(r.rule_id for r in rules)
    if vs:
        print(f"\noglint: {len(vs)} violation(s) [{ran}]",
              file=sys.stderr)
        return 1
    print(f"oglint: clean [{ran}]")
    return 0


if __name__ == "__main__":
    sys.exit(main())
