"""R9 — jit-boundary hygiene: silent recompiles, host syncs and dtype
promotion inside trace-reachable code.

R5 keeps *host state* out of traced code; R9 polices the three
performance hazards that survive R5 — each one erases a device win
without changing a single result bit:

1. **Silent recompile** (R902): a jit root that uses a plain Python
   parameter in a SHAPE position (``range(n)``, ``jnp.zeros(n)``,
   ``x.reshape(n)``, ``jnp.arange(n)``…) without declaring it in
   ``static_argnums``/``static_argnames``. jax hashes traced-array
   *shapes* but Python scalars by *value* only when static — a
   non-static shape-deriving arg re-traces and re-compiles the kernel
   per distinct value (the window-count-per-batch retrace class the
   runtime compile auditor, ops/compileaudit.py, catches dynamically).
2. **Host sync** (R901): ``.item()`` / ``.tolist()``, ``float()`` /
   ``int()`` / ``bool()`` over a traced parameter, ``np.asarray`` /
   ``np.array`` over a traced parameter, or an implicit bool (``if
   param:`` / ``while param:``) — each forces the device to drain and
   the value to cross D2H mid-trace (or throws ConcretizationError at
   the worst time). Static parameters are exempt: they are Python
   values at trace time by declaration.
3. **Silent dtype promotion** (R903): in the f32-capable paths (the
   Pallas fast tier, ops/pallas_agg.py, and any function whose name
   carries ``f32``), a dtype-less ``jnp.array``/``jnp.asarray``/
   ``np.array`` literal or an explicit float64 (``jnp.float64``,
   ``astype(float64)``, ``dtype=np.float64``) silently promotes the
   whole kernel to emulated f64 — the session runs jax_enable_x64, so
   a bare array literal is STRONG f64 and poisons every downstream op
   (weak Python scalars are safe; materialized arrays are not).

Scope: everything under ``opengemini_tpu/`` that mentions jax, same
as R5 — the two rules share the reachability walker
(``lint/jitwalk.py``). Suppress a reviewed site with
``# oglint: disable=R90x``.
"""

from __future__ import annotations

import ast

from .core import FileCtx, Rule, Violation, dotted
from .jitwalk import TracedFn, traced_functions

_SCOPE = ("opengemini_tpu/",)

# shape-position callables → positional args that ARE shapes (None =
# every positional arg): a non-static Python param flowing in here
# re-traces per value
_SHAPE_FNS = {"range": None, "jnp.arange": None,
              "jnp.zeros": (0,), "jnp.ones": (0,), "jnp.full": (0,),
              "jnp.empty": (0,), "jnp.eye": (0, 1),
              "jnp.linspace": (2,), "jnp.broadcast_to": (1,),
              "jax.ShapeDtypeStruct": (0,)}
_SHAPE_METHODS = {"reshape", "broadcast_to"}

_SYNC_CASTS = {"float", "int", "bool", "complex"}
_HOST_PULLERS = {"np.asarray", "np.array", "numpy.asarray",
                 "numpy.array"}

# f64-promoting constructs banned in f32-scoped traced code
_F64_NAMES = {"jnp.float64", "np.float64", "numpy.float64"}
_ARRAY_CTORS = {"jnp.array", "jnp.asarray", "np.array", "np.asarray",
                "numpy.array", "numpy.asarray"}


def _param_names(fn: ast.FunctionDef) -> set:
    a = fn.args
    out = {p.arg for p in a.args + a.posonlyargs + a.kwonlyargs}
    if a.vararg:
        out.add(a.vararg.arg)
    if a.kwarg:
        out.add(a.kwarg.arg)
    return out


# array metadata that is STATIC under trace: float(x.shape[0]) is a
# Python int at trace time, not a host sync
_STATIC_ATTRS = {"shape", "ndim", "dtype", "size", "itemsize",
                 "weak_type"}


def _traced_names(node: ast.AST) -> set:
    """Names reachable in an expression without crossing a STATIC
    metadata attribute: ``x.sum()`` yields x (traced), ``x.shape[0]``
    yields nothing (static under trace)."""
    out: set = set()

    def walk(n):
        if isinstance(n, ast.Attribute) and n.attr in _STATIC_ATTRS:
            return
        if isinstance(n, ast.Name):
            out.add(n.id)
            return
        for c in ast.iter_child_nodes(n):
            walk(c)

    walk(node)
    return out


def _is_f32_scope(ctx: FileCtx, tf: TracedFn) -> bool:
    return ("pallas_agg" in ctx.path or "f32" in tf.fn.name
            or tf.pallas)


class JitRule(Rule):
    rule_id = "R9"
    codes = {
        "R901": "host sync of a traced value inside jit-traced code",
        "R902": "shape-deriving Python arg without static_argnums",
        "R903": "f64 literal / dtype promotion in an f32 traced path",
    }

    def check(self, ctx: FileCtx) -> list[Violation]:
        if not ctx.path.startswith(_SCOPE):
            return []
        if "jax" not in ctx.source:
            return []
        traced = traced_functions(ctx.tree)
        out: list[Violation] = []
        for tf in traced.values():
            # traced params: everything not declared static. Closure
            # helpers keep the conservative view (all params traced) —
            # they receive traced operands from their root callers.
            params = _param_names(tf.fn) - tf.static
            out.extend(self._check_sync(ctx, tf, params))
            if tf.root and not tf.pallas:
                out.extend(self._check_static(ctx, tf, params))
            if _is_f32_scope(ctx, tf):
                out.extend(self._check_f64(ctx, tf))
        # de-dup per (line, code)
        seen, uniq = set(), []
        for v in sorted(out):
            if (v.line, v.code) not in seen:
                seen.add((v.line, v.code))
                uniq.append(v)
        return uniq

    # ------------------------------------------------- R901 host sync

    def _check_sync(self, ctx, tf: TracedFn, params: set) -> list:
        out = []
        fn = tf.fn
        for node in ast.walk(fn):
            if isinstance(node, ast.Call):
                d = dotted(node.func)
                if isinstance(node.func, ast.Attribute) \
                        and node.func.attr in ("item", "tolist") \
                        and _traced_names(node.func.value) & params:
                    out.append(self._v(
                        ctx, node, "R901",
                        f".{node.func.attr}() on a traced value in "
                        f"{fn.name}() drains the device mid-trace — "
                        "return the array and convert on host"))
                elif isinstance(node.func, ast.Name) \
                        and node.func.id in _SYNC_CASTS and node.args \
                        and _traced_names(node.args[0]) & params:
                    out.append(self._v(
                        ctx, node, "R901",
                        f"{node.func.id}() over a traced value in "
                        f"{fn.name}() host-syncs (or throws "
                        "ConcretizationError) — keep it an array, or "
                        "declare the arg static"))
                elif d in _HOST_PULLERS and node.args \
                        and _traced_names(node.args[0]) & params:
                    out.append(self._v(
                        ctx, node, "R901",
                        f"{d}() over a traced value in {fn.name}() is "
                        "an implicit D2H sync inside the trace — use "
                        "jnp, or pull after the jit boundary"))
            elif isinstance(node, (ast.If, ast.While)):
                t = node.test
                # bare `if param:` / `if param[i]:` / `if not param:`
                # — implicit bool of a traced value. Attribute chains
                # (x.ndim, x.shape) are static under trace and exempt.
                if isinstance(t, ast.UnaryOp) \
                        and isinstance(t.op, ast.Not):
                    t = t.operand
                if (isinstance(t, ast.Name) and t.id in params) or \
                        (isinstance(t, ast.Subscript)
                         and isinstance(t.value, ast.Name)
                         and t.value.id in params):
                    out.append(self._v(
                        ctx, node, "R901",
                        f"implicit bool of traced value in "
                        f"{fn.name}() — use jnp.where/lax.cond, or "
                        "declare the arg static"))
        return out

    # --------------------------------------------- R902 static hygiene

    def _check_static(self, ctx, tf: TracedFn, params: set) -> list:
        out = []
        fn = tf.fn
        flagged: set = set()
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            d = dotted(node.func)
            names: set = set()
            if d in _SHAPE_FNS:
                idxs = _SHAPE_FNS[d]
                for i, a in enumerate(node.args):
                    if idxs is not None and i not in idxs:
                        continue
                    names |= {n for n in _direct_names(a)
                              if n in params}
            elif isinstance(node.func, ast.Attribute) \
                    and node.func.attr in _SHAPE_METHODS:
                for a in node.args:
                    names |= {n for n in _direct_names(a)
                              if n in params}
            for nm in names - flagged:
                flagged.add(nm)
                out.append(self._v(
                    ctx, node, "R902",
                    f"param {nm!r} of jit root {fn.name}() derives a "
                    f"shape in {d or node.func.attr}() but is not in "
                    "static_argnums/static_argnames — every distinct "
                    "value re-traces AND re-compiles the kernel"))
        return out

    # ----------------------------------------------- R903 f64 in f32

    def _check_f64(self, ctx, tf: TracedFn) -> list:
        out = []
        fn = tf.fn
        for node in ast.walk(fn):
            d = dotted(node)
            if d in _F64_NAMES:
                out.append(self._v(
                    ctx, node, "R903",
                    f"float64 in f32 traced path {fn.name}() — the "
                    "fast tier pays emulated-f64 throughput for every "
                    "op downstream of this value"))
            elif isinstance(node, ast.Call):
                cd = dotted(node.func)
                if cd in _ARRAY_CTORS \
                        and not any(kw.arg == "dtype"
                                    for kw in node.keywords):
                    out.append(self._v(
                        ctx, node, "R903",
                        f"dtype-less {cd}() in f32 traced path "
                        f"{fn.name}() materializes STRONG f64 under "
                        "jax_enable_x64 and promotes the kernel — "
                        "pass dtype=jnp.float32"))
                elif isinstance(node.func, ast.Attribute) \
                        and node.func.attr == "astype" and node.args \
                        and dotted(node.args[0]) in _F64_NAMES:
                    out.append(self._v(
                        ctx, node, "R903",
                        f"astype(float64) in f32 traced path "
                        f"{fn.name}()"))
        return out

    @staticmethod
    def _v(ctx, node, code, msg) -> Violation:
        return Violation(ctx.path, node.lineno, code,
                         msg + " (see lint/jit_rule.py)")


def _direct_names(node: ast.AST) -> set:
    """Names reachable in an expression WITHOUT crossing an attribute
    access: ``n``, ``n + 1``, ``(a, b)`` yield names; ``x.shape[0]``
    yields nothing (shapes are static under trace)."""
    out: set = set()
    if isinstance(node, ast.Name):
        out.add(node.id)
    elif isinstance(node, (ast.BinOp,)):
        out |= _direct_names(node.left) | _direct_names(node.right)
    elif isinstance(node, (ast.Tuple, ast.List)):
        for e in node.elts:
            out |= _direct_names(e)
    elif isinstance(node, ast.UnaryOp):
        out |= _direct_names(node.operand)
    return out
