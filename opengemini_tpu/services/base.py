"""Background service lifecycle (role of reference services.Base)."""

from __future__ import annotations

import threading

from ..utils import get_logger

log = get_logger(__name__)


class Service:
    """Periodic background service: subclass implements run_once()."""

    name = "service"

    def __init__(self, interval_s: float):
        self.interval_s = interval_s
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    def start(self) -> None:
        if self._thread is not None:
            return
        self._stop.clear()
        self._thread = threading.Thread(target=self._loop, name=self.name,
                                        daemon=True)
        self._thread.start()
        log.info("service %s started (every %.0fs)", self.name,
                 self.interval_s)

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None

    def _loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.run_once()
            except Exception:
                log.exception("service %s tick failed", self.name)

    def run_once(self) -> None:
        raise NotImplementedError
