"""Compaction service: periodic level-compaction over all shards (driver
for storage/compact.py; role of the reference's background compaction
scheduler in engine/immutable/compact.go)."""

from __future__ import annotations

from ..storage.compact import Compactor
from ..utils import get_logger
from .base import Service

log = get_logger(__name__)


class CompactionService(Service):
    name = "compaction"

    def __init__(self, engine, interval_s: float = 60, fanout: int = 4,
                 sysctrl=None):
        super().__init__(interval_s)
        self.engine = engine
        self.fanout = fanout
        self.sysctrl = sysctrl       # compaction on/off admin knob

    def run_once(self) -> int:
        if self.sysctrl is not None and not self.sysctrl.compaction_enabled:
            return 0
        n = 0
        for db in list(self.engine.databases.values()):
            # opened shards only: cold lazy shards have no fresh
            # flushes; they join the plan once a query opens them
            for shard in db.opened_shards():
                n += Compactor(shard, self.fanout).run_once()
        return n
