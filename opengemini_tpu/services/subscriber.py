"""Subscriber service: forward written points to subscriber endpoints
(role of reference coordinator/subscriber.go:200-373 — per-db writers,
ALL = every destination, ANY = round-robin)."""

from __future__ import annotations

import queue
import threading
import urllib.request

from ..storage.rows import PointRow
from ..utils import get_logger

log = get_logger(__name__)


def rows_to_lp(rows: list[PointRow]) -> str:
    def esc(s, chars):
        for c in chars:
            s = s.replace(c, "\\" + c)
        return s

    out = []
    for r in rows:
        m = esc(r.measurement, ", ")
        tags = "".join(f",{esc(k, ', =')}={esc(v, ', =')}"
                       for k, v in sorted(r.tags.items()))
        fs = []
        for k, v in r.fields.items():
            k = esc(k, ", =")
            if isinstance(v, bool):
                fs.append(f"{k}={'t' if v else 'f'}")
            elif isinstance(v, int):
                fs.append(f"{k}={v}i")
            elif isinstance(v, float):
                fs.append(f"{k}={v!r}")
            else:
                vq = str(v).replace("\\", "\\\\").replace('"', '\\"')
                fs.append(f'{k}="{vq}"')
        out.append(f"{m}{tags} {','.join(fs)} {r.time}")
    return "\n".join(out)


class SubscriberService:
    """Hooks engine writes; ships line protocol to destinations
    asynchronously (bounded queue, drops with a log on overflow — the
    reference behaves the same under backpressure)."""

    def __init__(self, engine, catalog, max_queue: int = 1000):
        self.engine = engine
        self.catalog = catalog
        self._q: queue.Queue = queue.Queue(maxsize=max_queue)
        self._rr = 0
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        engine.write_hooks.append(self.on_write)

    def start(self) -> None:
        self._thread = threading.Thread(target=self._drain,
                                        name="subscriber", daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread:
            self._q.put(None)
            self._thread.join(timeout=5)

    def on_write(self, db: str, rows: list[PointRow]) -> None:
        subs = self.catalog.subscriptions_for(db)
        if not subs:
            return
        try:
            self._q.put_nowait((db, rows))
        except queue.Full:
            log.warning("subscriber queue full; dropping %d rows",
                        len(rows))

    def _drain(self) -> None:
        while not self._stop.is_set():
            item = self._q.get()
            if item is None:
                return
            db, rows = item
            body = rows_to_lp(rows).encode()
            for sub in self.catalog.subscriptions_for(db):
                dests = sub.destinations
                if not dests:
                    continue
                if sub.mode.upper() == "ANY":
                    dests = [dests[self._rr % len(dests)]]
                    self._rr += 1
                for d in dests:
                    self._send(d, db, body)

    @staticmethod
    def _send(dest: str, db: str, body: bytes) -> None:
        url = f"{dest.rstrip('/')}/write?db={db}"
        try:
            req = urllib.request.Request(url, data=body, method="POST")
            urllib.request.urlopen(req, timeout=10)
        except Exception as e:
            log.warning("subscriber push to %s failed: %s", dest, e)
