"""Subscriber service: forward written points to subscriber endpoints
(role of reference coordinator/subscriber.go:200-373 — per-destination
writer pools, configurable retry attempts, ALL = every destination,
ANY = round-robin).

Each destination owns a bounded queue and a small worker pool; a send
retries with exponential backoff before counting a drop. Backpressure
drops at the queue with a log line + counter — the reference behaves
the same (BalanceWriter drops on full channels)."""

from __future__ import annotations

import queue
import threading
import time
import urllib.request

from ..storage.rows import PointRow
from ..utils import get_logger

log = get_logger(__name__)

# cumulative metrics for the statistics pusher
# (reference statistics/subscriber.go analog)
from ..utils.stats import register_counters

SUB_STATS = register_counters("subscriber", {
    "queued": 0, "sent": 0, "failed": 0, "dropped": 0,
    "retries": 0})


def rows_to_lp(rows: list[PointRow]) -> str:
    def esc(s, chars):
        for c in chars:
            s = s.replace(c, "\\" + c)
        return s

    out = []
    for r in rows:
        m = esc(r.measurement, ", ")
        tags = "".join(f",{esc(k, ', =')}={esc(v, ', =')}"
                       for k, v in sorted(r.tags.items()))
        fs = []
        for k, v in r.fields.items():
            k = esc(k, ", =")
            if isinstance(v, bool):
                fs.append(f"{k}={'t' if v else 'f'}")
            elif isinstance(v, int):
                fs.append(f"{k}={v}i")
            elif isinstance(v, float):
                fs.append(f"{k}={v!r}")
            else:
                vq = str(v).replace("\\", "\\\\").replace('"', '\\"')
                fs.append(f'{k}="{vq}"')
        out.append(f"{m}{tags} {','.join(fs)} {r.time}")
    return "\n".join(out)


class _DestWriter:
    """One destination's bounded queue + worker pool with retry
    (reference subscriber.go writer goroutines)."""

    def __init__(self, dest: str, workers: int, max_queue: int,
                 attempts: int, backoff_s: float,
                 send_fn=None):
        self.dest = dest
        self.attempts = attempts
        self.backoff_s = backoff_s
        self._send_fn = send_fn or self._http_send
        self._q: queue.Queue = queue.Queue(maxsize=max_queue)
        self._stop = threading.Event()
        self._threads = [
            threading.Thread(target=self._run, daemon=True,
                             name=f"subscriber-{dest}-{i}")
            for i in range(max(1, workers))]
        for t in self._threads:
            t.start()

    def submit(self, db: str, batch: "_Batch") -> bool:
        from ..utils.stats import bump
        if self._stop.is_set():
            bump(SUB_STATS, "dropped")     # racing prune/stop: counted
            return False
        try:
            self._q.put_nowait((db, batch))
            bump(SUB_STATS, "queued")
            return True
        except queue.Full:
            bump(SUB_STATS, "dropped")
            log.warning("subscriber queue full for %s; dropping batch",
                        self.dest)
            return False

    def _run(self) -> None:
        from ..utils.stats import bump
        while True:
            try:
                # timed get: a full queue can swallow shutdown
                # sentinels, so workers also poll the stop flag
                item = self._q.get(timeout=0.2)
            except queue.Empty:
                if self._stop.is_set():
                    return
                continue
            if item is None:
                return
            db, batch = item
            body = batch.body()      # encode once, in a worker
            delay = self.backoff_s
            for attempt in range(self.attempts):
                try:
                    self._send_fn(self.dest, db, body)
                    bump(SUB_STATS, "sent")
                    break
                except Exception as e:
                    if attempt + 1 >= self.attempts:
                        bump(SUB_STATS, "failed")
                        log.warning(
                            "subscriber push to %s failed after %d "
                            "attempts: %s", self.dest, self.attempts, e)
                    else:
                        bump(SUB_STATS, "retries")
                        if self._stop.wait(delay):
                            return
                        delay *= 2

    @staticmethod
    def _http_send(dest: str, db: str, body: bytes) -> None:
        url = f"{dest.rstrip('/')}/write?db={db}"
        req = urllib.request.Request(url, data=body, method="POST")
        urllib.request.urlopen(req, timeout=10)

    def stop(self) -> None:
        from ..utils.stats import bump
        self._stop.set()          # workers exit via the timed get
        for _ in self._threads:
            try:
                self._q.put_nowait(None)   # fast path when not full
            except queue.Full:
                pass
        for t in self._threads:
            t.join(timeout=5)
        # leftover items will never send: account them as drops
        leftover = 0
        try:
            while True:
                if self._q.get_nowait() is not None:
                    leftover += 1
        except queue.Empty:
            pass
        if leftover:
            bump(SUB_STATS, "dropped", leftover)


class _Batch:
    """One write batch with LAZY line-protocol encoding: the hot write
    path queues rows untouched; the FIRST worker to need the body
    encodes it (shared across all destinations of the batch)."""

    __slots__ = ("db", "rows", "_body", "_lock")

    def __init__(self, db: str, rows: list):
        self.db = db
        self.rows = rows
        self._body = None
        self._lock = threading.Lock()

    def body(self) -> bytes:
        with self._lock:
            if self._body is None:
                self._body = rows_to_lp(self.rows).encode()
                self.rows = None
            return self._body


class SubscriberService:
    """Hooks engine writes; lazily builds one _DestWriter per
    (destination) and routes ALL/ANY per subscription. A janitor
    thread reaps pools for destinations no subscription references
    (prune must not depend on further writes arriving)."""

    def __init__(self, engine, catalog, max_queue: int = 1000,
                 workers_per_dest: int = 2, attempts: int = 3,
                 backoff_s: float = 0.1, send_fn=None,
                 prune_interval_s: float = 5.0):
        self.engine = engine
        self.catalog = catalog
        self.max_queue = max_queue
        self.workers_per_dest = workers_per_dest
        self.attempts = attempts
        self.backoff_s = backoff_s
        self._send_fn = send_fn
        self.prune_interval_s = prune_interval_s
        self._janitor = None
        self._writers: dict[str, _DestWriter] = {}
        self._rr: dict[str, int] = {}
        self._lock = threading.Lock()
        self._started = False
        engine.write_hooks.append(self.on_write)

    def start(self) -> None:
        self._started = True
        self._janitor = threading.Thread(target=self._janitor_loop,
                                         name="subscriber-janitor",
                                         daemon=True)
        self._janitor.start()

    def _janitor_loop(self) -> None:
        while self._started:
            time.sleep(self.prune_interval_s)
            if self._started:
                self._prune_writers()

    def stop(self) -> None:
        with self._lock:
            # _started flips under the lock so a racing on_write can
            # never create a writer AFTER the teardown snapshot
            self._started = False
            writers = list(self._writers.values())
            self._writers.clear()
        for w in writers:
            w.stop()

    def _writer(self, dest: str) -> _DestWriter | None:
        with self._lock:
            if not self._started:
                return None
            w = self._writers.get(dest)
            if w is None:
                w = _DestWriter(dest, self.workers_per_dest,
                                self.max_queue, self.attempts,
                                self.backoff_s, send_fn=self._send_fn)
                self._writers[dest] = w
            return w

    def _prune_writers(self) -> None:
        """Reap pools for destinations no subscription references
        anymore (subscription churn must not leak worker threads)."""
        try:
            live = {d for s in self.catalog.subscriptions.values()
                    for d in s.destinations}
        except Exception:
            return
        with self._lock:
            dead = [d for d in self._writers if d not in live]
            stale = [self._writers.pop(d) for d in dead]
        for w in stale:
            w.stop()

    def on_write(self, db: str, rows: list[PointRow]) -> None:
        if not self._started:
            return
        subs = self.catalog.subscriptions_for(db)
        if not subs:
            return
        batch = _Batch(db, rows)
        for sub in subs:
            dests = sub.destinations
            if not dests:
                continue
            if sub.mode.upper() == "ANY":
                key = f"{db}:{sub.name}"     # catalog's namespacing
                with self._lock:             # hooks run concurrently
                    i = self._rr.get(key, 0)
                    self._rr[key] = i + 1
                dests = [dests[i % len(dests)]]
            for d in dests:
                w = self._writer(d)
                if w is not None:
                    w.submit(db, batch)
