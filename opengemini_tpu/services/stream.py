"""Stream compute engine: continuous windowed aggregation at ingest (role
of reference app/ts-store/stream/stream.go:109-532 — RegisterTask :289,
WriteRows :514 — plus the sql-side routing points_writer.go:525).

Tasks filter incoming rows by source measurement, bucket them into
event-time windows per (group-tag values), and on watermark advance
(max event time - delay) flush closed windows as aggregated points into the
destination measurement."""

from __future__ import annotations

import threading
from collections import OrderedDict

from ..meta.catalog import StreamTask
from ..storage.rows import PointRow
from ..utils import get_logger

log = get_logger(__name__)

_AGGS = {
    "sum": lambda acc, v: (acc or 0.0) + v,
    "count": lambda acc, v: (acc or 0) + 1,
    "min": lambda acc, v: v if acc is None else min(acc, v),
    "max": lambda acc, v: v if acc is None else max(acc, v),
    "last": lambda acc, v: v,
    "first": lambda acc, v: acc if acc is not None else v,
}


class _WindowCache:
    """(window_start, group_key) → {field → acc} (+ mean numerators)."""

    def __init__(self, task: StreamTask):
        self.task = task
        self.windows: dict[tuple, dict] = {}
        self.max_event_time = 0
        self.last_seen_event = -1        # ticker idle detection
        # windows force-closed by the idle ticker: stragglers into them
        # count late instead of double-emitting (bounded set)
        self.flushed: "OrderedDict[tuple, None]" = OrderedDict()
        # per-task counters (reference stream statistics)
        self.rows_in = 0
        self.rows_filtered = 0
        self.rows_late = 0
        self.windows_flushed = 0

    def mark_flushed(self, key: tuple) -> None:
        self.flushed[key] = None
        while len(self.flushed) > 4096:
            self.flushed.popitem(last=False)


class StreamEngine:
    """Registered on the engine's write hook; owns all tasks of all dbs.

    flush_interval_s drives a background ticker that closes windows by
    WALL clock when ingest pauses (reference stream.go flush ticker) —
    without it the tail windows only flush at shutdown."""

    def __init__(self, engine, catalog, flush_interval_s: float = 0.0):
        self.engine = engine
        self.catalog = catalog
        self._lock = threading.Lock()
        self._caches: dict[tuple, _WindowCache] = {}
        engine.write_hooks.append(self.on_write)
        self._stop = threading.Event()
        self._ticker: threading.Thread | None = None
        if flush_interval_s > 0:
            self._ticker = threading.Thread(
                target=self._tick_loop, args=(flush_interval_s,),
                daemon=True, name="stream-flush")
            self._ticker.start()

    def stop(self) -> None:
        self._stop.set()
        if self._ticker is not None:
            self._ticker.join(timeout=5)

    def _tick_loop(self, interval_s: float) -> None:
        while not self._stop.wait(interval_s):
            pending: list[tuple[str, list[PointRow]]] = []
            with self._lock:
                for (db, _n), cache in self._caches.items():
                    # IDLE detection only — never advance the EVENT-time
                    # watermark by wall clock (that would drop backfill/
                    # replay ingest whose event times lag wall time as
                    # 'late'). A stream whose event time hasn't moved
                    # for a full tick has stalled: close its open
                    # windows, marking them flushed so stragglers count
                    # as late rather than double-emitting.
                    if cache.windows and \
                            cache.max_event_time == cache.last_seen_event:
                        out = self._drain(cache, mark_flushed=True)
                        if out:
                            pending.append((db, out))
                    cache.last_seen_event = cache.max_event_time
            for db, out in pending:
                try:
                    self.engine.write_points(db, out)
                except Exception:
                    log.exception("stream flush write failed")

    def task_stats(self) -> dict:
        with self._lock:
            return {f"{db}.{name}": {
                "rows_in": c.rows_in, "rows_filtered": c.rows_filtered,
                "rows_late": c.rows_late,
                "windows_flushed": c.windows_flushed,
                "open_windows": len(c.windows)}
                for (db, name), c in self._caches.items()}

    # ---- task admin ------------------------------------------------------

    def register(self, db: str, task: StreamTask) -> None:
        self.catalog.register_stream(db, task)
        with self._lock:
            self._caches[(db, task.name)] = _WindowCache(task)

    def drop(self, db: str, name: str) -> None:
        self.catalog.drop_stream(db, name)
        with self._lock:
            self._caches.pop((db, name), None)

    def load_tasks(self) -> None:
        for db in list(self.engine.databases):
            try:
                for t in self.catalog.stream_tasks(db):
                    with self._lock:
                        self._caches.setdefault((db, t.name),
                                                _WindowCache(t))
            except Exception:
                continue

    # ---- ingest hook -----------------------------------------------------

    def on_write(self, db: str, rows: list[PointRow]) -> None:
        with self._lock:
            caches = [(key, c) for key, c in self._caches.items()
                      if key[0] == db]
        if not caches:
            return
        # bucket the batch by measurement ONCE (not per task)
        by_mst: dict[str, list[PointRow]] = {}
        for r in rows:
            by_mst.setdefault(r.measurement, []).append(r)
        for (key_db, _name), cache in caches:
            src = cache.task.src_measurement
            if src in by_mst and src != cache.task.dest_measurement:
                self._feed(key_db, cache, by_mst[src])

    _EMPTY_KEY = ()

    def _feed(self, db: str, cache: _WindowCache,
              rows: list[PointRow]) -> None:
        t = cache.task
        cond = t.condition
        is_time_task = not t.group_tags     # time_task.go fast path
        out = []
        with self._lock:
            watermark = cache.max_event_time - t.delay_ns
            for r in rows:
                cache.rows_in += 1
                if cond and any(r.tags.get(k) != v
                                for k, v in cond.items()):
                    cache.rows_filtered += 1
                    continue
                win = r.time // t.interval_ns * t.interval_ns
                gkey = self._EMPTY_KEY if is_time_task else \
                    tuple(r.tags.get(k, "") for k in t.group_tags)
                if win + t.interval_ns <= watermark \
                        or (win, gkey) in cache.flushed:
                    # window already flushed — reference lateness
                    # policy: drop and count, never rewrite history
                    cache.rows_late += 1
                    continue
                acc = cache.windows.setdefault((win, gkey), {})
                for fname, func in t.calls.items():
                    v = r.fields.get(fname)
                    if v is None or not isinstance(v, (int, float)) \
                            or isinstance(v, bool):
                        continue
                    outname = f"{fname}_{func}"
                    if func == "mean":
                        s, c = acc.get(outname, (0.0, 0))
                        acc[outname] = (s + v, c + 1)
                    else:
                        acc[outname] = _AGGS[func](acc.get(outname), v)
                if r.time > cache.max_event_time:
                    cache.max_event_time = r.time
            out = self._collect_closed(cache)
        if out:
            self.engine.write_points(db, out)

    def _collect_closed(self, cache: _WindowCache) -> list[PointRow]:
        """Flush windows fully below the watermark."""
        t = cache.task
        watermark = cache.max_event_time - t.delay_ns
        return self._drain(cache, below=watermark)

    def _drain(self, cache: _WindowCache, below: int | None = None,
               mark_flushed: bool = False) -> list[PointRow]:
        """Pop + materialize windows (all of them, or those fully below
        ``below``); optionally mark them flushed for lateness tracking."""
        t = cache.task
        out = []
        for (win, gkey) in sorted(cache.windows):
            if below is not None and win + t.interval_ns > below:
                continue
            acc = cache.windows.pop((win, gkey))
            if mark_flushed:
                cache.mark_flushed((win, gkey))
            fields = {}
            for name, v in acc.items():
                if isinstance(v, tuple):  # mean (sum, count)
                    fields[name] = v[0] / v[1] if v[1] else 0.0
                else:
                    fields[name] = float(v)
            if fields:
                cache.windows_flushed += 1
                tags = dict(zip(t.group_tags, gkey))
                out.append(PointRow(t.dest_measurement, tags, fields, win))
        return out

    def flush_all(self) -> None:
        """Force-flush every open window (shutdown path)."""
        pending: list[tuple[str, list[PointRow]]] = []
        with self._lock:
            for (db, _name), cache in self._caches.items():
                t = cache.task
                out = []
                for (win, gkey) in sorted(cache.windows):
                    acc = cache.windows.pop((win, gkey))
                    fields = {k: (v[0] / v[1] if isinstance(v, tuple) and
                                  v[1] else float(v[0]) if
                                  isinstance(v, tuple) else float(v))
                              for k, v in acc.items()}
                    if fields:
                        out.append(PointRow(t.dest_measurement,
                                            dict(zip(t.group_tags, gkey)),
                                            fields, win))
                if out:
                    pending.append((db, out))
        for db, out in pending:
            self.engine.write_points(db, out)
