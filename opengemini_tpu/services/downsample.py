"""Downsample service: rewrite old shards at lower resolution (role of
reference services/downsample + engine side StartDownSampleTask,
engine/engine_downsample.go:92, stream_downsample.go).

For every shard fully older than a policy's age, each series is re-windowed
at the policy interval (mean for floats, sum for integers by default —
per-type calls configurable) and the shard's files are replaced by the
downsampled data. A marker file records the applied interval so a shard is
never downsampled twice at the same level."""

from __future__ import annotations

import os
import time

import numpy as np

from ..record import ColVal, DataType, Record, Schema
from ..utils import get_logger
from .base import Service

log = get_logger(__name__)


class DownsampleService(Service):
    name = "downsample"

    def __init__(self, engine, catalog, interval_s: float = 3600,
                 now_fn=None):
        super().__init__(interval_s)
        self.engine = engine
        self.catalog = catalog
        self.now_fn = now_fn or (lambda: int(time.time() * 1e9))

    def run_once(self) -> int:
        now = self.now_fn()
        done = 0
        for db_name in list(self.engine.databases):
            try:
                policies = self.catalog.downsample_policies(db_name)
            except Exception:
                continue
            if not policies:
                continue
            db = self.engine.databases[db_name]
            for shard in db.all_shards():
                for p in sorted(policies, key=lambda p: -p.age_ns):
                    if shard.end_time > now - p.age_ns:
                        continue
                    if self._level(shard) >= p.interval_ns:
                        continue
                    self.downsample_shard(shard, p)
                    done += 1
                    break
        return done

    @staticmethod
    def _marker(shard) -> str:
        return os.path.join(shard.path, "downsample.level")

    def _level(self, shard) -> int:
        try:
            with open(self._marker(shard)) as f:
                return int(f.read().strip())
        except (OSError, ValueError):
            return 0

    def downsample_shard(self, shard, policy) -> None:
        """Rewrite every measurement of the shard at policy.interval_ns."""
        shard.flush()
        with shard._lock:
            msts = list(shard._files)
        for mst in msts:
            self._downsample_measurement(shard, mst, policy)
        with open(self._marker(shard), "w") as f:
            f.write(str(policy.interval_ns))
        log.info("downsampled shard %d to %ds resolution", shard.shard_id,
                 policy.interval_ns // 10**9)

    def _downsample_measurement(self, shard, mst, policy) -> None:
        from ..storage.compact import merge_and_swap
        with shard._lock:
            readers = list(shard._files.get(mst, ()))
        if not readers:
            return
        merge_and_swap(shard, mst, readers,
                       transform=lambda rec, _sid:
                       _downsample_record(rec, policy))


def _downsample_record(rec: Record, policy) -> Record:
    """Window-aggregate one series record at policy.interval_ns."""
    t = rec.times
    w = t // policy.interval_ns
    # group boundaries over sorted times
    uniq, starts = np.unique(w, return_index=True)
    bounds = np.append(starts, len(t))
    out_times = (uniq * policy.interval_ns).astype(np.int64)
    fields = []
    cols = []
    for f, col in zip(rec.schema, rec.cols):
        if f.name == "time":
            continue
        call = policy.calls.get(f.type.name.lower(), "last")
        if col.values is None or not f.type.is_numeric:
            vals, valid = _reduce_strcol(col, bounds, call)
            fields.append(f)
            cols.append(ColVal(f.type, valid=valid, offsets=vals[0],
                               data=vals[1]))
            continue
        v, m = col.values, col.valid
        n_out = len(uniq)
        outv = np.zeros(n_out, dtype=np.float64)
        outm = np.zeros(n_out, dtype=np.bool_)
        for i in range(n_out):
            lo, hi = bounds[i], bounds[i + 1]
            vv = v[lo:hi][m[lo:hi]]
            if len(vv) == 0:
                continue
            outm[i] = True
            if call == "mean":
                outv[i] = vv.mean()
            elif call == "sum":
                outv[i] = vv.sum()
            elif call == "min":
                outv[i] = vv.min()
            elif call == "max":
                outv[i] = vv.max()
            elif call == "first":
                outv[i] = vv[0]
            elif call == "count":
                outv[i] = len(vv)
            else:  # last
                outv[i] = vv[-1]
        ftype = f.type if call not in ("mean",) else DataType.FLOAT
        fields.append(type(f)(f.name, ftype))
        cols.append(ColVal(ftype, outv.astype(ftype.numpy_dtype), outm))
    fields.append(rec.schema.fields[rec.schema.time_index])
    cols.append(ColVal(DataType.TIME, out_times))
    return Record(Schema(fields), cols)


def _reduce_strcol(col: ColVal, bounds, call: str):
    """last-valid string per window."""
    strs = col.to_strings()
    out = []
    for i in range(len(bounds) - 1):
        lo, hi = bounds[i], bounds[i + 1]
        pick = None
        for j in range(hi - 1, lo - 1, -1):
            if strs[j] is not None:
                pick = strs[j]
                break
        out.append(pick)
    c = ColVal.from_strings(out, col.type)
    return (c.offsets, c.data), c.valid
