"""Downsample service: rewrite old shards at lower resolution (role of
reference services/downsample + engine side StartDownSampleTask,
engine/engine_downsample.go:92, stream_downsample.go).

For every shard fully older than a policy's age, each series is re-windowed
at the policy interval (mean for floats, sum for integers by default —
per-type calls configurable) and the shard's files are replaced by the
downsampled data. A marker file records the applied interval so a shard is
never downsampled twice at the same level."""

from __future__ import annotations

import os
import time

import numpy as np

from ..record import ColVal, DataType, Record, Schema
from ..storage.tssp import TSSPWriter, TSSPReader
from ..utils import get_logger
from .base import Service

log = get_logger(__name__)


class DownsampleService(Service):
    name = "downsample"

    def __init__(self, engine, catalog, interval_s: float = 3600,
                 now_fn=None):
        super().__init__(interval_s)
        self.engine = engine
        self.catalog = catalog
        self.now_fn = now_fn or (lambda: int(time.time() * 1e9))

    def run_once(self) -> int:
        now = self.now_fn()
        done = 0
        for db_name in list(self.engine.databases):
            try:
                policies = self.catalog.downsample_policies(db_name)
            except Exception:
                continue
            if not policies:
                continue
            db = self.engine.databases[db_name]
            for shard in db.all_shards():
                for p in sorted(policies, key=lambda p: -p.age_ns):
                    if shard.end_time > now - p.age_ns:
                        continue
                    if self._level(shard) >= p.interval_ns:
                        continue
                    self.downsample_shard(shard, p)
                    done += 1
                    break
        return done

    @staticmethod
    def _marker(shard) -> str:
        return os.path.join(shard.path, "downsample.level")

    def _level(self, shard) -> int:
        try:
            with open(self._marker(shard)) as f:
                return int(f.read().strip())
        except (OSError, ValueError):
            return 0

    def downsample_shard(self, shard, policy) -> None:
        """Rewrite every measurement of the shard at policy.interval_ns."""
        shard.flush()
        with shard._lock:
            msts = list(shard._files)
        for mst in msts:
            self._downsample_measurement(shard, mst, policy)
        with open(self._marker(shard), "w") as f:
            f.write(str(policy.interval_ns))
        log.info("downsampled shard %d to %ds resolution", shard.shard_id,
                 policy.interval_ns // 10**9)

    def _downsample_measurement(self, shard, mst, policy) -> None:
        from ..storage.compact import iter_merged_series
        with shard._lock:
            readers = list(shard._files.get(mst, ()))
        if not readers:
            return
        with shard._lock:
            shard._file_seq += 1
            out_path = os.path.join(
                shard.path, "tssp", f"{mst}_{shard._file_seq:06d}.tssp")
        w = TSSPWriter(out_path, segment_size=shard.segment_size)
        wrote = False
        for sid, rec in iter_merged_series(readers):
            ds = _downsample_record(rec, policy)
            if ds.num_rows:
                w.write_series(sid, ds)
                wrote = True
        if wrote:
            w.finalize()
            new_reader = TSSPReader(out_path)
        else:
            w.abort()
            new_reader = None
        drop = {id(r) for r in readers}
        with shard._lock:
            # keep any files flushed concurrently since the snapshot
            current = shard._files.get(mst, [])
            kept = [r for r in current if id(r) not in drop]
            shard._files[mst] = (([new_reader] if new_reader else [])
                                 + kept)
        for r in readers:
            try:
                os.unlink(r.path)
            except OSError:
                pass


def _downsample_record(rec: Record, policy) -> Record:
    """Window-aggregate one series record at policy.interval_ns."""
    t = rec.times
    w = t // policy.interval_ns
    # group boundaries over sorted times
    uniq, starts = np.unique(w, return_index=True)
    bounds = np.append(starts, len(t))
    out_times = (uniq * policy.interval_ns).astype(np.int64)
    fields = []
    cols = []
    for f, col in zip(rec.schema, rec.cols):
        if f.name == "time":
            continue
        call = policy.calls.get(f.type.name.lower(), "last")
        if col.values is None or not f.type.is_numeric:
            vals, valid = _reduce_strcol(col, bounds, call)
            fields.append(f)
            cols.append(ColVal(f.type, valid=valid, offsets=vals[0],
                               data=vals[1]))
            continue
        v, m = col.values, col.valid
        n_out = len(uniq)
        outv = np.zeros(n_out, dtype=np.float64)
        outm = np.zeros(n_out, dtype=np.bool_)
        for i in range(n_out):
            lo, hi = bounds[i], bounds[i + 1]
            vv = v[lo:hi][m[lo:hi]]
            if len(vv) == 0:
                continue
            outm[i] = True
            if call == "mean":
                outv[i] = vv.mean()
            elif call == "sum":
                outv[i] = vv.sum()
            elif call == "min":
                outv[i] = vv.min()
            elif call == "max":
                outv[i] = vv.max()
            elif call == "first":
                outv[i] = vv[0]
            elif call == "count":
                outv[i] = len(vv)
            else:  # last
                outv[i] = vv[-1]
        ftype = f.type if call not in ("mean",) else DataType.FLOAT
        fields.append(type(f)(f.name, ftype))
        cols.append(ColVal(ftype, outv.astype(ftype.numpy_dtype), outm))
    fields.append(rec.schema.fields[rec.schema.time_index])
    cols.append(ColVal(DataType.TIME, out_times))
    return Record(Schema(fields), cols)


def _reduce_strcol(col: ColVal, bounds, call: str):
    """last-valid string per window."""
    strs = col.to_strings()
    out = []
    for i in range(len(bounds) - 1):
        lo, hi = bounds[i], bounds[i + 1]
        pick = None
        for j in range(hi - 1, lo - 1, -1):
            if strs[j] is not None:
                pick = strs[j]
                break
        out.append(pick)
    c = ColVal.from_strings(out, col.type)
    return (c.offsets, c.data), c.valid
