"""Arrow Flight ingest (role of reference services/arrowflight/service.go:65-131
+ coordinator/record_writer.go:79-326).

High-throughput columnar write path: clients ship Arrow record batches over
gRPC Flight ``DoPut``; the flight descriptor carries a JSON command
``{"db": ..., "rp": ..., "measurement": ..., "tag_columns": [...]}``
(the reference's descriptor carries db/rp/measurement the same way); an
optional handshake token auth gates writes (reference authServer in
service.go). Eligible batches take the COLUMNAR FAST LANE
(``batch_to_columns`` → ``Engine.write_record_batch``): tag grouping is
vectorized over dictionary codes and field/time columns land in the
engine as numpy arrays — no per-row PointRow objects on the hot path.
Ineligible batches (null or non-numeric fields) and
``OG_FLIGHT_COLUMNAR=0`` fall back to the row hatch
(``batch_to_rows`` → the same write entry as the HTTP path:
Engine.write_points or the cluster facade's PointsWriter). The lanes
are bit-identical at query time; only throughput differs.

Columnar conversion rules (reference record_writer.go ArrowRecordToNative):
  - "time" column: int64 ns or any arrow timestamp (normalised to ns);
    missing → server receive time.
  - tag columns: named in the descriptor, else every dictionary-encoded
    string column.
  - remaining columns: fields (float/int/bool/string); nulls are skipped
    per row, matching line-protocol sparse-field semantics.
"""

from __future__ import annotations

import hashlib
import hmac
import json
import secrets
import threading
import time

import numpy as np

from ..storage.rows import PointRow
from ..utils import get_logger
from ..utils.errors import GeminiError
from ..utils.stats import bump, register_counters

log = get_logger(__name__)

# Process-wide ingest counters for /debug/vars — the HTTP server has
# no handle on the Flight service instance, so do_put mirrors the
# per-instance stats here (see utils.stats.flight_collector).
FLIGHT_STATS = register_counters("flight", {
    "rows_written": 0, "batches": 0, "columnar_batches": 0,
    "write_errors": 0})

try:
    import pyarrow as pa
    import pyarrow.flight as flight
    HAVE_FLIGHT = True
except Exception:                                    # pragma: no cover
    pa = flight = None
    HAVE_FLIGHT = False


# --------------------------------------------------------------- conversion

def _default_tag_columns(batch) -> list[str]:
    return [f.name for f in batch.schema
            if pa.types.is_dictionary(f.type)]


def _extract_times(batch, col, recv_time_ns: int | None) -> np.ndarray:
    """"time" column → int64 ns array (timestamp units normalized)."""
    scale = 1
    if pa.types.is_timestamp(col.type):
        scale = {"s": 10**9, "ms": 10**6,
                 "us": 10**3, "ns": 1}[col.type.unit]
    t64 = col.cast(pa.int64())
    if t64.null_count:
        # fill nulls in arrow: going through float64 would round
        # every ns timestamp in the batch to ~2^53 precision
        import pyarrow.compute as pc
        now = (recv_time_ns if recv_time_ns is not None
               else time.time_ns())
        t64 = pc.fill_null(t64, now // scale)
    return t64.to_numpy(zero_copy_only=False) * scale


def _extract_column(col) -> list:
    """One column → Python value list; null-free numeric/bool columns
    go through numpy (one vectorized tolist(), ~10× to_pylist)."""
    t = col.type
    if col.null_count == 0 and (
            pa.types.is_integer(t) or pa.types.is_floating(t)
            or pa.types.is_boolean(t)):
        return col.to_numpy(zero_copy_only=False).tolist()
    return col.to_pylist()


def batch_to_rows(batch, measurement: str,
                  tag_columns: list[str] | None = None,
                  recv_time_ns: int | None = None) -> list[PointRow]:
    """Arrow RecordBatch/Table → PointRow list (reference
    record_writer.go:180 arrow → record.Record conversion).

    The row-wise HATCH of the Flight ingest path (strings, nulls,
    OG_FLIGHT_COLUMNAR=0): extraction is vectorized per COLUMN — numpy
    tolist() for null-free numerics, tag-tuple dict interning so a
    batch's few distinct series build their tag dicts once — and the
    null-free common case assembles rows with zip() instead of a
    per-(row, column) scan."""
    names = batch.schema.names
    if tag_columns is None:
        tag_columns = _default_tag_columns(batch)
    tag_set = set(tag_columns)
    n = batch.num_rows

    times = None
    tag_items: list[tuple[str, list]] = []
    field_items: list[tuple[str, list]] = []
    any_null = False
    for name, col in zip(names, batch.columns):
        if name == "time":
            times = _extract_times(batch, col, recv_time_ns)
            continue
        vals = _extract_column(col)
        any_null |= col.null_count > 0
        if name in tag_set:
            if vals and not isinstance(vals[0], (str, type(None))):
                vals = [v if v is None else str(v) for v in vals]
            tag_items.append((name, vals))
        else:
            field_items.append((name, vals))

    if times is None:
        now = recv_time_ns if recv_time_ns is not None else time.time_ns()
        times = np.full(n, now, dtype=np.int64)
    tl = times.tolist()

    if not any_null and field_items:
        fnames = [nm for nm, _ in field_items]
        tnames = [nm for nm, _ in tag_items]
        tag_cache: dict[tuple, dict] = {}

        def _tags(tv: tuple) -> dict:
            d = tag_cache.get(tv)
            if d is None:
                d = tag_cache[tv] = dict(zip(tnames, tv))
            return d

        tag_rows = (zip(*(v for _, v in tag_items)) if tag_items
                    else iter(() for _ in range(n)))
        return [PointRow(measurement, _tags(tuple(tv)),
                         dict(zip(fnames, fv)), t)
                for tv, fv, t in zip(
                    tag_rows, zip(*(v for _, v in field_items)), tl)]

    rows = []
    for i in range(n):
        tags, fields = {}, {}
        for name, vals in tag_items:
            v = vals[i]
            if v is not None:
                tags[name] = v if isinstance(v, str) else str(v)
        for name, vals in field_items:
            v = vals[i]
            if v is not None:
                fields[name] = v
        if fields:
            rows.append(PointRow(measurement, tags, fields, int(tl[i])))
    return rows


def batch_to_columns(batch, tag_columns: list[str] | None = None,
                     recv_time_ns: int | None = None):
    """Arrow RecordBatch → ``[(tags, times, {field: ndarray})]`` batches
    for ``Engine.write_record_batch`` — the COLUMNAR fast lane: no
    PointRow materialization, tag grouping via dictionary codes + one
    np.unique, field columns handed over as zero-copy numpy arrays.

    Returns None when the batch is ineligible (a field column is
    non-numeric or carries nulls — sparse-field semantics need the
    row hatch); eligibility is decided per batch so a mixed stream
    degrades batch-wise, never wrongly."""
    names = batch.schema.names
    if tag_columns is None:
        tag_columns = _default_tag_columns(batch)
    tag_set = set(tag_columns)
    n = batch.num_rows
    if n == 0:
        return []

    times = None
    code_cols: list[tuple[str, np.ndarray, list]] = []
    fields: dict[str, np.ndarray] = {}
    for name, col in zip(names, batch.columns):
        if name == "time":
            times = _extract_times(batch, col, recv_time_ns)
            continue
        if name in tag_set:
            if not pa.types.is_dictionary(col.type):
                try:
                    col = col.dictionary_encode()
                except Exception:
                    return None
            # null tag code -1: that row simply omits the tag
            codes = col.indices.to_numpy(zero_copy_only=False)
            codes = np.where(np.isnan(codes), -1, codes).astype(
                np.int64) if codes.dtype.kind == "f" \
                else codes.astype(np.int64)
            vocab = [v if v is None or isinstance(v, str) else str(v)
                     for v in col.dictionary.to_pylist()]
            code_cols.append((name, codes, vocab))
            continue
        t = col.type
        if col.null_count or not (
                pa.types.is_integer(t) or pa.types.is_floating(t)
                or pa.types.is_boolean(t)):
            return None
        a = col.to_numpy(zero_copy_only=False)
        if a.dtype == np.bool_:
            pass
        elif np.issubdtype(a.dtype, np.integer):
            a = a.astype(np.int64, copy=False)
        else:
            a = a.astype(np.float64, copy=False)
        fields[name] = a
    if not fields:
        return None
    if times is None:
        now = recv_time_ns if recv_time_ns is not None else time.time_ns()
        times = np.full(n, now, dtype=np.int64)
    times = np.ascontiguousarray(times, dtype=np.int64)

    if not code_cols:
        return [({}, times, fields)]
    # mixed-radix scalar key per row (code+1 per tag, radix = vocab
    # size + 2 so -1 nulls fit) instead of np.unique(axis=0) over a
    # stacked code matrix: the void-view row comparisons plus a second
    # stable argsort were ~80% of the lane's wall. One scalar sort
    # replaces both, and when the key space fits uint16 the stable
    # argsort is numpy's O(n) radix sort, not mergesort.
    key = code_cols[0][1] + 1
    span = len(code_cols[0][2]) + 2
    for _name, codes, vocab in code_cols[1:]:
        key = key * (len(vocab) + 2) + (codes + 1)
        span *= len(vocab) + 2
    if span <= (1 << 16):
        key = key.astype(np.uint16)
    order = np.argsort(key, kind="stable")
    ks = key[order]
    starts = np.nonzero(np.concatenate([[True], ks[1:] != ks[:-1]]))[0]
    bounds = np.concatenate([starts, [n]])
    radii = [len(vocab) + 2 for _n, _c, vocab in code_cols]
    out = []
    times_s = times[order]
    fields_s = {k: v[order] for k, v in fields.items()}
    for g in range(len(starts)):
        lo, hi = int(bounds[g]), int(bounds[g + 1])
        tags = {}
        k = int(ks[lo])
        for (name, _c, vocab), radix in zip(reversed(code_cols),
                                            reversed(radii)):
            k, code = divmod(k, radix)
            code -= 1
            if code >= 0 and vocab[code] is not None:
                tags[name] = vocab[code]
        out.append((dict(reversed(tags.items())), times_s[lo:hi],
                    {k2: v[lo:hi] for k2, v in fields_s.items()}))
    return out


# --------------------------------------------------------------------- auth

class TokenAuthHandler(flight.ServerAuthHandler if HAVE_FLIGHT else object):
    """Handshake auth (reference service.go authServer: user/password in,
    HMAC token out; every later call presents the token)."""

    def __init__(self, users: dict[str, str]):
        if HAVE_FLIGHT:
            super().__init__()
        self.users = users
        self._secret = secrets.token_bytes(16)

    def _token(self, username: str) -> bytes:
        mac = hmac.new(self._secret, username.encode(), hashlib.sha256)
        return (username + ":" + mac.hexdigest()).encode()

    def authenticate(self, outgoing, incoming):
        payload = incoming.read()
        try:
            creds = json.loads(payload.decode())
            user, pwd = creds["username"], creds["password"]
        except Exception:
            raise flight.FlightUnauthenticatedError("bad credentials payload")
        if self.users.get(user) != pwd:
            raise flight.FlightUnauthenticatedError("invalid username/password")
        outgoing.write(self._token(user))

    def is_valid(self, token):
        if not token:
            raise flight.FlightUnauthenticatedError("no token")
        try:
            user = token.decode().split(":", 1)[0]
        except UnicodeDecodeError:
            raise flight.FlightUnauthenticatedError("bad token")
        if not hmac.compare_digest(token, self._token(user)):
            raise flight.FlightUnauthenticatedError("bad token")
        return user.encode()


# ------------------------------------------------------------------- server

class ArrowFlightService((flight.FlightServerBase if HAVE_FLIGHT
                          else object)):
    """Flight ingest endpoint in front of any writer exposing
    ``write_points(db, rows)`` (Engine or ClusterFacade)."""

    def __init__(self, writer, host: str = "127.0.0.1", port: int = 0,
                 users: dict[str, str] | None = None,
                 max_rows_per_batch: int = 1_000_000):
        if not HAVE_FLIGHT:                          # pragma: no cover
            raise GeminiError("pyarrow.flight unavailable")
        self.auth = TokenAuthHandler(users) if users else None
        super().__init__(f"grpc://{host}:{port}", auth_handler=self.auth)
        self.writer = writer
        self.host = host
        self.max_rows_per_batch = max_rows_per_batch
        self.rows_written = 0
        self.batches = 0
        self.columnar_batches = 0
        self.write_errors = 0
        self._stats_lock = threading.Lock()
        self._serve_thread: threading.Thread | None = None

    @property
    def location(self) -> str:
        return f"grpc://{self.host}:{self.port}"

    # ---------------------------------------------------------- flight rpc

    def do_put(self, context, descriptor, reader, writer):
        try:
            cmd = json.loads(descriptor.command.decode())
            db = cmd["db"]
            measurement = cmd.get("measurement") or cmd["mst"]
        except Exception:
            raise flight.FlightServerError(
                "descriptor command must be JSON with db/measurement")
        tag_columns = cmd.get("tag_columns")
        recv = time.time_ns()
        from ..utils import knobs
        columnar_ok = (bool(knobs.get("OG_FLIGHT_COLUMNAR"))
                       and hasattr(self.writer, "write_record_batch"))
        for chunk in reader:
            batch = chunk.data
            if batch.num_rows > self.max_rows_per_batch:
                raise flight.FlightServerError("batch too large")
            # columnar fast lane: Arrow columns land directly in the
            # engine's bulk write (vectorized sid resolution + shard
            # slotting; zero PointRow materialization). Ineligible
            # batches (nulls / string fields) take the row hatch —
            # the two lanes are bit-identical at query time
            cols = (batch_to_columns(batch, tag_columns, recv)
                    if columnar_ok else None)
            try:
                if cols is not None:
                    self.writer.write_record_batch(
                        db, [(measurement, tg, tm, f)
                             for tg, tm, f in cols])
                    nrows = batch.num_rows
                else:
                    rows = batch_to_rows(
                        batch, measurement, tag_columns, recv)
                    self.writer.write_points(db, rows)
                    nrows = len(rows)
            except Exception as e:
                with self._stats_lock:
                    self.write_errors += 1
                bump(FLIGHT_STATS, "write_errors")
                raise flight.FlightServerError(f"write failed: {e}")
            with self._stats_lock:
                self.rows_written += nrows
                self.batches += 1
                if cols is not None:
                    self.columnar_batches += 1
            bump(FLIGHT_STATS, "rows_written", nrows)
            bump(FLIGHT_STATS, "batches")
            if cols is not None:
                bump(FLIGHT_STATS, "columnar_batches")

    def list_flights(self, context, criteria):
        return iter(())

    # ----------------------------------------------------------- lifecycle

    def start(self) -> None:
        self._serve_thread = threading.Thread(target=self.serve,
                                              name="arrow-flight",
                                              daemon=True)
        self._serve_thread.start()
        log.info("arrow flight ingest at %s", self.location)

    def stop(self) -> None:
        self.shutdown()
        if self._serve_thread is not None:
            self._serve_thread.join(timeout=5)
            self._serve_thread = None

    def stats(self) -> dict[str, int]:
        return {"rows_written": self.rows_written, "batches": self.batches,
                "columnar_batches": self.columnar_batches,
                "write_errors": self.write_errors}


# ------------------------------------------------------------------- client

class FlightWriter:
    """Client helper (role of the reference's Java/Python flight client
    examples): connects, optionally authenticates, ships tables."""

    def __init__(self, location: str, username: str = "",
                 password: str = ""):
        if not HAVE_FLIGHT:                          # pragma: no cover
            raise GeminiError("pyarrow.flight unavailable")
        self.client = flight.FlightClient(location)
        if username:
            self.client.authenticate(
                _ClientAuth(json.dumps({"username": username,
                                        "password": password}).encode()))

    def write_table(self, db: str, measurement: str, table,
                    tag_columns: list[str] | None = None) -> None:
        cmd = {"db": db, "measurement": measurement}
        if tag_columns is not None:
            cmd["tag_columns"] = tag_columns
        descriptor = flight.FlightDescriptor.for_command(
            json.dumps(cmd).encode())
        writer, _ = self.client.do_put(descriptor, table.schema)
        writer.write_table(table)
        writer.close()

    def close(self) -> None:
        self.client.close()


if HAVE_FLIGHT:
    class _ClientAuth(flight.ClientAuthHandler):
        def __init__(self, payload: bytes):
            super().__init__()
            self.payload = payload
            self.token = b""

        def authenticate(self, outgoing, incoming):
            outgoing.write(self.payload)
            self.token = incoming.read()

        def get_token(self):
            return self.token
