"""Arrow Flight ingest (role of reference services/arrowflight/service.go:65-131
+ coordinator/record_writer.go:79-326).

High-throughput columnar write path: clients ship Arrow record batches over
gRPC Flight ``DoPut``; the flight descriptor carries a JSON command
``{"db": ..., "rp": ..., "measurement": ..., "tag_columns": [...]}``
(the reference's descriptor carries db/rp/measurement the same way); an
optional handshake token auth gates writes (reference authServer in
service.go). Batches are converted columnar→rows and routed through the
same write entry as the HTTP path (Engine.write_points or the cluster
facade's PointsWriter — per-PT routing happens there).

Columnar conversion rules (reference record_writer.go ArrowRecordToNative):
  - "time" column: int64 ns or any arrow timestamp (normalised to ns);
    missing → server receive time.
  - tag columns: named in the descriptor, else every dictionary-encoded
    string column.
  - remaining columns: fields (float/int/bool/string); nulls are skipped
    per row, matching line-protocol sparse-field semantics.
"""

from __future__ import annotations

import hashlib
import hmac
import json
import secrets
import threading
import time

import numpy as np

from ..storage.rows import PointRow
from ..utils import get_logger
from ..utils.errors import GeminiError

log = get_logger(__name__)

try:
    import pyarrow as pa
    import pyarrow.flight as flight
    HAVE_FLIGHT = True
except Exception:                                    # pragma: no cover
    pa = flight = None
    HAVE_FLIGHT = False


# --------------------------------------------------------------- conversion

def batch_to_rows(batch, measurement: str,
                  tag_columns: list[str] | None = None,
                  recv_time_ns: int | None = None) -> list[PointRow]:
    """Arrow RecordBatch/Table → PointRow list (reference
    record_writer.go:180 arrow → record.Record conversion)."""
    names = batch.schema.names
    if tag_columns is None:
        tag_columns = [f.name for f in batch.schema
                       if pa.types.is_dictionary(f.type)]
    tag_set = set(tag_columns)
    n = batch.num_rows

    times = None
    col_vals: list[tuple[str, list]] = []
    for name, col in zip(names, batch.columns):
        if name == "time":
            scale = 1
            if pa.types.is_timestamp(col.type):
                scale = {"s": 10**9, "ms": 10**6,
                         "us": 10**3, "ns": 1}[col.type.unit]
            t64 = col.cast(pa.int64())
            if t64.null_count:
                # fill nulls in arrow: going through float64 would round
                # every ns timestamp in the batch to ~2^53 precision
                import pyarrow.compute as pc
                now = (recv_time_ns if recv_time_ns is not None
                       else time.time_ns())
                t64 = pc.fill_null(t64, now // scale)
            times = t64.to_numpy(zero_copy_only=False) * scale
            continue
        col_vals.append((name, col.to_pylist()))

    if times is None:
        now = recv_time_ns if recv_time_ns is not None else time.time_ns()
        times = np.full(n, now, dtype=np.int64)

    rows = []
    items = col_vals
    for i in range(n):
        tags, fields = {}, {}
        for name, vals in items:
            v = vals[i]
            if v is None:
                continue
            if name in tag_set:
                tags[name] = str(v)
            else:
                fields[name] = v
        if fields:
            rows.append(PointRow(measurement, tags, fields, int(times[i])))
    return rows


# --------------------------------------------------------------------- auth

class TokenAuthHandler(flight.ServerAuthHandler if HAVE_FLIGHT else object):
    """Handshake auth (reference service.go authServer: user/password in,
    HMAC token out; every later call presents the token)."""

    def __init__(self, users: dict[str, str]):
        if HAVE_FLIGHT:
            super().__init__()
        self.users = users
        self._secret = secrets.token_bytes(16)

    def _token(self, username: str) -> bytes:
        mac = hmac.new(self._secret, username.encode(), hashlib.sha256)
        return (username + ":" + mac.hexdigest()).encode()

    def authenticate(self, outgoing, incoming):
        payload = incoming.read()
        try:
            creds = json.loads(payload.decode())
            user, pwd = creds["username"], creds["password"]
        except Exception:
            raise flight.FlightUnauthenticatedError("bad credentials payload")
        if self.users.get(user) != pwd:
            raise flight.FlightUnauthenticatedError("invalid username/password")
        outgoing.write(self._token(user))

    def is_valid(self, token):
        if not token:
            raise flight.FlightUnauthenticatedError("no token")
        try:
            user = token.decode().split(":", 1)[0]
        except UnicodeDecodeError:
            raise flight.FlightUnauthenticatedError("bad token")
        if not hmac.compare_digest(token, self._token(user)):
            raise flight.FlightUnauthenticatedError("bad token")
        return user.encode()


# ------------------------------------------------------------------- server

class ArrowFlightService((flight.FlightServerBase if HAVE_FLIGHT
                          else object)):
    """Flight ingest endpoint in front of any writer exposing
    ``write_points(db, rows)`` (Engine or ClusterFacade)."""

    def __init__(self, writer, host: str = "127.0.0.1", port: int = 0,
                 users: dict[str, str] | None = None,
                 max_rows_per_batch: int = 1_000_000):
        if not HAVE_FLIGHT:                          # pragma: no cover
            raise GeminiError("pyarrow.flight unavailable")
        self.auth = TokenAuthHandler(users) if users else None
        super().__init__(f"grpc://{host}:{port}", auth_handler=self.auth)
        self.writer = writer
        self.host = host
        self.max_rows_per_batch = max_rows_per_batch
        self.rows_written = 0
        self.batches = 0
        self.write_errors = 0
        self._stats_lock = threading.Lock()
        self._serve_thread: threading.Thread | None = None

    @property
    def location(self) -> str:
        return f"grpc://{self.host}:{self.port}"

    # ---------------------------------------------------------- flight rpc

    def do_put(self, context, descriptor, reader, writer):
        try:
            cmd = json.loads(descriptor.command.decode())
            db = cmd["db"]
            measurement = cmd.get("measurement") or cmd["mst"]
        except Exception:
            raise flight.FlightServerError(
                "descriptor command must be JSON with db/measurement")
        tag_columns = cmd.get("tag_columns")
        recv = time.time_ns()
        for chunk in reader:
            batch = chunk.data
            if batch.num_rows > self.max_rows_per_batch:
                raise flight.FlightServerError("batch too large")
            rows = batch_to_rows(batch, measurement, tag_columns, recv)
            try:
                self.writer.write_points(db, rows)
            except Exception as e:
                with self._stats_lock:
                    self.write_errors += 1
                raise flight.FlightServerError(f"write failed: {e}")
            with self._stats_lock:
                self.rows_written += len(rows)
                self.batches += 1

    def list_flights(self, context, criteria):
        return iter(())

    # ----------------------------------------------------------- lifecycle

    def start(self) -> None:
        self._serve_thread = threading.Thread(target=self.serve,
                                              name="arrow-flight",
                                              daemon=True)
        self._serve_thread.start()
        log.info("arrow flight ingest at %s", self.location)

    def stop(self) -> None:
        self.shutdown()
        if self._serve_thread is not None:
            self._serve_thread.join(timeout=5)
            self._serve_thread = None

    def stats(self) -> dict[str, int]:
        return {"rows_written": self.rows_written, "batches": self.batches,
                "write_errors": self.write_errors}


# ------------------------------------------------------------------- client

class FlightWriter:
    """Client helper (role of the reference's Java/Python flight client
    examples): connects, optionally authenticates, ships tables."""

    def __init__(self, location: str, username: str = "",
                 password: str = ""):
        if not HAVE_FLIGHT:                          # pragma: no cover
            raise GeminiError("pyarrow.flight unavailable")
        self.client = flight.FlightClient(location)
        if username:
            self.client.authenticate(
                _ClientAuth(json.dumps({"username": username,
                                        "password": password}).encode()))

    def write_table(self, db: str, measurement: str, table,
                    tag_columns: list[str] | None = None) -> None:
        cmd = {"db": db, "measurement": measurement}
        if tag_columns is not None:
            cmd["tag_columns"] = tag_columns
        descriptor = flight.FlightDescriptor.for_command(
            json.dumps(cmd).encode())
        writer, _ = self.client.do_put(descriptor, table.schema)
        writer.write_table(table)
        writer.close()

    def close(self) -> None:
        self.client.close()


if HAVE_FLIGHT:
    class _ClientAuth(flight.ClientAuthHandler):
        def __init__(self, payload: bytes):
            super().__init__()
            self.payload = payload
            self.token = b""

        def authenticate(self, outgoing, incoming):
            outgoing.write(self.payload)
            self.token = incoming.read()

        def get_token(self):
            return self.token
