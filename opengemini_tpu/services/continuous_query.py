"""Continuous queries: periodic SELECT ... INTO execution (role of
reference services/continuousquery/service.go:53 + meta CQ lease).

Each CQ re-runs over the window (last_run, now] aligned to its every
interval, substituting the time bounds into the statement condition the way
the reference's CQ scheduler does."""

from __future__ import annotations

import time

from ..query import QueryExecutor, parse_query
from ..query.ast import BinaryExpr, FieldRef, Literal
from ..utils import get_logger
from .base import Service

log = get_logger(__name__)


class ContinuousQueryService(Service):
    name = "continuous_query"

    # a CQ that fell behind replays at most this many intervals (the very
    # first run would otherwise span from epoch 0 and always exceed the
    # executor's window cap, failing forever)
    MAX_CATCHUP_INTERVALS = 10

    def __init__(self, engine, catalog, interval_s: float = 10,
                 now_fn=None):
        super().__init__(interval_s)
        self.engine = engine
        self.catalog = catalog
        self.executor = QueryExecutor(engine)
        self.now_fn = now_fn or (lambda: int(time.time() * 1e9))

    def run_once(self) -> int:
        now = self.now_fn()
        ran = 0
        for db_name in list(self.engine.databases):
            try:
                cqs = self.catalog.continuous_queries(db_name)
            except Exception:
                continue
            for cq in cqs:
                # run when a full interval has elapsed since last run
                due = ((cq.last_run_ns // cq.every_ns) + 1) * cq.every_ns
                if now < due + cq.offset_ns:
                    continue
                t_end = (now - cq.offset_ns) // cq.every_ns * cq.every_ns
                t_start = cq.last_run_ns // cq.every_ns * cq.every_ns
                t_start = max(
                    t_start,
                    t_end - self.MAX_CATCHUP_INTERVALS * cq.every_ns)
                if t_start >= t_end:
                    continue
                try:
                    self._run_cq(db_name, cq, t_start, t_end)
                    self.catalog.set_cq_last_run(db_name, cq.name, t_end)
                    ran += 1
                except Exception:
                    log.exception("cq %s failed", cq.name)
        return ran

    def _run_cq(self, db_name: str, cq, t_start: int, t_end: int) -> None:
        (stmt,) = parse_query(cq.query)
        # bound the query to (t_start, t_end] on top of its own condition
        bound = BinaryExpr(
            "and",
            BinaryExpr(">=", FieldRef("time"), Literal(t_start)),
            BinaryExpr("<", FieldRef("time"), Literal(t_end)))
        stmt.condition = (bound if stmt.condition is None
                          else BinaryExpr("and", stmt.condition, bound))
        res = self.executor.execute(stmt, db_name)
        if "error" in res:
            raise RuntimeError(res["error"])
        log.debug("cq %s ran over [%d, %d)", cq.name, t_start, t_end)
