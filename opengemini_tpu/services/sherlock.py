"""Sherlock self-diagnosis (role of reference lib/sherlock/sherlock.go:29-101,
circle.go, profiles.go + services/sherlock/service.go).

Watches process CPU / memory / thread-count on an interval; when a
dimension breaches its threshold — either an absolute ceiling or a sudden
jump versus the recent moving average (the reference's "diff" trigger) —
it dumps a diagnostic profile to disk, with a per-dimension cooldown and a
bounded number of retained dumps.

Python equivalents of the Go pprof dumps:
  cpu     → multi-sample aggregated stack profile of all threads
  memory  → tracemalloc top allocations (if tracing) + gc / rss summary
  threads → full thread dump (the goroutine-dump analog)
"""

from __future__ import annotations

import gc
import os
import sys
import threading
import time
import traceback
from collections import deque
from dataclasses import dataclass, field

from ..utils import get_logger
from .base import Service

log = get_logger(__name__)

DIMENSIONS = ("cpu", "memory", "threads")


@dataclass
class SherlockConfig:
    """Thresholds mirror reference config lib/config/sherlock.go: per-dim
    max (absolute trigger), diff ratio vs moving average, cooldown."""
    dump_dir: str = "sherlock-dumps"
    cpu_max_pct: float = 90.0
    mem_max_bytes: int = 0              # 0 = disabled
    threads_max: int = 2000
    diff_ratio: float = 1.5             # jump trigger: value > ratio * avg
    min_history: int = 5                # samples before jump trigger arms
    cooldown_s: float = 60.0
    keep_dumps: int = 8


@dataclass
class _DimState:
    history: deque = field(default_factory=lambda: deque(maxlen=30))
    last_dump_ts: float = 0.0
    dumps: int = 0


def _rss_bytes() -> int:
    try:
        with open("/proc/self/statm") as f:
            return int(f.read().split()[1]) * os.sysconf("SC_PAGE_SIZE")
    except (OSError, ValueError, IndexError):
        import resource
        return resource.getrusage(resource.RUSAGE_SELF).ru_maxrss * 1024


class Sherlock(Service):
    """Self-diagnosis watcher (reference sherlock.go monitor loop)."""

    name = "sherlock"

    def __init__(self, config: SherlockConfig | None = None,
                 interval_s: float = 10.0):
        super().__init__(interval_s)
        self.config = config or SherlockConfig()
        self._state = {d: _DimState() for d in DIMENSIONS}
        self._last_cpu = self._cpu_clock()
        self._last_wall = time.monotonic()

    # ------------------------------------------------------------- sampling

    @staticmethod
    def _cpu_clock() -> float:
        import resource
        ru = resource.getrusage(resource.RUSAGE_SELF)
        return ru.ru_utime + ru.ru_stime

    def sample(self) -> dict[str, float]:
        now_cpu, now_wall = self._cpu_clock(), time.monotonic()
        dt = max(now_wall - self._last_wall, 1e-6)
        cpu_pct = 100.0 * (now_cpu - self._last_cpu) / dt
        self._last_cpu, self._last_wall = now_cpu, now_wall
        return {"cpu": cpu_pct, "memory": float(_rss_bytes()),
                "threads": float(threading.active_count())}

    # ------------------------------------------------------------- triggers

    def _limit(self, dim: str) -> float:
        c = self.config
        return {"cpu": c.cpu_max_pct, "memory": float(c.mem_max_bytes),
                "threads": float(c.threads_max)}[dim]

    def check_once(self) -> list[str]:
        """One monitor tick: sample, evaluate triggers, dump. Returns the
        list of dump paths written (for tests/ops visibility)."""
        sample = self.sample()
        written = []
        for dim, value in sample.items():
            st = self._state[dim]
            reason = self._trigger_reason(dim, value, st)
            st.history.append(value)
            if reason is None:
                continue
            now = time.monotonic()
            if now - st.last_dump_ts < self.config.cooldown_s:
                continue                      # reference cooldown semantics
            st.last_dump_ts = now
            path = self._dump(dim, value, reason)
            if path:
                written.append(path)
        return written

    def _trigger_reason(self, dim: str, value: float,
                        st: _DimState) -> str | None:
        limit = self._limit(dim)
        if limit > 0 and value > limit:
            return f"abs value {value:.1f} > max {limit:.1f}"
        if len(st.history) >= self.config.min_history:
            avg = sum(st.history) / len(st.history)
            if avg > 0 and value > self.config.diff_ratio * avg:
                return (f"jump value {value:.1f} > "
                        f"{self.config.diff_ratio:.2f}x avg {avg:.1f}")
        return None

    # ---------------------------------------------------------------- dumps

    def _dump(self, dim: str, value: float, reason: str) -> str | None:
        os.makedirs(self.config.dump_dir, exist_ok=True)
        ts = time.strftime("%Y%m%dT%H%M%S")
        path = os.path.join(self.config.dump_dir, f"{dim}-{ts}.prof.txt")
        try:
            with open(path, "w") as f:
                f.write(f"# sherlock {dim} dump: {reason}\n"
                        f"# value={value} time={time.time()}\n\n")
                f.write(self._profile(dim))
        except OSError as e:
            log.warning("sherlock dump failed: %s", e)
            return None
        st = self._state[dim]
        st.dumps += 1
        log.warning("sherlock: %s anomaly (%s) → %s", dim, reason, path)
        self._trim_dumps(dim)
        return path

    def _trim_dumps(self, dim: str) -> None:
        d = self.config.dump_dir
        try:
            files = sorted(f for f in os.listdir(d)
                           if f.startswith(dim + "-"))
        except OSError:
            return
        for old in files[:-self.config.keep_dumps]:
            try:
                os.unlink(os.path.join(d, old))
            except OSError:
                pass

    def _profile(self, dim: str) -> str:
        if dim == "cpu":
            return self._stack_profile(samples=20, interval_s=0.005)
        if dim == "memory":
            return self._memory_profile()
        return self._thread_dump()

    @staticmethod
    def _thread_dump() -> str:
        names = {t.ident: t.name for t in threading.enumerate()}
        out = []
        for tid, frame in sys._current_frames().items():
            out.append(f"--- thread {names.get(tid, '?')} ({tid}) ---")
            out.extend(s.rstrip() for s in traceback.format_stack(frame))
        return "\n".join(out) + "\n"

    @staticmethod
    def _stack_profile(samples: int, interval_s: float) -> str:
        """Sampling profile: aggregate innermost frames over N samples
        (the cheap stand-in for a Go cpu pprof)."""
        counts: dict[str, int] = {}
        for _ in range(samples):
            for frame in sys._current_frames().values():
                key = (f"{frame.f_code.co_filename}:{frame.f_lineno} "
                       f"{frame.f_code.co_name}")
                counts[key] = counts.get(key, 0) + 1
            time.sleep(interval_s)
        lines = [f"{n:6d}  {k}" for k, n in
                 sorted(counts.items(), key=lambda kv: -kv[1])]
        return "\n".join(lines) + "\n"

    @staticmethod
    def _memory_profile() -> str:
        out = [f"rss_bytes {_rss_bytes()}", f"gc_objects {len(gc.get_objects())}"]
        try:
            import tracemalloc
            if tracemalloc.is_tracing():
                snap = tracemalloc.take_snapshot()
                out.append("\n# top allocations")
                out.extend(str(s) for s in snap.statistics("lineno")[:25])
        except Exception:
            pass
        return "\n".join(out) + "\n"

    # ------------------------------------------------------------ lifecycle

    def run_once(self) -> None:
        self.check_once()

    def stats(self) -> dict[str, int]:
        return {f"{d}_dumps": self._state[d].dumps for d in DIMENSIONS}
