from .base import Service
from .retention import RetentionService
from .downsample import DownsampleService
from .compaction import CompactionService
from .continuous_query import ContinuousQueryService
from .stream import StreamEngine
from .subscriber import SubscriberService
from .hierarchical import HierarchicalStorageService
from .sherlock import Sherlock, SherlockConfig
from .iodetector import IODetector
