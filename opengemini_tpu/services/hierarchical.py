"""Hierarchical storage service (role of reference
services/hierarchical/service.go:75-139: moves warm shards whose time
range has aged past the policy to the cold object-storage tier; queries
keep working through detached reads).

A shard is eligible when its whole time range ended more than
``cold_after_ns`` ago (so it no longer takes writes) and it still has
local TSSP files. Memtables are flushed first so the move is complete.
"""

from __future__ import annotations

import time

from ..utils import get_logger
from .base import Service

log = get_logger(__name__)


class HierarchicalStorageService(Service):
    name = "hierarchical"

    def __init__(self, engine, store, cold_after_ns: int,
                 interval_s: float = 3600.0, now_ns=None):
        super().__init__(interval_s)
        self.engine = engine
        self.store = store
        self.cold_after_ns = cold_after_ns
        self.now_ns = now_ns or time.time_ns
        self.files_moved = 0
        self.shards_moved = 0

    def run_once(self) -> dict:
        cutoff = self.now_ns() - self.cold_after_ns
        moved_files = moved_shards = 0
        for db_name in list(self.engine.databases):
            try:
                db = self.engine.database(db_name)
            except KeyError:
                continue
            # end_time is derivable from the group index — only
            # shards COLD ENOUGH to move materialize (they must open
            # for the detach anyway); warm shards stay lazy
            sd = db.opts.shard_duration
            with db._lock:
                move_gis = [gi for gi in sorted(db.shards)
                            if (gi + 1) * sd <= cutoff]
            for gi in move_gis:
                shard = db.shard_for_time(gi * sd, create=False)
                if shard is None or shard.end_time > cutoff:
                    continue            # still warm
                try:
                    shard.flush()
                    n = shard.detach_files(
                        self.store, f"{db_name}/shard_{gi}")
                except Exception:
                    log.exception("hierarchical move of %s/shard_%s "
                                  "failed", db_name, gi)
                    continue
                if n:
                    moved_files += n
                    moved_shards += 1
                    log.info("moved %s/shard_%s to cold tier (%d files)",
                             db_name, gi, n)
        self.files_moved += moved_files
        self.shards_moved += moved_shards
        return {"files": moved_files, "shards": moved_shards}

    def stats(self) -> dict[str, int]:
        return {"files_moved": self.files_moved,
                "shards_moved": self.shards_moved}
