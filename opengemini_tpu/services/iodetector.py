"""IO hang detector (role of reference lib/iodetector/iodetector.go:55-77).

Two mechanisms, as in the reference:

1. *Operation pinning*: IO call sites wrap their disk operations in
   ``with detector.pin("wal-write")``; a background checker flags any
   pinned operation older than ``timeout_s`` and invokes ``on_hung``
   (the reference's response is suicide / flow-control; here the default
   sets a read-only flag callers can consult, and the callback is
   pluggable so a node app can escalate).

2. *Probe writes*: the detector periodically writes+fsyncs a small probe
   file in each watched directory and measures latency; a probe that
   exceeds the timeout is a hung-disk signal even when no workload IO is
   in flight.
"""

from __future__ import annotations

import os
import threading
import time
from contextlib import contextmanager
from dataclasses import dataclass

from ..utils import get_logger
from .base import Service

log = get_logger(__name__)


@dataclass
class _Pinned:
    name: str
    start: float
    thread: str


class IODetector(Service):
    name = "iodetector"

    def __init__(self, timeout_s: float = 30.0, interval_s: float = 5.0,
                 probe_dirs: tuple[str, ...] = (), on_hung=None):
        super().__init__(interval_s)
        self.timeout_s = timeout_s
        self.probe_dirs = list(probe_dirs)
        self.on_hung = on_hung or self._default_on_hung
        self.read_only = False             # flow-control flag (default action)
        self.hung_events = 0
        self._pins: dict[int, _Pinned] = {}
        self._next_id = 0
        self._lock = threading.Lock()

    # ------------------------------------------------------------- pinning

    @contextmanager
    def pin(self, name: str):
        """Mark an IO operation in flight (reference: timestamp registered
        before each disk op, cleared after)."""
        with self._lock:
            pid = self._next_id
            self._next_id += 1
            self._pins[pid] = _Pinned(name, time.monotonic(),
                                      threading.current_thread().name)
        try:
            yield
        finally:
            with self._lock:
                self._pins.pop(pid, None)

    def check_pins(self) -> list[_Pinned]:
        now = time.monotonic()
        with self._lock:
            stuck = [p for p in self._pins.values()
                     if now - p.start > self.timeout_s]
        for p in stuck:
            self._report(f"io op '{p.name}' on thread {p.thread} stuck "
                         f"{now - p.start:.1f}s (> {self.timeout_s}s)")
        return stuck

    # -------------------------------------------------------------- probes

    def probe_once(self) -> dict[str, float]:
        """Write+fsync a probe file per watched dir; returns latencies."""
        out = {}
        for d in self.probe_dirs:
            path = os.path.join(d, ".io-probe")
            t0 = time.monotonic()
            try:
                with open(path, "w") as f:
                    f.write(str(time.time()))
                    f.flush()
                    os.fsync(f.fileno())
                lat = time.monotonic() - t0
            except OSError as e:
                self._report(f"probe write failed in {d}: {e}")
                continue
            out[d] = lat
            if lat > self.timeout_s:
                self._report(f"probe write in {d} took {lat:.1f}s "
                             f"(> {self.timeout_s}s)")
        return out

    # ------------------------------------------------------------ reaction

    def _report(self, msg: str) -> None:
        self.hung_events += 1
        log.error("iodetector: %s", msg)
        try:
            self.on_hung(msg)
        except Exception:
            log.exception("iodetector on_hung callback failed")

    def _default_on_hung(self, msg: str) -> None:
        self.read_only = True

    def run_once(self) -> None:
        self.check_pins()
        self.probe_once()

    def stats(self) -> dict[str, int]:
        with self._lock:
            inflight = len(self._pins)
        return {"hung_events": self.hung_events, "inflight_ops": inflight,
                "read_only": int(self.read_only)}
