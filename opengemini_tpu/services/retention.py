"""Retention service: delete expired shards per retention policy duration
(role of reference services/retention/service.go:81-331)."""

from __future__ import annotations

import time

from ..utils import get_logger
from .base import Service

log = get_logger(__name__)


class RetentionService(Service):
    name = "retention"

    def __init__(self, engine, catalog, interval_s: float = 1800,
                 now_fn=None, logstore=None):
        super().__init__(interval_s)
        self.engine = engine
        self.catalog = catalog
        self.logstore = logstore      # optional LogStore: per-stream TTLs
        self.now_fn = now_fn or (lambda: int(time.time() * 1e9))

    def run_once(self) -> int:
        now = self.now_fn()
        dropped = 0
        if self.logstore is not None:
            try:
                dropped += self.logstore.apply_retention(now)
            except Exception:
                log.exception("logstore retention failed")
        for db_name in list(self.engine.databases):
            try:
                rp = self.catalog.retention_policy(db_name)
            except Exception:
                continue  # no catalog entry → infinite retention
            if rp.duration_ns <= 0:
                continue
            cutoff = now - rp.duration_ns
            db = self.engine.databases[db_name]
            # end_time derives from the group index — expired shards
            # drop WITHOUT materializing (lazy open stays lazy)
            sd = db.opts.shard_duration
            with db._lock:
                gis = sorted(db.shards)
            for gi in gis:
                if (gi + 1) * sd <= cutoff:
                    log.info("retention: dropping shard %d of %s "
                             "(end %d <= cutoff %d)", gi, db_name,
                             (gi + 1) * sd, cutoff)
                    db.drop_shard(gi)
                    dropped += 1
        return dropped
