"""SQL node: cluster-aware query execution (scatter/gather).

Role of the reference's sql-side coordinator: ClusterShardMapper
(coordinator/shard_mapper.go:60 — sources + time range → per-node
shard/pt sets), RemoteQuery fan-out (rpc_client.go), and the sql-side
final transforms (HashMerge + fill/order/limit).

ClusterExecutor speaks the same `execute(stmt, db) -> result dict`
surface as the single-node QueryExecutor, so the HTTP layer works
unchanged on top of either. ClusterFacade bundles it with a
PointsWriter to present the Engine-ish write surface.
"""

from __future__ import annotations

import threading
from dataclasses import replace

from ..query.ast import (BinaryExpr, Literal,
                         CreateDatabaseStatement, DeleteStatement,
                         DropDatabaseStatement, DropMeasurementStatement,
                         DropSeriesStatement, DropShardStatement,
                         FieldRef, SelectField, SelectStatement,
                         ShowStatement)
from ..query.condition import analyze_condition
from ..query.executor import (classify_select, finalize_partials,
                              inherit_dimensions, inherit_time_bounds,
                              merge_partials,
                              select_over_result, transform_raw_result)
from ..query.incremental import (IncAggCache, complete_prefix,
                                 inc_fingerprint, inc_validate,
                                 trim_left, trim_right)
from ..query.influxql import format_statement
from ..utils import deadline, failpoint, get_logger, knobs
from ..utils.errors import ErrQueryError, ErrQueryTimeout, GeminiError
from .meta_store import MetaClient
from .points_writer import PointsWriter
from .transport import ClientPool, RPCClient, RPCError

log = get_logger(__name__)

# reader-replica query routing (eventual consistency — see map_pts)
READER_ROUTING = bool(knobs.get("OG_READER_ROUTING"))

# how many store failures a scatter tolerates by default before the
# query errors instead of degrading to a flagged partial result
# (config: [data] max_failed_stores; influx partial-series analog)
MAX_FAILED_STORES = int(knobs.get("OG_MAX_FAILED_STORES"))


class ScatterResult(list):
    """Gathered per-store responses. `failed` lists the stores whose
    partitions are MISSING from the gather (tolerated failures): any
    result built from a ScatterResult with failures must carry an
    explicit `partial` flag — a silent partial is indistinguishable
    from a complete result."""

    def __init__(self, it=(), failed: list[str] | None = None):
        super().__init__(it)
        self.failed = list(failed or ())


def _tag_partial(res: dict, *scatters, degraded: bool = False) -> dict:
    """Stamp `partial: true` onto a result assembled from degraded
    scatters (InfluxDB partial-response semantics, surfaced through
    the HTTP layer untouched). Degradation is EITHER a tolerated
    store failure (ScatterResult.failed), a store that answered but
    with an unsound read barrier (response `degraded` flag — the scan
    may miss acked writes), or a caller-known condition passed via
    the `degraded` keyword."""
    failed = [f for s in scatters for f in getattr(s, "failed", ())]
    degraded = degraded or any(isinstance(r, dict) and r.get("degraded")
                               for s in scatters for r in s)
    if (failed or degraded) and isinstance(res, dict) \
            and "error" not in res:
        res = dict(res)
        res["partial"] = True
    return res


class ClusterExecutor:
    def __init__(self, meta: MetaClient, mesh=None,
                 max_failed_stores: int | None = None):
        self.meta = meta
        self._pool = ClientPool()
        self.inc_cache = IncAggCache()
        # partial-result tolerance: scatter degrades (with an explicit
        # partial flag) instead of failing when at most this many
        # stores are down; 0 = fail cleanly (default)
        self.max_failed_stores = (MAX_FAILED_STORES
                                  if max_failed_stores is None
                                  else max_failed_stores)
        # optional local device mesh: when set, grid-aligned per-store
        # partials merge ON DEVICE (psum of exact limb/count grids over
        # the data axis — parallel/meshquery.mesh_merge_partials)
        # instead of host numpy; ragged shapes fall back to the host
        # merge inside finalize_partials
        self.mesh = mesh

    def _client(self, addr: str) -> RPCClient:
        return self._pool.get(addr)

    def close(self) -> None:
        self._pool.close()

    # ------------------------------------------------------------- mapping

    def map_pts(self, db: str) -> dict[str, list[int]]:
        """node addr → partition ids to query there (shard_mapper.go:
        415-472 read distribution). Default: one owner per pt. With
        read/write node roles, a pt whose candidate set (owner +
        replicas) contains alive READER nodes is served by a reader —
        replicas hold identical partition state via the per-PT raft
        groups, so ingest (writers) and scans (readers) separate.

        Consistency note: replica apply is asynchronous, so reader
        routing is read-committed-EVENTUAL — a client may not see its
        own just-acked write on the very next query (the owner path
        guarantees read-your-writes). OG_READER_ROUTING=0 disables
        reader preference."""
        md = self.meta.data()
        if md.db(db) is None:
            self.meta.refresh()
            md = self.meta.data()
        info = md.db(db)
        if info is None:
            raise ErrQueryError(f"database not found: {db}")
        offline = [p.pt_id for p in md.pts.get(db, [])
                   if p.status != "online"]
        if offline:
            # a parked partition must fail the query loudly — silently
            # omitting it would return partial results indistinguishable
            # from correct ones
            raise ErrQueryError(
                f"partitions unavailable for {db}: {offline}")
        out: dict[str, list[int]] = {}
        for pt in md.pts.get(db, []):
            cands = [pt.owner] + [r for r in pt.replicas
                                  if r != pt.owner]
            nodes = [md.nodes[c] for c in cands
                     if c in md.nodes
                     and md.nodes[c].status == "alive"]
            readers = [n for n in nodes if n.role == "reader"] \
                if READER_ROUTING else []
            if readers:
                target = readers[pt.pt_id % len(readers)]
            else:
                target = md.nodes.get(pt.owner)
                if target is None:
                    raise ErrQueryError(
                        f"pt owner node {pt.owner} unknown")
            out.setdefault(target.addr, []).append(pt.pt_id)
        return out

    def _scatter(self, msg: str, db: str, body_extra: dict,
                 timeout: float = 120.0,
                 max_failed: int | None = None) -> ScatterResult:
        """Send one request per store node owning pts of db; gather.
        A store RPC failure refreshes the catalog and retries once —
        after a PT takeover the stale cache still routes to the dead
        node (reference metaclient retry loops, meta_client.go).

        Deadline: the per-RPC timeout is clamped by the request budget
        bound in the dispatching thread (utils.deadline) — a slow store
        consumes the REMAINING budget, never a fresh `timeout` per hop;
        an exhausted budget raises the typed ErrQueryTimeout.

        Partial results: with max_failed > 0 (default: this executor's
        max_failed_stores), up to that many stores may stay down after
        the refresh+retry — their partitions are omitted and the
        ScatterResult's `failed` list is non-empty, which callers MUST
        surface as an explicit `partial` flag."""
        if max_failed is None:
            max_failed = self.max_failed_stores
        dl = deadline.current()   # capture BEFORE the thread fan-out
        # trace context: thread-locals don't cross the fan-out threads,
        # so capture the parent span here and re-bind a per-store
        # "scatter" child inside each worker — the RPC client then
        # ships the context and grafts the store-side tree under it
        from ..utils import tracing as _tracing
        parent_sp = _tracing.current_span()
        parent_tid = _tracing.current_trace_id()
        last_err = None
        for attempt in range(2):
            if dl is not None:
                dl.check("scatter")
            per_node = self.map_pts(db)
            results: list = [None] * len(per_node)
            ok = [False] * len(per_node)
            errors: list[str] = []
            timed_out: list[str] = []
            lock = threading.Lock()

            def run(i: int, addr: str, pts: list[int],
                    results=results, ok=ok, errors=errors,
                    timed_out=timed_out, lock=lock):
                sc_sp = None
                if parent_sp is not None:
                    sc_sp = parent_sp.child("scatter")
                    sc_sp.add(addr=addr, msg=msg, pts=len(pts))
                try:
                    failpoint.inject("sql.scatter.delay")
                    if failpoint.inject("sql.scatter.drop"):
                        raise RPCError("failpoint: sql.scatter.drop")
                    t = dl.clamp(timeout) if dl is not None else timeout
                    body = {"db": db, "pts": pts, **body_extra}
                    if sc_sp is not None:
                        with sc_sp, _tracing.bind(sc_sp, parent_tid):
                            results[i] = self._client(addr).call(
                                msg, body, timeout=t)
                    else:
                        results[i] = self._client(addr).call(
                            msg, body, timeout=t)
                    ok[i] = True
                except ErrQueryTimeout as e:
                    with lock:
                        timed_out.append(str(e))
                except RPCError as e:
                    # a store that ran out the request budget is a
                    # deadline problem, not a failed-store problem —
                    # partial tolerance must not mask it
                    with lock:
                        if dl is not None and dl.expired:
                            timed_out.append(f"{addr}: {e}")
                        else:
                            errors.append(f"{addr}: {e}")
                except Exception as e:  # noqa: BLE001 — a dying worker
                    # (e.g. a failpoint armed with action=error) must
                    # surface as a failed store, never as a silent
                    # omission the gather would mistake for success
                    with lock:
                        errors.append(
                            f"{addr}: {type(e).__name__}: {e}")

            threads = [threading.Thread(target=run, args=(i, a, p))
                       for i, (a, p) in enumerate(per_node.items())]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            if timed_out:
                raise ErrQueryTimeout(
                    "query deadline exceeded in scatter: "
                    + "; ".join(timed_out[:3]))
            if not errors:
                return ScatterResult(
                    (r for i, r in enumerate(results)
                     if ok[i] and r is not None))
            last_err = "; ".join(errors)
            if attempt == 0:
                self.meta.refresh()
        if any(ok) and len(errors) <= max_failed:
            log.warning("scatter %s on %s degraded: tolerating %d "
                        "failed store(s): %s", msg, db, len(errors),
                        last_err)
            return ScatterResult(
                (r for i, r in enumerate(results)
                 if ok[i] and r is not None),
                failed=errors)
        raise ErrQueryError(last_err)

    # ------------------------------------------------------------- execute

    def execute(self, stmt, db: str | None = None, ctx=None,
                span=None, inc_query_id: str | None = None,
                iter_id: int = 0) -> dict:
        # ctx (QueryContext): accepted for HTTP-layer parity with the
        # single-node executor; scatter hops check it at the statement
        # boundary (store-side kill propagation is the RPC's concern).
        # span: the HTTP layer's per-statement trace span — scatter
        # workers pick it up via the thread-local context the HTTP
        # layer binds (utils.tracing.bind), so it is accepted here
        # only for signature parity with QueryExecutor.execute
        try:
            if ctx is not None and getattr(ctx, "killed", False):
                return {"error": f"query {ctx.qid} killed"}
            if isinstance(stmt, SelectStatement):
                if stmt.join is not None:
                    from ..query.join import execute_join
                    return execute_join(self, stmt, stmt.from_db or db)
                if stmt.extra_sources:
                    from ..query.join import execute_multi_source
                    return execute_multi_source(self, stmt,
                                                stmt.from_db or db)
                return self._select(stmt, stmt.from_db or db,
                                    inc_query_id=inc_query_id,
                                    iter_id=iter_id)
            if isinstance(stmt, ShowStatement):
                return self._show(stmt, stmt.on_db or db)
            if isinstance(stmt, CreateDatabaseStatement):
                self.meta.create_database(stmt.name)
                return {}
            if isinstance(stmt, DropDatabaseStatement):
                return self._drop_database(stmt.name)
            if isinstance(stmt, (DropMeasurementStatement,
                                 DeleteStatement, DropSeriesStatement,
                                 DropShardStatement)):
                return self._ddl(stmt, db)
            return {"error":
                    f"unsupported statement {type(stmt).__name__}"}
        except (ErrQueryError, GeminiError, RPCError) as e:
            return {"error": str(e)}

    def _select(self, stmt: SelectStatement, db: str | None,
                inc_query_id: str | None = None,
                iter_id: int = 0) -> dict:
        if db is None:
            return {"error": "database required"}
        if stmt.from_subquery is not None:
            # scatter/gather the inner select, then run the outer locally
            # over the materialized result (subquery results are already
            # globally merged, so the outer stage is single-node work)
            inner = inherit_time_bounds(stmt, stmt.from_subquery)
            inner = inherit_dimensions(stmt, inner)
            inner_res = self._select(inner, inner.from_db or db)
            if "error" in inner_res:
                return inner_res
            return select_over_result(stmt, db, inner_res)
        if stmt.from_regex is not None:
            # FROM /regex/: expand against the union of store-side
            # measurement catalogs, then run as a multi-source union
            # (per-measurement series sets, like FROM m1, m2)
            import re as _re
            rx = _re.compile(stmt.from_regex)
            names: set = set()
            # regex expansion must see EVERY store's catalog — a
            # partial union would silently drop whole measurements
            for r in self._scatter("store.measurements", db, {},
                                   max_failed=0):
                names.update(r.get("measurements", ()))
            matched = sorted(n for n in names if rx.search(n))
            if not matched:
                return {}
            stmt = replace(stmt, from_regex=None,
                           from_measurement=matched[0],
                           extra_sources=list(stmt.extra_sources)
                           + matched[1:])
            if stmt.extra_sources:
                from ..query.join import execute_multi_source
                return execute_multi_source(self, stmt, db)
        mst = stmt.from_measurement
        cs = classify_select(stmt)
        # the optimized plan's Exchange node picks the scatter payload
        # ('partials' vs 'raw') — the reference's NODE_EXCHANGE
        # consumption (select.go:209-212); classify_select still
        # supplies the field/agg details within that choice
        from ..query.logical import exchange_payload, plan_hints
        if cs.mode == "agg" and exchange_payload(stmt) == "partials":
            if inc_query_id:
                return self._select_agg_incremental(
                    stmt, db, mst, cs, inc_query_id, iter_id)
            q = format_statement(stmt)
            resps = self._scatter("store.select_partial", db, {"q": q})
            partials = [r["partial"] for r in resps]
            if self.mesh is not None and len(partials) > 1:
                from ..parallel.meshquery import mesh_merge_partials
                merged = mesh_merge_partials(self.mesh, partials)
                if merged is not None:
                    partials = [merged]
            return _tag_partial(
                finalize_partials(stmt, mst, cs, partials,
                                  plan=plan_hints(stmt)), resps)
        if cs.mode == "agg":
            # plan chose a RAW exchange for an aggregate (degradation /
            # rule override): scatter plain scans of the aggregate's
            # input fields and run the full aggregation locally over
            # the merged rows — slower, still exact
            names = sorted({a.field for a in cs.aggs} | cs.raw_refs)
            sub = replace(stmt,
                          fields=[SelectField(FieldRef(n))
                                  for n in names],
                          limit=0, offset=0, slimit=0, soffset=0,
                          order_desc=False)
            q = format_statement(sub)
            resps = self._scatter("store.select_raw", db, {"q": q})
            merged = self._merge_raw(sub, resps, names)
            return _tag_partial(select_over_result(stmt, db, merged),
                                resps)
        if cs.is_plain_raw:
            q = format_statement(stmt)
            resps = self._scatter("store.select_raw", db, {"q": q})
            field_order = (None if cs.has_wildcard
                           else [alias or name
                                 for name, alias in cs.raw_fields])
            return _tag_partial(self._merge_raw(stmt, resps, field_order),
                                resps)
        # expression / transform raw mode: ship a plain scan of the
        # referenced fields (limits stripped — transforms change row
        # counts), merge, then materialize at the sql node (the
        # reference's sql-side Materialize/transform stage)
        names = sorted(cs.raw_refs)
        sub = replace(stmt,
                      fields=[SelectField(FieldRef(n)) for n in names],
                      limit=0, offset=0, slimit=0, soffset=0,
                      order_desc=False)
        q = format_statement(sub)
        resps = self._scatter("store.select_raw", db, {"q": q})
        merged = self._merge_raw(sub, resps, names)
        return _tag_partial(transform_raw_result(cs, stmt, merged),
                            resps)

    def _select_agg_incremental(self, stmt, db, mst, cs,
                                inc_query_id: str, iter_id: int) -> dict:
        """Cluster incremental aggregation: the sql node caches the
        globally-MERGED partial state (trimmed to complete windows) and
        re-scatters only `time >= watermark` — the stores re-scan the
        tail, everything older is served from the cache (same semantics
        as QueryExecutor._partial_agg_incremental; see
        query/incremental.py)."""
        cond = analyze_condition(stmt.condition, set())
        err = inc_validate(stmt, cond)
        if err is not None:
            return {"error": err}
        fp = inc_fingerprint(db, mst, stmt, cond)
        cached = self.inc_cache.get(inc_query_id) if iter_id > 0 else None
        cached_p = None
        if cached is not None and cached.fingerprint == fp:
            cached_p = trim_left(cached.partial, cond.t_min)
            if cached_p is not None:
                cached_p = trim_right(cached_p, cond.t_max)

        degraded = False

        def scatter(s) -> list:
            nonlocal degraded
            resps = self._scatter("store.select_partial", db,
                                  {"q": format_statement(s)})
            if resps.failed or any(r.get("degraded") for r in resps):
                degraded = True
            return [r["partial"] for r in resps]

        if cached_p is not None:
            tail = replace(stmt, condition=BinaryExpr(
                "and", stmt.condition,
                BinaryExpr(">=", FieldRef("time"),
                           Literal(cached.watermark))))
            fresh = [p for p in scatter(tail) if p is not None]
            if not fresh:
                # nothing at/after the watermark: serve the cached
                # prefix, leave the entry untouched
                return _tag_partial(
                    finalize_partials(stmt, mst, cs, [cached_p]),
                    degraded=degraded)
            partial = merge_partials([cached_p] + fresh)
        else:
            partial = merge_partials(scatter(stmt))
        trimmed, watermark = complete_prefix(partial)
        if trimmed is not None and not degraded:
            # a degraded scatter must NEVER seed the incremental cache:
            # the missing stores' windows would be served as "complete"
            # forever after
            self.inc_cache.put(inc_query_id, fp, trimmed, watermark)
        return _tag_partial(finalize_partials(stmt, mst, cs, [partial]),
                            degraded=degraded)

    def _merge_raw(self, stmt: SelectStatement, resps: list,
                   field_order: list[str] | None = None) -> dict:
        """Merge raw-select series lists from stores: group by (name,
        tags), align columns (SELECT * may see different field sets per
        partition), concatenate + time-sort rows, apply limits
        globally. field_order preserves explicit SELECT order when
        partitions expose different field subsets; None (wildcard) widens
        to the sorted union."""
        groups: dict[tuple, dict] = {}
        for resp in resps:
            for series_list in resp["series_lists"]:
                for s in series_list:
                    key = (s["name"],
                           tuple(sorted((s.get("tags") or {}).items())))
                    g = groups.get(key)
                    if g is None:
                        groups[key] = {"name": s["name"],
                                       "tags": s.get("tags"),
                                       "columns": list(s["columns"]),
                                       "values": list(s["values"])}
                        continue
                    if s["columns"] == g["columns"]:
                        g["values"].extend(s["values"])
                        continue
                    # column sets differ: widen to the union — explicit
                    # SELECT keeps the selection order, wildcard sorts
                    # (matching the single-node wildcard field order)
                    present = set(g["columns"][1:]) | set(s["columns"][1:])
                    if field_order is not None:
                        ordered = [c for c in field_order if c in present]
                        ordered += sorted(present - set(ordered))
                    else:
                        ordered = sorted(present)
                    union = [g["columns"][0]] + ordered
                    if union != g["columns"]:
                        remap = [g["columns"].index(c)
                                 if c in g["columns"] else None
                                 for c in union]
                        g["values"] = [
                            [None if j is None else row[j] for j in remap]
                            for row in g["values"]]
                        g["columns"] = union
                    remap = [s["columns"].index(c)
                             if c in s["columns"] else None for c in union]
                    g["values"].extend(
                        [None if j is None else row[j] for j in remap]
                        for row in s["values"])
        series_out = []
        for key in sorted(groups, key=lambda k: (k[0], k[1])):
            g = groups[key]
            rows = sorted(g["values"], key=lambda r: r[0],
                          reverse=stmt.order_desc)
            if stmt.offset:
                rows = rows[stmt.offset:]
            if stmt.limit:
                rows = rows[:stmt.limit]
            if not rows:
                continue
            entry = {"name": g["name"], "columns": g["columns"],
                     "values": rows}
            if g["tags"]:
                entry["tags"] = g["tags"]
            series_out.append(entry)
        if stmt.soffset:
            series_out = series_out[stmt.soffset:]
        if stmt.slimit:
            series_out = series_out[:stmt.slimit]
        return {"series": series_out} if series_out else {}

    def _show(self, stmt: ShowStatement, db: str | None) -> dict:
        if stmt.what == "databases":
            names = sorted(self.meta.data().databases)
            return {"series": [{"name": "databases", "columns": ["name"],
                                "values": [[n] for n in names]}]}
        if db is None or self.meta.database(db) is None:
            self.meta.refresh()
            if self.meta.database(db) is None:
                return {"error": f"database not found: {db}"}
        # cardinality over the cluster: counts cannot merge by union —
        # scatter the LISTING form, dedup keys globally, then count
        # (exact, like the single-node path; reference SHOW ...
        # CARDINALITY exact mode)
        card_src = {"series cardinality": "series",
                    "measurement cardinality": "measurements",
                    "tag key cardinality": "tag keys",
                    "tag values cardinality": "tag values",
                    "field key cardinality": "field keys"}
        if stmt.what in card_src:
            inner = replace(stmt, what=card_src[stmt.what],
                            limit=0, offset=0)
            res = self._show(inner, db)
            if "error" in res:
                return res
            sers = res.get("series", [])
            # a degraded listing yields a degraded count — keep the flag
            inner_partial = bool(res.get("partial"))
            if stmt.what in ("series cardinality",
                             "measurement cardinality"):
                n = sum(len(s["values"]) for s in sers)
                return _tag_partial({"series": [{
                    "name": stmt.what,
                    "columns": ["cardinality estimation"],
                    "values": [[n]]}]}, degraded=inner_partial)
            out = [{"name": s["name"], "columns": ["count"],
                    "values": [[len(s["values"])]]} for s in sers]
            return _tag_partial({"series": out} if out else {},
                                degraded=inner_partial)
        # ship without LIMIT/OFFSET — they apply once, after the union
        q = format_statement(replace(stmt, limit=0, offset=0))
        resps = self._scatter("store.show", db, {"q": q})
        show_partial = bool(resps.failed)
        # union values per series name across stores
        merged: dict[str, dict] = {}
        for resp in resps:
            for series_list in resp["series_lists"]:
                for s in series_list:
                    g = merged.get(s["name"])
                    if g is None:
                        merged[s["name"]] = {"columns": s["columns"],
                                             "values": set(
                                                 tuple(v) for v in
                                                 s["values"])}
                    else:
                        g["values"].update(tuple(v) for v in s["values"])
        series_out = [{"name": name, "columns": m["columns"],
                       "values": [list(v) for v in sorted(m["values"])]}
                      for name, m in sorted(merged.items())]
        lo = stmt.offset
        hi = lo + stmt.limit if stmt.limit else None
        for s in series_out:
            s["values"] = s["values"][lo:hi]
        out = {"series": series_out} if series_out else {}
        return _tag_partial(out, degraded=show_partial)

    def _ddl(self, stmt, db: str | None) -> dict:
        """Scatter DROP MEASUREMENT / DELETE to every store owning PTs of
        the db (reference netstorage DDL message fan-out)."""
        if isinstance(stmt, DeleteStatement) \
                and not stmt.from_measurement:
            return {"error": "DELETE requires FROM <measurement>"}
        if db is None:
            return {"error": "database required"}
        if self.meta.database(db) is None:
            self.meta.refresh()
            if self.meta.database(db) is None:
                return {"error": f"database not found: {db}"}
        q = format_statement(stmt)
        # DDL is all-or-error: a "partial DROP" would leave zombie data
        resps = self._scatter("store.ddl", db, {"q": q}, max_failed=0)
        errs = [r.get("error", "ddl failed") for r in resps
                if r and not r.get("ok", True)]
        return {"error": "; ".join(errs)} if errs else {}

    def _drop_database(self, name: str) -> dict:
        try:
            self._scatter("store.drop_db", name, {}, max_failed=0)
        except ErrQueryError:
            pass                      # db may not exist on some stores
        self.meta.drop_database(name)
        return {}


class ClusterFacade:
    """Engine-shaped adapter for the HTTP layer in cluster mode: writes
    route through PointsWriter, `databases` reads the meta cache."""

    def __init__(self, meta: MetaClient, auto_create_db: bool = True):
        self.meta = meta
        self.writer = PointsWriter(meta, auto_create_db=auto_create_db)
        self.executor = ClusterExecutor(meta)

    @property
    def databases(self):
        return self.meta.data().databases

    def write_points(self, db: str, rows) -> int:
        return self.writer.write_points(db, rows)

    def write_lines(self, db: str, data: bytes,
                    default_time_ns: int = 0,
                    precision: str = "ns") -> int:
        """Columnar line-protocol scatter (points_writer._write_lines)."""
        return self.writer.write_lines(db, data,
                                       default_time_ns=default_time_ns,
                                       precision=precision)

    def create_database(self, name: str, **kw) -> None:
        self.meta.create_database(name, **kw)

    def drop_database(self, name: str) -> None:
        self.executor._drop_database(name)

    # ---------------------------------------------- range sharding ops

    def shard_split_points(self, db: str,
                           measurement: str | None = None) -> list[str]:
        """Balanced shard-key range bounds from store-side samples
        (reference Engine.GetShardSplitPoints engine/engine.go:930 +
        meta split points): one bound per partition, bounds[0] = ''."""
        info = self.meta.database(db)
        if info is None:
            raise ErrQueryError(f"database not found: {db}")
        if not info.shard_key:
            raise ErrQueryError(
                f"database {db} has no shard key configured")
        # bounds from a partial sample set would skew the ranges —
        # require every store
        resps = self.executor._scatter(
            "store.split_points", db,
            {"measurement": measurement, "shard_key": info.shard_key},
            max_failed=0)
        samples = sorted(s for r in resps for s in r.get("samples", ()))
        n = info.num_pts
        bounds = [""]
        for i in range(1, n):
            bounds.append(samples[i * len(samples) // n]
                          if samples else "")
        return bounds

    def rebalance_shard_ranges(self, db: str,
                               measurement: str | None = None
                               ) -> list[str]:
        """Compute split points and commit them as the db's shard-key
        ranges (existing + future shard groups); writes start range-
        routing once bounds are live. Returns the bounds."""
        bounds = self.shard_split_points(db, measurement)
        self.meta.set_shard_ranges(db, bounds)
        return bounds

    def close(self) -> None:
        self.writer.close()
        self.executor.close()
