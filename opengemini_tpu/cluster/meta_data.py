"""Replicated cluster catalog data model.

Role of the reference's meta data model (lib/util/lifted/influx/meta/
data.go:1-4200, shardinfo.go) — the state machine content replicated by
the meta raft group:

- DataNode: a store node (id, rpc addr, status) — data.go DataNode.
- PtInfo: logical partition of a database, owned by one node
  (engine/partition.go DBPTInfo assignment; moved on failure).
- ShardGroupInfo: one time slice of a database; holds one shard per
  partition. Routing: time → shard group, series hash → shard
  (ShardFor, shardinfo.go:369-375) or shard-key range (DestShard,
  shardinfo.go:359-366).

Everything is plain dict/dataclass state, JSON-serializable: the raft
FSM applies commands to a MetaData, snapshots marshal it whole.
"""

from __future__ import annotations

import bisect
from dataclasses import dataclass, field

NS_PER_HOUR = 3600 * 10**9
DEFAULT_SHARD_DURATION = 24 * 7 * NS_PER_HOUR

STATUS_ALIVE = "alive"
STATUS_FAILED = "failed"

PT_ONLINE = "online"
PT_OFFLINE = "offline"
PT_MIGRATING = "migrating"


ROLE_BOTH = "both"
ROLE_WRITER = "writer"
ROLE_READER = "reader"


@dataclass
class DataNode:
    id: int
    addr: str                      # store RPC address host:port
    status: str = STATUS_ALIVE
    last_heartbeat: int = 0        # ns timestamp, maintained by meta
    # read/write separation (reference AliveReadNodes
    # lib/metaclient/meta_client.go:623 + shard_mapper.go:415-472):
    # reader nodes serve queries from replicated partitions, writers
    # take ingest; "both" does either
    role: str = ROLE_BOTH

    def to_dict(self):
        return {"id": self.id, "addr": self.addr, "status": self.status,
                "last_heartbeat": self.last_heartbeat, "role": self.role}

    @classmethod
    def from_dict(cls, d):
        d = dict(d)
        d.setdefault("role", ROLE_BOTH)
        return cls(**d)


@dataclass
class PtInfo:
    db: str
    pt_id: int
    owner: int                     # node id
    status: str = PT_ONLINE
    replicas: list[int] = field(default_factory=list)  # replica node ids

    def to_dict(self):
        return {"db": self.db, "pt_id": self.pt_id, "owner": self.owner,
                "status": self.status, "replicas": self.replicas}

    @classmethod
    def from_dict(cls, d):
        return cls(**d)


@dataclass
class ShardInfo:
    id: int
    pt_id: int                     # owning partition
    min_key: str = ""              # range sharding bounds (optional)
    max_key: str = ""

    def to_dict(self):
        return {"id": self.id, "pt_id": self.pt_id,
                "min_key": self.min_key, "max_key": self.max_key}

    @classmethod
    def from_dict(cls, d):
        return cls(**d)


@dataclass
class ShardGroupInfo:
    id: int
    start_time: int                # [start, end) ns
    end_time: int
    shards: list[ShardInfo] = field(default_factory=list)
    deleted: bool = False

    def shard_for(self, h: int) -> ShardInfo:
        """Hash routing (reference ShardFor shardinfo.go:369-375)."""
        return self.shards[h % len(self.shards)]

    @property
    def ranged(self) -> bool:
        """True when shard-key range bounds have been assigned (until
        then key routing would dump everything into shard 0)."""
        return any(s.min_key for s in self.shards)

    def dest_shard(self, shard_key: str) -> ShardInfo:
        """Range routing (reference DestShard shardinfo.go:359-366):
        shards ordered by min_key; pick the last whose min_key <= key."""
        keys = [s.min_key for s in self.shards]
        i = bisect.bisect_right(keys, shard_key) - 1
        return self.shards[max(i, 0)]

    def contains(self, t: int) -> bool:
        return self.start_time <= t < self.end_time

    def overlaps(self, t_min: int, t_max: int) -> bool:
        return self.start_time <= t_max and t_min < self.end_time

    def to_dict(self):
        return {"id": self.id, "start_time": self.start_time,
                "end_time": self.end_time, "deleted": self.deleted,
                "shards": [s.to_dict() for s in self.shards]}

    @classmethod
    def from_dict(cls, d):
        return cls(id=d["id"], start_time=d["start_time"],
                   end_time=d["end_time"], deleted=d.get("deleted", False),
                   shards=[ShardInfo.from_dict(s) for s in d["shards"]])


@dataclass
class DatabaseInfo:
    name: str
    num_pts: int = 1
    replica_n: int = 1
    shard_duration: int = DEFAULT_SHARD_DURATION
    shard_groups: list[ShardGroupInfo] = field(default_factory=list)
    # range sharding (reference shardinfo.go:359 DestShard): tag names
    # forming the shard key; range_bounds[i] = min_key of shard i,
    # applied to every new shard group (bounds[0] is always "")
    shard_key: list[str] = field(default_factory=list)
    range_bounds: list[str] = field(default_factory=list)

    def to_dict(self):
        return {"name": self.name, "num_pts": self.num_pts,
                "replica_n": self.replica_n,
                "shard_duration": self.shard_duration,
                "shard_key": self.shard_key,
                "range_bounds": self.range_bounds,
                "shard_groups": [g.to_dict() for g in self.shard_groups]}

    @classmethod
    def from_dict(cls, d):
        return cls(name=d["name"], num_pts=d["num_pts"],
                   replica_n=d.get("replica_n", 1),
                   shard_duration=d["shard_duration"],
                   shard_key=list(d.get("shard_key", ())),
                   range_bounds=list(d.get("range_bounds", ())),
                   shard_groups=[ShardGroupInfo.from_dict(g)
                                 for g in d["shard_groups"]])


def _assign_bounds(shards: list[ShardInfo], bounds: list[str]) -> None:
    """Apply sorted range bounds to a shard list (min_key per shard,
    max_key = next shard's min, last open)."""
    for s, b in zip(shards, bounds):
        s.min_key = b
    for i, s in enumerate(shards[:-1]):
        s.max_key = shards[i + 1].min_key
    shards[-1].max_key = ""


class MetaData:
    """The replicated catalog. Mutations happen ONLY through apply() —
    the raft FSM entry point — so every replica deterministically reaches
    the same state (reference store_fsm.go)."""

    def __init__(self):
        self.version = 0
        self.nodes: dict[int, DataNode] = {}
        self.databases: dict[str, DatabaseInfo] = {}
        self.pts: dict[str, list[PtInfo]] = {}       # db -> pt list
        self.next_node_id = 1
        self.next_shard_id = 1
        self.next_sg_id = 1

    # ------------------------------------------------------------- queries

    def db(self, name: str) -> DatabaseInfo | None:
        return self.databases.get(name)

    def alive_nodes(self) -> list[DataNode]:
        return [n for n in self.nodes.values() if n.status == STATUS_ALIVE]

    def pt(self, db: str, pt_id: int) -> PtInfo | None:
        for pt in self.pts.get(db, []):
            if pt.pt_id == pt_id:
                return pt
        return None

    def pt_owner(self, db: str, pt_id: int) -> DataNode | None:
        pt = self.pt(db, pt_id)
        return self.nodes.get(pt.owner) if pt is not None else None

    def shard_group_for_time(self, db: str, t: int) -> ShardGroupInfo | None:
        info = self.databases.get(db)
        if info is None:
            return None
        for g in info.shard_groups:
            if not g.deleted and g.contains(t):
                return g
        return None

    def shard_groups_overlapping(self, db: str, t_min: int,
                                 t_max: int) -> list[ShardGroupInfo]:
        info = self.databases.get(db)
        if info is None:
            return []
        return [g for g in info.shard_groups
                if not g.deleted and g.overlaps(t_min, t_max)]

    def pts_by_node(self, db: str) -> dict[int, list[PtInfo]]:
        """node id → partitions of db it owns (online only)."""
        out: dict[int, list[PtInfo]] = {}
        for pt in self.pts.get(db, []):
            if pt.status == PT_ONLINE:
                out.setdefault(pt.owner, []).append(pt)
        return out

    # -------------------------------------------------------- FSM commands

    def apply(self, cmd: dict):
        """Apply one replicated command; returns the command's result.
        Must be deterministic — no wall clock, no randomness (timestamps
        ride inside the command)."""
        op = cmd["op"]
        fn = getattr(self, f"_apply_{op}", None)
        if fn is None:
            raise ValueError(f"unknown meta op {op!r}")
        res = fn(cmd)
        self.version += 1
        return res

    def _apply_create_node(self, cmd):
        addr = cmd["addr"]
        role = cmd.get("role", ROLE_BOTH)
        for n in self.nodes.values():
            if n.addr == addr:                      # re-join keeps the id
                n.status = STATUS_ALIVE
                n.last_heartbeat = cmd.get("now", 0)
                n.role = role
                return n.id
        nid = self.next_node_id
        self.next_node_id += 1
        self.nodes[nid] = DataNode(id=nid, addr=addr, role=role,
                                   last_heartbeat=cmd.get("now", 0))
        return nid

    def _apply_heartbeat(self, cmd):
        n = self.nodes.get(cmd["node_id"])
        if n is not None:
            n.last_heartbeat = cmd.get("now", 0)
            if n.status != STATUS_ALIVE:
                n.status = STATUS_ALIVE
        return None

    def _apply_set_node_status(self, cmd):
        n = self.nodes.get(cmd["node_id"])
        if n is not None:
            n.status = cmd["status"]
        return None

    def _apply_create_database(self, cmd):
        name = cmd["name"]
        if name in self.databases:
            return False
        if not self.alive_nodes():
            raise ValueError(
                "cannot create database: no alive data nodes registered")
        num_pts = cmd.get("num_pts") or len(self.alive_nodes())
        self.databases[name] = DatabaseInfo(
            name=name, num_pts=num_pts,
            replica_n=cmd.get("replica_n", 1),
            shard_duration=cmd.get("shard_duration",
                                   DEFAULT_SHARD_DURATION),
            shard_key=list(cmd.get("shard_key", ())))
        # assign PTs round-robin over alive WRITE-CAPABLE nodes (data.go
        # CreateDBPtView; reference excludes reader nodes from ownership
        # — owners take ingest). Readers join as replicas only.
        alive = sorted(n.id for n in self.alive_nodes())
        owners = sorted(n.id for n in self.alive_nodes()
                        if n.role != ROLE_READER) or alive
        pts = []
        for i in range(num_pts):
            owner = owners[i % len(owners)]
            # distinct non-owner replicas, clamped to the node count
            reps = []
            for r in range(1, len(alive)):
                if len(reps) >= cmd.get("replica_n", 1) - 1:
                    break
                cand = alive[(alive.index(owner) + r) % len(alive)]
                if cand != owner and cand not in reps:
                    reps.append(cand)
            pts.append(PtInfo(db=name, pt_id=i, owner=owner,
                              replicas=reps))
        self.pts[name] = pts
        return True

    def _apply_drop_database(self, cmd):
        self.databases.pop(cmd["name"], None)
        self.pts.pop(cmd["name"], None)
        return None

    def _apply_create_shard_group(self, cmd):
        """Idempotent: returns the existing group if one covers t."""
        db, t = cmd["db"], cmd["t"]
        info = self.databases.get(db)
        if info is None:
            raise ValueError(f"database not found: {db}")
        g = self.shard_group_for_time(db, t)
        if g is not None:
            return g.to_dict()
        sd = info.shard_duration
        start = t // sd * sd
        shards = []
        for pt in self.pts.get(db, []):
            shards.append(ShardInfo(id=self.next_shard_id,
                                    pt_id=pt.pt_id))
            self.next_shard_id += 1
        if info.range_bounds and len(info.range_bounds) == len(shards):
            _assign_bounds(shards, info.range_bounds)
        g = ShardGroupInfo(id=self.next_sg_id, start_time=start,
                           end_time=start + sd, shards=shards)
        self.next_sg_id += 1
        info.shard_groups.append(g)
        info.shard_groups.sort(key=lambda x: x.start_time)
        return g.to_dict()

    def _apply_set_shard_ranges(self, cmd):
        """Assign shard-key range bounds (reference split points →
        shardinfo ranges, engine/engine.go:930 GetShardSplitPoints):
        applies to every live shard group AND to future ones via
        DatabaseInfo.range_bounds. bounds[0] must be '' (open start)."""
        info = self.databases.get(cmd["db"])
        if info is None:
            raise ValueError(f"database not found: {cmd['db']}")
        bounds = list(cmd["bounds"])
        if not bounds or bounds[0] != "":
            raise ValueError("bounds[0] must be the open start ''")
        if sorted(bounds) != bounds:
            raise ValueError("bounds must be sorted")
        info.range_bounds = bounds
        for g in info.shard_groups:
            if g.deleted or len(g.shards) != len(bounds):
                continue
            _assign_bounds(g.shards, bounds)
        return True

    def _apply_delete_shard_group(self, cmd):
        info = self.databases.get(cmd["db"])
        if info is None:
            return None
        for g in info.shard_groups:
            if g.id == cmd["sg_id"]:
                g.deleted = True
        return None

    def _apply_move_pt(self, cmd):
        """Reassign a partition to a new owner (migration commit —
        reference migrate_state_machine.go assign/move events)."""
        for pt in self.pts.get(cmd["db"], []):
            if pt.pt_id == cmd["pt_id"]:
                old = pt.owner
                pt.owner = cmd["to_node"]
                if old != pt.owner and pt.owner in pt.replicas:
                    # replica promotion keeps the DATA-MEMBERSHIP set
                    # (owner + replicas) stable: the displaced owner
                    # takes the promoted replica's slot. Without this,
                    # a takeover shrinks the raft group's member view
                    # to {new owner} and the old owner can never
                    # rejoin after restart — the group stays below
                    # quorum and replicated writes to the PT hang
                    # forever instead of healing
                    pt.replicas = [old if r == pt.owner else r
                                   for r in pt.replicas]
                pt.status = cmd.get("status", PT_ONLINE)
                return True
        return False

    def _apply_set_pt_status(self, cmd):
        for pt in self.pts.get(cmd["db"], []):
            if pt.pt_id == cmd["pt_id"]:
                pt.status = cmd["status"]
                return True
        return False

    # ---------------------------------------------------------- snapshot

    def to_dict(self) -> dict:
        return {
            "version": self.version,
            "nodes": [n.to_dict() for n in self.nodes.values()],
            "databases": [d.to_dict() for d in self.databases.values()],
            "pts": {db: [p.to_dict() for p in pts]
                    for db, pts in self.pts.items()},
            "next_node_id": self.next_node_id,
            "next_shard_id": self.next_shard_id,
            "next_sg_id": self.next_sg_id,
        }

    @classmethod
    def from_dict(cls, d: dict) -> "MetaData":
        md = cls()
        md.version = d["version"]
        md.nodes = {n["id"]: DataNode.from_dict(n) for n in d["nodes"]}
        md.databases = {x["name"]: DatabaseInfo.from_dict(x)
                        for x in d["databases"]}
        md.pts = {db: [PtInfo.from_dict(p) for p in pts]
                  for db, pts in d["pts"].items()}
        md.next_node_id = d["next_node_id"]
        md.next_shard_id = d["next_shard_id"]
        md.next_sg_id = d["next_sg_id"]
        return md
