"""Raft consensus for the meta catalog (CPU-side, never touches devices).

Role of the reference's hashicorp-raft wrapper for ts-meta
(app/ts-meta/meta/raft_wrapper.go:23, store_fsm.go) — leader election,
replicated log, FSM apply, snapshots. The survey's guidance (SURVEY §7
hard parts) is to keep consensus boring and host-side; this is a direct,
compact Raft:

- randomized election timers, majority voting;
- one persistent replicator thread per peer (woken on propose /
  heartbeat tick — no per-tick thread churn);
- conflict-checked log truncation (same-leader duplicate/reordered
  appends never erase newer entries);
- a no-op entry committed at the start of each term so prior-term
  entries become committable immediately (Raft §5.4.2);
- persisted term/vote + indexed JSONL log tolerant of a torn tail;
- snapshot+truncate compaction, InstallSnapshot with staleness guard.

Single-voter configurations commit immediately (the ts-server
single-node deployment path).
"""

from __future__ import annotations

import json
import os
import random
import threading
import time

from ..utils import failpoint, get_logger
from .transport import RPCClient, RPCError, RPCServer

log = get_logger(__name__)

FOLLOWER, CANDIDATE, LEADER = "follower", "candidate", "leader"

ELECTION_MIN = 0.15
ELECTION_MAX = 0.30
HEARTBEAT = 0.05
SNAPSHOT_EVERY = 4096          # log entries between snapshots


# cumulative metrics for the statistics pusher (reference raft/meta
# statistics analog)
from ..utils.stats import register_counters

RAFT_STATS = register_counters("raft", {
    "elections_won": 0, "step_downs": 0, "snapshots": 0,
    "proposes": 0})


class NotLeader(Exception):
    def __init__(self, leader_hint: str | None):
        super().__init__(f"not leader (leader={leader_hint})")
        self.leader_hint = leader_hint


class RaftNode:
    """One raft voter.

    fsm_apply(cmd) -> result     applies a committed command.
    fsm_snapshot() -> dict       full FSM state.
    fsm_restore(dict)            load FSM state (on snapshot install).
    """

    def __init__(self, node_id: str, peers: dict[str, str],
                 data_dir: str, fsm_apply, fsm_snapshot, fsm_restore,
                 host: str = "127.0.0.1", port: int = 0,
                 server=None, msg_prefix: str = "raft",
                 snapshot_every: int = SNAPSHOT_EVERY):
        self.id = node_id
        self.peers = dict(peers)                  # id -> addr, incl self
        self.dir = data_dir
        os.makedirs(data_dir, exist_ok=True)
        self.fsm_apply = fsm_apply
        self.fsm_snapshot = fsm_snapshot
        self.fsm_restore = fsm_restore
        # embeddable mode: many raft groups (per-PT data replication,
        # reference lib/raftconn one etcd-raft node per partition)
        # multiplex over ONE shared RPCServer, disambiguated by message
        # prefix — the spdy-multiplexing analog. The embedding owner
        # manages the server lifecycle.
        self.msg_prefix = msg_prefix
        self.snapshot_every = snapshot_every
        self._owns_server = server is None

        # persistent state
        self.term = 0
        self.voted_for: str | None = None
        self.log: list[dict] = []          # {"idx", "term", "cmd"}
        self.log_base = 0                  # last snapshot-covered index
        self.base_term = 0
        self._load_state()

        # volatile
        self.state = FOLLOWER
        self.commit_index = self.log_base
        self.last_applied = self.log_base
        self.leader_id: str | None = None
        self.next_index: dict[str, int] = {}
        self.match_index: dict[str, int] = {}
        # per-peer last successful append-ack time (leader lease: see
        # leadership_held)
        self.ack_times: dict[str, float] = {}
        self._apply_results: dict[int, tuple] = {}
        self._apply_events: dict[int, threading.Event] = {}

        self._lock = threading.RLock()
        self._stop = threading.Event()
        self._last_heard = time.monotonic()
        # startup fence for the leader lease (ADVICE r5): leadership_held
        # assumes a peer that recently acked cannot vote for a
        # challenger, but a RESTARTED peer loses its leader_id and
        # _last_heard, so the stickiness check alone cannot protect the
        # old leader's lease. Votes are refused for ELECTION_MIN after
        # startup regardless of leader_id (see _on_request_vote).
        self._started_at = time.monotonic()
        self._clients: dict[str, RPCClient] = {}
        self._repl_wake: dict[str, threading.Event] = {}

        if server is None:
            self.server = RPCServer(
                host=host, port=port, name=f"raft-{node_id}",
                handlers={
                    f"{msg_prefix}.vote": self._on_request_vote,
                    f"{msg_prefix}.append": self._on_append_entries,
                    f"{msg_prefix}.snapshot": self._on_install_snapshot,
                })
        else:
            self.server = server
            server.register(f"{msg_prefix}.vote", self._on_request_vote)
            server.register(f"{msg_prefix}.append",
                            self._on_append_entries)
            server.register(f"{msg_prefix}.snapshot",
                            self._on_install_snapshot)
        self.addr = self.server.addr
        if node_id in self.peers and self.peers[node_id] != self.addr:
            self.peers[node_id] = self.addr

    # ------------------------------------------------------- persistence

    def _state_path(self):
        return os.path.join(self.dir, "raft_state.json")

    def _log_path(self):
        return os.path.join(self.dir, "raft_log.jsonl")

    def _snap_path(self):
        return os.path.join(self.dir, "raft_snapshot.json")

    def _persist_state(self):
        tmp = self._state_path() + ".tmp"
        with open(tmp, "w") as f:
            json.dump({"term": self.term, "voted_for": self.voted_for}, f)
        os.replace(tmp, self._state_path())

    def _append_log_disk(self, entries: list[dict]):
        with open(self._log_path(), "a") as f:
            for e in entries:
                f.write(json.dumps(e, separators=(",", ":")) + "\n")

    def _rewrite_log_disk(self):
        tmp = self._log_path() + ".tmp"
        with open(tmp, "w") as f:
            for e in self.log:
                f.write(json.dumps(e, separators=(",", ":")) + "\n")
        os.replace(tmp, self._log_path())

    def _load_state(self):
        if os.path.exists(self._state_path()):
            with open(self._state_path()) as f:
                st = json.load(f)
            self.term = st["term"]
            self.voted_for = st.get("voted_for")
        if os.path.exists(self._snap_path()):
            with open(self._snap_path()) as f:
                snap = json.load(f)
            self.log_base = snap["last_index"]
            self.base_term = snap["last_term"]
            self.fsm_restore(snap["fsm"])
        if os.path.exists(self._log_path()):
            entries = []
            with open(self._log_path()) as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        entries.append(json.loads(line))
                    except json.JSONDecodeError:
                        break   # torn tail from a crash mid-append
            # entries carry explicit indexes: drop anything the snapshot
            # already covers (crash between snapshot write and log
            # rewrite leaves the old log file behind) and any duplicate
            # indexes (keep the later write — it superseded the earlier)
            by_idx: dict[int, dict] = {}
            for e in entries:
                by_idx[e["idx"]] = e
            idx = self.log_base + 1
            self.log = []
            while idx in by_idx:
                self.log.append(by_idx[idx])
                idx += 1
            if len(self.log) != len([i for i in by_idx
                                     if i > self.log_base]):
                self._rewrite_log_disk()

    # --------------------------------------------------------- lifecycle

    def start(self):
        if self._owns_server:
            self.server.start()
        for pid in self.peers:
            if pid != self.id:
                self._repl_wake[pid] = threading.Event()
                threading.Thread(target=self._replicator, args=(pid,),
                                 daemon=True,
                                 name=f"raft-repl-{self.id}-{pid}").start()
        threading.Thread(target=self._ticker, daemon=True,
                         name=f"raft-tick-{self.id}").start()

    def stop(self):
        self._stop.set()
        for ev in self._repl_wake.values():
            ev.set()
        if self._owns_server:
            self.server.stop()
        for c in self._clients.values():
            c.close()

    def _client(self, peer_id: str) -> RPCClient:
        c = self._clients.get(peer_id)
        if c is None:
            c = self._clients[peer_id] = RPCClient(
                self.peers[peer_id], connect_timeout=1.0)
        return c

    # ------------------------------------------------------ index helpers

    def _last_index(self) -> int:
        return self.log_base + len(self.log)

    def _term_at(self, idx: int) -> int:
        if idx == self.log_base:
            return self.base_term
        return self.log[idx - self.log_base - 1]["term"]

    def _entries_from(self, idx: int) -> list[dict]:
        return self.log[idx - self.log_base - 1:]

    # ----------------------------------------------------------- election

    def _ticker(self):
        while not self._stop.is_set():
            time.sleep(0.01)
            with self._lock:
                state = self.state
                elapsed = time.monotonic() - self._last_heard
            if state == LEADER:
                self._wake_replicators()
                time.sleep(HEARTBEAT)
            elif elapsed > random.uniform(ELECTION_MIN, ELECTION_MAX):
                self._run_election()

    def _wake_replicators(self):
        for ev in self._repl_wake.values():
            ev.set()

    def _run_election(self):
        # fault injection: stall candidacy (split-vote / slow-CPU chaos)
        failpoint.inject("raft.election.delay")
        with self._lock:
            self.state = CANDIDATE
            self.term += 1
            self.voted_for = self.id
            self._persist_state()
            term = self.term
            self._last_heard = time.monotonic()
            last_idx = self._last_index()
            last_term = self._term_at(last_idx)
        votes = {self.id}
        if len(self.peers) == 1:
            self._become_leader(term)
            return
        lock = threading.Lock()
        done = threading.Event()

        def ask(pid):
            try:
                resp = self._client(pid).call(f"{self.msg_prefix}.vote", {
                    "term": term, "candidate": self.id,
                    "last_log_index": last_idx, "last_log_term": last_term,
                }, timeout=1.0)  # oglint: disable=R301 — election thread,
                # never request-scoped (see replicate above)
            except RPCError:
                return
            with lock:
                if resp and resp.get("granted"):
                    votes.add(pid)
                    if len(votes) * 2 > len(self.peers):
                        done.set()
                elif resp and resp.get("term", 0) > term:
                    with self._lock:
                        self._step_down(resp["term"])
                    done.set()

        for pid in self.peers:
            if pid != self.id:
                threading.Thread(target=ask, args=(pid,),
                                 daemon=True).start()
        done.wait(timeout=ELECTION_MIN)
        with self._lock:
            won = (self.state == CANDIDATE and self.term == term
                   and len(votes) * 2 > len(self.peers))
        if won:
            self._become_leader(term)

    def _become_leader(self, term: int):
        with self._lock:
            if self.term != term:
                return
            if self.state != CANDIDATE and len(self.peers) > 1:
                return
            self.state = LEADER
            self.leader_id = self.id
            nxt = self._last_index() + 1
            self.next_index = {p: nxt for p in self.peers if p != self.id}
            self.match_index = {p: 0 for p in self.peers if p != self.id}
            log.info("raft %s became leader term=%d", self.id, term)
            from ..utils.stats import bump as _bump
            _bump(RAFT_STATS, "elections_won")
            # commit a no-op so prior-term entries become committable
            # now, not at the next client proposal (Raft §5.4.2)
            self._append_entry(None)
            if len(self.peers) == 1:
                self._advance_commit(self._last_index())
        self._wake_replicators()

    def _step_down(self, term: int):
        from ..utils.stats import bump as _bump
        _bump(RAFT_STATS, "step_downs")
        # caller holds lock
        if term > self.term:
            self.term = term
            self.voted_for = None
            self._persist_state()
        self.state = FOLLOWER
        self._last_heard = time.monotonic()

    def _append_entry(self, cmd) -> int:
        # caller holds lock
        entry = {"idx": self._last_index() + 1, "term": self.term,
                 "cmd": cmd}
        self.log.append(entry)
        self._append_log_disk([entry])
        return entry["idx"]

    # ---------------------------------------------------------- handlers

    def _on_request_vote(self, body):
        with self._lock:
            # leader stickiness (raft §6 / etcd CheckQuorum): refuse to
            # vote while a live leader was heard within ELECTION_MIN.
            # This is ALSO the premise of the leader lease
            # (leadership_held): a follower that just acked an append
            # must not be able to elect a challenger inside the lease
            # window
            if (self.state == FOLLOWER and self.leader_id is not None
                    and body["term"] > self.term
                    and time.monotonic() - self._last_heard
                    < ELECTION_MIN):
                return {"term": self.term, "granted": False}
            # restart lease hole (ADVICE r5): a freshly-(re)started node
            # has leader_id None, so the stickiness check above cannot
            # protect a live leader's lease — yet that leader may hold a
            # lease anchored on THIS node's pre-restart ack. Refuse all
            # votes for ELECTION_MIN after startup, regardless of
            # leader_id; at worst a cold cluster's first election slips
            # one timeout.
            if (body["term"] > self.term
                    and time.monotonic() - self._started_at
                    < ELECTION_MIN):
                return {"term": self.term, "granted": False}
            if body["term"] > self.term:
                self._step_down(body["term"])
            granted = False
            if body["term"] == self.term and \
                    self.voted_for in (None, body["candidate"]):
                my_last = self._last_index()
                my_term = self._term_at(my_last)
                up_to_date = (body["last_log_term"], body["last_log_index"]) \
                    >= (my_term, my_last)
                if up_to_date:
                    granted = True
                    self.voted_for = body["candidate"]
                    self._persist_state()
                    self._last_heard = time.monotonic()
            return {"term": self.term, "granted": granted}

    def _on_append_entries(self, body):
        with self._lock:
            if body["term"] < self.term:
                return {"term": self.term, "success": False}
            if body["term"] > self.term or self.state != FOLLOWER:
                self._step_down(body["term"])
            self.leader_id = body["leader"]
            self._last_heard = time.monotonic()
            prev_idx = body["prev_log_index"]
            if prev_idx > self._last_index():
                return {"term": self.term, "success": False,
                        "hint": self._last_index() + 1}
            if prev_idx < self.log_base:
                return {"term": self.term, "success": False,
                        "hint": self.log_base + 1}
            if self._term_at(prev_idx) != body["prev_log_term"]:
                return {"term": self.term, "success": False,
                        "hint": max(prev_idx, self.log_base + 1)}
            # append with conflict check: truncate ONLY at a term
            # mismatch — duplicate/reordered frames from the same leader
            # must not erase newer entries (Raft §5.3)
            new = []
            truncated = False
            for e in body["entries"]:
                idx = e["idx"]
                if idx <= self.log_base:
                    continue
                if not new and idx <= self._last_index():
                    if self._term_at(idx) == e["term"]:
                        continue         # identical entry already present
                    self.log = self.log[:idx - self.log_base - 1]
                    truncated = True
                    new.append(e)
                else:
                    new.append(e)
            if truncated:
                self.log.extend(new)
                self._rewrite_log_disk()
            elif new:
                self.log.extend(new)
                self._append_log_disk(new)
            if body["leader_commit"] > self.commit_index:
                self._advance_commit(min(body["leader_commit"],
                                         self._last_index()))
            return {"term": self.term, "success": True}

    def _on_install_snapshot(self, body):
        with self._lock:
            if body["term"] < self.term:
                return {"term": self.term}
            self._step_down(body["term"])
            self.leader_id = body["leader"]
            self._last_heard = time.monotonic()
            snap = body["snapshot"]
            # staleness guard: never rewind past what we've committed
            if snap["last_index"] <= self.commit_index:
                return {"term": self.term}
            self.fsm_restore(snap["fsm"])
            self.log = []
            self.log_base = snap["last_index"]
            self.base_term = snap["last_term"]
            self.commit_index = self.log_base
            self.last_applied = self.log_base
            tmp = self._snap_path() + ".tmp"
            with open(tmp, "w") as f:
                json.dump(snap, f)
            os.replace(tmp, self._snap_path())
            self._rewrite_log_disk()
            return {"term": self.term}

    # -------------------------------------------------------- replication

    def _replicator(self, pid: str):
        """Persistent per-peer replication loop: sleeps until woken by a
        heartbeat tick or a proposal, then pushes whatever the peer is
        missing. One in-flight RPC per peer at a time."""
        ev = self._repl_wake[pid]
        while not self._stop.is_set():
            ev.wait(timeout=HEARTBEAT)
            ev.clear()
            if self._stop.is_set():
                return
            with self._lock:
                if self.state != LEADER:
                    continue
            try:
                again = True
                while again and not self._stop.is_set():
                    again = self._replicate_once(pid)
            except RPCError:
                continue

    def _replicate_once(self, pid: str) -> bool:
        """One append/snapshot exchange. Returns True when the peer still
        lags (caller loops)."""
        with self._lock:
            if self.state != LEADER:
                return False
            term = self.term
            nxt = self.next_index.get(pid, self._last_index() + 1)
            if nxt <= self.log_base:
                body = {"term": term, "leader": self.id,
                        "snapshot": {"last_index": self.log_base,
                                     "last_term": self.base_term,
                                     "fsm": self.fsm_snapshot()}}
                kind = f"{self.msg_prefix}.snapshot"
            else:
                prev = nxt - 1
                entries = self._entries_from(nxt)
                body = {"term": term, "leader": self.id,
                        "prev_log_index": prev,
                        "prev_log_term": self._term_at(prev),
                        "entries": entries,
                        "leader_commit": self.commit_index}
                kind = f"{self.msg_prefix}.append"
        # fault injection: lose this replication exchange (the peer
        # simply lags and the replicator retries — same as a dropped
        # frame on the wire)
        if failpoint.inject("raft.replicate.drop"):
            raise RPCError("failpoint: raft.replicate.drop")
        t_sent = time.monotonic()
        # consensus-internal traffic: replicator threads are never
        # request-scoped (contextvars do not cross threads), and the
        # loop's `except RPCError` must stay the only exit — a
        # deadline raise here would kill the peer's replication
        resp = self._client(pid).call(
            kind, body, timeout=5.0)  # oglint: disable=R301
        with self._lock:
            if self.state != LEADER or self.term != term:
                return False
            if resp.get("term", 0) > self.term:
                self._step_down(resp["term"])
                return False
            if kind == f"{self.msg_prefix}.snapshot":
                self.next_index[pid] = self.log_base + 1
                self.match_index[pid] = self.log_base
                return self.next_index[pid] <= self._last_index()
            if resp.get("success"):
                sent = body["entries"]
                top = body["prev_log_index"] + len(sent)
                self.match_index[pid] = max(self.match_index.get(pid, 0),
                                            top)
                self.next_index[pid] = self.match_index[pid] + 1
                # lease anchor = SEND time: the peer's election timer
                # reset happened no earlier than the request left, so
                # response latency cannot stretch the lease window
                self.ack_times[pid] = t_sent
                self._maybe_commit()
                return self.next_index[pid] <= self._last_index()
            self.next_index[pid] = resp.get(
                "hint", max(nxt - 1, self.log_base + 1))
            return True

    def _maybe_commit(self):
        # caller holds lock; commit the highest index replicated on a
        # majority with an entry from the current term
        for idx in range(self._last_index(), self.commit_index, -1):
            if self._term_at(idx) != self.term:
                break
            count = 1 + sum(1 for m in self.match_index.values() if m >= idx)
            if count * 2 > len(self.peers):
                self._advance_commit(idx)
                break

    def _advance_commit(self, idx: int):
        # caller holds lock
        self.commit_index = idx
        while self.last_applied < self.commit_index:
            nxt = self.last_applied + 1
            entry = self.log[nxt - self.log_base - 1]
            if entry["cmd"] is None:                   # term-start no-op
                outcome = (None, None)
            else:
                try:
                    res = self.fsm_apply(entry["cmd"])
                    outcome = (res, None)
                except Exception as e:
                    outcome = (None, e)
            # last_applied advances only AFTER fsm_apply completes:
            # the follower-read barrier polls it without the lock, and
            # the old pre-apply increment opened a window where
            # applied == target while the engine write was still in
            # flight — an intermittent stale read (VERDICT r4 weak #2)
            self.last_applied = nxt
            ev = self._apply_events.pop(nxt, None)
            if ev is not None:
                self._apply_results[nxt] = outcome
                ev.set()
        if len(self.log) >= self.snapshot_every:
            self._compact()

    def _compact(self):
        # caller holds lock; snapshot applied prefix, truncate log.
        # Crash safety: the snapshot file lands atomically first; if we
        # die before the log rewrite, _load_state drops covered/duplicate
        # indexes via the per-entry idx fields.
        # fault injection BEFORE any mutation: a failed compaction
        # leaves log + snapshot exactly as they were and is NON-fatal —
        # the commit that triggered it already applied; compaction
        # simply retries at the next commit (a real snapshot-write
        # failure behaves the same way)
        try:
            failpoint.inject("raft.snapshot.err")
        except failpoint.FailpointError as e:
            log.warning("raft %s: snapshot compaction failed "
                        "(injected): %s", self.id, e)
            return
        applied_off = self.last_applied - self.log_base
        if applied_off <= 0:
            return
        from ..utils.stats import bump as _bump
        _bump(RAFT_STATS, "snapshots")
        snap = {"last_index": self.last_applied,
                "last_term": self._term_at(self.last_applied),
                "fsm": self.fsm_snapshot()}
        tmp = self._snap_path() + ".tmp"
        with open(tmp, "w") as f:
            json.dump(snap, f)
        os.replace(tmp, self._snap_path())
        self.log = self.log[applied_off:]
        self.log_base = snap["last_index"]
        self.base_term = snap["last_term"]
        self._rewrite_log_disk()

    # -------------------------------------------------------------- API

    def leadership_held(self) -> bool:
        """Leader-lease check: True when a MAJORITY of peers acked an
        append within the last ELECTION_MIN·0.8. A peer that acked at
        time t cannot grant a vote to a challenger before t +
        ELECTION_MIN (its election timer was just reset), so within
        this window no other node can have been elected — the local
        commit_index is safe to serve as a read-index without an RPC
        round. The 0.8 margin absorbs scheduler latency between the
        ack's timestamping and this check."""
        with self._lock:
            if self.state != LEADER:
                return False
            if len(self.peers) == 1:
                return True
            now = time.monotonic()
            fresh = 1 + sum(1 for t in self.ack_times.values()
                            if now - t < ELECTION_MIN * 0.8)
            return fresh * 2 > len(self.peers)

    def propose(self, cmd: dict, timeout: float = 10.0):
        """Replicate one command; returns fsm_apply's result once
        committed. Raises NotLeader with a redirect hint on followers."""
        from ..utils.stats import bump as _bump
        _bump(RAFT_STATS, "proposes")
        # fault injection: proposal rejected before touching the log
        # (callers see the same surface as a leaderless/failed propose)
        failpoint.inject("raft.propose.err")
        with self._lock:
            if self.state != LEADER:
                hint = self.peers.get(self.leader_id) \
                    if self.leader_id else None
                raise NotLeader(hint)
            idx = self._append_entry(cmd)
            ev = threading.Event()
            self._apply_events[idx] = ev
            if len(self.peers) == 1:
                self._advance_commit(idx)
        if len(self.peers) > 1:
            self._wake_replicators()
        if not ev.wait(timeout):
            with self._lock:
                self._apply_events.pop(idx, None)
                # the commit may have raced the timeout: _advance_commit
                # pops the event, stores the result, THEN sets it — so a
                # stored result means the command actually applied
                if idx in self._apply_results:
                    res, err = self._apply_results.pop(idx)
                    if err is not None:
                        raise err
                    return res
            raise RPCError("raft commit timeout")
        with self._lock:
            res, err = self._apply_results.pop(idx)
        if err is not None:
            raise err
        return res

    def wait_leader(self, timeout: float = 5.0) -> str | None:
        """Block until some node is leader; returns its id."""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            with self._lock:
                if self.state == LEADER:
                    return self.id
                if self.leader_id is not None:
                    return self.leader_id
            time.sleep(0.02)
        return None

    @property
    def is_leader(self) -> bool:
        with self._lock:
            return self.state == LEADER
