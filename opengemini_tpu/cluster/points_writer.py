"""PointsWriter: route rows to shards and fan out to store nodes.

Role of the reference's coordinator PointsWriter
(coordinator/points_writer.go:228 RetryWritePointRows → routeAndMap →
writeShardMap → writeRowToShard): time → shard group (created on demand
through meta raft), series hash → shard → partition → owner node; rows
batch per (node, pt) and ship in parallel with retry-after-refresh on
node failure.
"""

from __future__ import annotations

import threading

from ..storage.rows import PointRow
from ..utils import deadline, failpoint, get_logger
from ..utils.errors import ErrQueryTimeout, GeminiError
from .hashing import series_hash, shard_key_of  # noqa: F401 (re-export)
from .meta_store import MetaClient
from .store_node import rows_to_wire
from .transport import ClientPool, RPCError

log = get_logger(__name__)


class ErrPartialWrite(GeminiError):
    def __init__(self, written: int, errors: list[str]):
        super().__init__(
            f"partial write: {written} written; errors: {'; '.join(errors)}")
        self.written = written


class PointsWriter:
    def __init__(self, meta: MetaClient, auto_create_db: bool = True,
                 max_retries: int = 2):
        self.meta = meta
        self.auto_create_db = auto_create_db
        self.max_retries = max_retries
        self._pool = ClientPool()

    def _client(self, addr: str):
        return self._pool.get(addr)

    def close(self) -> None:
        self._pool.close()

    # ------------------------------------------------------------- routing

    def _ensure_db(self, db: str):
        info = self.meta.database(db)
        if info is None:
            if not self.auto_create_db:
                raise GeminiError(f"database not found: {db}")
            try:
                self.meta.create_database(db)
            except RPCError as e:
                # a concurrent create elsewhere shows up as the db
                # appearing on refresh; anything else is the root cause
                self.meta.refresh()
                if self.meta.database(db) is None:
                    raise GeminiError(
                        f"cannot create database {db}: {e}") from e
            info = self.meta.database(db)
            if info is None:
                raise GeminiError(f"cannot create database: {db}")
        return info

    def _route(self, db: str, rows: list[PointRow]):
        """rows → {(node_addr, pt_id, owner_id): [rows]}; creates shard
        groups on demand (points_writer.go:622
        updateShardGroupAndShardKey)."""
        rt = _Router(self, db)
        batches: dict[tuple, list[PointRow]] = {}
        for r in rows:
            batches.setdefault(
                rt.target(r.time, series_hash(r.measurement, r.tags),
                          r.tags), []).append(r)
        return batches

    def _scatter_send(self, db: str, items: dict, msg: str,
                      make_wire) -> int:
        """Ship one payload per (addr, pt, owner) concurrently with
        refresh-and-retry (shared by the row and line-bytes writers —
        the subtle owner re-resolution lives ONCE). Raises
        ErrPartialWrite when any target exhausts its retries. The
        per-batch RPC timeout is clamped by the write budget bound in
        the dispatching thread (utils.deadline): retries spend the
        REMAINING budget, never a fresh timeout each attempt."""
        written = 0
        errors: list[str] = []
        lock = threading.Lock()
        dl = deadline.current()   # capture BEFORE the thread fan-out

        def send(addr: str, pt: int, owner_id: int, src):
            nonlocal written
            last: Exception | None = None
            for _attempt in range(self.max_retries + 1):
                # owner id travels with the batch: the store rejects
                # writes for partitions it no longer owns, so a stale
                # route can never silently ack rows into an orphaned
                # engine db (they'd be invisible to queries)
                wire = make_wire(pt, owner_id, src)
                try:
                    t = dl.clamp(60.0) if dl is not None else 60.0
                    resp = self._client(addr).call(msg, wire, timeout=t)
                    with lock:
                        written += resp["written"]
                    return
                except ErrQueryTimeout as e:
                    last = e
                    break             # budget gone: retrying cannot help
                except RPCError as e:
                    last = e
                    if dl is not None and dl.expired:
                        break
                    # partition may have moved: re-resolve the owner
                    self.meta.refresh()
                    owner = self.meta.data().pt_owner(db, pt)
                    if owner is not None:
                        addr, owner_id = owner.addr, owner.id
                except Exception as e:  # noqa: BLE001 — a dying worker
                    # (e.g. a failpoint armed with action=error) must
                    # land in `errors`: a thread that vanishes before
                    # errors.append would turn lost rows into a 204 ack
                    last = e
                    break
            with lock:
                errors.append(f"pt {pt} @ {addr}: {last}")

        threads = [threading.Thread(target=send, args=(a, p, o, src))
                   for (a, p, o), src in items.items()]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        if errors:
            raise ErrPartialWrite(written, errors)
        return written

    # -------------------------------------------------------------- write

    def write_points(self, db: str, rows: list[PointRow]) -> int:
        failpoint.inject("points_writer.write.err")
        if not rows:
            return 0
        self._ensure_db(db)
        batches = self._route(db, rows)
        return self._scatter_send(
            db, batches, "store.write_rows",
            lambda pt, owner, batch: {"db": db, "pt": pt,
                                      "owner": owner,
                                      "rows": rows_to_wire(batch)})

    def write_lines(self, db: str, data: bytes,
                    default_time_ns: int = 0,
                    precision: str = "ns") -> int:
        """Columnar cluster ingest: lex the line-protocol payload ONCE,
        route every line by (time slot, series hash) with series keys
        parsed once per unique key, and scatter RAW LINE BYTES per
        partition; each store runs its local columnar fast path
        (`utils.lineprotocol.ingest_lines`). The role of the
        reference's RecordWriter scatter (coordinator/
        record_writer.go:79 — typed columns per PT queue), done at the
        line-bytes level. Falls back to the per-row path for exotic
        payloads or when the native lexer is unavailable."""
        import numpy as np

        from ..native import LpParseError, lp_lex
        from ..utils.lineprotocol import (PRECISION_NS, parse_lines,
                                          parse_series_key, ts_overflows)
        failpoint.inject("points_writer.write.err")
        mult = PRECISION_NS.get(precision)
        if mult is None:
            from ..utils.errors import ErrInvalidLineProtocol
            raise ErrInvalidLineProtocol(f"bad precision {precision}")
        if isinstance(data, str):
            data = data.encode()

        def slow() -> int:
            rows = parse_lines(data.decode("utf-8", errors="replace"),
                               default_time_ns, precision)
            return self.write_points(db, rows)

        try:
            lex = lp_lex(data)
        except LpParseError:
            return slow()
        if lex is None or lex.n_lines == 0:
            return slow()
        if ts_overflows(lex.ts, mult):
            return slow()             # int64 overflow: loud python path
        self._ensure_db(db)
        rt = _Router(self, db)
        ts = np.where(lex.has_ts.astype(bool), lex.ts * mult,
                      default_time_ns)
        mv = memoryview(data)
        key_cache: dict[bytes, tuple] = {}
        spans: dict[tuple, list[int]] = {}
        for i in range(lex.n_lines):
            so = lex.series_off[i]
            k = bytes(mv[so:so + lex.series_len[i]])
            ent = key_cache.get(k)
            if ent is None:
                mstr, tags = parse_series_key(
                    k.decode("utf-8", errors="replace"))
                ent = key_cache[k] = (series_hash(mstr, tags), tags)
            spans.setdefault(
                rt.target(int(ts[i]), ent[0], ent[1]), []).append(i)
        payloads = {
            tgt: b"\n".join(bytes(mv[lex.series_off[i]:lex.line_end[i]])
                            for i in idxs)
            for tgt, idxs in spans.items()}
        return self._scatter_send(
            db, payloads, "store.write_lines",
            lambda pt, owner, payload: {
                "db": db, "pt": pt, "owner": owner, "data": payload,
                "default_time_ns": default_time_ns,
                "precision": precision})


class _Router:
    """Per-write routing context shared by the row and line paths:
    shard groups cache per time slot (created on demand through meta
    raft) and (slot, pt) targets cache so a million-line payload pays
    two dict hits per line, not a catalog walk."""

    def __init__(self, pw: PointsWriter, db: str):
        self.pw = pw
        self.db = db
        self.md = pw.meta.data()
        self.info = self.md.db(db)
        self.sg_cache: dict[int, object] = {}
        self.tgt_cache: dict[tuple, tuple] = {}

    def target(self, t: int, h: int, tags: dict) -> tuple:
        """(addr, pt_id, owner_id) for a row at time t with series
        hash h (range-sharded dbs route by shard key instead)."""
        slot = t // self.info.shard_duration
        sg = self.sg_cache.get(slot)
        if sg is None:
            sg = self.md.shard_group_for_time(self.db, t)
            if sg is None:
                self.pw.meta.create_shard_group(self.db, t)
                self.md = self.pw.meta.data()
                self.info = self.md.db(self.db)
                sg = self.md.shard_group_for_time(self.db, t)
                if sg is None:
                    raise GeminiError("failed to create shard group")
            self.sg_cache[slot] = sg
        if self.info.shard_key and sg.ranged:
            # range routing (reference DestShard shardinfo.go:359)
            shard = sg.dest_shard(shard_key_of(tags,
                                               self.info.shard_key))
        else:
            shard = sg.shard_for(h)
        key = (slot, shard.pt_id)
        tgt = self.tgt_cache.get(key)
        if tgt is not None:
            return tgt
        pt = self.md.pt(self.db, shard.pt_id)
        if pt is None or self.md.nodes.get(pt.owner) is None:
            raise GeminiError(
                f"no owner node for {self.db} pt {shard.pt_id}")
        if pt.status != "online":
            # transient during migration: one refresh, then fail
            # loudly rather than ack rows into a parked partition
            self.pw.meta.refresh()
            self.md = self.pw.meta.data()
            pt = self.md.pt(self.db, shard.pt_id)
            if pt is None or pt.status != "online":
                raise GeminiError(
                    f"{self.db} pt {shard.pt_id} is offline")
        owner = self.md.nodes[pt.owner]
        tgt = (owner.addr, shard.pt_id, owner.id)
        self.tgt_cache[key] = tgt
        return tgt
