"""PointsWriter: route rows to shards and fan out to store nodes.

Role of the reference's coordinator PointsWriter
(coordinator/points_writer.go:228 RetryWritePointRows → routeAndMap →
writeShardMap → writeRowToShard): time → shard group (created on demand
through meta raft), series hash → shard → partition → owner node; rows
batch per (node, pt) and ship in parallel with retry-after-refresh on
node failure.
"""

from __future__ import annotations

import threading

from ..storage.rows import PointRow
from ..utils import failpoint, get_logger
from ..utils.errors import GeminiError
from .hashing import series_hash, shard_key_of  # noqa: F401 (re-export)
from .meta_store import MetaClient
from .store_node import rows_to_wire
from .transport import ClientPool, RPCError

log = get_logger(__name__)


class ErrPartialWrite(GeminiError):
    def __init__(self, written: int, errors: list[str]):
        super().__init__(
            f"partial write: {written} written; errors: {'; '.join(errors)}")
        self.written = written


class PointsWriter:
    def __init__(self, meta: MetaClient, auto_create_db: bool = True,
                 max_retries: int = 2):
        self.meta = meta
        self.auto_create_db = auto_create_db
        self.max_retries = max_retries
        self._pool = ClientPool()

    def _client(self, addr: str):
        return self._pool.get(addr)

    def close(self) -> None:
        self._pool.close()

    # ------------------------------------------------------------- routing

    def _ensure_db(self, db: str):
        info = self.meta.database(db)
        if info is None:
            if not self.auto_create_db:
                raise GeminiError(f"database not found: {db}")
            try:
                self.meta.create_database(db)
            except RPCError as e:
                # a concurrent create elsewhere shows up as the db
                # appearing on refresh; anything else is the root cause
                self.meta.refresh()
                if self.meta.database(db) is None:
                    raise GeminiError(
                        f"cannot create database {db}: {e}") from e
            info = self.meta.database(db)
            if info is None:
                raise GeminiError(f"cannot create database: {db}")
        return info

    def _route(self, db: str, rows: list[PointRow]):
        """rows → {(node_addr, pt_id): [rows]}; creates shard groups on
        demand (points_writer.go:622 updateShardGroupAndShardKey)."""
        md = self.meta.data()
        info = md.db(db)
        batches: dict[tuple[str, int], list[PointRow]] = {}
        sg_cache: dict[int, object] = {}
        for r in rows:
            slot = r.time // info.shard_duration
            sg = sg_cache.get(slot)
            if sg is None:
                sg = md.shard_group_for_time(db, r.time)
                if sg is None:
                    self.meta.create_shard_group(db, r.time)
                    md = self.meta.data()
                    info = md.db(db)
                    sg = md.shard_group_for_time(db, r.time)
                    if sg is None:
                        raise GeminiError("failed to create shard group")
                sg_cache[slot] = sg
            if info.shard_key and sg.ranged:
                # range routing (reference DestShard shardinfo.go:359)
                shard = sg.dest_shard(shard_key_of(r.tags,
                                                   info.shard_key))
            else:
                shard = sg.shard_for(series_hash(r.measurement, r.tags))
            pt = md.pt(db, shard.pt_id)
            if pt is None or md.nodes.get(pt.owner) is None:
                raise GeminiError(
                    f"no owner node for {db} pt {shard.pt_id}")
            if pt.status != "online":
                # transient during migration: one refresh, then fail
                # loudly rather than ack rows into a parked partition
                self.meta.refresh()
                md = self.meta.data()
                pt = md.pt(db, shard.pt_id)
                if pt is None or pt.status != "online":
                    raise GeminiError(
                        f"{db} pt {shard.pt_id} is offline")
            owner = md.nodes[pt.owner]
            batches.setdefault((owner.addr, shard.pt_id, owner.id),
                               []).append(r)
        return batches

    # -------------------------------------------------------------- write

    def write_points(self, db: str, rows: list[PointRow]) -> int:
        failpoint.inject("points_writer.write.err")
        if not rows:
            return 0
        self._ensure_db(db)
        batches = self._route(db, rows)
        written = 0
        errors: list[str] = []
        lock = threading.Lock()

        def send(addr: str, pt: int, owner_id: int,
                 batch: list[PointRow]):
            nonlocal written
            last: Exception | None = None
            for attempt in range(self.max_retries + 1):
                # owner id travels with the batch: the store rejects
                # writes for partitions it no longer owns, so a stale
                # route can never silently ack rows into an orphaned
                # engine db (they'd be invisible to queries)
                wire = {"db": db, "pt": pt, "owner": owner_id,
                        "rows": rows_to_wire(batch)}
                try:
                    resp = self._client(addr).call("store.write_rows", wire)
                    with lock:
                        written += resp["written"]
                    return
                except RPCError as e:
                    last = e
                    # partition may have moved: re-resolve the owner
                    self.meta.refresh()
                    md = self.meta.data()
                    owner = md.pt_owner(db, pt)
                    if owner is not None:
                        addr, owner_id = owner.addr, owner.id
            with lock:
                errors.append(f"pt {pt} @ {addr}: {last}")

        threads = [threading.Thread(target=send, args=(a, p, o, b))
                   for (a, p, o), b in batches.items()]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        if errors:
            raise ErrPartialWrite(written, errors)
        return written
