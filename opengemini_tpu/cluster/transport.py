"""Typed binary RPC transport between node roles.

Role of the reference's spdy multiplexed RPC
(engine/executor/spdy/multiplexed_connection.go:119,
multiplexed_session.go) and the netstorage client
(lib/netstorage/storage.go): many concurrent request/response (and
streaming-response) exchanges multiplexed over one TCP connection,
with typed messages.

Wire format (one frame):

    u32 frame_len | u32 header_len | header-json | array buffers...

The header carries {"t": msg_type, "rid": request id, "seq": frame seq,
"done": last-frame flag, "err": error string, "body": payload}. numpy
arrays and bytes inside body are swapped for descriptors and shipped as
raw little-endian buffers after the header (no base64, no pickling) —
this is the data plane for partial aggregate states, so copies matter.
"""

from __future__ import annotations

import contextlib
import json
import random
import socket
import struct
import threading
import time
import uuid
from queue import Empty, Queue

import numpy as np

from ..utils import deadline, failpoint, get_logger

log = get_logger(__name__)

# cumulative transport metrics (reference statistics/spdy.go analog)
from ..utils.stats import register_counters

RPC_STATS = register_counters("rpc", {
    "requests": 0, "responses": 0, "errors": 0,
    "bytes_in": 0, "bytes_out": 0,
    "breaker_trips": 0, "breaker_fast_fails": 0})

MAX_FRAME = 1 << 30


class RPCError(Exception):
    """Remote handler raised, or transport failed."""


class CircuitOpenError(RPCError):
    """Fast failure: the peer's circuit breaker is open. Raised without
    touching the socket, so a dead peer costs callers microseconds, not
    a connect timeout."""


# ------------------------------------------------------- circuit breaker

class CircuitBreaker:
    """Per-peer circuit breaker (reference pattern: fail fast on a dead
    store instead of stacking every caller behind connect timeouts).

    closed → N consecutive transport failures → open. While open, calls
    raise CircuitOpenError immediately until the cooldown elapses; then
    ONE caller becomes the half-open probe. Probe success closes the
    breaker; probe failure re-opens it with the cooldown doubled
    (jittered exponential backoff, capped), so a long-dead peer is
    probed ever more lazily but recovery is still automatic.

    Only transport-level failures count (connect refused/timeout,
    connection lost, response timeout) — a handler exception proves the
    peer alive and RESETS the failure count.
    """

    fail_threshold = 3
    base_cooldown_s = 0.5
    # probes are one cheap connect attempt — cap the backoff low so a
    # peer that comes BACK is rediscovered within seconds (a 30s cap
    # starved HA migrate retries against freshly-restarted stores)
    max_cooldown_s = 5.0

    def __init__(self, addr: str):
        self.addr = addr
        self._lock = threading.Lock()
        self.state = "closed"          # closed | open | half_open
        self.failures = 0              # consecutive transport failures
        self.open_cycles = 0           # consecutive trips (backoff exp)
        self.probe_at = 0.0            # monotonic time of next probe
        self.trips = 0
        self.fast_fails = 0
        self.probes = 0
        self._probe_t = 0.0            # when the current probe started

    def allow(self) -> bool:
        """Gate one call. Returns True when this call is the half-open
        probe; raises CircuitOpenError when the breaker is open."""
        with self._lock:
            if self.state == "closed":
                return False
            now = time.monotonic()
            if self.state == "open" and now >= self.probe_at:
                self.state = "half_open"
                self.probes += 1
                self._probe_t = now
                return True
            if self.state == "half_open" \
                    and now - self._probe_t > self.max_cooldown_s * 2:
                # the in-flight probe never reported back (caller died
                # mid-call) — a stuck half-open must not fast-fail
                # forever; promote this caller to a fresh probe
                self.probes += 1
                self._probe_t = now
                return True
            # open before cooldown, or a probe is already in flight
            self.fast_fails += 1
            from ..utils.stats import bump as _bump
            _bump(RPC_STATS, "breaker_fast_fails")
            raise CircuitOpenError(
                f"circuit open to {self.addr} "
                f"({self.failures} consecutive failures; "
                f"next probe in {max(0.0, self.probe_at - time.monotonic()):.2f}s)")

    def record_success(self) -> None:
        with self._lock:
            self.state = "closed"
            self.failures = 0
            self.open_cycles = 0

    def record_failure(self) -> None:
        with self._lock:
            self.failures += 1
            if self.state == "half_open" \
                    or self.failures >= self.fail_threshold:
                self._trip_locked()

    def _trip_locked(self) -> None:
        self.state = "open"
        self.trips += 1
        from ..utils.stats import bump as _bump
        _bump(RPC_STATS, "breaker_trips")
        # exponent capped: open_cycles grows without bound on a
        # long-dead peer and 2**N overflows float past ~1024 cycles
        cool = min(self.base_cooldown_s
                   * (2 ** min(self.open_cycles, 16)),
                   self.max_cooldown_s)
        # full jitter band 0.5x..1.5x: simultaneous trips across callers
        # must not re-probe a struggling peer in lockstep
        cool *= 0.5 + random.random()
        self.open_cycles += 1
        self.probe_at = time.monotonic() + cool

    def force(self, opened: bool) -> None:
        """Operator override (/debug/ctrl): trip or reset the breaker."""
        with self._lock:
            if opened:
                self.failures = max(self.failures, self.fail_threshold)
                self._trip_locked()
            else:
                self.state = "closed"
                self.failures = 0
                self.open_cycles = 0

    def snapshot(self) -> dict:
        with self._lock:
            d = {"state": self.state, "failures": self.failures,
                 "trips": self.trips, "fast_fails": self.fast_fails,
                 "probes": self.probes}
            if self.state == "open":
                d["probe_in_s"] = round(
                    max(0.0, self.probe_at - time.monotonic()), 3)
            return d


# one breaker per peer ADDRESS, shared by every RPCClient/pool in the
# process — all callers benefit from (and feed) the same dead-peer signal
_breakers: dict[str, CircuitBreaker] = {}
_breakers_lock = threading.Lock()
BREAKERS_ENABLED = True


def breaker_for(addr: str) -> CircuitBreaker:
    with _breakers_lock:
        b = _breakers.get(addr)
        if b is None:
            b = _breakers[addr] = CircuitBreaker(addr)
        return b


def breaker_stats() -> dict[str, dict]:
    with _breakers_lock:
        items = list(_breakers.items())
    return {addr: b.snapshot() for addr, b in items}


def reset_breakers() -> None:
    """Drop all breaker state (tests; operator full-reset)."""
    with _breakers_lock:
        _breakers.clear()


# ----------------------------------------------------------------- codec

def _extract(obj, bufs: list):
    """Replace ndarrays/bytes with descriptors, appending their buffers."""
    if isinstance(obj, np.ndarray):
        a = np.ascontiguousarray(obj)
        if a.dtype.byteorder == ">":
            a = a.astype(a.dtype.newbyteorder("<"))
        bufs.append(memoryview(a).cast("B"))
        return {"__nd__": len(bufs) - 1, "d": a.dtype.str, "s": list(a.shape)}
    if isinstance(obj, (bytes, bytearray, memoryview)):
        bufs.append(memoryview(bytes(obj)))
        return {"__by__": len(bufs) - 1}
    if isinstance(obj, dict):
        return {k: _extract(v, bufs) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [_extract(v, bufs) for v in obj]
    if isinstance(obj, (np.integer,)):
        return int(obj)
    if isinstance(obj, (np.floating,)):
        return float(obj)
    if isinstance(obj, (np.bool_,)):
        return bool(obj)
    return obj


def _restore(obj, bufs: list[bytes]):
    if isinstance(obj, dict):
        if "__nd__" in obj:
            buf = bufs[obj["__nd__"]]
            return np.frombuffer(buf, dtype=np.dtype(obj["d"])) \
                     .reshape(obj["s"]).copy()
        if "__by__" in obj:
            return bytes(bufs[obj["__by__"]])
        return {k: _restore(v, bufs) for k, v in obj.items()}
    if isinstance(obj, list):
        return [_restore(v, bufs) for v in obj]
    return obj


def encode_frame(header: dict, body) -> bytes:
    bufs: list[memoryview] = []
    header = dict(header)
    header["body"] = _extract(body, bufs)
    header["bl"] = [len(b) for b in bufs]
    hj = json.dumps(header, separators=(",", ":")).encode()
    total = 4 + len(hj) + sum(len(b) for b in bufs)
    out = bytearray(4 + total)
    struct.pack_into("<II", out, 0, total, len(hj))
    pos = 8
    out[pos:pos + len(hj)] = hj
    pos += len(hj)
    for b in bufs:
        out[pos:pos + len(b)] = b
        pos += len(b)
    return bytes(out)


def decode_frame(payload: bytes) -> dict:
    (hlen,) = struct.unpack_from("<I", payload, 0)
    header = json.loads(payload[4:4 + hlen].decode())
    pos = 4 + hlen
    bufs = []
    for n in header.get("bl", []):
        bufs.append(payload[pos:pos + n])
        pos += n
    header["body"] = _restore(header.get("body"), bufs)
    return header


def _read_exact(sock: socket.socket, n: int) -> bytes:
    chunks = []
    got = 0
    while got < n:
        c = sock.recv(min(n - got, 1 << 20))
        if not c:
            raise ConnectionError("connection closed")
        chunks.append(c)
        got += len(c)
    return b"".join(chunks)


def read_frame(sock: socket.socket) -> dict:
    (flen,) = struct.unpack("<I", _read_exact(sock, 4))
    if flen > MAX_FRAME:
        raise RPCError(f"frame too large: {flen}")
    from ..utils.stats import bump as _bump
    _bump(RPC_STATS, "bytes_in", flen + 4)
    return decode_frame(_read_exact(sock, flen))


# ---------------------------------------------------------------- server

class RPCServer:
    """Threaded RPC server. Handlers: {msg_type: fn(body) -> body | generator}.
    A generator handler streams frames (seq=0..n, done on last) — the analog
    of the reference's chunk responser streaming partial results back over
    spdy (app/ts-store/transport/handler/select.go)."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 handlers: dict | None = None, name: str = "rpc"):
        self.handlers = handlers or {}
        self.name = name
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, port))
        self._sock.listen(128)
        self.host, self.port = self._sock.getsockname()
        self._stop = threading.Event()
        self._threads: list[threading.Thread] = []
        self._conns: set[socket.socket] = set()
        self._lock = threading.Lock()

    @property
    def addr(self) -> str:
        return f"{self.host}:{self.port}"

    def register(self, msg_type: str, fn) -> None:
        self.handlers[msg_type] = fn

    def start(self) -> None:
        t = threading.Thread(target=self._accept_loop,
                             name=f"{self.name}-accept", daemon=True)
        t.start()
        self._threads.append(t)

    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                conn, _ = self._sock.accept()
            except OSError:
                return
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            with self._lock:
                self._conns.add(conn)
            t = threading.Thread(target=self._conn_loop, args=(conn,),
                                 name=f"{self.name}-conn", daemon=True)
            t.start()

    def _conn_loop(self, conn: socket.socket) -> None:
        wlock = threading.Lock()
        try:
            while not self._stop.is_set():
                frame = read_frame(conn)
                t = threading.Thread(
                    target=self._dispatch, args=(conn, wlock, frame),
                    daemon=True)
                t.start()
        except (ConnectionError, OSError):
            pass
        finally:
            with self._lock:
                self._conns.discard(conn)
            try:
                conn.close()
            except OSError:
                pass

    def _dispatch(self, conn, wlock, frame: dict) -> None:
        rid = frame.get("rid")
        mtype = frame.get("t")
        fn = self.handlers.get(mtype)
        from ..utils.stats import bump as _bump
        _bump(RPC_STATS, "requests")

        def send(body, seq=0, done=True, err=None, extra=None):
            data = encode_frame(
                {"t": mtype, "rid": rid, "seq": seq, "done": done,
                 **({"err": err} if err else {}),
                 **(extra or {})}, body)
            _bump(RPC_STATS, "responses")
            _bump(RPC_STATS, "bytes_out", len(data))
            if err:
                _bump(RPC_STATS, "errors")
            with wlock:
                conn.sendall(data)

        if fn is None:
            send(None, err=f"no handler for {mtype!r}")
            return
        # trace-context propagation (utils/tracing flight recorder):
        # a sampled caller ships {"tc": {"tid": ...}} — run the handler
        # under a server-side root span (thread-local bind, this
        # dispatch owns its thread) and return the finished tree on the
        # final frame so the sql node merges sql→store into ONE tree
        tc = frame.get("tc")
        srv_sp = None
        if isinstance(tc, dict):
            from ..utils import tracing as _tracing
            srv_sp = _tracing.Span(f"store:{mtype}")
            srv_sp.start_ns = time.perf_counter_ns()
            srv_sp.add(node=self.name)

        def _done_extra():
            if srv_sp is None:
                return None
            srv_sp.end_ns = time.perf_counter_ns()
            return {"tspan": srv_sp.to_dict()}

        if srv_sp is not None:
            from ..utils import tracing as _tracing
            cm = _tracing.bind(srv_sp, (tc or {}).get("tid"))
        else:
            cm = contextlib.nullcontext()
        try:
            # the whole dispatch — handler call AND streaming drain —
            # runs inside the bound context: generator handlers create
            # spans at next() time, and frames still go out one by one
            # (a traced request must not buffer the stream in memory)
            with cm:
                res = fn(frame.get("body"))
                if hasattr(res, "__next__"):   # streaming handler
                    seq = 0
                    last = None
                    have = False
                    for item in res:
                        if have:
                            send(last, seq=seq, done=False)
                            seq += 1
                        last, have = item, True
                    send(last if have else None, seq=seq, done=True,
                         extra=_done_extra())
                else:
                    send(res, extra=_done_extra())
        except Exception as e:   # handler errors travel to the caller
            log.exception("%s handler %s failed", self.name, mtype)
            try:
                send(None, err=f"{type(e).__name__}: {e}",
                     extra=_done_extra())
            except OSError:
                pass

    def stop(self) -> None:
        self._stop.set()
        try:
            self._sock.close()
        except OSError:
            pass
        with self._lock:
            conns = list(self._conns)
        for c in conns:
            try:
                c.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                c.close()
            except OSError:
                pass


# ---------------------------------------------------------------- client

class RPCClient:
    """One multiplexed connection to a peer; thread-safe concurrent calls.
    Reconnects lazily on failure (the connection-pool role of
    spdy/multiplexed_session_pool.go is served by reconnect + one shared
    multiplexed conn per peer)."""

    def __init__(self, addr: str, connect_timeout: float = 5.0):
        host, port = addr.rsplit(":", 1)
        self.addr = (host, int(port))
        self.addr_str = f"{host}:{int(port)}"
        self.connect_timeout = connect_timeout
        self._sock: socket.socket | None = None
        self._wlock = threading.Lock()      # serializes frame writes
        self._conn_lock = threading.Lock()  # serializes (re)connects —
        # kept separate so a slow connect never blocks writers on a
        # healthy socket or stacks callers behind a dead peer's timeout
        self._pending: dict[str, Queue] = {}
        self._plock = threading.Lock()
        self._recv_thread: threading.Thread | None = None

    def _ensure(self) -> socket.socket:
        s = self._sock
        if s is not None:
            return s
        with self._conn_lock:
            if self._sock is not None:
                return self._sock
            try:
                # injected connect failure surfaces as the refused
                # connection it simulates (breaker + retry paths see
                # the same exception type as the real fault)
                failpoint.inject("transport.connect.err")
            except failpoint.FailpointError as e:
                raise ConnectionError(str(e)) from e
            s = socket.create_connection(self.addr,
                                         timeout=self.connect_timeout)
            s.settimeout(None)
            s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            self._recv_thread = threading.Thread(
                target=self._recv_loop, args=(s,), daemon=True)
            self._recv_thread.start()
            self._sock = s
            return s

    def _recv_loop(self, s: socket.socket) -> None:
        try:
            while True:
                frame = read_frame(s)
                with self._plock:
                    entry = self._pending.get(frame.get("rid"))
                if entry is not None:
                    entry[1].put(frame)
        except Exception:
            # any receiver death (disconnect, oversized/corrupt frame)
            # must fail this socket's callers and allow reconnect —
            # a silently dead receiver would wedge the client forever
            self._fail_pending("connection lost", sock=s)

    def _fail_pending(self, why: str,
                      sock: socket.socket | None = None) -> None:
        """Fail calls in flight on `sock` (or all, when closing). Only
        tears down the current connection if it IS `sock` — a caller
        holding a stale socket must not kill a healthy reconnect."""
        with self._conn_lock:
            if sock is None or self._sock is sock:
                if self._sock is not None:
                    try:
                        self._sock.close()
                    except OSError:
                        pass
                    self._sock = None
        with self._plock:
            failed = [(rid, e) for rid, e in self._pending.items()
                      if sock is None or e[0] is sock]
            for rid, _ in failed:
                del self._pending[rid]
        for _, (_, q) in failed:
            # "xport" marks a synthetic transport-failure frame so the
            # circuit breaker can tell it from a remote handler error
            # (which proves the peer alive)
            q.put({"err": why, "done": True, "body": None, "xport": True})

    def call(self, msg_type: str, body=None, timeout: float = 60.0):
        """Single request/response. Raises RPCError on handler error."""
        frames = list(self.call_stream(msg_type, body, timeout))
        return frames[-1] if frames else None

    def call_stream(self, msg_type: str, body=None, timeout: float = 60.0):
        """Request with streaming response: yields each frame's body.
        Consults the peer's circuit breaker (fail-fast on dead peers)
        and clamps the wait by any deadline bound in this thread.

        Trace propagation (utils/tracing): when a span context is
        bound in this thread, the frame header carries the trace id
        (``tc``) and a child span ``rpc:<msg>`` wraps the exchange;
        the peer's span tree (final-frame ``tspan`` header) grafts
        under it — the sql→store fan-out merges into one tree."""
        rid = uuid.uuid4().hex
        q: Queue = Queue()
        s = None
        br = breaker_for(self.addr_str) if BREAKERS_ENABLED else None
        from ..utils import tracing as _tracing
        parent_sp = _tracing.current_span()
        rpc_sp = None
        if parent_sp is not None:
            rpc_sp = parent_sp.child(f"rpc:{msg_type}")
            rpc_sp.start_ns = time.perf_counter_ns()
            rpc_sp.add(peer=self.addr_str)
        # fault injection: simulate a dropped/slow RPC (reference plants
        # failpoints in the spdy transport, SURVEY.md §4). RPCError is
        # what real transport failures surface as — the injected fault
        # must exercise the same retry/failover/breaker paths
        if failpoint.inject("transport.send.drop"):
            if br is not None:
                br.record_failure()
            raise RPCError("failpoint: transport.send.drop")
        failpoint.inject("transport.send.delay")
        # clamp BEFORE consulting the breaker: an exhausted budget must
        # not claim the half-open probe slot and then bail without ever
        # reporting back (that would fast-fail every caller until the
        # stale-probe promotion window)
        requested_timeout = timeout
        timeout = deadline.clamp(timeout)
        curtailed = timeout < requested_timeout
        if br is not None:
            br.allow()                  # raises CircuitOpenError if open
        try:
            s = self._ensure()
            with self._plock:
                self._pending[rid] = (s, q)
            header = {"t": msg_type, "rid": rid}
            if rpc_sp is not None:
                header["tc"] = {"tid": _tracing.current_trace_id()
                                or ""}
            data = encode_frame(header, body)
            with self._wlock:
                if self._sock is not s:
                    raise ConnectionError("connection lost")
                s.sendall(data)
            limit = time.monotonic() + timeout
            while True:
                left = limit - time.monotonic()
                if left <= 0:
                    # a timeout on a deadline-CURTAILED wait is
                    # caller-side evidence (tight budget), not
                    # peer-death evidence — it must not trip the
                    # process-wide breaker for a healthy-but-slow peer
                    if br is not None and not curtailed:
                        br.record_failure()
                    raise RPCError(
                        f"timeout waiting for {msg_type} from "
                        f"{self.addr[0]}:{self.addr[1]}")
                try:
                    frame = q.get(timeout=min(left, 1.0))
                except Empty:
                    continue
                if rpc_sp is not None and frame.get("tspan"):
                    try:
                        # rebase: the peer's clock base is only
                        # comparable when it shares this process;
                        # otherwise the tree shifts rigidly into this
                        # RPC's local window (final frame ≈ rpc end)
                        rpc_sp.attach(_tracing.rebase_into(
                            _tracing.Span.from_dict(frame["tspan"]),
                            rpc_sp.start_ns, time.perf_counter_ns()))
                    except Exception:   # a malformed remote tree must
                        pass            # never fail the data path
                if frame.get("err"):
                    if br is not None:
                        if frame.get("xport"):
                            br.record_failure()
                        else:
                            # a handler error is PROOF the peer is alive
                            br.record_success()
                    raise RPCError(frame["err"])
                yield frame.get("body")
                if frame.get("done", True):
                    if br is not None:
                        br.record_success()
                    return
        except (ConnectionError, OSError) as e:
            if br is not None:
                br.record_failure()
            self._fail_pending(str(e), sock=s)
            raise RPCError(f"rpc to {self.addr}: {e}") from e
        finally:
            with self._plock:
                self._pending.pop(rid, None)
            if rpc_sp is not None:
                rpc_sp.end_ns = time.perf_counter_ns()

    def try_call(self, msg_type: str, body=None, timeout: float = 60.0,
                 retries: int = 2, backoff: float = 0.2):
        """call() with reconnect retries (transient failures) and
        jittered exponential backoff. An open circuit breaker or an
        exhausted deadline short-circuits the remaining retries — both
        mean waiting longer cannot help this call."""
        from ..utils.errors import ErrQueryTimeout
        err = None
        dl = deadline.current()
        for i in range(retries + 1):
            try:
                return self.call(msg_type, body, timeout)
            except CircuitOpenError:
                raise                    # retrying now is the stacking
                # behavior the breaker exists to prevent
            except ErrQueryTimeout:
                raise                    # budget gone: stop immediately
            except RPCError as e:
                err = e
                if i < retries:
                    pause = backoff * (2 ** i) * (0.5 + random.random())
                    if dl is not None:
                        left = dl.remaining()
                        if left <= pause:
                            break
                    time.sleep(pause)
        raise err

    def close(self) -> None:
        self._fail_pending("client closed")


class ClientPool:
    """Shared addr→RPCClient cache (the one reconnect/close point for
    PointsWriter, ClusterExecutor and store peer calls)."""

    def __init__(self):
        import threading
        self._clients: dict[str, RPCClient] = {}
        self._lock = threading.Lock()

    def get(self, addr: str) -> RPCClient:
        with self._lock:
            c = self._clients.get(addr)
            if c is None:
                c = self._clients[addr] = RPCClient(addr)
            return c

    def call(self, addr: str, msg: str, body: dict,
             timeout: float = 30.0):
        return self.get(addr).call(msg, body, timeout=timeout)

    def close(self) -> None:
        with self._lock:
            for c in self._clients.values():
                c.close()
            self._clients.clear()
