"""HA: failure detection, partition takeover, balancing.

Role of the reference's meta-side HA plane (SURVEY §2.5/§3.5):
- ClusterManager (app/ts-meta/meta/cluster_manager.go:65) — consumes
  membership events; here membership is raft-replicated heartbeats
  (the serf-gossip equivalent, SURVEY §2.6: "JAX distributed runtime
  heartbeats + coordinator service"), swept periodically on the leader.
- MigrateStateMachine (migrate_state_machine.go:40) — executes PT
  assign/move events with retries: mark offline → target store loads the
  partition → commit new ownership in the raft catalog.
- Balancer (balance_manager.go) — background PT spread across alive
  stores.

Consensus and takeover stay strictly CPU-side; device state is never
coupled to membership (SURVEY §7 hard-parts list).
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field

from ..utils import failpoint, get_logger
from .meta_data import PT_OFFLINE, PT_ONLINE, STATUS_ALIVE, STATUS_FAILED
from .transport import RPCClient, RPCError

log = get_logger(__name__)

DEFAULT_FAILURE_TIMEOUT_S = 10.0
DEFAULT_SWEEP_S = 2.0


@dataclass
class MigrateEvent:
    """One PT reassignment (reference assign_event.go / move_event.go)."""
    db: str
    pt_id: int
    from_node: int
    to_node: int
    attempts: int = 0
    done: threading.Event = field(default_factory=threading.Event)
    error: str | None = None


class MigrateStateMachine:
    """Executes migrate events against the replicated catalog + stores.

    Protocol per event (reference migrate_state_machine.go:66-197):
      1. raft: set_pt_status(db, pt, OFFLINE)   — writes stop routing here
      2. rpc:  target store.load_pt             — open partition engine
      3. raft: move_pt(db, pt, to_node, ONLINE) — commit new owner
    A failed step retries up to max_attempts, then the event parks the PT
    offline (operator-visible) rather than flapping.
    """

    def __init__(self, meta_client, max_attempts: int = 3,
                 retry_pause_s: float = 0.5):
        self.meta = meta_client
        self.max_attempts = max_attempts
        # pause between attempts: an instantaneous burst can be eaten
        # whole by a target's open circuit breaker before its next
        # probe; half a second lets the probe happen
        self.retry_pause_s = retry_pause_s
        self._clients: dict[str, RPCClient] = {}
        self._lock = threading.Lock()

    def _client(self, addr: str) -> RPCClient:
        with self._lock:
            c = self._clients.get(addr)
            if c is None:
                c = self._clients[addr] = RPCClient(addr)
            return c

    def close(self) -> None:
        with self._lock:
            for c in self._clients.values():
                c.close()
            self._clients.clear()

    def execute(self, ev: MigrateEvent) -> bool:
        md = self.meta.data()
        target = md.nodes.get(ev.to_node)
        if target is None:
            ev.error = f"target node {ev.to_node} unknown"
            ev.done.set()
            return False
        while ev.attempts < self.max_attempts:
            ev.attempts += 1
            try:
                # fault injection: a migrate step fails inside the retry
                # loop — with maxhits=N the event recovers on attempt
                # N+1; without, the PT parks offline (operator-visible)
                failpoint.inject("ha.migrate.err")
                self.meta.apply({"op": "set_pt_status", "db": ev.db,
                                 "pt_id": ev.pt_id, "status": PT_OFFLINE})
                # background migration driver: bounded by
                # max_attempts, never request-scoped — a deadline
                # raise would escape the RPCError retry handler
                self._client(target.addr).call(
                    "store.load_pt", {"db": ev.db, "pt": ev.pt_id},
                    timeout=30.0)  # oglint: disable=R301
                self.meta.apply({"op": "move_pt", "db": ev.db,
                                 "pt_id": ev.pt_id, "to_node": ev.to_node,
                                 "status": PT_ONLINE})
                log.info("migrated %s/pt%d: node %d -> %d", ev.db,
                         ev.pt_id, ev.from_node, ev.to_node)
                ev.done.set()
                return True
            except (RPCError, OSError, failpoint.FailpointError) as e:
                ev.error = str(e)
                log.warning("migrate %s/pt%d attempt %d failed: %s",
                            ev.db, ev.pt_id, ev.attempts, e)
                if ev.attempts < self.max_attempts:
                    time.sleep(self.retry_pause_s)
        log.error("migrate %s/pt%d gave up after %d attempts (pt stays "
                  "offline)", ev.db, ev.pt_id, ev.attempts)
        ev.done.set()
        return False


class ClusterManager:
    """Leader-side failure detector + takeover driver.

    sweep(now) is the event pump (reference processEvent/processFailedDbPt
    cluster_manager.go:323,482): nodes whose raft-replicated heartbeat is
    stale beyond failure_timeout are marked FAILED and every PT they own
    is migrated — replica nodes preferred, else the least-loaded alive
    node.
    """

    def __init__(self, meta_client,
                 failure_timeout_s: float = DEFAULT_FAILURE_TIMEOUT_S,
                 sweep_s: float = DEFAULT_SWEEP_S,
                 now_fn=time.time_ns,
                 is_leader_fn=None):
        self.meta = meta_client
        self.failure_timeout_s = failure_timeout_s
        self.sweep_s = sweep_s
        self.now_fn = now_fn
        # only the raft leader drives takeover — concurrent sweeps from
        # several voters would double-migrate the same PT
        self.is_leader_fn = is_leader_fn or (lambda: True)
        self.msm = MigrateStateMachine(meta_client)
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        # no takeover until a full timeout has elapsed since this manager
        # started: after leadership change / process resume, stores need
        # one heartbeat round before their timestamps mean anything
        self._grace_until_ns = now_fn() + int(failure_timeout_s * 1e9)
        # per-PT redrive backoff: a parked PT whose retry keeps failing
        # (e.g. load_pt hangs on a disk fault) must not block every
        # sweep — each PT gets one migrate burst per backoff window
        self._redrive_after: dict[tuple, float] = {}
        self.redrive_backoff_s = 10.0

    # ------------------------------------------------------------ lifecycle

    def start(self) -> None:
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="cluster-manager")
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
        self.msm.close()

    def _loop(self) -> None:
        while not self._stop.wait(self.sweep_s):
            if not self.is_leader_fn():
                continue
            try:
                self.sweep(self.now_fn())
            except Exception as e:   # noqa: BLE001 — keep the detector alive
                log.error("cluster manager sweep failed: %s", e)

    # ---------------------------------------------------------------- sweep

    def sweep(self, now_ns: int) -> list[MigrateEvent]:
        """One detection+takeover pass; returns the executed events.
        now_ns: nanosecond clock, same unit as the raft-replicated
        heartbeat timestamps."""
        if now_ns < self._grace_until_ns:
            return []
        # fault injection: a failed sweep pass must never kill the
        # detector loop (_loop catches and logs, like any sweep error)
        failpoint.inject("ha.sweep.err")
        # heartbeat applies don't push snapshots to clients — pull a
        # fresh catalog or every node looks stale
        self.meta.refresh()
        md = self.meta.data()
        timeout_ns = int(self.failure_timeout_s * 1e9)
        alive = [n for n in md.nodes.values() if n.status == STATUS_ALIVE]
        stale = [n for n in alive
                 if now_ns - n.last_heartbeat >= timeout_ns]
        if not stale:
            # no new failures: re-drive parked partitions (reference
            # processFailedDbPt retry, cluster_manager.go:482) — a PT
            # left OFFLINE by an exhausted migrate (its target was dead
            # too) comes back once its owner or a replica rejoins
            return self._redrive_parked(md, {n.id for n in alive})
        # mass-staleness guard: when MOST nodes look dead at once, the
        # likely fault is on OUR side (meta partition / suspended leader
        # / stalled heartbeat processing) — cascading takeover would
        # domino every PT onto dataless nodes. Hold off; a real mass
        # outage still gets handled once some nodes heartbeat back in.
        if len(stale) * 2 > len(alive):
            log.error(
                "%d/%d nodes stale at once — refusing takeover "
                "(suspected meta-side fault)", len(stale), len(alive))
            return []
        events: list[MigrateEvent] = []
        for node in stale:
            log.warning("node %d (%s) heartbeat stale %.1fs -> FAILED",
                        node.id, node.addr,
                        (now_ns - node.last_heartbeat) / 1e9)
            self.meta.apply({"op": "set_node_status", "node_id": node.id,
                             "status": STATUS_FAILED})
            events.extend(self._takeover(node.id))
        return events

    def _redrive_parked(self, md, alive_ids: set) -> list[MigrateEvent]:
        """Retry OFFLINE partitions whose owner or a replica is alive
        again. Safe to run every sweep: migrations execute synchronously
        in this (leader-only) sweep thread, so a PT can never be seen
        OFFLINE here while a takeover for it is still in flight."""
        events: list[MigrateEvent] = []
        now = time.monotonic()
        for db, pts in md.pts.items():
            for pt in pts:
                if pt.status == PT_ONLINE:
                    continue
                key = (db, pt.pt_id)
                if now < self._redrive_after.get(key, 0.0):
                    continue
                cands = [pt.owner] + [r for r in pt.replicas
                                      if r != pt.owner]
                target = next((c for c in cands if c in alive_ids), None)
                if target is None:
                    continue
                log.warning("re-driving parked %s/pt%d -> node %d",
                            db, pt.pt_id, target)
                ev = MigrateEvent(db=db, pt_id=pt.pt_id,
                                  from_node=pt.owner, to_node=target)
                if self.msm.execute(ev):
                    self._redrive_after.pop(key, None)
                else:
                    self._redrive_after[key] = \
                        time.monotonic() + self.redrive_backoff_s
                events.append(ev)
        return events

    def _takeover(self, failed_node: int) -> list[MigrateEvent]:
        # fault injection: stall takeover (slow-failover chaos window)
        failpoint.inject("ha.takeover.delay")
        self.meta.refresh()
        md = self.meta.data()
        alive = {n.id for n in md.alive_nodes()}
        if not alive:
            log.error("no alive nodes to take over PTs of node %d",
                      failed_node)
            return []
        load = {nid: 0 for nid in alive}
        for pts in md.pts.values():
            for pt in pts:
                if pt.owner in load:
                    load[pt.owner] += 1
        events = []
        for db, pts in md.pts.items():
            for pt in pts:
                if pt.owner != failed_node:
                    continue
                # replica nodes first (with per-PT replication enabled
                # they hold the data; without it takeover restores
                # ROUTING only — the failed node's rows are unavailable
                # until it rejoins), else least-loaded alive node
                # (reference cluster_manager node choice :438)
                cands = [r for r in pt.replicas if r in alive]
                if cands:
                    target = cands[0]
                elif pt.replicas:
                    # REPLICATED pt with no live data member: park it
                    # OFFLINE (typed "partitions unavailable" errors)
                    # rather than hand routing to a non-member whose
                    # empty engine would serve silently-wrong results;
                    # _redrive_parked restores it when a member rejoins
                    log.error(
                        "%s/pt%d: no live replica to take over — "
                        "parking offline until a data member rejoins",
                        db, pt.pt_id)
                    self.meta.apply({"op": "set_pt_status", "db": db,
                                     "pt_id": pt.pt_id,
                                     "status": PT_OFFLINE})
                    continue
                else:
                    target = min(sorted(alive), key=lambda n: load[n])
                load[target] = load.get(target, 0) + 1
                ev = MigrateEvent(db=db, pt_id=pt.pt_id,
                                  from_node=failed_node, to_node=target)
                self.msm.execute(ev)
                events.append(ev)
        return events


class Balancer:
    """Background PT balance (reference balance_manager.go): move PTs from
    the most- to the least-loaded alive store while the spread exceeds
    one."""

    def __init__(self, meta_client, msm: MigrateStateMachine | None = None):
        self.meta = meta_client
        self.msm = msm or MigrateStateMachine(meta_client)

    def plan(self) -> list[MigrateEvent]:
        """Compute (but do not execute) the next round of balancing
        moves: one move per overloaded node per round."""
        md = self.meta.data()
        alive = sorted(n.id for n in md.alive_nodes())
        if len(alive) < 2:
            return []
        load: dict[int, list] = {nid: [] for nid in alive}
        for db, pts in md.pts.items():
            for pt in pts:
                if pt.status == PT_ONLINE and pt.owner in load:
                    load[pt.owner].append((db, pt.pt_id))
        moves = []
        while True:
            hi = max(alive, key=lambda n: len(load[n]))
            lo = min(alive, key=lambda n: len(load[n]))
            if len(load[hi]) - len(load[lo]) <= 1:
                break
            db, pt_id = load[hi].pop()
            load[lo].append((db, pt_id))
            moves.append(MigrateEvent(db=db, pt_id=pt_id, from_node=hi,
                                      to_node=lo))
        return moves

    def rebalance(self) -> list[MigrateEvent]:
        moves = self.plan()
        for ev in moves:
            self.msm.execute(ev)
        return moves
