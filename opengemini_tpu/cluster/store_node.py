"""Store node service: RPC handlers over a local storage Engine.

Role of the reference's ts-store transport servers
(app/ts-store/transport/server_insert.go:34 — InsertProcessor writes,
app/ts-store/transport/server_select.go:52 — SelectProcessor queries,
handler/select.go:129 executing the pushed-down sub-plan per shard).

Partitions: each (database, pt) the node owns maps to one engine
database named ``db@pt`` — partition data stays physically separate so
a partition can be migrated wholesale (reference DBPTInfo,
engine/partition.go).

Query handlers return *partial aggregate states*
(QueryExecutor.partial_agg wire format) — the sql node merges them, so
the heavy reduction runs here, on-device, next to the data.
"""

from __future__ import annotations

import threading
import time
from dataclasses import replace

from ..query.ast import SelectStatement, ShowStatement
from ..query.condition import analyze_condition
from ..query.executor import (QueryExecutor, classify_select,
                              merge_partials)
from ..query.influxql import parse_query
from ..storage.engine import Engine, EngineOptions
from ..utils.stats import bump as _bump_stat
from ..storage.rows import PointRow
from ..utils import failpoint, get_logger
from .transport import RPCServer

log = get_logger(__name__)


def db_key(db: str, pt: int) -> str:
    """Engine-database name for one partition of a logical database."""
    return f"{db}@{pt}"


def rows_to_wire(rows: list[PointRow]) -> list:
    return [[r.measurement, r.tags, r.fields, r.time] for r in rows]


def rows_from_wire(wire: list) -> list[PointRow]:
    return [PointRow(m, t, f, tm) for m, t, f, tm in wire]


class StoreNode:
    """One ts-store: engine + RPC service. Registration/heartbeat to the
    meta cluster is handled by the app wrapper (app/nodes.py)."""

    def __init__(self, data_dir: str, host: str = "127.0.0.1",
                 port: int = 0, opts: EngineOptions | None = None):
        self.engine = Engine(data_dir, opts)
        self.executor = QueryExecutor(self.engine)
        self.node_id: int | None = None          # set after registration
        self.server = RPCServer(host=host, port=port, name="store",
                                handlers={
                                    "store.ping": self._on_ping,
                                    "store.write_rows": self._on_write,
                                    "store.write_lines":
                                        self._on_write_lines,
                                    "store.select_partial": self._on_select_partial,
                                    "store.select_raw": self._on_select_raw,
                                    "store.show": self._on_show,
                                    "store.drop_db": self._on_drop_db,
                                    "store.ddl": self._on_ddl,
                                    "store.measurements": self._on_measurements,
                                    "store.load_pt": self._on_load_pt,
                                    "store.drop_pt": self._on_drop_pt,
                                    "store.split_points":
                                        self._on_split_points,
                                    "store.ensure_group":
                                        self._on_ensure_group,
                                    "store.raft_write":
                                        self._on_raft_write,
                                    "store.raft_commit":
                                        self._on_raft_commit,
                                })
        self.addr = self.server.addr
        # bumped from the RPC server's per-connection threads — a bare
        # `+=` here is the unlocked read-modify-write oglint R6 exists
        # to catch (utils.stats.bump holds the shared counter lock)
        self.stats = {"writes": 0, "rows_written": 0, "selects": 0}
        # per-PT raft replication (cluster/replication.py); wired by the
        # app wrapper once the node is registered with meta
        self.replication = None
        from .transport import ClientPool
        self._peers = ClientPool()

    def start(self) -> None:
        self.server.start()

    def stop(self) -> None:
        # shutdown is exception-safe stage by stage: a failure tearing
        # down replication/peers must NEVER leave the listener bound
        # (a restart on the same port would then fail EADDRINUSE) or
        # the engine open
        try:
            if self.replication is not None:
                self.replication.stop()
        finally:
            try:
                self._peers.close()
            finally:
                try:
                    self.server.stop()
                finally:
                    self.engine.close()

    def peer_call(self, addr: str, msg: str, body: dict,
                  timeout: float = 30.0):
        """Store→store RPC (raft write forwarding, group fanout)."""
        return self._peers.call(addr, msg, body, timeout=timeout)

    # ------------------------------------------------------------ handlers

    def _on_ping(self, body):
        return {"ok": True, "node_id": self.node_id,
                "now": time.time_ns()}

    def _on_load_pt(self, body):
        """Open (or create) one partition's engine database — the target
        side of PT migration (reference store PtProcessor,
        app/ts-store/transport/handler/migration.go; engine preload
        engine_ha.go). Creating the db opens shards + replays WAL."""
        dbk = db_key(body["db"], body["pt"])
        self.engine.create_database(dbk)
        return {"loaded": dbk}

    def _on_drop_pt(self, body):
        """Release a migrated-away partition's local engine state."""
        dbk = db_key(body["db"], body["pt"])
        if dbk in self.engine.databases:
            self.engine.drop_database(dbk)
        return {"dropped": dbk}

    def _on_split_points(self, body):
        """Sample shard-key values of this node's partitions (reference
        Engine.GetShardSplitPoints engine/engine.go:930) — the sql node
        merges samples across stores and derives balanced range bounds."""
        db, pts = body["db"], body["pts"]
        mst = body.get("measurement")
        shard_key = body["shard_key"]
        from .hashing import shard_key_of
        cap = int(body.get("cap", 20000))
        samples: list[str] = []
        for pt in pts:
            dbk = db_key(db, pt)
            if dbk not in self.engine.databases:
                continue
            for s in self.engine.database(dbk).all_shards():
                msts = [mst] if mst else s.measurements()
                for m in msts:
                    for sid in s.series_ids(m).tolist():
                        tags = s.index.tags_of(sid)
                        samples.append(shard_key_of(tags, shard_key))
                        if len(samples) >= cap:
                            return {"samples": sorted(samples)}
        return {"samples": sorted(samples)}

    def _on_write(self, body):
        # fault injection: store-side write failure AFTER transport
        # succeeded (exercises writer retry with a healthy connection)
        failpoint.inject("store.write.err")
        owner = body.get("owner")
        if (owner is not None and self.node_id is not None
                and owner != self.node_id):
            # stale route after a PT migration: reject so the writer
            # refreshes its catalog instead of acking rows into an
            # engine db queries no longer look at
            raise ValueError(
                f"not pt owner: write addressed to node {owner}, "
                f"this is node {self.node_id}")
        db, pt = body["db"], body["pt"]
        if self.replication is not None \
                and self.replication.replicated(db, pt):
            # consistent-replication mode: the batch commits through the
            # PT raft group; the FSM applies it to every member's engine
            n = self.replication.write(db, pt, body["rows"])
        else:
            rows = rows_from_wire(body["rows"])
            n = self.engine.write_points(db_key(db, pt), rows)
        _bump_stat(self.stats, "writes")
        _bump_stat(self.stats, "rows_written", n)
        return {"written": n}

    def _on_write_lines(self, body):
        """Raw line-protocol bytes for ONE partition (the sql node's
        columnar scatter, points_writer._write_lines): the local
        columnar fast path ingests them; replicated partitions parse
        to rows and commit through the PT raft group so the FSM
        semantics stay row-based."""
        failpoint.inject("store.write.err")   # same site as _on_write:
        # one logical fault covers both store-side write planes
        owner = body.get("owner")
        if (owner is not None and self.node_id is not None
                and owner != self.node_id):
            raise ValueError(
                f"not pt owner: write addressed to node {owner}, "
                f"this is node {self.node_id}")
        db, pt = body["db"], body["pt"]
        if self.replication is not None \
                and self.replication.replicated(db, pt):
            from ..utils.lineprotocol import parse_lines
            rows = parse_lines(
                body["data"].decode("utf-8", errors="replace"),
                body.get("default_time_ns", 0),
                body.get("precision", "ns"))
            n = self.replication.write(db, pt, rows_to_wire(rows))
        else:
            from ..utils.lineprotocol import ingest_lines
            n = ingest_lines(self.engine, db_key(db, pt), body["data"],
                             body.get("default_time_ns", 0),
                             body.get("precision", "ns"))
        _bump_stat(self.stats, "writes")
        _bump_stat(self.stats, "rows_written", n)
        return {"written": n}

    def _on_ensure_group(self, body):
        if self.replication is None:
            raise ValueError("replication not enabled on this node")
        g = self.replication.ensure_group(body["db"], body["pt"])
        return {"member": g is not None}

    def _on_raft_write(self, body):
        """Leader-forwarded replicated write (netstorage raft routing).
        forward=False: one hop only — a deposed leader answers
        NotLeader instead of bouncing the batch back (see
        replication.write)."""
        if self.replication is None:
            raise ValueError("replication not enabled on this node")
        n = self.replication.write(body["db"], body["pt"], body["rows"],
                                   forward=False)
        return {"written": n}

    def _parse_select(self, q: str) -> SelectStatement:
        stmts = parse_query(q)
        if len(stmts) != 1 or not isinstance(stmts[0], SelectStatement):
            raise ValueError("store.select expects one SELECT statement")
        # the partition key (db@pt) is authoritative here — a db
        # qualifier inside the statement must not override it
        return replace(stmts[0], from_db=None, from_rp=None)

    def _on_raft_commit(self, body):
        """Group commit index for a peer's follower-read barrier."""
        if self.replication is None:
            return {"commit": 0}
        return {"commit":
                self.replication.commit_index(body["db"], body["pt"])}

    def _read_barrier(self, db: str, pts: list[int]) -> bool:
        """Replicated partitions: apply-catch-up before scanning
        (replication.read_barrier — read-your-writes on follower
        owners). Barriers run in parallel: a leaderless group must
        not serialize its wait in front of the other partitions.
        Returns True when EVERY barrier was sound; False means the
        scan may miss acked writes and the response must say so."""
        if self.replication is None:
            return True
        live = []
        member_hole = False
        for pt in pts:
            if self.replication.has_group(db, pt):
                live.append(pt)
            elif db_key(db, pt) in self.engine.databases \
                    and self.replication.replicated(db, pt):
                # this store holds an engine db and the ROUTE for a
                # replicated pt but is no raft member of it (stale
                # routing / takeover races): it cannot prove the scan
                # complete — flag rather than serve silently
                member_hole = True
        if not live:
            return not member_hole
        if len(live) == 1:
            return self.replication.read_barrier(db, live[0]) \
                and not member_hole
        sound = [True] * len(live)

        def one(i: int, pt: int):
            sound[i] = self.replication.read_barrier(db, pt)

        threads = [threading.Thread(target=one, args=(i, pt))
                   for i, pt in enumerate(live)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        return all(sound) and not member_hole

    def _on_select_partial(self, body):
        """Partial aggregation over this node's partitions of a db; the
        per-pt partials merge locally first (intra-node exchange) so one
        state grid travels back."""
        # fault injection: a slow/failing store select — the sql node's
        # deadline clamp (not a fresh per-hop timeout) bounds the wait
        failpoint.inject("store.select.delay")
        stmt = self._parse_select(body["q"])
        db, pts = body["db"], body["pts"]
        barrier_sound = self._read_barrier(db, pts)
        _bump_stat(self.stats, "selects")
        # sampled sql→store traces: the RPC server bound a store-side
        # root span for this hop (transport._dispatch) — thread it
        # into partial_agg so the store's reader_scan/device_agg/
        # device_pull phases ride back to the sql node's merged tree
        from ..utils import tracing as _tracing
        hop_span = _tracing.current_span()
        partials = []
        for pt in pts:
            dbk = db_key(db, pt)
            if dbk not in self.engine.databases:
                continue
            # regex sources/dimensions expand against THIS node's
            # schema (the sql node ships them verbatim; an unexpanded
            # RegexDim would drop the group tags from the partial)
            st = stmt
            from ..query.ast import RegexDim
            if st.from_regex is not None or any(
                    isinstance(d.expr, RegexDim) for d in st.dimensions):
                st = self.executor._expand_regexes(st, dbk)
                if st is None:
                    continue
            mst = st.from_measurement
            cs = classify_select(st)
            tag_keys = {k for s in self.engine.database(dbk).all_shards()
                        for k in s.index.tag_keys(mst)}
            cond = analyze_condition(st.condition, tag_keys)
            p = self.executor.partial_agg(st, dbk, mst, cs, cond,
                                          tag_keys, span=hop_span)
            if p is not None:
                partials.append(p)
        out = {"partial": merge_partials(partials)}
        if not barrier_sound:
            # degraded barrier: the sql node must flag the merged
            # result partial — a silent maybe-stale aggregate is
            # indistinguishable from a correct one
            out["degraded"] = True
        return out

    def _on_select_raw(self, body):
        """Raw rows for non-aggregate selects. Row limits are applied at
        the sql node after the global merge (a series group may span
        partitions only when there is no GROUP BY) — but are pushed down
        as a per-store cap when there is no OFFSET (reference
        LimitPushdown rules, heu_rule.go)."""
        failpoint.inject("store.select.delay")
        stmt = self._parse_select(body["q"])
        db, pts = body["db"], body["pts"]
        barrier_sound = self._read_barrier(db, pts)
        _bump_stat(self.stats, "selects")
        pushdown_limit = 0
        if stmt.limit and not stmt.offset:
            pushdown_limit = stmt.limit
        sub = replace(stmt, limit=pushdown_limit, offset=0,
                      slimit=0, soffset=0)
        results = []
        for pt in pts:
            dbk = db_key(db, pt)
            if dbk not in self.engine.databases:
                continue
            res = self.executor.execute(sub, dbk)
            if "error" in res:
                raise ValueError(res["error"])
            if res.get("series"):
                results.append(res["series"])
        out = {"series_lists": results}
        if not barrier_sound:
            out["degraded"] = True
        return out

    def _on_show(self, body):
        """SHOW fan-out: run against each owned partition, sql unions."""
        stmts = parse_query(body["q"])
        if len(stmts) != 1 or not isinstance(stmts[0], ShowStatement):
            raise ValueError("store.show expects one SHOW statement")
        stmt = replace(stmts[0], on_db=None)
        out = []
        for pt in body["pts"]:
            dbk = db_key(body["db"], pt)
            if dbk not in self.engine.databases:
                continue
            res = self.executor.execute(stmt, dbk)
            if "error" in res:
                raise ValueError(res["error"])
            if res.get("series"):
                out.append(res["series"])
        return {"series_lists": out}

    def _on_measurements(self, body):
        out: set[str] = set()
        for pt in body["pts"]:
            dbk = db_key(body["db"], pt)
            if dbk in self.engine.databases:
                out.update(self.engine.measurements(dbk))
        return {"measurements": sorted(out)}

    def _on_ddl(self, body):
        """Execute a DDL/DML statement (DROP MEASUREMENT, DELETE) on each
        local partition of the db — scattered from the sql node like the
        reference's netstorage DDL messages (lib/netstorage/
        message_types.go)."""
        from ..query import parse_query
        (stmt,) = parse_query(body["q"])
        errs = []
        for pt in body["pts"]:
            dbk = db_key(body["db"], pt)
            if dbk not in self.engine.databases:
                continue
            res = self.executor.execute(stmt, dbk)
            if "error" in res:
                errs.append(res["error"])
        if errs:
            return {"ok": False, "error": "; ".join(errs)}
        return {"ok": True}

    def _on_drop_db(self, body):
        db = body["db"]
        for name in [n for n in self.engine.databases
                     if n == db or n.startswith(db + "@")]:
            self.engine.drop_database(name)
        return {"ok": True}
