"""Cluster layer: meta consensus, RPC transport, routing, distribution.

TPU-native replacement for the reference's cluster stack:
- transport: typed binary RPC (role of spdy, engine/executor/spdy/, and
  netstorage, lib/netstorage/storage.go) — control + data plane between
  sql/store/meta node roles. On-device aggregation exchange stays in
  parallel/ (XLA collectives); this transport carries host-side partial
  states and control messages only.
- raft: CPU-side raft consensus for the meta catalog (role of hashicorp
  raft, app/ts-meta/meta/raft_wrapper.go:23).
- meta_data / meta_store / meta_client: replicated cluster catalog
  (role of lib/util/lifted/influx/meta/data.go + app/ts-meta/meta/store.go
  + lib/metaclient/meta_client.go:332).
- points_writer: time+hash routing write fan-out (coordinator/
  points_writer.go:228).
- shard_mapper: query scatter/gather with partial-agg merge
  (coordinator/shard_mapper.go:60).
"""

from .hashing import series_hash, fnv1a64
from .transport import RPCServer, RPCClient, RPCError
from .meta_data import MetaData, DataNode, ShardGroupInfo, PtInfo

__all__ = [
    "series_hash", "fnv1a64",
    "RPCServer", "RPCClient", "RPCError",
    "MetaData", "DataNode", "ShardGroupInfo", "PtInfo",
]
