"""Meta node: raft-replicated catalog service + client library.

Role of the reference's ts-meta store (app/ts-meta/meta/store.go,
store_fsm.go — FSM applying typed commands to the Data model) and of
the MetaClient used by sql/store nodes
(lib/metaclient/meta_client.go:332 — cached Data snapshot, retry loops,
leader redirects).
"""

from __future__ import annotations

import threading
import time

from ..utils import failpoint, get_logger
from ..utils.deadline import clamp as _dl_clamp
from .meta_data import MetaData
from .raft import NotLeader, RaftNode
from .transport import RPCClient, RPCError, RPCServer

log = get_logger(__name__)


class MetaServer:
    """One ts-meta voter: raft node whose FSM is a MetaData, plus the
    client-facing RPC endpoint (meta.apply / meta.snapshot / meta.ping)."""

    def __init__(self, node_id: str, raft_peers: dict[str, str],
                 data_dir: str, host: str = "127.0.0.1",
                 client_port: int = 0, raft_port: int = 0):
        self.data = MetaData()
        self._data_lock = threading.RLock()
        self.raft = RaftNode(
            node_id, raft_peers, data_dir,
            fsm_apply=self._fsm_apply,
            fsm_snapshot=self._fsm_snapshot,
            fsm_restore=self._fsm_restore,
            host=host, port=raft_port)
        self.server = RPCServer(host=host, port=client_port,
                                name=f"meta-{node_id}", handlers={
                                    "meta.apply": self._on_apply,
                                    "meta.snapshot": self._on_snapshot,
                                    "meta.ping": lambda b: {"ok": True},
                                })
        self.addr = self.server.addr

    # FSM hooks (called with raft's lock held — keep them fast)
    def _fsm_apply(self, cmd):
        with self._data_lock:
            return self.data.apply(cmd)

    def _fsm_snapshot(self):
        with self._data_lock:
            return self.data.to_dict()

    def _fsm_restore(self, d):
        with self._data_lock:
            self.data = MetaData.from_dict(d)

    # client-facing handlers
    def _on_apply(self, body):
        # fault injection: this voter rejects the mutation (the client's
        # meta-addr retry loop must route around it)
        failpoint.inject("meta.apply.err")
        try:
            cmd = body["cmd"]
            if cmd.get("op") in ("heartbeat", "create_node"):
                # stamp liveness with the RECEIVING side's clock: the
                # failure sweep runs on this (leader) host, so cross-node
                # clock skew must not enter the staleness arithmetic
                # (reference uses meta-side receipt time)
                cmd = dict(cmd, now=time.time_ns())
            res = self.raft.propose(cmd)
            with self._data_lock:
                ver = self.data.version
            return {"ok": True, "result": res, "version": ver}
        except NotLeader as e:
            return {"ok": False, "redirect": self._leader_client_addr(),
                    "error": str(e)}
        except (ValueError, KeyError) as e:
            # deterministic FSM rejection: retrying elsewhere cannot help
            return {"ok": False, "fatal": True,
                    "error": f"{type(e).__name__}: {e}"}

    def _leader_client_addr(self) -> str | None:
        """Map the raft leader's raft addr to its client addr: by
        convention peers dict values are raft addrs and the client addr
        is carried in the snapshot exchange; for simplicity the client
        retries its configured meta addr list on redirect."""
        return None

    def _on_snapshot(self, body):
        # fault injection: slow catalog pulls (stale-cache chaos window)
        failpoint.inject("meta.snapshot.delay")
        # read raft state BEFORE taking _data_lock: raft paths acquire
        # raft._lock → _data_lock (fsm hooks), so taking _data_lock first
        # and then touching raft would invert the order and deadlock
        is_leader = self.raft.is_leader
        with self._data_lock:
            return {"version": self.data.version,
                    "data": self.data.to_dict(),
                    "is_leader": is_leader}

    def start(self):
        self.raft.start()
        self.server.start()

    def stop(self):
        self.server.stop()
        self.raft.stop()


class MetaClient:
    """Client to the meta cluster with a cached catalog snapshot.

    Reference: lib/metaclient/meta_client.go:332 — all sql/store nodes
    hold one; reads hit the local cache, writes go to the raft leader
    (retrying across configured meta addresses)."""

    def __init__(self, meta_addrs: list[str], refresh_s: float = 1.0):
        self.addrs = list(meta_addrs)
        self.refresh_s = refresh_s
        self._clients = {a: RPCClient(a) for a in self.addrs}
        self.cache = MetaData()
        self._lock = threading.RLock()
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    # ------------------------------------------------------------ plumbing

    def apply(self, cmd: dict, timeout: float = 10.0,
              refresh: bool = True):
        """Run a catalog mutation through raft, trying each meta addr
        until the leader accepts. refresh=False skips the follow-up
        snapshot pull (fire-and-forget mutations like heartbeats)."""
        last_err: Exception | None = None
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            for addr in self.addrs:
                try:
                    resp = self._clients[addr].call(
                        "meta.apply", {"cmd": cmd},
                        timeout=_dl_clamp(5.0))
                except RPCError as e:
                    last_err = e
                    continue
                if resp.get("ok"):
                    if refresh:
                        self.refresh(min_version=resp.get("version", 0))
                    return resp.get("result")
                if resp.get("fatal"):
                    raise RPCError(resp.get("error", "rejected"))
                last_err = RPCError(resp.get("error", "not leader"))
            time.sleep(0.05)
        raise last_err or RPCError("meta apply failed")

    def refresh(self, min_version: int = 0,
                timeout: float = 5.0) -> None:
        """Pull a catalog snapshot at least min_version new, preferring
        the leader's copy (followers lag one heartbeat behind commit)."""
        deadline = time.monotonic() + timeout
        while True:
            best = None
            for addr in self.addrs:
                try:
                    resp = self._clients[addr].call(
                        "meta.snapshot", None,
                        timeout=_dl_clamp(5.0))
                except RPCError:
                    continue
                if best is None or resp["version"] > best["version"] \
                        or (resp.get("is_leader")
                            and resp["version"] >= best["version"]):
                    best = resp
                if resp.get("is_leader"):
                    break
            if best is not None and best["version"] >= min_version:
                with self._lock:
                    if best["version"] >= self.cache.version:
                        self.cache = MetaData.from_dict(best["data"])
                return
            if time.monotonic() >= deadline:
                return
            time.sleep(0.05)

    def start_watch(self) -> None:
        """Poll-refresh the cached snapshot (role of the reference's meta
        watch/callback channel)."""
        def loop():
            while not self._stop.is_set():
                try:
                    self.refresh()
                except Exception:
                    pass
                self._stop.wait(self.refresh_s)
        self._thread = threading.Thread(target=loop, daemon=True,
                                        name="metaclient-watch")
        self._thread.start()

    def close(self) -> None:
        self._stop.set()
        for c in self._clients.values():
            c.close()

    # ------------------------------------------------------- typed ops

    def create_node(self, addr: str, role: str = "both") -> int:
        return self.apply({"op": "create_node", "addr": addr,
                           "role": role, "now": time.time_ns()})

    def heartbeat(self, node_id: int) -> None:
        self.apply({"op": "heartbeat", "node_id": node_id,
                    "now": time.time_ns()}, refresh=False)

    def create_database(self, name: str, num_pts: int | None = None,
                        replica_n: int = 1,
                        shard_duration: int | None = None,
                        shard_key: list[str] | None = None) -> None:
        cmd = {"op": "create_database", "name": name,
               "replica_n": replica_n}
        if num_pts is not None:
            cmd["num_pts"] = num_pts
        if shard_duration is not None:
            cmd["shard_duration"] = shard_duration
        if shard_key:
            cmd["shard_key"] = list(shard_key)
        self.apply(cmd)

    def set_shard_ranges(self, db: str, bounds: list[str]) -> None:
        self.apply({"op": "set_shard_ranges", "db": db,
                    "bounds": list(bounds)})

    def drop_database(self, name: str) -> None:
        self.apply({"op": "drop_database", "name": name})

    def create_shard_group(self, db: str, t: int) -> dict:
        return self.apply({"op": "create_shard_group", "db": db, "t": t})

    def move_pt(self, db: str, pt_id: int, to_node: int) -> None:
        self.apply({"op": "move_pt", "db": db, "pt_id": pt_id,
                    "to_node": to_node})

    def set_node_status(self, node_id: int, status: str) -> None:
        self.apply({"op": "set_node_status", "node_id": node_id,
                    "status": status})

    # ------------------------------------------------------ cached reads

    def data(self) -> MetaData:
        with self._lock:
            return self.cache

    def database(self, name: str):
        return self.data().db(name)

    def shard_group_for_time(self, db: str, t: int):
        return self.data().shard_group_for_time(db, t)
