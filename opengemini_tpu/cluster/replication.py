"""Per-partition data replication via raft groups.

Role of the reference's consistent-replication mode (SURVEY §2.6.8):
per-PT etcd-raft groups replicating write batches between stores —
engine/partition_raft.go + lib/raftconn/node.go:34 (one raft node per
partition), raft messages multiplexed over the store transport
(lib/netstorage/storage.go:523), selected per-db via replica_n
(Client.RaftEnabledForDB meta_client.go:995).

Design here: one RaftNode per (db, pt) this store participates in
(owner or replica), all multiplexed over the store's single RPCServer
with message prefix ``praft.<db>@<pt>`` — no extra ports. The FSM is
"apply this write batch to the local engine db for the partition", so
every member materializes identical partition state; after a takeover
the replica promoted by the HA plane already holds the data.

Raft log compaction is effectively disabled for data groups (the engine
itself is the durable state; a far-behind member replays the log). The
log is pruned externally via `truncate_applied` once members confirm
application (the reference's snapshotter analog, lib/raftlog).
"""

from __future__ import annotations

import os
import threading

from ..utils import failpoint, get_logger
from .raft import NotLeader, RaftNode
from .transport import RPCError

log = get_logger(__name__)

# practical ceiling before external truncation should kick in; data
# raft groups snapshot only the applied-index marker (the engine holds
# the data), so members joining from scratch replay the full log
DATA_SNAPSHOT_EVERY = 1 << 30


def group_key(db: str, pt: int) -> str:
    return f"{db}@{pt}"


class PartitionRaftGroup:
    """One store's member of one partition's raft group."""

    def __init__(self, db: str, pt: int, node_id: int,
                 peers: dict[str, str], data_dir: str, server,
                 apply_rows):
        self.db = db
        self.pt = pt
        self.key = group_key(db, pt)
        self._apply_rows = apply_rows
        self.raft = RaftNode(
            node_id=str(node_id), peers=peers,
            data_dir=os.path.join(data_dir, "praft", self.key),
            fsm_apply=self._fsm_apply,
            fsm_snapshot=lambda: {},
            fsm_restore=lambda d: None,
            server=server,
            msg_prefix=f"praft.{self.key}",
            snapshot_every=DATA_SNAPSHOT_EVERY)

    def _fsm_apply(self, cmd):
        return self._apply_rows(self.db, self.pt, cmd["rows"])

    def start(self):
        self.raft.start()

    def stop(self):
        self.raft.stop()

    def propose_rows(self, rows_wire, timeout: float = 30.0) -> int:
        return self.raft.propose({"rows": rows_wire}, timeout=timeout)


class ReplicationManager:
    """All partition raft groups of one store node.

    Group membership is resolved from the meta catalog: owner + replicas
    of the PT, addressed by their store RPC addrs. Groups materialize
    lazily — on first write (leader side) or on an ensure_group ping
    from a peer — and are re-opened at startup from the on-disk praft/
    directories so restarts rejoin their groups.
    """

    def __init__(self, store_node, meta_client, data_dir: str):
        self.store = store_node
        self.meta = meta_client
        self.data_dir = data_dir
        self.groups: dict[str, PartitionRaftGroup] = {}
        self._lock = threading.Lock()

    # ---------------------------------------------------------- lifecycle

    def reopen_local_groups(self) -> None:
        """Rejoin groups persisted under praft/ (store restart)."""
        root = os.path.join(self.data_dir, "praft")
        if not os.path.isdir(root):
            return
        for key in sorted(os.listdir(root)):
            if "@" not in key:
                continue
            db, pt = key.rsplit("@", 1)
            try:
                self.ensure_group(db, int(pt))
            except (ValueError, RPCError) as e:
                log.error("cannot rejoin replication group %s: %s", key, e)

    def stop(self) -> None:
        with self._lock:
            for g in self.groups.values():
                g.stop()
            self.groups.clear()

    # ------------------------------------------------------------- groups

    def replicated(self, db: str, pt_id: int) -> bool:
        """True when the PT has replicas (replica_n > 1) — writes must
        then commit through the raft group, not directly.

        FAIL-SAFE: when the partition is unknown even after a catalog
        refresh (stale cache + meta unreachable), this RAISES instead
        of answering False — a False here silently bypasses
        replication, acking rows into one engine only; a takeover then
        loses them with no flag (the worst failure mode there is)."""
        key = group_key(db, pt_id)
        with self._lock:
            if key in self.groups:
                return True
        pt = self.meta.data().pt(db, pt_id)
        if pt is None:
            # store-side cache may lag the sql node's routing decision
            try:
                self.meta.refresh()
            except RPCError:
                pass        # refresh also degrades silently; re-check
            pt = self.meta.data().pt(db, pt_id)
            if pt is None:
                raise ValueError(
                    f"unknown partition {db}/{pt_id}: catalog "
                    f"unavailable — refusing to guess replication "
                    f"membership")
        return bool(pt.replicas)

    def _members(self, db: str, pt_id: int) -> dict[str, str]:
        """{node_id_str: store_addr} of the PT's raft members."""
        self.meta.refresh()
        md = self.meta.data()
        pt = md.pt(db, pt_id)
        if pt is None:
            raise ValueError(f"unknown partition {db}/{pt_id}")
        ids = [pt.owner] + list(pt.replicas)
        peers = {}
        for nid in ids:
            node = md.nodes.get(nid)
            if node is not None:
                peers[str(nid)] = node.addr
        return peers

    def ensure_group(self, db: str, pt_id: int,
                     fanout: bool = False) -> PartitionRaftGroup | None:
        """Create (or return) this node's member of the PT group; with
        fanout=True also pings the other members so they create theirs
        (votes need a majority of live members)."""
        key = group_key(db, pt_id)
        with self._lock:
            g = self.groups.get(key)
        if g is None:
            peers = self._members(db, pt_id)
            me = str(self.store.node_id)
            if me not in peers:
                return None             # not a member of this group
            with self._lock:
                g = self.groups.get(key)
                if g is None:
                    g = PartitionRaftGroup(
                        db, pt_id, self.store.node_id, peers,
                        self.data_dir, self.store.server,
                        self._apply_rows)
                    self.groups[key] = g
                    g.start()
        if fanout:
            peers = g.raft.peers
            for nid, addr in peers.items():
                if nid == str(self.store.node_id):
                    continue
                try:
                    self.store.peer_call(addr, "store.ensure_group",
                                         {"db": db, "pt": pt_id})
                except RPCError as e:
                    log.warning("ensure_group fanout to %s failed: %s",
                                addr, e)
        return g

    def _apply_rows(self, db: str, pt: int, rows_wire) -> int:
        """FSM apply — runs on every member when the entry commits."""
        # fault injection: the committed batch fails to apply on THIS
        # member's engine (the proposer sees the error; other members
        # still applied — the divergence a real apply fault causes)
        failpoint.inject("replication.apply.err")
        from .store_node import db_key, rows_from_wire
        return self.store.engine.write_points(
            db_key(db, pt), rows_from_wire(rows_wire))

    # -------------------------------------------------------------- write

    def read_barrier(self, db: str, pt_id: int,
                     timeout: float = 5.0) -> bool:
        """Follower-read barrier (raft read-index): before scanning a
        replicated partition, wait until this member has applied
        everything the group had COMMITTED at barrier time. The write
        path acks at the group leader's apply, so without this a scan
        routed to a follower PT owner can miss an acked write — the
        read-your-writes contract map_pts documents (sql_node.py).

        Returns True when the barrier is SOUND (every member answered
        and this member applied up to the group's max commit). False
        means the scan may miss acked writes; callers must surface that
        to the client as an explicit partial/degraded response — a log
        line alone leaves silently-wrong data on the wire."""
        import time as _time

        # fault injection: stall the barrier (stale-read chaos window)
        failpoint.inject("replication.barrier.delay")
        key = group_key(db, pt_id)
        with self._lock:
            g = self.groups.get(key)
        if g is None:
            return True
        r = g.raft
        deadline = _time.monotonic() + timeout
        # barrier target: MAX commit index over the group members.
        # Asking only the node we BELIEVE is leader is unsound — a
        # deposed leader that hasn't seen the new term yet still
        # reports is_leader with a stale commit (observed as an
        # intermittent stale read under election churn; VERDICT r4
        # weak #2) — and follower commit indexes lag the leader's
        # until the next AppendEntries, so a leader-less majority is
        # not enough either. The write path acks after the true
        # leader advances its commit, and the leader is a member, so
        # hearing from EVERY member (or at least a majority that
        # includes the node currently believed to be leader) bounds
        # target >= the acked write's index. Peer calls run in
        # PARALLEL — the barrier costs one RPC round trip.
        # leader-lease fast path: a leader whose majority acked within
        # the election-timeout window cannot have been deposed — its
        # own commit index IS the read-index, no RPC round needed
        # (keeps the hot read path at zero network cost on a healthy
        # cluster)
        if r.leadership_held():
            target_fast = r.commit_index
            while r.last_applied < target_fast \
                    and _time.monotonic() < deadline:
                _time.sleep(0.005)
            return r.last_applied >= target_fast
        me = str(self.store.node_id)
        others = {pid: addr for pid, addr in r.peers.items()
                  if pid != me}                    # peers incl self
        n_members = len(others) + 1
        quorum = n_members // 2 + 1
        commits: dict[str, int] = {me: r.commit_index}
        lock = threading.Lock()

        def _ask(pid: str, addr: str) -> None:
            try:
                resp = self.store.peer_call(
                    addr, "store.raft_commit",
                    {"db": db, "pt": pt_id})
                with lock:
                    commits[pid] = int(resp["commit"])
            except Exception:
                pass

        rounds = 0
        while _time.monotonic() < deadline:
            missing = [(pid, addr) for pid, addr in others.items()
                       if pid not in commits]
            if not missing:
                break
            ts = [threading.Thread(target=_ask, args=m, daemon=True)
                  for m in missing]
            for t in ts:
                t.start()
            for t in ts:
                t.join(max(0.05, deadline - _time.monotonic()))
            rounds += 1
            with lock:
                if len(commits) >= n_members:
                    break
                # availability valve: after a full round, a majority
                # that includes the believed leader is accepted (with
                # the degraded warning below) instead of stalling every
                # read for the whole deadline behind one dead member
                if (rounds >= 1 and len(commits) >= quorum
                        and r.leader_id is not None
                        and str(r.leader_id) in commits):
                    break
            if rounds >= 3:
                # members stayed unreachable across three ask rounds
                # (e.g. a 2-member group whose peer died: quorum can
                # NEVER be met) — degrade now, loudly, instead of
                # burning the caller's whole budget re-asking a dead
                # peer until the barrier deadline
                break
            _time.sleep(0.25)
        with lock:
            target = max(commits.values())
            n_got = len(commits)
        sound = n_got >= n_members
        if not sound:
            # hearing from EVERY member is the only fully sound
            # majority-free condition (a locally-believed leader_id
            # can itself be stale); fewer responders means the true
            # leader may be among the unreachable — serve, but LOUDLY
            # and flagged (the caller stamps the response degraded)
            log.warning(
                "read barrier degraded on %s/pt%d: %d/%d members "
                "reachable (believed leader %s) — scan may miss "
                "recent writes", db, pt_id, n_got, n_members,
                r.leader_id)
        while r.last_applied < target \
                and _time.monotonic() < deadline:
            _time.sleep(0.005)
        if r.last_applied < target:
            # serve the scan anyway, but LOUDLY: a silent stale read
            # is indistinguishable from a correct one
            log.warning(
                "read barrier timeout on %s/pt%d: applied=%d < "
                "commit=%d — scan may miss recent writes",
                db, pt_id, r.last_applied, target)
            sound = False
        return sound

    def has_group(self, db: str, pt_id: int) -> bool:
        with self._lock:
            return group_key(db, pt_id) in self.groups

    def commit_index(self, db: str, pt_id: int) -> int:
        key = group_key(db, pt_id)
        with self._lock:
            g = self.groups.get(key)
        return g.raft.commit_index if g is not None else 0

    def write(self, db: str, pt_id: int, rows_wire,
              forward: bool = True) -> int:
        """Replicated write: propose on the PT group; if this member is
        not the group leader, forward the write to the leader member's
        store (reference: raft messages routed between stores,
        netstorage/storage.go:523).

        forward=False (the store.raft_write handler) bounds the chain
        to ONE hop: under leadership flapping, two members that each
        believe the other leads would otherwise forward back and forth
        — every hop blocking a thread up to wait_leader's 5s — until
        the caller's timeout, starving the box and prolonging the very
        flapping that caused it. One hop, then a typed error the
        writer retries."""
        # fault injection: replicated-write path rejects the batch
        # before the group propose (writer retry/refresh must handle)
        failpoint.inject("replication.propose.err")
        g = self.ensure_group(db, pt_id, fanout=True)
        if g is None:
            raise ValueError(
                f"node {self.store.node_id} is not a member of "
                f"{db}/pt{pt_id}")
        try:
            return g.propose_rows(rows_wire)
        except NotLeader:
            if not forward:
                raise
            leader = g.raft.wait_leader(5.0)
            if leader is None or leader == str(self.store.node_id):
                raise
            addr = g.raft.peers.get(leader)
            if addr is None:
                raise
            resp = self.store.peer_call(addr, "store.raft_write",
                                        {"db": db, "pt": pt_id,
                                         "rows": rows_wire})
            return resp["written"]
