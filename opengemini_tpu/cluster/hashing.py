"""Stable series-key hashing for shard routing.

Role of the reference's shard-key hash used by ShardGroupInfo.ShardFor
(lib/util/lifted/influx/meta/shardinfo.go:369-375). FNV-1a 64 is stable
across processes and platforms (Python's hash() is salted, so it cannot
route consistently between nodes).
"""

from __future__ import annotations


def shard_key_of(tags: dict, shard_key: list[str]) -> str:
    """Row's shard-key string: joined values of the key tags — the ONE
    encoding shared by range routing (points_writer) and split-point
    sampling (store_node); they must stay byte-identical."""
    return "\x00".join(tags.get(k, "") for k in shard_key)

_FNV_OFFSET = 0xCBF29CE484222325
_FNV_PRIME = 0x100000001B3
_MASK = 0xFFFFFFFFFFFFFFFF


def fnv1a64(data: bytes) -> int:
    h = _FNV_OFFSET
    for b in data:
        h ^= b
        h = (h * _FNV_PRIME) & _MASK
    return h


def _mix(h: int) -> int:
    """splitmix64 finalizer. Raw FNV-1a's low bit is the XOR of all byte
    low bits — keys differing in paired digits (host=h0,dc=dc0 vs
    host=h1,dc=dc1) collide mod 2^k, which is exactly how shard routing
    folds the hash. The avalanche makes every output bit depend on every
    input bit."""
    h ^= h >> 30
    h = (h * 0xBF58476D1CE4E5B9) & _MASK
    h ^= h >> 27
    h = (h * 0x94D049BB133111EB) & _MASK
    h ^= h >> 31
    return h


def series_hash(measurement: str, tags: dict[str, str]) -> int:
    """Routing hash of the canonical series key (measurement + sorted
    tags): FNV-1a with an avalanche finalizer."""
    parts = [measurement]
    for k in sorted(tags):
        parts.append(f"{k}={tags[k]}")
    return _mix(fnv1a64(",".join(parts).encode()))
