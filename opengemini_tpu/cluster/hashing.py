"""Stable series-key hashing for shard routing.

Role of the reference's shard-key hash used by ShardGroupInfo.ShardFor
(lib/util/lifted/influx/meta/shardinfo.go:369-375). FNV-1a 64 is stable
across processes and platforms (Python's hash() is salted, so it cannot
route consistently between nodes).
"""

from __future__ import annotations

_FNV_OFFSET = 0xCBF29CE484222325
_FNV_PRIME = 0x100000001B3
_MASK = 0xFFFFFFFFFFFFFFFF


def fnv1a64(data: bytes) -> int:
    h = _FNV_OFFSET
    for b in data:
        h ^= b
        h = (h * _FNV_PRIME) & _MASK
    return h


def series_hash(measurement: str, tags: dict[str, str]) -> int:
    """Hash of the canonical series key (measurement + sorted tags)."""
    parts = [measurement]
    for k in sorted(tags):
        parts.append(f"{k}={tags[k]}")
    return fnv1a64(",".join(parts).encode())
