"""Native (C++) components behind ctypes, with pure-Python fallbacks.

Role of the reference's cgo-gated native code (SURVEY §2.7 native checklist):
LZ4 block codec (lib/util/lifted/encoding/lz4/lz4.c behind
lz4_linux_amd64.go:19) and the C++ full-text index (engine/index/textindex/
FullTextIndex.cpp behind textbuilder_linux_amd64.go:17-20). Like the
reference — which stubs both off linux/amd64 — every native entry point here
has a pure-Python fallback producing byte-identical output, so the framework
runs anywhere and the native path is a transparent accelerator.

The shared library builds lazily on first import (g++ is in the image); a
build failure downgrades to the fallbacks with a one-line warning.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading

import numpy as np

_NATIVE_DIR = os.path.join(os.path.dirname(__file__), "..", "..", "native")


def _knobs_get(name: str):
    from ..utils import knobs
    return knobs.get(name)


def _lib_path() -> str:
    """Path of the shared library: OG_NATIVE_LIB overrides (the
    sanitizer runner points this at the ASan/UBSan build so the
    regular parity suites replay against instrumented codecs).
    Resolved at LOAD time, not import time."""
    override = _knobs_get("OG_NATIVE_LIB")
    if override:
        return os.path.abspath(override)
    return os.path.abspath(os.path.join(_NATIVE_DIR, "libogn.so"))


_lib = None
_lib_lock = threading.Lock()
_build_attempted = False


def _load():
    """Load (building if needed) the native library; None on failure."""
    global _lib, _build_attempted
    if _lib is not None:
        return _lib
    with _lib_lock:
        if _lib is not None:
            return _lib
        # resolve AT LOAD TIME so an OG_NATIVE_LIB set after import
        # (test/harness ordering) still selects the override — the
        # rebuild-skip and the CDLL must agree on one path
        lib_path = _lib_path()
        overridden = bool(_knobs_get("OG_NATIVE_LIB"))

        # (re)build when missing OR stale vs any source (a new source
        # file must trigger a rebuild of the existing .so)
        def _stale() -> bool:
            if not os.path.exists(lib_path):
                return True
            so_m = os.path.getmtime(lib_path)
            nd = os.path.abspath(_NATIVE_DIR)
            return any(
                os.path.getmtime(os.path.join(nd, f)) > so_m
                for f in os.listdir(nd)
                if f.endswith((".cpp", ".h")) or f == "Makefile")
        if overridden:
            # explicit library override (sanitizer runs): load it
            # as-is, never rebuild over it
            pass
        elif _stale() and not _build_attempted:
            _build_attempted = True
            try:
                subprocess.run(
                    ["make", "-C", os.path.abspath(_NATIVE_DIR), "-B"],
                    capture_output=True, timeout=120, check=True)
            except Exception:
                return None
        if not os.path.exists(lib_path):
            return None
        try:
            lib = ctypes.CDLL(lib_path)
        except OSError:
            return None
        try:
            _bind(lib)
        except AttributeError:
            # stale .so missing newer symbols and rebuild unavailable:
            # honor the documented downgrade-to-fallbacks contract
            return None
        _lib = lib
        return _lib


def _bind(lib) -> None:
        lib.og_lz4_max_compressed.restype = ctypes.c_int64
        lib.og_lz4_max_compressed.argtypes = [ctypes.c_int64]
        for fn in (lib.og_lz4_compress, lib.og_lz4_decompress):
            fn.restype = ctypes.c_int64
            fn.argtypes = [ctypes.c_char_p, ctypes.c_int64,
                           ctypes.POINTER(ctypes.c_uint8), ctypes.c_int64]
        lib.og_ti_builder_new.restype = ctypes.c_void_p
        lib.og_ti_builder_add.argtypes = [
            ctypes.c_void_p, ctypes.c_uint32, ctypes.c_char_p,
            ctypes.c_int64]
        lib.og_ti_builder_finish.restype = ctypes.c_int64
        lib.og_ti_builder_finish.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.POINTER(ctypes.c_uint8))]
        lib.og_ti_builder_free.argtypes = [ctypes.c_void_p]
        lib.og_ti_blob_free.argtypes = [ctypes.POINTER(ctypes.c_uint8)]
        lib.og_ti_open.restype = ctypes.c_void_p
        lib.og_ti_open.argtypes = [ctypes.c_char_p, ctypes.c_int64]
        lib.og_ti_close.argtypes = [ctypes.c_void_p]
        lib.og_ti_search.restype = ctypes.c_int64
        lib.og_ti_search.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int64,
            ctypes.POINTER(ctypes.c_uint32), ctypes.c_int64]
        lib.og_ti_search_prefix.restype = ctypes.c_int64
        lib.og_ti_search_prefix.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int64,
            ctypes.POINTER(ctypes.c_uint32), ctypes.c_int64]
        lib.og_ti_search_all.restype = ctypes.c_int64
        lib.og_ti_search_all.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int64,
            ctypes.c_char_p, ctypes.c_int64,
            ctypes.POINTER(ctypes.c_uint32), ctypes.c_int64]
        lib.og_ti_builder_add2.restype = None
        lib.og_ti_builder_add2.argtypes = [
            ctypes.c_void_p, ctypes.c_uint32, ctypes.c_char_p,
            ctypes.c_int64, ctypes.c_char_p, ctypes.c_int64]
        lib.og_gorilla_encode.restype = ctypes.c_int64
        lib.og_gorilla_encode.argtypes = [
            ctypes.POINTER(ctypes.c_double), ctypes.c_int64,
            ctypes.POINTER(ctypes.c_uint8), ctypes.c_int64]
        lib.og_gorilla_decode.restype = ctypes.c_int64
        lib.og_gorilla_decode.argtypes = [
            ctypes.c_char_p, ctypes.c_int64,
            ctypes.POINTER(ctypes.c_double), ctypes.c_int64]
        _i64p = ctypes.POINTER(ctypes.c_int64)
        _i32p = ctypes.POINTER(ctypes.c_int32)
        _u8p = ctypes.POINTER(ctypes.c_uint8)
        _f64p = ctypes.POINTER(ctypes.c_double)
        lib.og_lp_lex.restype = ctypes.c_int64
        lib.og_lp_lex.argtypes = [
            ctypes.c_char_p, ctypes.c_int64,
            _i64p, _i32p, _i64p, _u8p, _i64p, _i64p, _i32p,
            ctypes.c_int64,
            _i32p, _u8p, _f64p, _i64p, _i64p, _i32p, ctypes.c_int64,
            _i64p, _i32p, _i64p, _i64p]
        _u64p = ctypes.POINTER(ctypes.c_uint64)
        lib.og_blake2b8_batch.restype = None
        lib.og_blake2b8_batch.argtypes = [_u8p, _i64p, ctypes.c_int64,
                                          _u64p]
        lib.og_limb_sums.restype = None
        lib.og_limb_sums.argtypes = [
            _f64p, _i64p, _i64p, _i64p, ctypes.c_int64,
            ctypes.c_int64, ctypes.c_int64, _f64p, _u8p]
        lib.og_finalize_exact.restype = None
        lib.og_finalize_exact.argtypes = [
            _f64p, ctypes.c_int64, ctypes.c_int64, ctypes.c_int64,
            _f64p, _i64p, _i64p]
        _u32p = ctypes.POINTER(ctypes.c_uint32)
        lib.og_unpack_limbs.restype = None
        lib.og_unpack_limbs.argtypes = [
            _u32p, ctypes.c_int64, ctypes.c_int64, ctypes.c_int64,
            ctypes.c_int64, ctypes.c_int64, ctypes.c_int64, _f64p]
        _i8p = ctypes.POINTER(ctypes.c_int8)
        lib.og_fold_lattice.restype = None
        lib.og_fold_lattice.argtypes = [
            _i8p, _i32p, _u8p, ctypes.c_int64, ctypes.c_int64,
            _i64p, _i64p, ctypes.c_int64, ctypes.c_int64,
            ctypes.c_int64, ctypes.c_int64, ctypes.c_int64,
            _f64p, _f64p, _u8p]


def native_available() -> bool:
    return _load() is not None


# ------------------------------------------------------------------- LZ4

def lz4_compress(data: bytes) -> bytes:
    lib = _load()
    if lib is None:
        return _py_lz4_compress(data)
    cap = lib.og_lz4_max_compressed(len(data))
    # numpy buffer, not a ctypes array: slicing a ctypes array to bytes
    # goes through a Python list (measured 4MB/s vs 400MB/s)
    dst = np.empty(cap, dtype=np.uint8)
    n = lib.og_lz4_compress(
        data, len(data),
        dst.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)), cap)
    if n < 0:
        raise ValueError("lz4 compress failed")
    return dst[:n].tobytes()


def lz4_decompress(data: bytes, decompressed_size: int) -> bytes:
    lib = _load()
    if lib is None:
        return _py_lz4_decompress(data, decompressed_size)
    dst = np.empty(max(decompressed_size, 1), dtype=np.uint8)
    n = lib.og_lz4_decompress(
        data, len(data),
        dst.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        decompressed_size)
    if n != decompressed_size:
        raise ValueError(
            f"lz4 decompress: got {n}, want {decompressed_size}")
    return dst[:decompressed_size].tobytes()


# Pure-Python LZ4 block format (same format as native — interoperable).

def _py_lz4_compress(data: bytes) -> bytes:
    # literal-only stream: valid LZ4 blocks, no matching (fallback is about
    # correctness + interop, not ratio)
    out = bytearray()
    n = len(data)
    litlen = n
    if litlen >= 15:
        out.append(15 << 4)
        rem = litlen - 15
        while rem >= 255:
            out.append(255)
            rem -= 255
        out.append(rem)
    else:
        out.append(litlen << 4)
    out += data
    return bytes(out)


def _py_lz4_decompress(data: bytes, size: int) -> bytes:
    out = bytearray()
    i, n = 0, len(data)
    while i < n:
        token = data[i]
        i += 1
        litlen = token >> 4
        if litlen == 15:
            while True:
                b = data[i]
                i += 1
                litlen += b
                if b != 255:
                    break
        out += data[i:i + litlen]
        i += litlen
        if i >= n:
            break
        off = data[i] | (data[i + 1] << 8)
        i += 2
        mlen = token & 15
        if mlen == 15:
            while True:
                b = data[i]
                i += 1
                mlen += b
                if b != 255:
                    break
        mlen += 4
        if off == 0 or off > len(out):
            raise ValueError("corrupt lz4 block")
        start = len(out) - off
        for k in range(mlen):  # overlap-safe forward copy
            out.append(out[start + k])
    if len(out) != size:
        raise ValueError(f"lz4: got {len(out)} bytes, want {size}")
    return bytes(out)


# ------------------------------------------------------------ text index

_MAX_TOKEN = 64


def tokenize(text: bytes) -> list[bytes]:
    """Lowercased alnum/underscore/UTF-8 tokens, truncated to 64 bytes —
    byte-identical with the native tokenizer (og_tokenize + low())."""
    toks = []
    i, n = 0, len(text)
    while i < n:
        while i < n and not _is_tok(text[i]):
            i += 1
        start = i
        while i < n and _is_tok(text[i]):
            i += 1
        if i > start:
            toks.append(text[start:i].lower()[:_MAX_TOKEN])
    return toks


def _is_tok(c: int) -> bool:
    return (97 <= c <= 122 or 48 <= c <= 57 or 65 <= c <= 90
            or c == 95 or c >= 0x80)


class TextIndexBuilder:
    """Builds the inverted-index blob; native-backed when available."""

    def __init__(self):
        self._lib = _load()
        if self._lib is not None:
            self._h = self._lib.og_ti_builder_new()
        else:
            self._postings: dict[bytes, list[int]] = {}

    def add(self, doc_id: int, text: bytes | str,
            delims: bytes | None = None) -> None:
        """`delims` configures the tokenizer for this document (tokens
        = runs NOT containing any delim byte); queries must pass the
        same set to search_all. Default: alnum/underscore/UTF-8."""
        if isinstance(text, str):
            text = text.encode()
        if self._lib is not None:
            if delims is None:
                self._lib.og_ti_builder_add(self._h, doc_id, text,
                                            len(text))
            else:
                self._lib.og_ti_builder_add2(self._h, doc_id, text,
                                             len(text), delims,
                                             len(delims))
            return
        toks = (tokenize(text) if delims is None
                else tokenize_delims(text, delims))
        for tok in toks:
            lst = self._postings.setdefault(tok, [])
            if not lst or lst[-1] != doc_id:
                lst.append(doc_id)

    def finish(self) -> bytes:
        if self._lib is not None:
            if self._h is None:
                raise ValueError("finish() already called")
            out = ctypes.POINTER(ctypes.c_uint8)()
            n = self._lib.og_ti_builder_finish(self._h, ctypes.byref(out))
            try:
                if n < 0:
                    raise MemoryError("text index build failed")
                blob = ctypes.string_at(out, n)
                self._lib.og_ti_blob_free(out)
            finally:
                self._lib.og_ti_builder_free(self._h)
                self._h = None
            return blob
        return _py_ti_finish(self._postings)


def _py_varint(out: bytearray, v: int) -> None:
    while v >= 0x80:
        out.append((v & 0x7F) | 0x80)
        v >>= 7
    out.append(v)


def _py_ti_finish(postings: dict[bytes, list[int]]) -> bytes:
    import struct
    toks = sorted(postings)
    tokbytes = bytearray()
    posts = bytearray()
    tab = bytearray()
    for t in toks:
        toff, poff = len(tokbytes), len(posts)
        tokbytes += t
        prev = 0
        for d in postings[t]:
            _py_varint(posts, d - prev)
            prev = d
        tab += struct.pack("<IHII", toff, len(t), len(postings[t]), poff)
    return (struct.pack("<IIII", 0x0671D301, len(toks), len(tokbytes),
                        len(posts)) + bytes(tab) + bytes(tokbytes)
            + bytes(posts))


class TextIndexReader:
    """Searches a finished blob: token -> sorted doc-id array."""

    def __init__(self, blob: bytes):
        self._blob = blob
        self._lib = _load()
        if self._lib is not None:
            self._h = self._lib.og_ti_open(blob, len(blob))
            if not self._h:
                raise ValueError("corrupt text index blob")
        else:
            self._open_py(blob)

    def _open_py(self, blob: bytes) -> None:
        import struct
        magic, ntok, tb, pb = struct.unpack_from("<IIII", blob, 0)
        if magic != 0x0671D301:
            raise ValueError("corrupt text index blob")
        self._entries = []
        pos = 16
        for _ in range(ntok):
            self._entries.append(struct.unpack_from("<IHII", blob, pos))
            pos += 14
        self._tokbytes = blob[pos:pos + tb]
        self._posts = blob[pos + tb:pos + tb + pb]

    def search(self, token: bytes | str) -> np.ndarray:
        """Doc ids containing the token (empty array if absent)."""
        if isinstance(token, str):
            token = token.encode()
        token = token.lower()[:_MAX_TOKEN]
        if self._lib is not None:
            cap = 1024
            while True:
                out = np.empty(cap, dtype=np.uint32)
                n = self._lib.og_ti_search(
                    self._h, token, len(token),
                    out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint32)),
                    cap)
                if n == -2:
                    cap *= 8
                    continue
                if n < 0:
                    return np.empty(0, dtype=np.uint32)
                return out[:n]
        return self._search_py(token)

    def _search_py(self, token: bytes) -> np.ndarray:
        if not hasattr(self, "_entries"):
            self._open_py(self._blob)
        lo, hi = 0, len(self._entries) - 1
        while lo <= hi:
            mid = (lo + hi) // 2
            toff, tlen, cnt, poff = self._entries[mid]
            t = self._tokbytes[toff:toff + tlen]
            if t == token:
                return self._decode_at(mid)
            if t < token:
                lo = mid + 1
            else:
                hi = mid - 1
        return np.empty(0, dtype=np.uint32)

    def _decode_at(self, mid: int) -> np.ndarray:
        toff, tlen, cnt, poff = self._entries[mid]
        out = np.empty(cnt, dtype=np.uint32)
        doc = 0
        p = poff
        for i in range(cnt):
            d, shift = 0, 0
            while True:
                b = self._posts[p]
                p += 1
                d |= (b & 0x7F) << shift
                if not b & 0x80:
                    break
                shift += 7
            doc += d
            out[i] = doc
        return out

    def search_prefix(self, prefix: bytes | str) -> np.ndarray:
        """Doc ids whose tokens START WITH `prefix` (sorted, deduped) —
        the reference text index's prefix-query surface."""
        if isinstance(prefix, str):
            prefix = prefix.encode()
        prefix = prefix.lower()[:_MAX_TOKEN]
        if self._lib is not None:
            cap = 4096
            while True:
                out = np.empty(cap, dtype=np.uint32)
                n = self._lib.og_ti_search_prefix(
                    self._h, prefix, len(prefix),
                    out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint32)),
                    cap)
                if n == -2:
                    cap *= 8
                    continue
                return out[:max(n, 0)]
        if not hasattr(self, "_entries"):
            self._open_py(self._blob)
        # binary lower bound, then the matching CONTIGUOUS range
        # (tokens are sorted — mirrors the native lower_bound_tok)
        lo, hi = 0, len(self._entries)
        while lo < hi:
            mid = (lo + hi) // 2
            toff, tlen, _c, _p = self._entries[mid]
            if self._tokbytes[toff:toff + tlen] < prefix:
                lo = mid + 1
            else:
                hi = mid
        docs: list = []
        for mid in range(lo, len(self._entries)):
            toff, tlen, _c, _p = self._entries[mid]
            if not self._tokbytes[toff:toff + tlen].startswith(prefix):
                break
            docs.append(self._decode_at(mid))
        if not docs:
            return np.empty(0, dtype=np.uint32)
        return np.unique(np.concatenate(docs))

    def search_all(self, text: bytes | str,
                   delims: bytes | None = None) -> np.ndarray:
        """Doc ids containing EVERY token of `text` (conjunctive
        search — the phrase-candidate set; CLV carries positions for
        exact phrase verification). `delims` must match the builder's
        tokenizer configuration."""
        if isinstance(text, str):
            text = text.encode()
        if self._lib is not None:
            cap = 4096
            while True:
                out = np.empty(cap, dtype=np.uint32)
                n = self._lib.og_ti_search_all(
                    self._h, text, len(text),
                    delims, len(delims) if delims else 0,
                    out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint32)),
                    cap)
                if n == -2:
                    cap *= 8
                    continue
                return out[:max(n, 0)]
        toks = (tokenize(text) if delims is None
                else tokenize_delims(text, delims))
        acc = None
        for t in toks:
            docs = self.search(t)
            if len(docs) == 0:
                return np.empty(0, dtype=np.uint32)
            acc = docs if acc is None else \
                np.intersect1d(acc, docs, assume_unique=True)
        return acc if acc is not None else np.empty(0, dtype=np.uint32)

    def close(self) -> None:
        if self._lib is not None and self._h:
            self._lib.og_ti_close(self._h)
            self._h = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


def tokenize_delims(text: bytes, delims: bytes) -> list[bytes]:
    """Delimiter-set tokenizer (per-field tokenizer config, reference
    textindex option): tokens are maximal runs of bytes NOT in
    `delims`, lowercased, truncated — byte-identical with the native
    for_tokens(delims)."""
    dset = set(delims)
    toks = []
    i, n = 0, len(text)
    while i < n:
        while i < n and text[i] in dset:
            i += 1
        start = i
        while i < n and text[i] not in dset:
            i += 1
        if i > start:
            toks.append(text[start:i].lower()[:_MAX_TOKEN])
    return toks


# --------------------------------------------------------------- gorilla

def gorilla_encode(values: np.ndarray):
    """Native gorilla XOR encode; returns None when the native library is
    unavailable (caller falls back to the Python codec — byte-identical
    output either way)."""
    lib = _load()
    if lib is None:
        return None
    v = np.ascontiguousarray(values, dtype=np.float64)
    if len(v) == 0:
        return b""
    cap = 16 + 10 * len(v)
    dst = np.empty(cap, dtype=np.uint8)
    n = lib.og_gorilla_encode(
        v.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
        len(v), dst.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        cap)
    if n < 0:
        return None
    return dst[:n].tobytes()


def gorilla_decode(buf, n: int):
    """Native gorilla decode; None when unavailable. Raises ValueError on
    truncated input (same contract as the Python reader running dry)."""
    lib = _load()
    if lib is None:
        return None
    out = np.empty(n, dtype=np.float64)
    if n == 0:
        return out
    raw = buf if isinstance(buf, bytes) else bytes(buf)
    rc = lib.og_gorilla_decode(
        raw, len(raw),
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_double)), n)
    if rc != 0:
        raise ValueError("gorilla decode failed (truncated or corrupt "
                         "input)")
    return out


# --------------------------------------------------------- line protocol

class LpLex:
    """Flat columnar lex of a line-protocol buffer (see
    native/lineprotocol.cpp). All arrays are trimmed views."""

    __slots__ = ("n_lines", "series_off", "series_len", "ts", "has_ts",
                 "line_end", "field_lo", "field_n", "fname_id", "ftype",
                 "fval", "ival", "sval_off", "sval_len", "names")

    def __init__(self, **kw):
        for k, v in kw.items():
            setattr(self, k, v)


class LpParseError(ValueError):
    def __init__(self, pos: int):
        super().__init__(f"line protocol parse error at byte {pos}")
        self.pos = pos


def lp_lex(data: bytes):
    """Lex a line-protocol payload natively. Returns LpLex, raises
    LpParseError on malformed input (caller falls back to the Python
    parser for its richer error messages), or returns None when the
    native library is unavailable."""
    lib = _load()
    if lib is None:
        return None
    n = len(data)
    cap_lines = max(64, n // 16)
    cap_fields = max(64, n // 8)
    while True:
        so = np.empty(cap_lines, dtype=np.int64)
        sl = np.empty(cap_lines, dtype=np.int32)
        ts = np.empty(cap_lines, dtype=np.int64)
        ht = np.empty(cap_lines, dtype=np.uint8)
        lend = np.empty(cap_lines, dtype=np.int64)
        flo = np.empty(cap_lines, dtype=np.int64)
        fn = np.empty(cap_lines, dtype=np.int32)
        fid = np.empty(cap_fields, dtype=np.int32)
        fty = np.empty(cap_fields, dtype=np.uint8)
        fv = np.empty(cap_fields, dtype=np.float64)
        iv = np.empty(cap_fields, dtype=np.int64)
        svo = np.empty(cap_fields, dtype=np.int64)
        svl = np.empty(cap_fields, dtype=np.int32)
        no = np.empty(256, dtype=np.int64)
        nl_ = np.empty(256, dtype=np.int32)
        nn = ctypes.c_int64(0)
        err = ctypes.c_int64(0)

        def p(a, t):
            return a.ctypes.data_as(ctypes.POINTER(t))

        rc = lib.og_lp_lex(
            data, n,
            p(so, ctypes.c_int64), p(sl, ctypes.c_int32),
            p(ts, ctypes.c_int64), p(ht, ctypes.c_uint8),
            p(lend, ctypes.c_int64),
            p(flo, ctypes.c_int64), p(fn, ctypes.c_int32), cap_lines,
            p(fid, ctypes.c_int32), p(fty, ctypes.c_uint8),
            p(fv, ctypes.c_double), p(iv, ctypes.c_int64),
            p(svo, ctypes.c_int64), p(svl, ctypes.c_int32), cap_fields,
            p(no, ctypes.c_int64), p(nl_, ctypes.c_int32),
            ctypes.byref(nn), ctypes.byref(err))
        if rc == -1:
            cap_lines *= 2
            continue
        if rc == -2:
            cap_fields *= 2
            continue
        if rc == -3:
            raise LpParseError(int(err.value))
        if rc == -4:
            return None          # >256 distinct names: python path
        nlines = int(rc)
        nfields = int(flo[nlines - 1] + fn[nlines - 1]) if nlines else 0
        names = [data[int(o):int(o) + int(ln)]
                 for o, ln in zip(no[:nn.value], nl_[:nn.value])]
        return LpLex(
            n_lines=nlines, series_off=so[:nlines],
            series_len=sl[:nlines], ts=ts[:nlines], has_ts=ht[:nlines],
            line_end=lend[:nlines],
            field_lo=flo[:nlines], field_n=fn[:nlines],
            fname_id=fid[:nfields], ftype=fty[:nfields],
            fval=fv[:nfields], ival=iv[:nfields],
            sval_off=svo[:nfields], sval_len=svl[:nfields],
            names=names)


# ------------------------------------------------------- batch blake2b-8

def blake2b8_batch(buf, offsets: np.ndarray):
    """Hash n packed rows (row i = buf[offsets[i]:offsets[i+1]]) with
    BLAKE2b digest_size=8, returning (n,) uint64 little-endian digests
    — the series-index key hash (tsi._key_hash) in one native pass.
    Falls back to hashlib per row."""
    offsets = np.ascontiguousarray(offsets, dtype=np.int64)
    n = len(offsets) - 1
    out = np.empty(n, dtype=np.uint64)
    lib = _load()
    if lib is not None:
        b = np.frombuffer(buf, dtype=np.uint8) \
            if not isinstance(buf, np.ndarray) else buf
        b = np.ascontiguousarray(b, dtype=np.uint8)
        lib.og_blake2b8_batch(
            b.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
            offsets.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            n, out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)))
        return out
    import hashlib
    mv = memoryview(buf)
    for i in range(n):
        out[i] = int.from_bytes(
            hashlib.blake2b(mv[offsets[i]:offsets[i + 1]],
                            digest_size=8).digest(), "little")
    return out


# ------------------------------------------------- fused limb span sums

def limb_sums(values: np.ndarray, starts: np.ndarray, ends: np.ndarray,
              E: np.ndarray, k_limbs: int, limb_bits: int):
    """Per-series exact-sum limb accumulation: decompose each value of
    span [starts[i], ends[i]) at scale E[i] and sum the limbs —
    ops/exactsum.decompose + np.add.reduceat fused into one pass.
    Returns (limbs (S, K) f64, exact (S,) bool), or None when the
    native library is unavailable (caller runs the numpy path)."""
    lib = _load()
    if lib is None:
        return None
    values = np.ascontiguousarray(values, dtype=np.float64)
    starts = np.ascontiguousarray(starts, dtype=np.int64)
    ends = np.ascontiguousarray(ends, dtype=np.int64)
    E = np.ascontiguousarray(E, dtype=np.int64)
    if k_limbs > 16:        # C side sizes its scale table at 16
        return None
    S = len(starts)
    limbs = np.zeros((S, k_limbs), dtype=np.float64)
    exact = np.empty(S, dtype=np.uint8)
    lib.og_limb_sums(_p(values, ctypes.c_double),
                     _p(starts, ctypes.c_int64),
                     _p(ends, ctypes.c_int64),
                     _p(E, ctypes.c_int64), S, k_limbs, limb_bits,
                     _p(limbs, ctypes.c_double),
                     _p(exact, ctypes.c_uint8))
    return limbs, exact.astype(bool)


def unpack_limbs_fast(u32: np.ndarray, top_row: int, words_row: int,
                      K: int, k0: int, K_full: int):
    """One-pass reassembly of the packed uint32 transport into the
    (S, K_full) f64 limb grid (ops/blockagg.unpack_packed digit loop).
    None when the native library is unavailable."""
    lib = _load()
    if lib is None or K > 16:
        return None
    u32 = np.ascontiguousarray(u32, dtype=np.uint32)
    S = u32.shape[1]
    out = np.empty((S, K_full), dtype=np.float64)
    lib.og_unpack_limbs(_p(u32, ctypes.c_uint32), S, top_row,
                        words_row, K, k0, K_full,
                        _p(out, ctypes.c_double))
    return out


def fold_lattice(c8: np.ndarray, l32, b8, gids: np.ndarray,
                 w0: np.ndarray, W: int, ns: int, k0: int, K: int,
                 K_full: int, counts: np.ndarray, limbs, bad) -> bool:
    """Accumulate one slab's window lattice (c8 (B, WL) int8 counts,
    l32 (K, B, WL) int32 limb partials, b8 (B, WL) uint8 bad flags)
    into the flat cell grids in place (ops/blockagg.fold_lattices).
    K=0 folds the count plane only; limb plane k lands at column k0+k.
    False → caller runs the numpy fallback."""
    lib = _load()
    if lib is None:
        return False
    B, WL = c8.shape[0], c8.shape[1]
    _null_f64 = ctypes.cast(0, ctypes.POINTER(ctypes.c_double))
    _null_u8 = ctypes.cast(0, ctypes.POINTER(ctypes.c_uint8))
    _null_i32 = ctypes.cast(0, ctypes.POINTER(ctypes.c_int32))
    lib.og_fold_lattice(
        _p(c8, ctypes.c_int8),
        _p(l32, ctypes.c_int32) if l32 is not None else _null_i32,
        _p(b8, ctypes.c_uint8) if b8 is not None else _null_u8,
        B, WL, _p(gids, ctypes.c_int64), _p(w0, ctypes.c_int64),
        W, ns, k0, K, K_full, _p(counts, ctypes.c_double),
        _p(limbs, ctypes.c_double) if limbs is not None else _null_f64,
        _p(bad, ctypes.c_uint8) if bad is not None else _null_u8)
    return True


def finalize_exact_fast(limbs: np.ndarray, limb_bits: int, E: int):
    """Single-pass correctly-rounded finalization of (n, 6) limb
    totals: (out (n,) f64, hazard_idx (nh,) int64) — hazard cells need
    the caller's exact big-int fallback (their out entries are
    unspecified). None when the native library is unavailable or
    K != 6 (caller runs the numpy path)."""
    lib = _load()
    # the C kernel hardcodes the K=6 / B=18 component layout (72/36
    # scale split, 2^17 hazard bound); any other geometry must take
    # the numpy path
    if lib is None or limbs.shape[-1] != 6 or limb_bits != 18:
        return None
    flat = np.ascontiguousarray(limbs.reshape(-1, 6), dtype=np.float64)
    n = len(flat)
    out = np.empty(n, dtype=np.float64)
    hazard = np.empty(n, dtype=np.int64)
    nh = np.zeros(1, dtype=np.int64)
    lib.og_finalize_exact(_p(flat, ctypes.c_double), n, limb_bits, E,
                          _p(out, ctypes.c_double),
                          _p(hazard, ctypes.c_int64),
                          _p(nh, ctypes.c_int64))
    return out, hazard[:int(nh[0])]


# ------------------------------------------------------ row materializer

_pyrows = None
_pyrows_attempted = False


def _load_pyrows():
    """CPython row-builder extension (native/pyrows.cpp); builds with
    the shared library. None → caller uses the numpy/Python path."""
    global _pyrows, _pyrows_attempted
    if _pyrows is not None or _pyrows_attempted:
        return _pyrows
    _pyrows_attempted = True
    if _load() is None:        # triggers the make that also builds it
        return None
    path = os.path.abspath(os.path.join(_NATIVE_DIR, "ogpyrows.so"))
    if not os.path.exists(path):
        return None
    try:
        import importlib.util
        spec = importlib.util.spec_from_file_location("ogpyrows", path)
        m = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(m)
        _pyrows = m
    except Exception:
        _pyrows = None
    return _pyrows


def build_rows(times: np.ndarray, cols: list, masks: list,
               G: int, W: int):
    """C-speed assembly of the flat [t, v0, v1, ...] row list for a
    dense (G, W) result grid. cols: list of (G*W,) arrays (float64 or
    int64); masks: parallel list of (G*W,) uint8 arrays or None (0 →
    cell becomes None). Returns the flat list of G*W rows, or None when
    the extension is unavailable."""
    m = _load_pyrows()
    if m is None or len(cols) > 64:
        return None
    t = np.ascontiguousarray(times, dtype=np.int64)
    prep_c, prep_m, keep = [], [], [t]
    for c, mk in zip(cols, masks):
        if c.dtype == np.int64:
            kind = 1
        elif c.dtype == np.float64:
            kind = 0
        else:
            return None
        c = np.ascontiguousarray(c)
        keep.append(c)
        prep_c.append((c.ctypes.data, kind))
        if mk is None:
            prep_m.append(0)
        else:
            mk = np.ascontiguousarray(mk, dtype=np.uint8)
            keep.append(mk)
            prep_m.append(mk.ctypes.data)
    return m.build_rows(t.ctypes.data, tuple(prep_c), tuple(prep_m),
                        G, W)


def build_group_rows(times: np.ndarray, cols: list, masks: list,
                     keep, desc: bool, offset: int, limit: int):
    """C-speed assembly of ONE group's [t, v0, ...] rows for the
    grouped-interval shapes the dense build_rows can't express:
    `keep` ((W,) bool/uint8 or None) selects which windows emit rows
    (fill-none sparsity), rows reverse under `desc`, then
    offset/limit slice (limit 0 = uncapped). cols: list of (W,)
    float64/int64 arrays for THIS group; masks: parallel (W,)
    uint8/bool arrays or None (0 → cell becomes None). Returns the
    row list, or None when the extension is unavailable."""
    m = _load_pyrows()
    if m is None or len(cols) > 64 \
            or not hasattr(m, "build_group_rows"):
        return None
    t = np.ascontiguousarray(times, dtype=np.int64)
    prep_c, prep_m, alive = [], [], [t]
    for c, mk in zip(cols, masks):
        if c.dtype == np.int64:
            kind = 1
        elif c.dtype == np.float64:
            kind = 0
        else:
            return None
        c = np.ascontiguousarray(c)
        alive.append(c)
        prep_c.append((c.ctypes.data, kind))
        if mk is None:
            prep_m.append(0)
        else:
            mk = np.ascontiguousarray(mk, dtype=np.uint8)
            alive.append(mk)
            prep_m.append(mk.ctypes.data)
    if keep is None:
        keep_addr = 0
    else:
        keep = np.ascontiguousarray(keep, dtype=np.uint8)
        alive.append(keep)
        keep_addr = keep.ctypes.data
    return m.build_group_rows(t.ctypes.data, tuple(prep_c),
                              tuple(prep_m), keep_addr, len(t),
                              1 if desc else 0, int(offset),
                              int(limit))


def build_topk_rows(times: np.ndarray, cols: list, oks: list,
                    nwin: np.ndarray, emit: np.ndarray):
    """C-speed batched winner-row assembly for the device ORDER BY/
    LIMIT cut: times (G, k) int64, cols (G, k) float64/int64, oks
    parallel (G, k) bool (False → None cell), nwin (G,) winner counts
    in output row order, emit (G,) group gate. Returns a list of G
    entries (row list or None), or None when the extension is
    unavailable (caller uses the Python fallback)."""
    m = _load_pyrows()
    if m is None or len(cols) > 64 \
            or not hasattr(m, "build_topk_rows"):
        return None
    G, k = times.shape
    t = np.ascontiguousarray(times, dtype=np.int64)
    nw = np.ascontiguousarray(nwin, dtype=np.int64)
    em = np.ascontiguousarray(emit, dtype=np.uint8)
    prep_c, prep_m, alive = [], [], [t, nw, em]
    for c, mk in zip(cols, oks):
        if c.dtype == np.int64:
            kind = 1
        elif c.dtype == np.float64:
            kind = 0
        else:
            return None
        c = np.ascontiguousarray(c)
        alive.append(c)
        prep_c.append((c.ctypes.data, kind))
        mk = np.ascontiguousarray(mk, dtype=np.uint8)
        alive.append(mk)
        prep_m.append(mk.ctypes.data)
    return m.build_topk_rows(t.ctypes.data, tuple(prep_c),
                             tuple(prep_m), nw.ctypes.data,
                             em.ctypes.data, G, k)


# ------------------------------------------------------- series sid map

def _bind_map(lib) -> None:
    if getattr(lib, "_og_map_bound", False):
        return
    _i64p = ctypes.POINTER(ctypes.c_int64)
    _u64p = ctypes.POINTER(ctypes.c_uint64)
    _u8p = ctypes.POINTER(ctypes.c_uint8)
    lib.og_map_new.restype = ctypes.c_void_p
    lib.og_map_new.argtypes = [ctypes.c_int64]
    lib.og_map_free.argtypes = [ctypes.c_void_p]
    lib.og_map_len.restype = ctypes.c_int64
    lib.og_map_len.argtypes = [ctypes.c_void_p]
    lib.og_map_get.restype = ctypes.c_int64
    lib.og_map_get.argtypes = [ctypes.c_void_p, ctypes.c_uint64]
    lib.og_map_put.restype = None
    lib.og_map_put.argtypes = [ctypes.c_void_p, ctypes.c_uint64,
                               ctypes.c_int64]
    lib.og_map_put_if_absent.restype = ctypes.c_int64
    lib.og_map_put_if_absent.argtypes = [ctypes.c_void_p,
                                         ctypes.c_uint64,
                                         ctypes.c_int64]
    lib.og_map_put_batch.restype = None
    lib.og_map_put_batch.argtypes = [ctypes.c_void_p, _u64p, _i64p,
                                     ctypes.c_int64]
    lib.og_map_items.restype = None
    lib.og_map_items.argtypes = [ctypes.c_void_p, _u64p, _i64p]
    lib.og_map_probe.restype = ctypes.c_int64
    lib.og_map_probe.argtypes = [ctypes.c_void_p, _u64p, ctypes.c_int64,
                                 ctypes.c_int64, _i64p, _u8p]
    lib.og_build_keys.restype = ctypes.c_int64
    lib.og_build_keys.argtypes = [_u8p, _i64p, _i64p, ctypes.c_int64,
                                  ctypes.c_int64, _u8p, _i64p, _u8p,
                                  _i64p]
    lib._og_map_bound = True


def _p(a, t):
    return a.ctypes.data_as(ctypes.POINTER(t))


class SidMap:
    """uint64 key-hash → int64 sid map for the series index: a native
    open-addressing table (flat arrays, ~24MB at 1M series) with a
    plain-dict fallback. The native batch probe turns the index's
    get-or-assign loop into one C call per ingest batch."""

    __slots__ = ("_h", "_d")

    def __init__(self, cap_hint: int = 64):
        lib = _load()
        if lib is not None:
            _bind_map(lib)
            self._h = lib.og_map_new(cap_hint)
            self._d = None
        else:
            self._h = None
            self._d = {}

    def __len__(self) -> int:
        if self._d is not None:
            return len(self._d)
        return int(_lib.og_map_len(self._h))

    def get(self, h: int):
        if self._d is not None:
            return self._d.get(h)
        v = _lib.og_map_get(self._h, h)
        return None if v == -1 else int(v)

    def put(self, h: int, sid: int) -> None:
        if self._d is not None:
            self._d[h] = sid
        else:
            _lib.og_map_put(self._h, h, sid)

    def put_if_absent(self, h: int, sid: int):
        """Insert h->sid if missing (returns None); otherwise return
        the existing sid untouched — one native call."""
        if self._d is not None:
            cur = self._d.setdefault(h, sid)
            return None if cur == sid else cur
        v = _lib.og_map_put_if_absent(self._h, h, sid)
        return None if v == -1 else int(v)

    def probe(self, hashes: np.ndarray, next_sid: int):
        """(sids (n,) i64, isnew (n,) bool, advanced next_sid); misses
        are assigned consecutive sids from next_sid, in-batch
        duplicates resolve to the first occurrence."""
        hashes = np.ascontiguousarray(hashes, dtype=np.uint64)
        n = len(hashes)
        out = np.empty(n, dtype=np.int64)
        isnew = np.empty(n, dtype=np.uint8)
        if self._d is not None:
            d = self._d
            for i, h in enumerate(hashes.tolist()):
                sid = d.get(h)
                if sid is None:
                    sid = next_sid
                    next_sid += 1
                    d[h] = sid
                    isnew[i] = 1
                else:
                    isnew[i] = 0
                out[i] = sid
            return out, isnew.astype(bool), next_sid
        nxt = _lib.og_map_probe(self._h, _p(hashes, ctypes.c_uint64),
                                n, next_sid,
                                _p(out, ctypes.c_int64),
                                _p(isnew, ctypes.c_uint8))
        return out, isnew.astype(bool), int(nxt)

    def put_batch(self, keys: np.ndarray, vals: np.ndarray) -> None:
        keys = np.ascontiguousarray(keys, dtype=np.uint64)
        vals = np.ascontiguousarray(vals, dtype=np.int64)
        if self._d is not None:
            self._d.update(zip(keys.tolist(), vals.tolist()))
            return
        _lib.og_map_put_batch(self._h, _p(keys, ctypes.c_uint64),
                              _p(vals, ctypes.c_int64), len(keys))

    def items_arrays(self):
        """(keys (n,) u64, sids (n,) i64) — snapshot serialization."""
        if self._d is not None:
            n = len(self._d)
            return (np.fromiter(self._d.keys(), dtype=np.uint64,
                                count=n),
                    np.fromiter(self._d.values(), dtype=np.int64,
                                count=n))
        n = len(self)
        ks = np.empty(n, dtype=np.uint64)
        vs = np.empty(n, dtype=np.int64)
        _lib.og_map_items(self._h, _p(ks, ctypes.c_uint64),
                          _p(vs, ctypes.c_int64))
        return ks, vs

    def __del__(self):
        h = getattr(self, "_h", None)
        if h is not None and _lib is not None:
            try:
                _lib.og_map_free(h)
            except Exception:
                pass


def build_keys(cols_b: list, seps: list):
    """Assemble per-row key strings from K fixed-width 'S' columns:
    row i = seps[0]+col0[i]+seps[1]+col1[i]+... Returns (packed uint8
    buffer, (n+1,) offsets), or None when native is unavailable."""
    lib = _load()
    if lib is None:
        return None
    _bind_map(lib)
    n = len(cols_b[0])
    K = len(cols_b)
    widths = np.array([c.dtype.itemsize for c in cols_b],
                      dtype=np.int64)
    col_off = np.zeros(K + 1, dtype=np.int64)
    np.cumsum(widths * n, out=col_off[1:])
    buf = np.empty(int(col_off[-1]), dtype=np.uint8)
    for j, c in enumerate(cols_b):
        flat = np.ascontiguousarray(c).view(np.uint8)
        buf[col_off[j]:col_off[j + 1]] = flat.ravel()
    sep_buf = np.frombuffer(b"".join(seps), dtype=np.uint8)
    if len(sep_buf) == 0:
        sep_buf = np.empty(0, dtype=np.uint8)
    sep_off = np.zeros(K + 1, dtype=np.int64)
    np.cumsum([len(s) for s in seps], out=sep_off[1:])
    cap = int(col_off[-1]) + int(sep_off[-1]) * n
    out = np.empty(max(cap, 1), dtype=np.uint8)
    offs = np.empty(n + 1, dtype=np.int64)
    total = lib.og_build_keys(
        _p(buf, ctypes.c_uint8), _p(col_off, ctypes.c_int64),
        _p(widths, ctypes.c_int64), K, n,
        _p(sep_buf, ctypes.c_uint8), _p(sep_off, ctypes.c_int64),
        _p(out, ctypes.c_uint8), _p(offs, ctypes.c_int64))
    return out[:total], offs


def log_pack(payload_buf: np.ndarray, offs: np.ndarray,
             sids: np.ndarray):
    """Assemble the series-index log stream (<u32 len><u64 sid>payload
    per record) from packed payload rows. None when native is
    unavailable."""
    lib = _load()
    if lib is None:
        return None
    _bind_map(lib)
    try:
        lib.og_log_pack.restype
    except AttributeError:
        return None
    lib.og_log_pack.argtypes = [
        ctypes.POINTER(ctypes.c_uint8), ctypes.POINTER(ctypes.c_int64),
        ctypes.POINTER(ctypes.c_int64), ctypes.c_int64,
        ctypes.POINTER(ctypes.c_uint8)]
    offs = np.ascontiguousarray(offs, dtype=np.int64)
    sids = np.ascontiguousarray(sids, dtype=np.int64)
    n = len(sids)
    out = np.empty(int(offs[-1]) + 12 * n, dtype=np.uint8)
    lib.og_log_pack(_p(payload_buf, ctypes.c_uint8),
                    _p(offs, ctypes.c_int64), _p(sids, ctypes.c_int64),
                    n, _p(out, ctypes.c_uint8))
    return out.tobytes()


def scatter_fields(M: np.ndarray, spec: list) -> bool:
    """Scatter per-record fields into record matrix M (n, recsize):
    spec = [(record_offset, (n, w) uint8 matrix)]. One record-major
    native pass; False when native is unavailable (caller falls back
    to per-field strided assignment)."""
    lib = _load()
    if lib is None or not spec:
        return lib is not None and not spec
    _bind_map(lib)
    try:
        lib.og_scatter_fields.argtypes
    except AttributeError:
        return False
    lib.og_scatter_fields.restype = None
    lib.og_scatter_fields.argtypes = [
        ctypes.POINTER(ctypes.c_uint8), ctypes.c_int64, ctypes.c_int64,
        ctypes.POINTER(ctypes.c_void_p), ctypes.POINTER(ctypes.c_int64),
        ctypes.POINTER(ctypes.c_int64), ctypes.c_int64]
    n, recsize = M.shape
    F = len(spec)
    mats = [np.ascontiguousarray(m) for _o, m in spec]
    srcs = (ctypes.c_void_p * F)(*[m.ctypes.data for m in mats])
    offs = np.array([o for o, _m in spec], dtype=np.int64)
    widths = np.array([m.shape[1] for m in mats], dtype=np.int64)
    lib.og_scatter_fields(
        _p(M, ctypes.c_uint8), recsize, n, srcs,
        _p(offs, ctypes.c_int64), _p(widths, ctypes.c_int64), F)
    return True
