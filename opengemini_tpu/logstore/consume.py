"""Consume cursors (reference handler_logstore_consume.go).

The reference encodes a multi-part cursor (shard/segment/offset + task
state); here a cursor is the stream-monotonic record seq, wrapped in an
opaque versioned token so clients cannot depend on its shape."""

from __future__ import annotations

import base64
import struct

_MAGIC = b"ogc1"
_FMT = struct.Struct("<4sq")


def encode_cursor(seq: int) -> str:
    return base64.urlsafe_b64encode(_FMT.pack(_MAGIC, seq)).decode()


def decode_cursor(token: str) -> int:
    try:
        raw = base64.urlsafe_b64decode(token.encode())
        magic, seq = _FMT.unpack(raw)
    except Exception:
        raise ValueError(f"invalid cursor {token!r}")
    if magic != _MAGIC:
        raise ValueError(f"invalid cursor {token!r}")
    return seq
