"""Log repositories, streams, and segment storage.

Reference mapping:
- Repository/LogStream catalog + TTL → `handler_logstore.go:198-489`
  (serveCreateRepository/serveCreateLogstream; a logstream's `ttl` drives
  retention like a shard-group duration).
- Segment = the reference's log block (`lib/logstore/block_container.go`):
  an append-sealed run of records with a per-block token **bloom filter**
  (`lib/logstore/bloomfilter.go`) for query pruning, plus a per-segment
  CLV inverted index (engine/index/clv) for token/phrase search.
- BlockCache/HotDataDetector → `lib/logstore/block_cache.go`,
  `lru_cache.go`, `hot_data_detector.go`: sealed segment payloads drop to
  disk and reload through an LRU; repeatedly-hit segments are "hot" and
  pinned.

Records are addressed by a stream-monotonic int64 `seq` — the consume
cursor (consume.py) and the CLV row id at the same time (unique, unlike
timestamps). Segments own the seq range [base_seq, base_seq + n).
"""

from __future__ import annotations

import json
import os
import re
import threading
import time
from collections import OrderedDict
from dataclasses import dataclass, field

import numpy as np

from ..index.clv import (FUZZY, MATCH, MATCH_PHRASE, Analyzer, CLVIndex,
                         tokenize)
from ..index.sparse import Bloom
from ..utils import get_logger

log = get_logger(__name__)

DEFAULT_SEGMENT_ROWS = 8192
DEFAULT_TTL_DAYS = 7
_NS_PER_DAY = 86400 * 10**9
_TOMBSTONE_SUFFIX = ".deleted"


@dataclass
class LogRecord:
    seq: int
    time: int                     # ns
    content: str
    tags: dict = field(default_factory=dict)

    def to_obj(self, highlight: list[str] | None = None) -> dict:
        o = {"cursor": self.seq, "timestamp": self.time,
             "content": self.content, "tags": self.tags}
        if highlight:
            o["highlight"] = _highlight(self.content, highlight)
        return o


def _highlight(content: str, tokens: list[str]) -> list[dict]:
    """Split content into {fragment, highlight} pieces around query-token
    hits (reference getHighlightFragments, handler_logstore_query.go:482)."""
    if not tokens:
        return [{"fragment": content, "highlight": False}]
    pat = "|".join(re.escape(t) for t in sorted(tokens, key=len,
                                                reverse=True))
    out = []
    last = 0
    for m in re.finditer(pat, content, re.IGNORECASE):
        if m.start() > last:
            out.append({"fragment": content[last:m.start()],
                        "highlight": False})
        out.append({"fragment": m.group(0), "highlight": True})
        last = m.end()
    if last < len(content):
        out.append({"fragment": content[last:], "highlight": False})
    return out


# ------------------------------------------------------------------ segment

class Segment:
    """One sealed-or-active run of log records with its own CLV index and
    (when sealed) a token bloom filter + on-disk payload."""

    def __init__(self, seg_id: int, base_seq: int, path: str | None,
                 analyzer: Analyzer | None = None):
        self.seg_id = seg_id
        self.base_seq = base_seq
        self.path = path
        self.n = 0
        self.min_time = 2**63 - 1
        self.max_time = -2**63
        self.sealed = False
        self.bloom: Bloom | None = None
        self.index = CLVIndex(analyzer)
        self._records: list[LogRecord] | None = []
        self._tokens: set[str] = set()
        # guards _records against the shared-cache eviction race: another
        # stream's touch() may evict this segment mid-read
        self._rlock = threading.Lock()

    # ---- write

    def append(self, rec: LogRecord) -> None:
        assert not self.sealed
        self._records.append(rec)
        self.n += 1
        self.min_time = min(self.min_time, rec.time)
        self.max_time = max(self.max_time, rec.time)
        self.index.add(self.seg_id, rec.seq, rec.content)
        for t, _p in tokenize(rec.content):
            self._tokens.add(t)

    def seal(self, rewrite: bool = True) -> None:
        """Freeze: build the bloom filter, persist the payload, allow the
        in-memory record list to be evicted. rewrite=False when the
        payload file already holds exactly these records (recovery path —
        avoids rewriting the whole dataset on startup)."""
        if self.sealed:
            return
        self.bloom = Bloom.build([t.encode() for t in self._tokens]) \
            if self._tokens else None
        if self.path and rewrite:
            tmp = self.path + ".tmp"
            with open(tmp, "w") as f:
                for r in self._records:
                    f.write(json.dumps(
                        {"seq": r.seq, "t": r.time, "c": r.content,
                         "g": r.tags}) + "\n")
            os.replace(tmp, self.path)
        self.sealed = True
        self._tokens = set()

    def evict(self) -> bool:
        """Drop the in-memory payload (sealed + persisted only)."""
        with self._rlock:
            if self.sealed and self.path and self._records is not None:
                self._records = None
                return True
            return False

    @property
    def resident(self) -> bool:
        return self._records is not None

    # ---- read

    def records(self) -> list[LogRecord]:
        with self._rlock:
            if self._records is None:
                recs = []
                with open(self.path) as f:
                    for line in f:
                        o = json.loads(line)
                        recs.append(LogRecord(o["seq"], o["t"], o["c"],
                                              o.get("g", {})))
                self._records = recs
            return self._records

    def record_by_seq(self, seq: int) -> LogRecord | None:
        i = seq - self.base_seq
        recs = self.records()
        if 0 <= i < len(recs):
            return recs[i]
        return None

    def may_match(self, tokens: list[str]) -> bool:
        """Bloom prune: every plain query token must maybe-exist
        (reference bloomfilter_cache_reader.go). Wildcards skip."""
        if not self.sealed or self.bloom is None:
            return True
        for t in tokens:
            if "*" in t or "?" in t:
                continue
            if not self.bloom.may_contain(t.encode()):
                return False
        return True

    @classmethod
    def load(cls, seg_id: int, path: str,
             analyzer: Analyzer | None = None) -> "Segment":
        """Rebuild a sealed segment from its payload file (open path)."""
        with open(path) as f:
            objs = [json.loads(line) for line in f]
        base = objs[0]["seq"] if objs else 0
        seg = cls(seg_id, base, path, analyzer)
        for o in objs:
            seg.append(LogRecord(o["seq"], o["t"], o["c"], o.get("g", {})))
        seg.seal(rewrite=False)
        return seg


# ----------------------------------------------------- cache + hot detector

class BlockCache:
    """LRU bound on resident sealed-segment payloads (reference
    lib/logstore/block_cache.go + lru_cache.go). Hot segments are exempt
    from eviction."""

    def __init__(self, max_resident: int = 16,
                 detector: "HotDataDetector | None" = None):
        self.max_resident = max_resident
        self.detector = detector or HotDataDetector()
        self._lru: OrderedDict[tuple, Segment] = OrderedDict()
        self._lock = threading.Lock()
        self.evictions = 0

    def forget(self, key: tuple) -> None:
        """Drop one segment's cache + detector state (retention/delete) —
        keys are never reused, so stale entries would leak forever."""
        with self._lock:
            self._lru.pop(key, None)
            self.detector.forget(key)

    def forget_prefix(self, prefix: tuple) -> None:
        with self._lock:
            for k in [k for k in self._lru if k[:len(prefix)] == prefix]:
                del self._lru[k]
            self.detector.forget_prefix(prefix)

    def touch(self, key: tuple, seg: Segment) -> None:
        with self._lock:
            self.detector.record(key)
            self._lru[key] = seg
            self._lru.move_to_end(key)
            while len(self._lru) > self.max_resident:
                victim = None
                for k in self._lru:       # oldest first
                    if not self.detector.is_hot(k):
                        victim = k
                        break
                if victim is None:        # everything hot: evict oldest
                    victim = next(iter(self._lru))
                seg = self._lru.pop(victim)
                if seg.evict():
                    self.evictions += 1


class HotDataDetector:
    """Flags blocks accessed ≥ `threshold` times inside `window_s`
    (reference lib/logstore/hot_data_detector.go)."""

    def __init__(self, threshold: int = 4, window_s: float = 60.0):
        self.threshold = threshold
        self.window_s = window_s
        self._hits: dict[tuple, list[float]] = {}

    def record(self, key: tuple, now: float | None = None) -> None:
        now = time.monotonic() if now is None else now
        hits = self._hits.setdefault(key, [])
        hits.append(now)
        cutoff = now - self.window_s
        while hits and hits[0] < cutoff:
            hits.pop(0)

    def is_hot(self, key: tuple, now: float | None = None) -> bool:
        now = time.monotonic() if now is None else now
        hits = self._hits.get(key, ())
        return sum(1 for h in hits if h >= now - self.window_s) \
            >= self.threshold

    def forget(self, key: tuple) -> None:
        self._hits.pop(key, None)

    def forget_prefix(self, prefix: tuple) -> None:
        for k in [k for k in self._hits if k[:len(prefix)] == prefix]:
            del self._hits[k]


# ------------------------------------------------------------- query parse

def parse_log_query(q: str) -> list[tuple[int, str]]:
    """Parse a keyword query into (qtype, term) clauses, all ANDed:
    bare tokens → MATCH, "quoted strings" → MATCH_PHRASE, tokens with
    * or ? → FUZZY. Empty query matches everything."""
    clauses: list[tuple[int, str]] = []
    for m in re.finditer(r'"([^"]*)"|(\S+)', q or ""):
        if m.group(1) is not None:
            if m.group(1).strip():
                clauses.append((MATCH_PHRASE, m.group(1)))
        else:
            term = m.group(2)
            if "*" in term or "?" in term:
                clauses.append((FUZZY, term))
            else:
                clauses.append((MATCH, term))
    return clauses


# ------------------------------------------------------------------ stream

def _locked(fn):
    """Hold the stream lock for the whole call: readers walk the active
    segment's CLV postings, which append() mutates concurrently under
    the ThreadingHTTPServer."""
    def wrap(self, *a, **k):
        with self._lock:
            if self.deleted:
                raise KeyError(f"logstream {self.name} not found")
            return fn(self, *a, **k)
    wrap.__name__ = fn.__name__
    wrap.__doc__ = fn.__doc__
    return wrap


class LogStream:
    """One log stream: ordered segments + per-segment CLV/bloom search."""

    def __init__(self, repo: str, name: str, dirpath: str | None,
                 ttl_days: float = DEFAULT_TTL_DAYS,
                 segment_rows: int = DEFAULT_SEGMENT_ROWS,
                 cache: BlockCache | None = None):
        self.repo = repo
        self.name = name
        self.dir = dirpath
        self.ttl_days = ttl_days
        self.segment_rows = segment_rows
        self.cache = cache or BlockCache()
        self._lock = threading.RLock()
        self.deleted = False
        self.segments: list[Segment] = []
        self._active: Segment | None = None
        self.next_seq = 0
        self.total_records = 0
        if dirpath:
            os.makedirs(dirpath, exist_ok=True)
            self._recover()

    def _recover(self) -> None:
        meta = os.path.join(self.dir, "meta.json")
        if os.path.exists(meta):
            with open(meta) as f:
                self.ttl_days = float(json.load(f).get(
                    "ttl_days", self.ttl_days))
        files = sorted(f for f in os.listdir(self.dir)
                       if f.startswith("seg") and f.endswith(".log"))
        for f in files:
            seg_id = int(f[3:-4])
            seg = Segment.load(seg_id, os.path.join(self.dir, f))
            self.segments.append(seg)
            self.next_seq = max(self.next_seq, seg.base_seq + seg.n)
            self.total_records += seg.n

    def save_meta(self) -> None:
        """Persist stream properties (TTL) so restarts keep them."""
        if not self.dir:
            return
        tmp = os.path.join(self.dir, "meta.json.tmp")
        with open(tmp, "w") as f:
            json.dump({"ttl_days": self.ttl_days}, f)
        os.replace(tmp, os.path.join(self.dir, "meta.json"))

    def _seg_path(self, seg_id: int) -> str | None:
        return os.path.join(self.dir, f"seg{seg_id:08d}.log") \
            if self.dir else None

    # ---- write

    def append(self, entries: list[dict]) -> int:
        """entries: [{"content": str, "timestamp": ns, "tags": {...}}].
        Returns count written (reference serveRecord ingest). Coerces and
        validates every entry BEFORE writing any — no partial writes on
        bad input."""
        coerced = []
        for i, e in enumerate(entries):
            if not isinstance(e, dict):
                raise ValueError(
                    f"log entry must be an object, got {type(e).__name__}")
            try:
                ts = int(e.get("timestamp", time.time_ns()))
                tags = e.get("tags", {})
                if not isinstance(tags, dict):
                    raise TypeError("tags must be an object")
                coerced.append((ts, str(e.get("content", "")),
                                dict(tags)))
            except (TypeError, ValueError) as err:
                raise ValueError(f"bad log entry {i}: {err}")
        with self._lock:
            if self.deleted:
                raise KeyError(f"logstream {self.name} not found")
            for ts, content, tags in coerced:
                if self._active is None \
                        or self._active.n >= self.segment_rows:
                    self._roll()
                self._active.append(
                    LogRecord(self.next_seq, ts, content, tags))
                self.next_seq += 1
                self.total_records += 1
            return len(coerced)

    def _roll(self) -> None:
        if self._active is not None:
            self._active.seal()
            self.cache.touch((self.repo, self.name,
                              self._active.seg_id), self._active)
        seg_id = self.segments[-1].seg_id + 1 if self.segments else 0
        seg = Segment(seg_id, self.next_seq, self._seg_path(seg_id))
        self.segments.append(seg)
        self._active = seg

    def seal_active(self) -> None:
        with self._lock:
            if self._active is not None:
                self._active.seal()
                self._active = None

    # ---- search

    def _matching_seqs(self, seg: Segment,
                       clauses: list[tuple[int, str]]) -> np.ndarray:
        """Seqs in one segment matching all clauses (AND)."""
        if not clauses:
            return seg.base_seq + np.arange(seg.n, dtype=np.int64)
        acc: np.ndarray | None = None
        for qtype, term in clauses:
            hits = seg.index.search(term, qtype)
            rows = hits.get(seg.seg_id, np.empty(0, dtype=np.int64))
            acc = rows if acc is None else acc[np.isin(acc, rows)]
            if not len(acc):
                break
        return acc

    def _scan_matches(self, clauses, t_min: int | None,
                      t_max: int | None, t_max_inclusive: bool,
                      reverse: bool = False, scroll: int | None = None):
        """Yield matching LogRecords: the shared time-prune → bloom-prune
        → CLV-search → per-record time-filter pipeline behind query/
        histogram/analytics. Callers hold the stream lock (@_locked).
        `scroll` prunes to records strictly past that seq in scan
        direction — whole segments out of seq range are skipped before
        any index search or record decode."""
        plain = [t for ty, term in clauses if ty != FUZZY
                 for t, _p in tokenize(term)]
        segs = self.segments
        for seg in (reversed(segs) if reverse else segs):
            if seg.n == 0:
                continue
            if scroll is not None and (
                    seg.base_seq >= scroll if reverse
                    else seg.base_seq + seg.n <= scroll + 1):
                continue
            if t_min is not None and seg.max_time < t_min:
                continue
            if t_max is not None and (
                    seg.min_time > t_max if t_max_inclusive
                    else seg.min_time >= t_max):
                continue
            if not seg.may_match(plain):
                continue
            seqs = self._matching_seqs(seg, clauses)
            if not len(seqs):
                continue
            self.cache.touch((self.repo, self.name, seg.seg_id), seg)
            for s in (seqs[::-1] if reverse else seqs):
                if scroll is not None and (
                        s >= scroll if reverse else s <= scroll):
                    continue
                r = seg.record_by_seq(int(s))
                if r is None:
                    continue
                if t_min is not None and r.time < t_min:
                    continue
                if t_max is not None and (
                        r.time > t_max if t_max_inclusive
                        else r.time >= t_max):
                    continue
                yield r

    @_locked
    def query(self, q: str = "", t_min: int | None = None,
              t_max: int | None = None, limit: int = 100,
              reverse: bool = True, highlight: bool = False,
              scroll: int | None = None) -> list[dict]:
        """Keyword search (reference serveQueryLog): time-pruned segments
        → bloom prune → CLV search → records, newest first by default.
        `scroll` pages a search (reference serveQueryLogByCursor): only
        records strictly past that seq in scan direction are returned —
        pass the previous page's last cursor to continue."""
        clauses = parse_log_query(q)
        out: list[LogRecord] = []
        for r in self._scan_matches(clauses, t_min, t_max,
                                    t_max_inclusive=True,
                                    reverse=reverse, scroll=scroll):
            out.append(r)
            if len(out) >= limit:
                break
        hl = [term for ty, term in clauses if ty != FUZZY] \
            if highlight else None
        hl_tokens = [t for term in hl or [] for t, _p in tokenize(term)]
        return [r.to_obj(hl_tokens if highlight else None) for r in out]

    @_locked
    def histogram(self, q: str = "", t_min: int = 0, t_max: int = 0,
                  interval: int = 60 * 10**9) -> list[dict]:
        """Per-time-bucket match counts (reference serveAggLogQuery /
        getHistogramsForAggLog); window is [t_min, t_max)."""
        clauses = parse_log_query(q)
        n_buckets = max(int((t_max - t_min + interval - 1) // interval), 1)
        times = [r.time for r in self._scan_matches(
            clauses, t_min, t_max, t_max_inclusive=False)]
        if times:
            b = ((np.asarray(times, dtype=np.int64) - t_min)
                 // interval)
            counts = np.bincount(b, minlength=n_buckets)
        else:
            counts = np.zeros(n_buckets, dtype=np.int64)
        return [{"from": int(t_min + i * interval),
                 "to": int(min(t_min + (i + 1) * interval, t_max)),
                 "count": int(c)} for i, c in enumerate(counts)]

    @_locked
    def analytics(self, q: str = "", t_min: int | None = None,
                  t_max: int | None = None,
                  group_by: str = "", limit: int = 10) -> dict:
        """Top tag values by matching-log count over [t_min, t_max] —
        INCLUSIVE bounds, same as query()/the /logs endpoint (reference
        serveAnalytics, handler_logstore_query.go:823). Empty group_by
        returns only the total; records lacking the group_by tag count
        toward the total but form no group."""
        clauses = parse_log_query(q)
        counts: dict[str, int] = {}
        total = 0
        for r in self._scan_matches(clauses, t_min, t_max,
                                    t_max_inclusive=True):
            total += 1
            if group_by and group_by in r.tags:
                v = r.tags[group_by]
                counts[v] = counts.get(v, 0) + 1
        groups = sorted(counts.items(), key=lambda kv: (-kv[1], kv[0]))
        return {"total": total,
                "groups": [{"value": v, "count": c}
                           for v, c in groups[:limit]]}

    @_locked
    def context(self, seq: int, before: int = 10, after: int = 10
                ) -> list[dict]:
        """Records around a cursor (reference serveContextQueryLog)."""
        lo, hi = max(seq - before, 0), seq + after + 1
        out = []
        segs = self.segments
        for seg in segs:
            if seg.base_seq + seg.n <= lo or seg.base_seq >= hi:
                continue
            self.cache.touch((self.repo, self.name, seg.seg_id), seg)
            for s in range(max(lo, seg.base_seq),
                           min(hi, seg.base_seq + seg.n)):
                r = seg.record_by_seq(s)
                if r is not None:
                    out.append(r.to_obj())
        return out

    # ---- consume

    @_locked
    def read_from(self, seq: int, count: int = 100
                  ) -> tuple[list[dict], int]:
        """Cursor tail-read: up to `count` records with seq >= cursor;
        returns (records, next_cursor) (reference serveConsumeLogs)."""
        out = []
        segs = self.segments
        for seg in segs:
            if seg.base_seq + seg.n <= seq:
                continue
            self.cache.touch((self.repo, self.name, seg.seg_id), seg)
            for s in range(max(seq, seg.base_seq), seg.base_seq + seg.n):
                out.append(seg.record_by_seq(s).to_obj())
                if len(out) >= count:
                    return out, int(out[-1]["cursor"]) + 1
        next_cur = int(out[-1]["cursor"]) + 1 if out else seq
        return out, next_cur

    @_locked
    def consume_cursors(self, n: int, from_seq: int = 0) -> list[dict]:
        """Split the remaining stream into n contiguous ranges for
        parallel consumers (reference serveGetConsumeCursors,
        handler_logstore_consume.go — per-PT cursor fan-out). Each entry:
        {"from": seq, "to": seq_exclusive}; the last range is open-ended
        (consumers tail it with read_from)."""
        n = max(int(n), 1)
        # a stale/forged cursor past the stream end must not invert the
        # open range (to < from)
        end = max(self.next_seq, from_seq)
        total = end - from_seq
        step = total // n
        out = []
        pos = from_seq
        for i in range(n):
            hi = end if i == n - 1 else pos + step
            out.append({"from": int(pos), "to": int(hi),
                        "open": i == n - 1})
            pos = hi
        return out

    @_locked
    def cursor_at_time(self, t: int) -> int:
        """Smallest seq with record time >= t (reference
        serveConsumeCursorTime)."""
        segs = self.segments
        for seg in segs:
            if seg.n == 0 or seg.max_time < t:
                continue
            self.cache.touch((self.repo, self.name, seg.seg_id), seg)
            for s in range(seg.base_seq, seg.base_seq + seg.n):
                r = seg.record_by_seq(s)
                if r.time >= t:
                    return s
        return self.next_seq

    # ---- retention

    def apply_retention(self, now_ns: int | None = None) -> int:
        """Drop sealed segments entirely older than the TTL; returns
        segments removed (reference logstream ttl + retention service)."""
        now_ns = time.time_ns() if now_ns is None else now_ns
        cutoff = now_ns - int(self.ttl_days * _NS_PER_DAY)
        removed = 0
        with self._lock:
            keep = []
            for seg in self.segments:
                if seg.sealed and seg.max_time < cutoff:
                    if seg.path and os.path.exists(seg.path):
                        os.remove(seg.path)
                    self.total_records -= seg.n
                    removed += 1
                    self.cache.forget((self.repo, self.name, seg.seg_id))
                else:
                    keep.append(seg)
            self.segments = keep
        return removed

    def forget_cached(self) -> None:
        """Drop every cache/detector entry of this stream (stream
        deletion)."""
        self.cache.forget_prefix((self.repo, self.name))

    def stats(self) -> dict:
        return {"records": self.total_records,
                "segments": len(self.segments),
                "resident": sum(1 for s in self.segments if s.resident),
                "ttl_days": self.ttl_days}


# ------------------------------------------------------------------- store

class Repository:
    def __init__(self, name: str, dirpath: str | None):
        self.name = name
        self.dir = dirpath
        self.streams: dict[str, LogStream] = {}
        self.props: dict = {}


_NAME_RE = re.compile(r"^[A-Za-z0-9._-]+$")


def _validate_name(kind: str, name: str) -> None:
    """Repo/stream names become directory components under the logstore
    root — reject anything that could traverse out of it ('..' resolves
    to the engine data dir; a later DELETE would rmtree it)."""
    if (not _NAME_RE.fullmatch(name) or name in (".", "..")
            or os.sep in name or (os.altsep and os.altsep in name)):
        raise ValueError(f"invalid {kind} name {name!r}")


class LogStore:
    """Repository/logstream catalog rooted at a directory (reference
    repository≈database, logstream≈measurement with TTL)."""

    def __init__(self, root: str | None = None):
        self.root = root
        self._lock = threading.Lock()
        self.repos: dict[str, Repository] = {}
        self.cache = BlockCache()
        self._deleting: set[tuple[str, str]] = set()
        if root:
            os.makedirs(root, exist_ok=True)
            for rname in sorted(os.listdir(root)):
                rdir = os.path.join(root, rname)
                if not os.path.isdir(rdir):
                    continue
                repo = Repository(rname, rdir)
                for sname in sorted(os.listdir(rdir)):
                    sdir = os.path.join(rdir, sname)
                    if not os.path.isdir(sdir):
                        continue
                    if re.search(r"\.deleted\.[0-9a-f]+$", sname):
                        # crash mid-delete: finish the job, never
                        # resurrect the data as a live stream (exact
                        # tombstone pattern — a legacy stream merely
                        # CONTAINING '.deleted' is not destroyed)
                        import shutil
                        shutil.rmtree(sdir, ignore_errors=True)
                        continue
                    repo.streams[sname] = LogStream(
                        rname, sname, sdir, cache=self.cache)
                self.repos[rname] = repo

    # ---- repository CRUD (serveCreateRepository et al.)

    def create_repository(self, name: str) -> None:
        _validate_name("repository", name)
        with self._lock:
            if name in self.repos:
                raise ValueError(f"repository {name} already exists")
            rdir = os.path.join(self.root, name) if self.root else None
            if rdir:
                os.makedirs(rdir, exist_ok=True)
            self.repos[name] = Repository(name, rdir)

    def delete_repository(self, name: str) -> None:
        with self._lock:
            repo = self.repos.pop(name, None)
            if repo is None:
                raise KeyError(f"repository {name} not found")
            self.cache.forget_prefix((name,))
            if repo.dir and os.path.isdir(repo.dir):
                import shutil
                shutil.rmtree(repo.dir)

    def list_repositories(self) -> list[str]:
        return sorted(self.repos)

    # ---- logstream CRUD (serveCreateLogstream et al.)

    def create_logstream(self, repo: str, name: str,
                         ttl_days: float = DEFAULT_TTL_DAYS) -> None:
        _validate_name("logstream", name)
        with self._lock:
            r = self._repo(repo)
            if name in r.streams:
                raise ValueError(f"logstream {name} already exists")
            if (repo, name) in self._deleting:
                raise ValueError(
                    f"logstream {name} is being deleted, retry")
            if _TOMBSTONE_SUFFIX in name:
                raise ValueError(f"invalid logstream name {name!r}")
            sdir = os.path.join(r.dir, name) if r.dir else None
            st = LogStream(repo, name, sdir, ttl_days=ttl_days,
                           cache=self.cache)
            st.save_meta()
            r.streams[name] = st

    def delete_logstream(self, repo: str, name: str) -> None:
        with self._lock:
            r = self._repo(repo)
            s = r.streams.pop(name, None)
            if s is None:
                raise KeyError(f"logstream {name} not found")
            # recreates of this name are refused until the files are gone
            # (create_logstream checks _deleting) — so the slow file work
            # below can run without any lock
            self._deleting.add((repo, name))
        try:
            # wait out in-flight reads/writes (they hold s._lock for the
            # whole op, so no file under the dir is open after this);
            # the deleted flag stops later ops from re-inserting cache
            # entries or touching the removed files
            with s._lock:
                s.deleted = True
                s.forget_cached()
            if s.dir and os.path.isdir(s.dir):
                import shutil

                # tombstone-rename first: a crash mid-rmtree must not
                # leave a half-deleted dir that recovery would resurrect
                # (unique suffix: an earlier failed rmtree's tombstone
                # must not block the rename)
                tomb = s.dir + _TOMBSTONE_SUFFIX + f".{time.time_ns():x}"
                os.rename(s.dir, tomb)
                shutil.rmtree(tomb, ignore_errors=True)
        finally:
            with self._lock:
                self._deleting.discard((repo, name))

    def list_logstreams(self, repo: str) -> list[str]:
        return sorted(self._repo(repo).streams)

    def update_logstream(self, repo: str, name: str,
                         ttl_days: float) -> None:
        st = self.stream(repo, name)
        st.ttl_days = ttl_days
        st.save_meta()

    def _repo(self, name: str) -> Repository:
        r = self.repos.get(name)
        if r is None:
            raise KeyError(f"repository {name} not found")
        return r

    def stream(self, repo: str, name: str) -> LogStream:
        s = self._repo(repo).streams.get(name)
        if s is None:
            raise KeyError(f"logstream {name} not found")
        return s

    def apply_retention(self, now_ns: int | None = None) -> int:
        n = 0
        for r in list(self.repos.values()):
            for s in list(r.streams.values()):
                n += s.apply_retention(now_ns)
        return n
