"""Logstore / logkeeper product mode.

Role of the reference's log-storage stack (SURVEY.md §2.7): `lib/logstore/`
(log blocks with per-block token bloom filters, block LRU caches, hot-data
detector), the logstream/repository catalog (`handler_logstore.go`), the
keyword/histogram/context query APIs (`handler_logstore_query.go`) and the
cursor-based consume APIs (`handler_logstore_consume.go`).
"""

from .store import (LogStore, Repository, LogStream, LogRecord, Segment,
                    BlockCache, HotDataDetector, parse_log_query)
from .consume import encode_cursor, decode_cursor
