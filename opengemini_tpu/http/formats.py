"""HTTP response formats: CSV, msgpack, chunked JSON.

Role of the reference's ResponseWriter
(lib/util/lifted/influx/httpd/response_writer.go): /query results
render as JSON (default), CSV (Accept: application/csv | text/csv) or
msgpack (Accept: application/x-msgpack); `chunked=true[&chunk_size=N]`
streams one JSON object per chunk instead of a single document.

The msgpack encoder is a minimal spec-complete writer for the JSON-ish
value domain results live in (maps/arrays/str/bytes/int/float/bool/nil)
— the runtime image carries no msgpack library.
"""

from __future__ import annotations

import struct
from typing import Iterator


# ------------------------------------------------------------------ csv

def results_to_csv(payload: dict) -> str:
    """Reference CSV shape: header name,tags,time,<columns...>; tags
    rendered as k=v comma-joined; one section per series."""
    out: list[str] = []
    for res in payload.get("results", []):
        for s in res.get("series", []):
            cols = s.get("columns", [])
            out.append(",".join(["name", "tags"] + [_csv_escape(c)
                                                    for c in cols]))
            tags = ",".join(f"{k}={v}" for k, v in
                            sorted(s.get("tags", {}).items()))
            for row in s.get("values", []):
                cells = [_csv_escape(s.get("name", "")),
                         _csv_escape(tags)]
                cells += ["" if v is None else
                          (repr(v) if isinstance(v, float)
                           else _csv_escape(v))
                          for v in row]
                out.append(",".join(cells))
        if "error" in res:
            out.append(f"error,{_csv_escape(res['error'])}")
    return "\n".join(out) + ("\n" if out else "")


def _csv_escape(v) -> str:
    s = str(v)
    if any(c in s for c in ",\"\n"):
        return '"' + s.replace('"', '""') + '"'
    return s


# -------------------------------------------------------------- msgpack

def msgpack_encode(obj) -> bytes:
    buf = bytearray()
    _mp(obj, buf)
    return bytes(buf)


def _mp(o, buf: bytearray) -> None:
    if o is None:
        buf.append(0xC0)
    elif o is True:
        buf.append(0xC3)
    elif o is False:
        buf.append(0xC2)
    elif isinstance(o, int):
        if 0 <= o < 128:
            buf.append(o)
        elif -32 <= o < 0:
            buf.append(o & 0xFF)
        elif -(1 << 63) <= o < (1 << 64):
            if o >= 0:
                buf.append(0xCF)
                buf += struct.pack(">Q", o)
            else:
                buf.append(0xD3)
                buf += struct.pack(">q", o)
        else:
            raise ValueError("int out of msgpack range")
    elif isinstance(o, float):
        buf.append(0xCB)
        buf += struct.pack(">d", o)
    elif isinstance(o, str):
        b = o.encode()
        n = len(b)
        if n < 32:
            buf.append(0xA0 | n)
        elif n < 256:
            buf += bytes([0xD9, n])
        elif n < 65536:
            buf.append(0xDA)
            buf += struct.pack(">H", n)
        else:
            buf.append(0xDB)
            buf += struct.pack(">I", n)
        buf += b
    elif isinstance(o, (bytes, bytearray)):
        n = len(o)
        if n < 256:
            buf += bytes([0xC4, n])
        elif n < 65536:
            buf.append(0xC5)
            buf += struct.pack(">H", n)
        else:
            buf.append(0xC6)
            buf += struct.pack(">I", n)
        buf += o
    elif isinstance(o, (list, tuple)):
        n = len(o)
        if n < 16:
            buf.append(0x90 | n)
        elif n < 65536:
            buf.append(0xDC)
            buf += struct.pack(">H", n)
        else:
            buf.append(0xDD)
            buf += struct.pack(">I", n)
        for x in o:
            _mp(x, buf)
    elif isinstance(o, dict):
        n = len(o)
        if n < 16:
            buf.append(0x80 | n)
        elif n < 65536:
            buf.append(0xDE)
            buf += struct.pack(">H", n)
        else:
            buf.append(0xDF)
            buf += struct.pack(">I", n)
        for k, v in o.items():
            _mp(str(k), buf)
            _mp(v, buf)
    else:
        # numpy scalars etc: fall back on their python value
        item = getattr(o, "item", None)
        if item is not None:
            _mp(item(), buf)
        else:
            raise TypeError(f"cannot msgpack {type(o)}")


# -------------------------------------------------------------- chunked

def chunk_results(payload: dict, chunk_size: int) -> Iterator[dict]:
    """Split a /query result into a stream of per-series (and per-
    chunk_size row block) partial result objects — reference
    response_writer chunked mode. Each yielded object is a complete
    {"results": [...]} document; all but the last carry "partial"."""
    chunks: list[dict] = []
    for res in payload.get("results", []):
        sid = res.get("statement_id", 0)
        series = res.get("series")
        if not series:
            chunks.append({"results": [dict(res)]})
            continue
        for s in series:
            rows = s.get("values", [])
            if not rows or chunk_size <= 0:
                blocks = [rows]
            else:
                blocks = [rows[i:i + chunk_size]
                          for i in range(0, len(rows), chunk_size)]
            for bi, block in enumerate(blocks):
                entry = {k: v for k, v in s.items() if k != "values"}
                entry["values"] = block
                chunks.append({"results": [
                    {"statement_id": sid, "series": [entry]}]})
    if not chunks:
        chunks.append({"results": []})
    for i, c in enumerate(chunks):
        if i < len(chunks) - 1:
            c["results"][0]["partial"] = True
        yield c
