"""InfluxDB-1.x-compatible HTTP API (role of the reference httpd layer,
lib/util/lifted/influx/httpd/handler.go:223-496 route table; serveWrite
:1260; serveQuery :1002).

Endpoints:
    POST /write?db=<db>[&precision=ns|u|ms|s|m|h]   line protocol (gzip ok)
    GET/POST /query?q=<influxql>[&db=][&epoch=]     JSON results
    GET  /ping                                      204
    GET  /health                                    JSON status
    GET  /debug/vars                                runtime stats
    GET/POST /api/v1/query, /api/v1/query_range     PromQL (handler_prom.go
        :362,:367 analog); /api/v1/labels :637, /api/v1/label/<n>/values,
        /api/v1/series :721

Python stdlib ThreadingHTTPServer: the data plane is the TPU compute path,
the HTTP layer only parses/formats; a C++ ingest front-end can replace this
behind the same API surface.
"""

from __future__ import annotations

import gzip
import json
import re
import threading
import time
import urllib.parse
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from .. import __version__
from ..query import QueryExecutor, ParseError, parse_query
from ..utils import deadline, get_logger, knobs, tracing
from ..utils.errors import GeminiError
from ..utils.resources import ResourceExhausted
from ..utils.lineprotocol import PRECISION_NS

log = get_logger(__name__)

# per-request latency distributions (flight-recorder tentpole): the
# monotonic httpd counters say HOW MANY, these say HOW SLOW — p50/p99
# surface in /debug/vars and the stats pusher, full bucket vectors in
# Prometheus histogram form on /metrics
from ..utils.stats import Histogram, exp_bounds  # noqa: E402
from ..utils.stats import observe as _observe  # noqa: E402
from ..utils.stats import register_histograms  # noqa: E402

HTTP_HIST: dict = register_histograms("httpd", {
    # end-to-end /query and /write handler wall
    "query_latency_ms": Histogram(exp_bounds(0.25, 1 << 20)),
    "write_latency_ms": Histogram(exp_bounds(0.25, 1 << 20)),
    # per-route request wall (transport framing included)
    "route_query_ms": Histogram(exp_bounds(0.25, 1 << 20)),
    "route_write_ms": Histogram(exp_bounds(0.25, 1 << 20)),
    "route_api_ms": Histogram(exp_bounds(0.25, 1 << 20)),
    "route_debug_ms": Histogram(exp_bounds(0.25, 1 << 20)),
    "route_other_ms": Histogram(exp_bounds(0.25, 1 << 20)),
})


def _route_class(path: str) -> str:
    if path == "/query":
        return "query"
    if path == "/write":
        return "write"
    if path.startswith("/api/"):
        return "api"
    if path.startswith("/debug") or path == "/metrics":
        return "debug"
    return "other"

_PASSWORD_RE = re.compile(
    r"(password(?:\s+for\s+\S+\s*=)?\s*)'(?:[^']|'')*'", re.IGNORECASE)


def _redact_passwords(qtext: str) -> str:
    """WITH PASSWORD '...' / SET PASSWORD FOR u = '...' → '[REDACTED]'
    before the query text reaches any log line."""
    return _PASSWORD_RE.sub(r"\1'[REDACTED]'", qtext)


class HttpServer:
    def __init__(self, engine, host: str = "127.0.0.1", port: int = 8086,
                 prom_db: str = "prometheus", executor=None, config=None):
        """`engine` needs write_points(); queries go through `executor`
        (defaults to the single-node QueryExecutor; the cluster sql node
        passes a ClusterExecutor). Prom endpoints need a local scanning
        engine and disable themselves on a cluster facade. `config` is a
        utils.config.Config wiring limits, slow-query threshold, stats."""
        from collections import deque

        from ..promql import PromEngine
        from ..query.manager import QueryManager
        from ..utils.config import Config
        from ..utils.resources import QueryResources
        from ..utils.syscontrol import SysControl
        self.engine = engine
        self.config = config or Config()
        local = hasattr(engine, "scan_series")
        self.query_manager = QueryManager()
        self.resources = QueryResources(
            self.config.data.max_concurrent_queries,
            self.config.data.max_queued_queries,
            self.config.data.max_series_per_query)
        # user catalog + auth (reference [http] auth-enabled + meta users)
        import os as _os

        from ..meta.users import UserStore
        upath = getattr(config, "users_path", None) if config else None
        data = getattr(engine, "data_path", None) \
            or getattr(engine, "path", None)
        if upath is None and isinstance(data, str):
            upath = _os.path.join(data, "users.json")
        self.user_store = UserStore(upath)
        if self.config.http.auth_enabled and upath is None:
            log.warning("auth enabled but no durable user path "
                        "(cluster facade without data_dir): users are "
                        "in-memory and lost on restart")
        # local catalog (CQs, retention policies) for the single node;
        # the cluster path keeps its catalog in the meta raft store
        self.catalog = None
        if local and isinstance(data, str):
            from ..meta.catalog import Catalog
            self.catalog = Catalog(_os.path.join(data, "catalog.json"))
        self.executor = executor or QueryExecutor(
            engine, query_manager=self.query_manager,
            resources=self.resources, users=self.user_store,
            catalog=self.catalog)
        if config is not None \
                and hasattr(self.executor, "max_failed_stores"):
            # cluster executor: config sets the scatter degradation
            # tolerance ([data] max_failed_stores)
            self.executor.max_failed_stores = \
                config.data.max_failed_stores
        self.sysctrl = SysControl(engine if local else None)
        # device query scheduler (query/scheduler.py): wire the config
        # limits; env (OG_SCHED_SLOTS et al) overrides inside configure
        from ..query import scheduler as _qsched
        _qsched.get_scheduler().configure(
            max_concurrent=self.config.data.max_concurrent_queries,
            max_queued=self.config.data.max_queued_queries)
        self.prom = PromEngine(engine, prom_db) if local else None
        self.prom_db = prom_db
        # logstore product mode (reference logkeeper; lazy — only pays
        # when the repository/logstream APIs are used)
        self._logstore = None
        self._logstore_lock = threading.Lock()
        # plan cache (reference SqlPlanTemplate/GetPlanType pool)
        from ..query.plancache import PlanCache
        self.plan_cache = PlanCache()
        self.host = host
        self.port = port
        self._httpd: ThreadingHTTPServer | None = None
        self._thread: threading.Thread | None = None
        self.stats = {"writes": 0, "points_written": 0, "queries": 0,
                      "write_errors": 0, "query_errors": 0,
                      "slow_queries": 0, "auth_failures": 0,
                      "started_at": time.time()}
        self.slow_log: "deque" = deque(maxlen=32)
        self._stats_lock = threading.Lock()
        # statistics pusher (reference lib/statisticsPusher)
        self.stats_pusher = None
        if self.config.stats.enabled:
            from ..utils.stats import (StatisticsPusher, engine_collector,
                                       readcache_collector,
                                       runtime_collector)
            sp = StatisticsPusher(
                interval_s=self.config.stats.interval_ns / 1e9,
                push_path=self.config.stats.push_path,
                engine=engine if local else None,
                store_database=self.config.stats.store_database)
            from ..utils.stats import (compaction_collector,
                                       device_collector,
                                       device_decode_collector,
                                       devicecache_collector,
                                       executor_collector, raft_collector,
                                       rpc_collector, subscriber_collector,
                                       wal_collector)
            sp.register("runtime", runtime_collector)
            sp.register("readcache", readcache_collector)
            sp.register("executor", executor_collector)
            sp.register("devicecache", devicecache_collector)
            sp.register("device_decode",
                        device_decode_collector)
            sp.register("device", device_collector)
            from ..ops.devstats import phase_collector
            sp.register("query_phases", phase_collector)
            from ..utils.stats import scheduler_collector
            sp.register("scheduler", scheduler_collector)
            from ..utils.stats import hbm_collector
            sp.register("hbm", hbm_collector)
            from ..utils.stats import resultcache_collector
            sp.register("resultcache", resultcache_collector)
            from ..utils.stats import devicefault_collector
            sp.register("devicefault", devicefault_collector)
            from ..utils.stats import (compileaudit_collector,
                                       xfer_collector)
            sp.register("compileaudit", compileaudit_collector)
            sp.register("xfer", xfer_collector)
            from ..utils.stats import latency_collector
            sp.register("latency", latency_collector)
            sp.register("wal", wal_collector)
            from ..utils.stats import flight_collector
            sp.register("flight", flight_collector)
            sp.register("raft", raft_collector)
            sp.register("subscriber", subscriber_collector)
            sp.register("compaction", compaction_collector)
            sp.register("rpc", rpc_collector)
            if local:
                sp.register("engine", engine_collector(engine))
            sp.register("httpd", lambda: dict(self.stats))
            self.stats_pusher = sp

    def _bump(self, key: str, n: int = 1) -> None:
        with self._stats_lock:
            self.stats[key] += n

    def _request_budget(self, params: dict, cfg_ns: int) -> float | None:
        """Effective request budget in seconds: the configured ceiling,
        optionally LOWERED by a client ?timeout= param (a client may ask
        for less patience, never more). None = unbounded."""
        ceil_s = cfg_ns / 1e9 if cfg_ns else None
        req = params.get("timeout")
        if req:
            try:
                req_s = float(req)
            except ValueError:
                req_s = 0.0
            if req_s > 0:
                return min(req_s, ceil_s) if ceil_s else req_s
        return ceil_s

    @staticmethod
    def _is_user_stmt(stmt) -> bool:
        from ..query.ast import (CreateUserStatement, DropUserStatement,
                                 GrantStatement, RevokeStatement,
                                 SetPasswordStatement,
                                 ShowGrantsStatement, ShowStatement)
        return isinstance(stmt, (CreateUserStatement, DropUserStatement,
                                 SetPasswordStatement, GrantStatement,
                                 RevokeStatement,
                                 ShowGrantsStatement)) or \
            (isinstance(stmt, ShowStatement) and stmt.what == "users")

    def _exec_user_stmt(self, stmt) -> dict:
        from ..meta.users import execute_user_statement
        return execute_user_statement(self.user_store, stmt)

    def _deny_privilege(self, stmt, user) -> str | None:
        """Admin gate for destructive/user statements when auth is
        enforced (reference httpd privilege checks). A non-admin may
        still change their own password."""
        from ..query.ast import (AlterRPStatement, CreateCQStatement,
                                 CreateDatabaseStatement,
                                 CreateMeasurementStatement,
                                 CreateRPStatement,
                                 CreateUserStatement, DeleteStatement,
                                 DropCQStatement,
                                 DropDatabaseStatement,
                                 DropMeasurementStatement,
                                 DropRPStatement,
                                 DropUserStatement, KillQueryStatement,
                                 SetPasswordStatement)
        if self._bootstrap_only():
            # zero users with auth on: only first-admin creation passes
            if isinstance(stmt, CreateUserStatement) and stmt.admin:
                return None
            return ("create an admin user first: CREATE USER <name> "
                    "WITH PASSWORD '<pw>' WITH ALL PRIVILEGES")
        if not self.auth_required():
            return None
        if isinstance(stmt, SetPasswordStatement) and user is not None \
                and stmt.name == user.name:
            return None
        from ..query.ast import (CreateDownsampleStatement,
                                 CreateSubscriptionStatement,
                                 DropDownsampleStatement,
                                 DropSeriesStatement,
                                 DropShardStatement,
                                 DropSubscriptionStatement,
                                 GrantStatement, RevokeStatement,
                                 ShowGrantsStatement)
        admin_only = (CreateUserStatement, DropUserStatement,
                      SetPasswordStatement, CreateDatabaseStatement,
                      CreateMeasurementStatement, CreateCQStatement,
                      DropCQStatement, CreateRPStatement,
                      AlterRPStatement, DropRPStatement,
                      DropDatabaseStatement, DropMeasurementStatement,
                      DropSeriesStatement, DropShardStatement,
                      DeleteStatement, KillQueryStatement,
                      GrantStatement, RevokeStatement,
                      ShowGrantsStatement, CreateSubscriptionStatement,
                      DropSubscriptionStatement,
                      CreateDownsampleStatement,
                      DropDownsampleStatement)
        if isinstance(stmt, admin_only) and (user is None
                                             or not user.admin):
            return "admin privilege required"
        return None

    @staticmethod
    def _select_read_dbs(sel, default_db, out: set) -> set:
        """Every database a SELECT reads from, recursively: top-level
        FROM, db-qualified extra sources, subqueries, join sides (a
        db-qualified inner source must not bypass enforcement)."""
        out.add(sel.from_db or default_db)
        for src in sel.extra_sources:
            if isinstance(src, tuple):
                out.add(src[0] or default_db)
        if sel.from_subquery is not None:
            HttpServer._select_read_dbs(sel.from_subquery,
                                        sel.from_db or default_db, out)
        if sel.join is not None:
            HttpServer._select_read_dbs(sel.join.left, default_db, out)
            HttpServer._select_read_dbs(sel.join.right, default_db, out)
        return out

    def _deny_db_access(self, stmt, user, db) -> str | None:
        """Per-database privilege enforcement for data statements
        (reference GRANT semantics enforced in httpd): SELECT/SHOW need
        READ on every database the statement touches (subqueries, join
        sides and multi-source FROM included); SELECT ... INTO also
        needs WRITE on the target db. Admin statements are separately
        gated."""
        from ..query.ast import (ExplainStatement, SelectStatement,
                                 ShowStatement)
        if not self.auth_required() or (user is not None and user.admin):
            return None
        sel = None
        if isinstance(stmt, SelectStatement):
            sel = stmt
        elif isinstance(stmt, ExplainStatement):
            sel = stmt.select
        elif isinstance(stmt, ShowStatement):
            if stmt.what in ("databases", "queries", "stats"):
                return None
            if stmt.what == "diagnostics":
                # build/system facts (paths, executables) — admin-only,
                # matching the reference ShowDiagnosticsStatement
                return "admin privilege required"
            if stmt.what in ("subscriptions", "downsamples") \
                    and not stmt.on_db:
                # cross-database enumeration (destination URLs, policy
                # details) is admin-only, matching the reference
                return "admin privilege required"
            tdb = stmt.on_db or db
            if tdb:
                return self._deny_db_op(user, tdb, "READ")
            return None
        if sel is None:
            return None
        for tdb in self._select_read_dbs(sel, db, set()):
            if tdb:
                deny = self._deny_db_op(user, tdb, "READ")
                if deny:
                    return deny
        if sel.into_measurement:
            wdb = sel.into_db or db
            if wdb:
                return self._deny_db_op(user, wdb, "WRITE")
        return None

    def _deny_db_op(self, user, db: str, need: str) -> str | None:
        """Per-db grant gate shared by the write and prom-remote
        endpoints; returns the 403 message, or None when allowed."""
        if not self.auth_required() or self.user_store.authorized(
                user, db, need):
            return None
        verb = "write to" if need == "WRITE" else "read from"
        return (f'"{getattr(user, "name", "")}" user is not '
                f'authorized to {verb} database "{db}"')

    def auth_required(self) -> bool:
        """Credentials are demanded once any user exists. With auth
        enabled but zero users the API is NOT open: only the bootstrap
        CREATE USER ... WITH ALL PRIVILEGES statement is allowed (influx
        1.x rule — see _bootstrap_only / _deny_privilege)."""
        return bool(self.config.http.auth_enabled and
                    len(self.user_store))

    def _bootstrap_only(self) -> bool:
        return bool(self.config.http.auth_enabled
                    and len(self.user_store) == 0)

    @property
    def logstore(self):
        if self._logstore is None:
            with self._logstore_lock:
                if self._logstore is None:
                    import os

                    from ..logstore import LogStore
                    root = None
                    data = getattr(self.engine, "data_path", None) \
                        or getattr(self.engine, "path", None)
                    if isinstance(data, str):
                        root = os.path.join(data, "logstore")
                    self._logstore = LogStore(root)
        return self._logstore

    # --------------------------------------------------- logstore endpoints

    def handle_logstore(self, method: str, path: str, params: dict,
                        body: bytes) -> tuple[int, dict]:
        """Repository/logstream catalog + log ingest/query/consume APIs
        (reference handler.go:382-459 route table; paths kept
        compatible)."""
        from ..logstore import decode_cursor, encode_cursor
        ls = self.logstore
        parts = [p for p in path.split("/") if p]
        try:
            # /api/v1/repository[/{repo}]
            if parts[:3] == ["api", "v1", "repository"]:
                if method == "GET" and len(parts) == 3:
                    return 200, {"repositories": ls.list_repositories()}
                repo = parts[3]
                if method == "POST":
                    ls.create_repository(repo)
                    return 201, {"repository": repo}
                if method == "DELETE":
                    ls.delete_repository(repo)
                    return 200, {}
                if method == "GET":
                    r = ls.repos.get(repo)
                    if r is None:
                        return 404, {"error": f"repository {repo} "
                                     "not found"}
                    return 200, {"repository": repo,
                                 "logstreams": sorted(r.streams)}
            # /api/v1/logstream/{repo}[/{stream}]
            if parts[:3] == ["api", "v1", "logstream"]:
                repo = parts[3]
                if len(parts) == 4 and method == "GET":
                    return 200, {"logstreams": ls.list_logstreams(repo)}
                stream = parts[4]
                if method == "POST":
                    opts = json.loads(body or b"{}")
                    ls.create_logstream(repo, stream,
                                        ttl_days=float(
                                            opts.get("ttl", 7)))
                    return 201, {"logstream": stream}
                if method == "DELETE":
                    ls.delete_logstream(repo, stream)
                    return 200, {}
                if method == "PUT":
                    opts = json.loads(body or b"{}")
                    ls.update_logstream(repo, stream,
                                        float(opts["ttl"]))
                    return 200, {}
                if method == "GET":
                    return 200, ls.stream(repo, stream).stats()
            # /repo/{r}/logstreams/{s}/<op>
            if parts[0] == "repo" and len(parts) >= 4 \
                    and parts[2] == "logstreams":
                repo, stream_name = parts[1], parts[3]
                op = "/".join(parts[4:])
                stream = ls.stream(repo, stream_name)
                if op == "records" and method == "POST":
                    payload = json.loads(body or b"{}")
                    logs = payload if isinstance(payload, list) \
                        else payload.get("logs", [])
                    n = stream.append(logs)
                    return 200, {"success": True, "written": n}
                t_min = int(params["from"]) if "from" in params else None
                t_max = int(params["to"]) if "to" in params else None
                if op in ("logs", "logbycursor"):
                    scroll = decode_cursor(params["cursor"]) \
                        if "cursor" in params else None
                    rows = stream.query(
                        params.get("q", ""), t_min, t_max,
                        limit=int(params.get("limit", 100)),
                        reverse=params.get("reverse", "true") != "false",
                        highlight=params.get("highlight") == "true",
                        scroll=scroll)
                    out = {"logs": rows, "count": len(rows)}
                    if rows:
                        out["cursor"] = encode_cursor(
                            int(rows[-1]["cursor"]))
                    return 200, out
                if op == "histogram":
                    if t_min is None or t_max is None:
                        return 400, {"error": "from and to required"}
                    hist = stream.histogram(
                        params.get("q", ""), t_min, t_max,
                        interval=int(params.get(
                            "interval", 60 * 10**9)))
                    return 200, {"histograms": hist,
                                 "count": sum(h["count"] for h in hist)}
                if op == "analytics":
                    res = stream.analytics(
                        params.get("q", ""), t_min, t_max,
                        group_by=params.get("group_by", ""),
                        limit=int(params.get("limit", 10)))
                    return 200, res
                if op == "context":
                    cur = decode_cursor(params["cursor"])
                    rows = stream.context(
                        cur, before=int(params.get("before", 10)),
                        after=int(params.get("after", 10)))
                    return 200, {"logs": rows}
                if op == "consume/logs":
                    cur = decode_cursor(params["cursor"]) \
                        if "cursor" in params else 0
                    rows, nxt = stream.read_from(
                        cur, count=int(params.get("count", 100)))
                    return 200, {"logs": rows,
                                 "cursor": encode_cursor(nxt)}
                if op == "consume/cursors":
                    frm = decode_cursor(params["cursor"]) \
                        if "cursor" in params else 0
                    ranges = stream.consume_cursors(
                        int(params.get("count", 1)), frm)
                    return 200, {"cursors": [
                        {"from": encode_cursor(r["from"]),
                         "to": encode_cursor(r["to"]),
                         "open": r["open"]} for r in ranges]}
                if op == "consume/cursor-time":
                    seq = stream.cursor_at_time(int(params["time"]))
                    return 200, {"cursor": encode_cursor(seq)}
            return 404, {"error": f"not found: {method} {path}"}
        except IndexError:
            return 400, {"error": f"bad path: {path}"}
        except (KeyError, ValueError) as e:
            code = 404 if "not found" in str(e) else 400
            return code, {"error": str(e)}

    # ------------------------------------------------------------ lifecycle

    def start(self) -> None:
        # initialize the JAX backend from the MAIN thread: plugin discovery
        # (axon) can fail when first touched from a request worker thread
        try:
            import jax
            jax.devices()
        except Exception as e:  # pragma: no cover
            log.warning("jax backend init failed: %s", e)
        outer = self

        class Handler(_Handler):
            server_ref = outer

        self._httpd = ThreadingHTTPServer((self.host, self.port), Handler)
        self.port = self._httpd.server_address[1]  # resolve port 0
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        name="httpd", daemon=True)
        self._thread.start()
        if self.stats_pusher is not None:
            self.stats_pusher.start()
        # device utilization timeline (ops/hbm.py): background sampler
        # feeding /debug/device; OG_DEVUTIL_MS <= 0 disables
        if float(knobs.get("OG_DEVUTIL_MS")) > 0:
            from ..ops import hbm as _hbm
            _hbm.sampler().start()
        log.info("http listening on %s:%d", self.host, self.port)

    def stop(self) -> None:
        from ..ops import hbm as _hbm
        _hbm.sampler().stop()
        if self.stats_pusher is not None:
            self.stats_pusher.stop()
        if self._httpd:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None

    # ----------------------------------------------------------- handlers

    # ------------------------------------------------ flight recorder

    def _slow_threshold_ns(self) -> int:
        """Slow-query threshold: OG_SLOW_QUERY_MS when set (> 0), else
        the [http] slow_query_threshold config (previously declared
        and never read); 0 disables slow detection."""
        ms = float(knobs.get("OG_SLOW_QUERY_MS"))
        if ms > 0:
            return int(ms * 1e6)
        return int(self.config.http.slow_query_threshold_ns)

    @staticmethod
    def _tenant_of(headers) -> str:
        """X-OG-Tenant request header → tenant identity for fair-share
        admission and attribution ("" = the default tenant). Bounded:
        a hostile header must not mint unbounded scheduler state."""
        if headers is None:
            return ""
        try:
            t = (headers.get("X-OG-Tenant") or "").strip()
        except Exception:
            return ""
        return t[:64]

    def _trace_begin(self, kind: str, headers=None):
        """(trace_id, root_span | None, sampled): head-sample roll for
        one request. A client-supplied X-OG-Trace header forces the
        sample and fixes the trace id (cross-service correlation)."""
        hdr_tid = None
        if headers is not None:
            try:
                hdr_tid = headers.get("X-OG-Trace")
            except Exception:
                hdr_tid = None
        sampled = bool(hdr_tid) or tracing.should_sample()
        trace_id = (hdr_tid or tracing.new_trace_id())[:32]
        root = tracing.new_trace(kind) if sampled else None
        return trace_id, root, sampled

    def _finish_trace(self, kind: str, text: str, db: str | None,
                      t0_ns: int, trace_id: str, root, sampled: bool,
                      tstat: dict, meta: dict | None = None,
                      tenant: str = "",
                      cache_status: str = "") -> None:
        """Close one request's trace: classify (ok/error/shed/killed/
        slow), log + ring-retain slow queries (the now-wired
        slow_query_threshold), record into the flight recorder. A
        sampled-out OK request records NOTHING (overhead guard)."""
        dur_ns = time.perf_counter_ns() - t0_ns
        status = tstat.get("status", "ok")
        thresh = self._slow_threshold_ns()
        slow = thresh > 0 and dur_ns >= thresh and kind == "query"
        if status == "ok" and slow:
            status = "slow"
        text = _redact_passwords(text)
        phases = {}
        if root is not None:
            root.end_ns = time.perf_counter_ns()
            tracing.annotate_overlap(root)
            from ..ops.devstats import PHASE_NAMES
            for s in root.walk():
                if s.name in PHASE_NAMES:
                    phases[s.name] = round(
                        phases.get(s.name, 0.0)
                        + s.duration_ns / 1e6, 3)
        if slow:
            self._bump("slow_queries")
            entry = {"trace_id": trace_id, "query": text,
                     "db": db or "", "at": time.time(),
                     "duration_ms": round(dur_ns / 1e6, 3),
                     "phases_ms": phases}
            with self._stats_lock:
                self.slow_log.append(entry)
            log.warning(
                "slow query (%.1fms > %.1fms) db=%s trace_id=%s "
                "phases_ms=%s: %s", dur_ns / 1e6, thresh / 1e6,
                db or "", trace_id, phases, text)
        if sampled or status != "ok":
            tracing.recorder().record(tracing.TraceRecord(
                trace_id=trace_id, kind=kind, text=text, db=db or "",
                start_wall=time.time() - dur_ns / 1e9,
                duration_ns=int(dur_ns), status=status,
                error=tstat.get("error", ""), sampled=sampled,
                root=root, tenant=tenant,
                cache_status=cache_status))
            if meta is not None:
                meta["trace_id"] = trace_id

    def handle_write(self, params: dict, body: bytes, user=None,
                     headers=None,
                     meta: dict | None = None) -> tuple[int, dict]:
        """Tracing front of the write path: every write rolls the head
        sample (X-OG-Trace forces it and pins the id, like /query);
        failed writes are retained in the slow/error ring and the
        recorded trace id rides back via ``meta`` → X-OG-Trace-Id."""
        t0 = time.perf_counter_ns()
        trace_id, root, sampled = self._trace_begin("write", headers)
        code, payload = self._handle_write_inner(params, body,
                                                 user=user)
        _observe(HTTP_HIST, "write_latency_ms",
                 (time.perf_counter_ns() - t0) / 1e6,
                 trace_id=trace_id if sampled else None)
        tstat = {"status": "ok" if code < 400 else "error",
                 "error": (payload or {}).get("error", "")}
        if root is not None:
            root.add(db=params.get("db") or "", code=code)
        self._finish_trace("write",
                           f"POST /write db={params.get('db') or ''}",
                           params.get("db"), t0, trace_id, root,
                           sampled, tstat, meta,
                           tenant=self._tenant_of(headers))
        return code, payload

    def _handle_write_inner(self, params: dict, body: bytes,
                            user=None) -> tuple[int, dict]:
        if self.sysctrl.readonly:
            self._bump("write_errors")
            return 403, {"error": "server is in readonly mode"}
        db = params.get("db")
        if not db:
            return 400, {"error": "database is required"}
        deny = self._deny_db_op(user, db, "WRITE")
        if deny:
            self._bump("write_errors")
            return 403, {"error": deny}
        precision = params.get("precision", "ns")
        budget = self._request_budget(params,
                                      self.config.data.write_timeout_ns)
        try:
            # decode ONCE: the utf-8 gate and the fallback parser share
            # this str; the fast paths lex the raw bytes
            body_text = body.decode("utf-8")
            # one write budget end-to-end: the points-writer fan-out and
            # its retries consume the remainder (utils.deadline)
            with deadline.bind(budget, what="write"):
                if hasattr(self.engine, "write_lines"):
                    # cluster facade: lex once, scatter raw line bytes
                    # per partition (points_writer._write_lines)
                    n = self.engine.write_lines(
                        db, body,
                        default_time_ns=int(time.time() * 1e9),
                        precision=precision)
                else:
                    from ..utils.lineprotocol import ingest_lines
                    n = ingest_lines(
                        self.engine, db, body,
                        default_time_ns=int(time.time() * 1e9),
                        precision=precision, text=body_text)
        except GeminiError as e:
            self._bump("write_errors")
            return 400, {"error": str(e)}
        except UnicodeDecodeError:
            self._bump("write_errors")
            return 400, {"error": "body must be utf-8 line protocol"}
        except Exception as e:  # engine bug must not kill the connection
            log.exception("write failed")
            self._bump("write_errors")
            return 500, {"error": f"internal error: {e}"}
        self._bump("writes")
        self._bump("points_written", n)
        return 204, {}

    def _admit_query(self, stmts, db, ctx):
        """Shared admission for every SELECT-bearing request (/query
        and flux): scheduler weighted-fair slot when OG_SCHED is on,
        the legacy counting gate otherwise. Returns (ticket,
        gate_held) — exactly one is set; raises SchedShed /
        ResourceExhausted / GeminiError (killed or out of budget while
        queued) for the caller to map onto its response shape."""
        from ..query import scheduler as _qsched
        if _qsched.enabled():
            sch = _qsched.get_scheduler()
            # the plan-derived estimate probes shard indexes — skip it
            # when nothing consumes it (unlimited slots AND no cell
            # budget: admission instant-grants either way)
            if sch.max_concurrent > 0 or sch.max_cells > 0:
                cost = _qsched.estimate_request_cost(self.executor,
                                                     stmts, db)
                # result-cache discount: a range mostly covered by a
                # valid cached entry admits at its live-edge cost —
                # warm dashboards must not queue behind estimates for
                # work the cache will resolve (the estimate only; the
                # serve path revalidates everything)
                try:
                    from ..query import resultcache as _rc
                    cost = _rc.discount_cost(
                        self.executor, stmts, db,
                        getattr(ctx, "tenant", ""), cost)
                except Exception:
                    log.exception("result-cache admission discount "
                                  "failed")
            else:
                cost = _qsched.QueryCost(0)
            if ctx is not None:
                ctx.cost_cells = cost.cells
            return sch.admit(ctx=ctx, cost=cost), False
        # OG_SCHED=0 fallback: no-op unless max_concurrent_queries is
        # configured — today's path, byte for byte
        self.resources.queries.acquire(ctx=ctx)
        return None, True

    def handle_query(self, params: dict, user=None, headers=None,
                     meta: dict | None = None) -> tuple[int, dict]:
        qtext = params.get("q")
        if not qtext:
            return 400, {"error": "missing required parameter \"q\""}
        db = params.get("db")
        epoch = params.get("epoch")
        # incremental-aggregation polling (reference IncQuery/IterID)
        inc_qid = params.get("inc_query_id")
        try:
            iter_id = int(params.get("iter_id", 0))
        except ValueError:
            return 400, {"error": "iter_id must be an integer"}
        self._bump("queries")
        plan = self.plan_cache.get(qtext)
        if plan is not None:
            stmts = plan.stmts
        else:
            try:
                stmts = parse_query(qtext)
            except ParseError as e:
                self._bump("query_errors")
                return 400, {"error": f"error parsing query: {e}"}
            # user statements carry plaintext passwords — never retain
            # the raw text in the cache (reference redacts them too)
            if not any(self._is_user_stmt(s) for s in stmts):
                self.plan_cache.put(qtext, stmts)
        results = []
        budget = self._request_budget(params,
                                      self.config.data.query_timeout_ns)
        from ..ops import devstats as _dstat
        from ..query import scheduler as _qsched
        from ..query.ast import SelectStatement
        # flight recorder (tentpole): head-sample roll; sampled
        # requests carry a span tree end to end, sampled-out requests
        # see span=None everywhere (the pre-PR-7 hot path, no span
        # allocations) but are still retained in the slow/error ring
        # when they fail or run slow
        t_q0 = time.perf_counter_ns()
        trace_id, root, sampled = self._trace_begin("query", headers)
        tenant = self._tenant_of(headers)
        if root is not None:
            root.add(db=db or "", statements=len(stmts),
                     tenant=tenant or "default")
        tstat = {"status": "ok", "error": ""}
        # register at ENQUEUE time: a queued query is visible to SHOW
        # QUERIES (status "queued") and killable before admission;
        # the tenant identity rides the ctx into scheduler fair-share
        # accounting and the result-cache key
        ctx = self.query_manager.attach(qtext, db, tenant=tenant) \
            if self.query_manager is not None else None
        if ctx is not None:
            ctx.trace_id = trace_id
        ticket = None
        gate_held = False
        try:
            # ONE budget covers the whole request (all statements):
            # admission wait, every scatter hop, RPC retry and store
            # wait below consume the remainder — a slow store can never
            # stack fresh per-hop timeouts past this point
            # (utils.deadline)
            with deadline.bind(budget, what="query"):
                if any(isinstance(s, SelectStatement) for s in stmts):
                    adm_sp = root.child("sched_queue") \
                        if root is not None else None
                    if adm_sp is not None:
                        adm_sp.start_ns = time.perf_counter_ns()
                    try:
                        ticket, gate_held = self._admit_query(
                            stmts, db, ctx)
                    except _qsched.SchedShed as e:
                        self._bump("query_errors")
                        tstat.update(status="shed", error=str(e))
                        payload = {
                            "error": str(e),
                            "retry_after": round(e.retry_after_s, 3)}
                        if e.reason:
                            payload["reason"] = e.reason
                        return e.http_code, payload
                    except ResourceExhausted as e:
                        self._bump("query_errors")
                        tstat.update(status="shed", error=str(e))
                        return 503, {"error": str(e)}
                    except GeminiError as e:
                        # killed or out of budget while queued: an
                        # ordinary query error, never a dead connection
                        self._bump("query_errors")
                        tstat.update(
                            status=("killed" if ctx is not None
                                    and ctx.killed else "error"),
                            error=str(e))
                        return 200, {"results": [
                            {"statement_id": 0, "error": str(e)}]}
                    finally:
                        if adm_sp is not None:
                            adm_sp.end_ns = time.perf_counter_ns()
                            adm_sp.add(queued=bool(
                                ctx is not None and ctx.queue_ns))
                    # admission wait joins the cumulative phase split
                    # (and its histogram) even when it was ~0
                    _dstat.bump_phase(
                        "sched_queue",
                        ctx.queue_ns if ctx is not None else 0)
                for i, stmt in enumerate(stmts):
                    try:
                        deny = self._deny_privilege(stmt, user) \
                            or self._deny_db_access(stmt, user, db)
                        if deny is not None:
                            res = {"error": deny}
                        elif self._is_user_stmt(stmt):
                            # executed against the server's own user
                            # catalog — works identically over the
                            # cluster facade (whose executor has no
                            # user branch)
                            res = self._exec_user_stmt(stmt)
                        else:
                            # one cache slot per statement of a
                            # multi-statement query
                            stmt_qid = f"{inc_qid}#{i}" if inc_qid \
                                else None
                            if root is not None:
                                # per-statement span, bound as the
                                # thread's trace context so cluster
                                # scatter hops propagate it over RPC
                                ssp = root.child("statement")
                                ssp.start_ns = time.perf_counter_ns()
                                ssp.add(statement_id=i)
                                try:
                                    with tracing.bind(ssp, trace_id):
                                        res = self.executor.execute(
                                            stmt, db, ctx=ctx,
                                            span=ssp,
                                            inc_query_id=stmt_qid,
                                            iter_id=iter_id)
                                finally:
                                    ssp.end_ns = \
                                        time.perf_counter_ns()
                            else:
                                res = self.executor.execute(
                                    stmt, db, ctx=ctx,
                                    inc_query_id=stmt_qid,
                                    iter_id=iter_id)
                    except GeminiError as e:
                        # typed budget/engine errors (ErrQueryTimeout
                        # et al)
                        res = {"error": str(e)}
                    except Exception as e:  # an executor bug must not
                        # kill the connection
                        log.exception("query execution failed: %s",
                                      _redact_passwords(qtext))
                        res = {"error": f"internal error: {e}"}
                    res = dict(res)
                    res["statement_id"] = i
                    if epoch and "series" in res:
                        _convert_epoch(res["series"], epoch)
                    if "error" in res:
                        self._bump("query_errors")
                        if tstat["status"] == "ok":
                            tstat.update(
                                status=("killed" if ctx is not None
                                        and ctx.killed else "error"),
                                error=res["error"])
                    results.append(res)
        finally:
            if ticket is not None:
                # cost-model calibration (device observatory): grade
                # the admission estimate against this query's measured
                # actuals. No-op when OG_SCHED_CALIB=0 (the PR 4
                # byte-identity gate).
                _qsched.get_scheduler().record_ctx(ticket, ctx)
                ticket.release()
            if gate_held:
                self.resources.queries.release()
            if ctx is not None:
                self.query_manager.detach(ctx)
            _observe(HTTP_HIST, "query_latency_ms",
                     (time.perf_counter_ns() - t_q0) / 1e6,
                     trace_id=trace_id if sampled else None)
            cstat = getattr(ctx, "cache_status", "") \
                if ctx is not None else ""
            if root is not None and cstat:
                root.add(cache_status=cstat)
            self._finish_trace("query", qtext, db, t_q0, trace_id,
                               root, sampled, tstat, meta,
                               tenant=tenant, cache_status=cstat)
        return 200, {"results": results}

    def metrics_text(self, fmt: str = "prometheus") -> str:
        """Prometheus text exposition of the internal collectors
        (reference httpd serveMetrics, handler.go /metrics route).
        ``fmt="openmetrics"`` emits the OpenMetrics 1.0 dialect
        instead: same families, plus flight-recorder trace-id
        exemplars on the histogram buckets and the mandatory ``# EOF``
        terminator — slow buckets link straight to /debug/trace?id=."""
        from ..utils.stats import (compaction_collector,
                                   compileaudit_collector,
                                   device_collector,
                                   device_decode_collector,
                                   devicecache_collector,
                                   devicefault_collector,
                                   engine_collector, executor_collector,
                                   flight_collector,
                                   hbm_collector, raft_collector,
                                   readcache_collector,
                                   resultcache_collector,
                                   rpc_collector, runtime_collector,
                                   scheduler_collector,
                                   subscriber_collector, wal_collector,
                                   xfer_collector)
        from ..ops.devstats import phase_collector
        groups = {"runtime": runtime_collector(),
                  "readcache": readcache_collector(),
                  "executor": executor_collector(),
                  "devicecache": devicecache_collector(),
                  "device_decode": device_decode_collector(),
                  "device": device_collector(),
                  "query_phases": phase_collector(),
                  "scheduler": scheduler_collector(),
                  "hbm": hbm_collector(),
                  "resultcache": resultcache_collector(),
                  "devicefault": devicefault_collector(),
                  "compileaudit": compileaudit_collector(),
                  "xfer": xfer_collector(),
                  "wal": wal_collector(),
                  "flight": flight_collector(),
                  "raft": raft_collector(),
                  "subscriber": subscriber_collector(),
                  "compaction": compaction_collector(),
                  "rpc": rpc_collector(),
                  "httpd": dict(self.stats)}
        if hasattr(self.engine, "scan_series"):
            try:
                groups["engine"] = engine_collector(self.engine)()
            except Exception:
                pass
        om = fmt == "openmetrics"
        lines = []
        for grp, vals in groups.items():
            for k, v in sorted(vals.items()):
                if isinstance(v, bool) or not isinstance(v,
                                                         (int, float)):
                    continue
                name = f"opengemini_{grp}_{k}"
                lines.append(f"# HELP {name} {grp} collector "
                             f"metric {k}")
                lines.append(f"# TYPE {name} gauge")
                lines.append(f"{name} {v}")
        # registered latency/size histograms (query latency, queue
        # wait, D2H bytes, phases, routes, estimate-error ratios) in
        # native histogram exposition — _bucket{le=}/_sum/_count, with
        # exemplars in the OpenMetrics dialect
        from ..utils.stats import histograms_prometheus
        lines.extend(histograms_prometheus(openmetrics=om))
        if om:
            lines.append("# EOF")
        return "\n".join(lines) + "\n"

    # --------------------------------------------------- flux endpoint

    def handle_flux(self, body: bytes, content_type: str,
                    user=None, headers=None
                    ) -> tuple[int, dict | None, str | None]:
        """POST /api/v2/query — Flux pipeline queries (reference
        flux-read route handler.go:484-496; openGemini's own
        serveFluxQuery is a "not implementation" stub — here the
        common subset executes by transpiling onto the SELECT path).
        Returns (code, json_payload, csv_text): exactly one of the
        last two is non-None."""
        from ..query.flux import compile_flux, flux_csv
        from ..query.influxql import ParseError
        if not self.config.http.flux_enabled:
            return 403, {"error":
                         "Flux query service disabled. Verify "
                         "flux-enabled=true in the [http] section of "
                         "the config."}, None
        if "json" in (content_type or ""):
            try:
                doc = json.loads(body.decode("utf-8"))
            except Exception as e:
                return 400, {"code": "invalid",
                             "message": f"bad json body: {e}"}, None
            qtext = doc.get("query", "")
        else:
            qtext = body.decode("utf-8", "replace")
        if not qtext.strip():
            return 400, {"code": "invalid",
                         "message": "missing flux query"}, None
        self._bump("queries")
        try:
            comp = compile_flux(qtext, time.time_ns())
        except ParseError as e:     # FluxError subclasses ParseError,
            # and compile_flux ends in parse_query of the generated
            # InfluxQL — both must answer 400, not kill the connection
            self._bump("query_errors")
            return 400, {"code": "invalid", "message": str(e)}, None
        deny = self._deny_db_access(comp.stmt, user, comp.db)
        if deny is not None:
            self._bump("query_errors")
            return 403, {"code": "forbidden", "message": deny}, None
        # flux selects go through the same serving runtime as /query:
        # admission (weighted-fair slot + shed), SHOW QUERIES
        # registration and killability — a monster must not bypass the
        # scheduler by arriving in flux clothing
        from ..query import scheduler as _qsched
        ctx = self.query_manager.attach(
            qtext, comp.db, tenant=self._tenant_of(headers)) \
            if self.query_manager is not None else None
        ticket = None
        gate_held = False
        budget = self.config.data.query_timeout_ns / 1e9 \
            if self.config.data.query_timeout_ns else None
        try:
            with deadline.bind(budget, what="query"):
                try:
                    ticket, gate_held = self._admit_query(
                        [comp.stmt], comp.db, ctx)
                except _qsched.SchedShed as e:
                    self._bump("query_errors")
                    payload = {
                        "code": ("unavailable" if e.http_code == 503
                                 else "too many requests"),
                        "message": str(e),
                        "retry_after": round(e.retry_after_s, 3)}
                    if e.reason:
                        payload["reason"] = e.reason
                    return e.http_code, payload, None
                except ResourceExhausted as e:
                    self._bump("query_errors")
                    return 503, {"code": "unavailable",
                                 "message": str(e)}, None
                except GeminiError as e:
                    self._bump("query_errors")
                    return 400, {"code": "invalid",
                                 "message": str(e)}, None
                try:
                    res = self.executor.execute(comp.stmt, comp.db,
                                                ctx=ctx)
                except GeminiError as e:
                    self._bump("query_errors")
                    return 400, {"code": "invalid",
                                 "message": str(e)}, None
                except Exception as e:
                    log.exception("flux execution failed")
                    self._bump("query_errors")
                    return 500, {"code": "internal error",
                                 "message": str(e)}, None
        finally:
            if ticket is not None:
                # same estimate-vs-actual grading as /query — a flux
                # monster must not dodge calibration either
                _qsched.get_scheduler().record_ctx(ticket, ctx)
                ticket.release()
            if gate_held:
                self.resources.queries.release()
            if ctx is not None:
                self.query_manager.detach(ctx)
        if "error" in res:
            self._bump("query_errors")
            return 400, {"code": "invalid",
                         "message": res["error"]}, None
        return 200, None, flux_csv(res, comp.shape)

    # --------------------------------------------------- prom endpoints

    def handle_prom_remote(self, path: str, params: dict, body: bytes,
                           user=None
                           ) -> tuple[int, dict | None, bytes | None]:
        """Prometheus remote write/read: snappy-block protobuf bodies
        (reference handler_prom.go:54,146). Returns (code, json_payload,
        raw_body) — raw_body set for the binary read response."""
        from ..prom import (decode_read_request, decode_write_request,
                            encode_read_response, handle_remote_read,
                            records_from_write_request,
                            rows_from_write_request)
        # default to the PromQL engine's database so /api/v1/query sees
        # remote-written samples
        db = params.get("db") or (self.prom.db if self.prom is not None
                                  else "prometheus")
        need = "WRITE" if path.endswith("/write") else "READ"
        deny = self._deny_db_op(user, db, need)
        if deny:
            self._bump("auth_failures")
            return 403, {"error": deny}, None
        if path.endswith("/write"):
            if self.sysctrl.readonly:
                self._bump("write_errors")
                return 403, {"error": "server is in readonly mode"}, None
            try:
                wr = decode_write_request(body)
                use_mat = hasattr(self.engine, "write_series_matrix")
                use_bulk = hasattr(self.engine, "write_record_batch")
                if use_mat:
                    from ..prom import matrices_from_write_request
                    mats, recs = matrices_from_write_request(wr)
                elif use_bulk:
                    mats, recs = (), records_from_write_request(wr)
                else:
                    rows = rows_from_write_request(wr)
            except Exception as e:
                self._bump("write_errors")
                return 400, {"error": f"bad remote write body: {e}"}, None
            try:
                # matrix path for aligned scrape groups, columnar bulk
                # frames for the rest (the row path builds a PointRow
                # per sample)
                if use_mat or use_bulk:
                    from ..prom.remote import VALUE_FIELD
                    n = 0
                    for mst, keys, cols, times, vals in mats:
                        n += self.engine.write_series_matrix(
                            db, mst, keys, cols, times,
                            {VALUE_FIELD: vals})
                    if recs:
                        n += self.engine.write_record_batch(db, recs)
                else:
                    n = self.engine.write_points(db, rows)
            except GeminiError as e:
                self._bump("write_errors")
                return 400, {"error": str(e)}, None
            except Exception as e:  # engine bug must not kill the conn
                log.exception("prom remote write failed")
                self._bump("write_errors")
                return 500, {"error": f"internal error: {e}"}, None
            self._bump("writes")
            self._bump("points_written", n)
            return 204, {}, None
        try:
            req = decode_read_request(body)
        except Exception as e:
            return 400, {"error": f"bad remote read body: {e}"}, None
        eng = self.engine
        if not hasattr(eng, "database"):
            # cluster facade: remote read runs store-side
            eng = getattr(eng, "engine", None)
            if eng is None:
                return 501, {"error": "remote read not available "
                             "on this node"}, None
        try:
            resp = handle_remote_read(eng, db, req)
        except Exception as e:
            log.exception("remote read failed")
            return 500, {"error": f"internal error: {e}"}, None
        return 200, None, encode_read_response(resp)

    def handle_prom(self, path: str, params: dict,
                    multi: dict | None = None) -> tuple[int, dict]:
        """Parse/format only — evaluation and metadata lookups live in
        PromEngine. `multi` carries repeatable params (match[])."""
        from ..promql import PromParseError
        from ..promql.engine import PromQLError

        def err(code, etype, msg):
            return code, {"status": "error", "errorType": etype,
                          "error": msg}

        if self.prom is None:
            return err(501, "unavailable",
                       "prom endpoints need a local storage engine")

        is_query = path in ("/api/v1/query", "/api/v1/query_range")
        if is_query:
            self._bump("queries")
        try:
            if path == "/api/v1/query":
                q = params.get("query")
                if not q:
                    return err(400, "bad_data", "query missing")
                t = _prom_time(params.get("time"), time.time())
                data = self.prom.query_instant(q, t)
                return 200, {"status": "success",
                             "data": {"resultType": "vector",
                                      "result": data}}
            if path == "/api/v1/query_range":
                q = params.get("query")
                if not q:
                    return err(400, "bad_data", "query missing")
                start = _prom_time(params.get("start"), None)
                end = _prom_time(params.get("end"), None)
                step = _prom_duration(params.get("step"))
                if start is None or end is None or step is None:
                    return err(400, "bad_data",
                               "start/end/step are required")
                if end < start:
                    return err(400, "bad_data", "end before start")
                data = self.prom.query_range(q, start, end, step)
                return 200, {"status": "success",
                             "data": {"resultType": "matrix",
                                      "result": data}}
            if path == "/api/v1/labels":
                return 200, {"status": "success",
                             "data": self.prom.labels()}
            if path.startswith("/api/v1/label/") and \
                    path.endswith("/values"):
                name = path[len("/api/v1/label/"):-len("/values")]
                return 200, {"status": "success",
                             "data": self.prom.label_values(name)}
            if path == "/api/v1/series":
                sels = (multi or {}).get("match[]") or (
                    [params["match[]"]] if "match[]" in params else [])
                if not sels:
                    return err(400, "bad_data", "match[] missing")
                return 200, {"status": "success",
                             "data": self.prom.series(sels)}
            return err(404, "bad_data", f"unknown prom endpoint {path}")
        except (PromParseError, PromQLError, _PromBadParam) as e:
            if is_query:
                self._bump("query_errors")
            return err(400, "bad_data", str(e))
        except Exception as e:
            if is_query:
                self._bump("query_errors")
            log.exception("prom query failed")
            return err(500, "internal", str(e))


class _PromBadParam(Exception):
    pass


def _prom_time(s: str | None, default) -> int | None:
    """Prom time param: unix seconds (float) or RFC3339 → ns."""
    if s is None:
        return int(default * 1e9) if default is not None else None
    try:
        return int(float(s) * 1e9)
    except OverflowError:
        raise _PromBadParam(f"time value out of range: {s!r}")
    except ValueError:
        pass
    from ..query.influxql import ParseError, parse_time_literal
    try:
        return parse_time_literal(s)
    except ParseError:
        raise _PromBadParam(f"invalid time value: {s!r}")


def _prom_duration(s: str | None) -> int | None:
    if not s:
        return None
    try:
        v = float(s)
        if v <= 0:
            raise _PromBadParam(f"step must be positive: {s!r}")
        return int(v * 1e9)
    except OverflowError:
        raise _PromBadParam(f"step out of range: {s!r}")
    except ValueError:
        pass
    from ..promql.parser import PromParseError, parse_duration
    try:
        return parse_duration(s)
    except PromParseError:
        raise _PromBadParam(f"invalid step: {s!r}")


def _convert_epoch(series: list, epoch: str) -> None:
    div = PRECISION_NS.get(epoch)
    if div is None or div == 1:
        return
    for s in series:
        if s.get("columns") and s["columns"][0] == "time":
            for row in s["values"]:
                row[0] = row[0] // div


class _Handler(BaseHTTPRequestHandler):
    server_ref: HttpServer = None  # type: ignore
    protocol_version = "HTTP/1.1"

    def log_message(self, fmt, *args):  # route to our logger, not stderr
        # request lines can carry URL-encoded passwords (GET /query with
        # CREATE USER, or influx u/p params) — redact before logging
        def _clean(a):
            if not isinstance(a, str):
                return a
            # redact p= BEFORE unquoting (an encoded '&'/'+' inside the
            # password would otherwise split it and leak the tail) AND
            # after (an encoded parameter NAME '%70=' only becomes 'p='
            # once unquoted)
            a = re.sub(r"([?&]p=)[^&\s]*", r"\1[REDACTED]", a)
            a = urllib.parse.unquote_plus(a)
            a = re.sub(r"([?&]p=)[^&\s]*", r"\1[REDACTED]", a)
            return _redact_passwords(a)
        log.debug("%s " + fmt, self.address_string(),
                  *(_clean(a) for a in args))

    # ---- helpers ---------------------------------------------------------

    def _params(self) -> dict:
        u = urllib.parse.urlparse(self.path)
        return {k: v[0] for k, v in
                urllib.parse.parse_qs(u.query).items()}

    def _params_multi(self) -> dict:
        u = urllib.parse.urlparse(self.path)
        return urllib.parse.parse_qs(u.query)

    def _form_params(self, params: dict) -> dict:
        """Merge an x-www-form-urlencoded POST body under the URL params
        (URL wins). Non-form bodies are ignored."""
        ctype = self.headers.get("Content-Type", "")
        body = self._body()
        if body and "application/x-www-form-urlencoded" in ctype:
            form = {k: v[0] for k, v in
                    urllib.parse.parse_qs(body.decode()).items()}
            form.update(params)
            return form
        return params

    def _path(self) -> str:
        return urllib.parse.urlparse(self.path).path

    _AUTH_OPEN = {"/ping", "/health"}

    def _auth(self):
        """Returns (ok, user). When not ok, a 401 was already sent.
        Credentials: Basic auth header or influx-style u/p params."""
        srv = self.server_ref
        if self._path() in self._AUTH_OPEN:
            return True, None
        if srv._bootstrap_only():
            # auth on, zero users: only /query is reachable, and the
            # statement gate there only passes first-admin creation
            if self._path() == "/query":
                return True, None
            self.close_connection = True
            self._reply(401, {"error": "create an admin user first"},
                        headers={"Connection": "close"})
            return False, None
        if not srv.auth_required():
            return True, None
        import base64
        u = p = None
        hdr = self.headers.get("Authorization", "")
        if hdr.startswith("Basic "):
            try:
                u, p = base64.b64decode(hdr[6:]).decode().split(":", 1)
            except Exception:
                pass
        else:
            params = self._params()
            u, p = params.get("u"), params.get("p")
            if u is None:
                # influx 1.x clients may POST u/p in the form body
                try:
                    form = self._form_params({})
                    u, p = form.get("u"), form.get("p")
                except Exception:
                    pass
        user = srv.user_store.authenticate(u or "", p or "") \
            if u is not None else None
        if user is None:
            # drain the unread body: replying without consuming it
            # desyncs HTTP/1.1 keep-alive; close to be safe
            try:
                self._body()
            except Exception:
                pass
            self.close_connection = True
            self._reply(401, {"error": "authorization required"},
                        headers={"WWW-Authenticate":
                                 'Basic realm="opengemini"',
                                 "Connection": "close"})
            return False, None
        return True, user

    def _admin_gate(self, user) -> bool:
        """403 unless auth is off or the user is admin — /debug/ctrl and
        logstore catalog mutations mirror the admin_only statement list
        (reference httpd privilege checks)."""
        srv = self.server_ref
        if not srv.auth_required() or (user is not None and user.admin):
            return True
        # drain any unread body and close: replying mid-body desyncs
        # HTTP/1.1 keep-alive (same hazard handled in _auth's 401 path)
        try:
            self._body()
        except Exception:
            pass
        self.close_connection = True
        self._reply(403, {"error": "admin privilege required"},
                    headers={"Connection": "close"})
        return False

    @staticmethod
    def _is_logstore_catalog(path: str) -> bool:
        return (path.startswith("/api/v1/repository")
                or path.startswith("/api/v1/logstream"))

    def _body(self) -> bytes:
        # cached: _auth may need form-body credentials before the route
        # handler consumes the same body
        cached = getattr(self, "_body_cache", None)
        if cached is not None:
            return cached
        ln = int(self.headers.get("Content-Length", 0) or 0)
        raw = self.rfile.read(ln) if ln else b""
        if self.headers.get("Content-Encoding") == "gzip":
            raw = gzip.decompress(raw)
        self._body_cache = raw
        return raw

    def _reply_query(self, code: int, payload: dict,
                     params: dict | None = None,
                     extra_headers: dict | None = None) -> None:
        """/query responses honor Accept (csv/msgpack) and chunked
        streaming (reference response_writer.go). ``params`` must be the
        handler's MERGED params (URL + form body) so chunked=true in a
        form-encoded POST body is honored too. ``extra_headers`` rides
        every branch (X-OG-Trace-Id of a recorded trace)."""
        if params is None:
            params = self._params()
        if code in (429, 503) and isinstance(payload, dict) \
                and "retry_after" in payload:
            # admission shed (scheduler 429 / paused 503): the body
            # carries retry_after seconds and the header mirrors it so
            # plain HTTP clients can back off without parsing JSON
            self._reply(code, payload, headers={
                "Retry-After":
                    str(max(1, int(round(payload["retry_after"])))),
                **(extra_headers or {})})
            return
        accept = self.headers.get("Accept", "")
        if code == 200 and params.get("chunked") == "true":
            from .formats import chunk_results
            try:
                chunk_size = int(params.get("chunk_size") or 10000)
            except ValueError:
                chunk_size = 10000
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Transfer-Encoding", "chunked")
            self.send_header("Access-Control-Allow-Origin", "*")
            for k, v in (extra_headers or {}).items():
                self.send_header(k, v)
            self.end_headers()
            for c in chunk_results(payload, chunk_size):
                blob = json.dumps(c).encode() + b"\n"
                self.wfile.write(f"{len(blob):x}\r\n".encode())
                self.wfile.write(blob + b"\r\n")
            self.wfile.write(b"0\r\n\r\n")
            return
        want_csv = ("application/csv" in accept
                    or "text/csv" in accept)
        from .serializer import stream_json_enabled
        if (code == 200 and stream_json_enabled()
                and "application/x-msgpack" not in accept
                and any(s.get("values")
                        for r in payload.get("results", [])
                        for s in (r.get("series") or ()))):
            # result-bearing responses stream: series entries encode
            # behind a bounded queue while this thread writes the
            # socket — the 380MB-document json.dumps stall is gone
            # (OG_STREAM_JSON=0 restores the buffered route)
            self._stream_query(payload, csv=want_csv,
                               extra_headers=extra_headers)
            return
        if code == 200 and want_csv:
            from .formats import results_to_csv
            body = results_to_csv(payload).encode()
            ctype = "text/csv"
        elif "application/x-msgpack" in accept:
            from .formats import msgpack_encode
            body = msgpack_encode(payload)
            ctype = "application/x-msgpack"
        else:
            self._reply(code, payload, headers=extra_headers)
            return
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Access-Control-Allow-Origin", "*")
        self.send_header("Content-Length", str(len(body)))
        for k, v in (extra_headers or {}).items():
            self.send_header(k, v)
        self.end_headers()
        self.wfile.write(body)

    def _stream_query(self, payload: dict, csv: bool,
                      extra_headers: dict | None = None) -> None:
        """Chunked-transfer emit of a /query result (streaming
        serialization tentpole): pieces encode on a background thread
        behind a small bounded queue while THIS thread writes the
        socket, so JSON/CSV encoding overlaps the send — and when the
        executor hands a lazy series iterable, overlaps finalize too.
        Body bytes are identical to the buffered route (golden-tested);
        only the transfer framing changes. Wall is accounted as the
        ``serialize`` query phase."""
        from ..ops import devstats
        from .serializer import (iter_results_csv, iter_results_json,
                                 stream_chunks)
        t0 = time.perf_counter_ns()
        pieces = iter_results_csv(payload) if csv else \
            iter_results_json(payload)
        self.send_response(200)
        self.send_header("Content-Type",
                         "text/csv" if csv else "application/json")
        if not csv:
            self.send_header("X-Influxdb-Version",
                             "1.8-opengemini-tpu-" + __version__)
        self.send_header("Access-Control-Allow-Origin", "*")
        self.send_header("Transfer-Encoding", "chunked")
        for k, v in (extra_headers or {}).items():
            self.send_header(k, v)
        self.end_headers()
        w = self.wfile
        for p in stream_chunks(pieces):
            if not p:
                continue
            w.write(f"{len(p):x}\r\n".encode())
            w.write(p)
            w.write(b"\r\n")
        w.write(b"0\r\n\r\n")
        devstats.bump_phase("serialize", time.perf_counter_ns() - t0)

    def _reply(self, code: int, payload: dict | None = None,
               headers: dict | None = None) -> None:
        body = (json.dumps(payload).encode() + b"\n") if payload is not None \
            else b""
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("X-Influxdb-Version", "1.8-opengemini-tpu-"
                         + __version__)
        # the OPTIONS preflight advertises CORS; actual responses must
        # carry the origin header too or browsers block the body
        self.send_header("Access-Control-Allow-Origin", "*")
        self.send_header("Content-Length", str(len(body)))
        for k, v in (headers or {}).items():
            self.send_header(k, v)
        self.end_headers()
        if body:
            self.wfile.write(body)

    # ---- methods ---------------------------------------------------------

    def do_GET(self):
        t0 = time.perf_counter_ns()
        try:
            self._do_GET()
        finally:
            _observe(HTTP_HIST,
                     f"route_{_route_class(self._path())}_ms",
                     (time.perf_counter_ns() - t0) / 1e6)

    def do_POST(self):
        t0 = time.perf_counter_ns()
        try:
            self._do_POST()
        finally:
            _observe(HTTP_HIST,
                     f"route_{_route_class(self._path())}_ms",
                     (time.perf_counter_ns() - t0) / 1e6)

    def _do_GET(self):
        srv = self.server_ref
        path = self._path()
        ok, user = self._auth()
        if not ok:
            return
        if path in ("/ping", "/status"):
            self._reply(204)
            return
        if path == "/health":
            self._reply(200, {"name": "opengemini-tpu", "status": "pass",
                              "version": __version__})
            return
        if path == "/metrics":
            # Prometheus text exposition of the internal collectors
            # (reference serveMetrics); ?format=openmetrics (or an
            # OpenMetrics Accept header) switches to the exemplar-
            # bearing OpenMetrics 1.0 dialect
            om = (self._params().get("format") == "openmetrics"
                  or "application/openmetrics-text"
                  in (self.headers.get("Accept") or ""))
            fmt = "openmetrics" if om else "prometheus"
            body = srv.metrics_text(fmt).encode()
            self.send_response(200)
            self.send_header("Content-Type",
                             "application/openmetrics-text; "
                             "version=1.0.0; charset=utf-8" if om else
                             "text/plain; version=0.0.4; charset=utf-8")
            self.send_header("Access-Control-Allow-Origin", "*")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
            return
        if path == "/debug/vars":
            # httpd counters stay top-level (compat); the device plane,
            # cache-tier, and per-phase groups nest below so an
            # operator can read transfer volumes, DeviceBlockCache
            # hit/miss/eviction, and the executor phase split without
            # attaching EXPLAIN ANALYZE
            from ..ops.devstats import device_collector, phase_collector
            from ..storage.wal import recovery_summary
            from ..utils.stats import (device_decode_collector,
                                       devicecache_collector,
                                       devicefault_collector,
                                       flight_collector,
                                       hbm_collector,
                                       histogram_summaries,
                                       resultcache_collector,
                                       scheduler_collector,
                                       wal_collector)
            out = dict(srv.stats)
            out["device"] = device_collector()
            out["devicecache"] = devicecache_collector()
            out["device_decode"] = device_decode_collector()
            out["query_phases"] = phase_collector()
            out["scheduler"] = scheduler_collector()
            out["hbm"] = hbm_collector()
            out["resultcache"] = resultcache_collector()
            out["devicefault"] = devicefault_collector()
            # compile-cache + transfer audit layer (ops/compileaudit):
            # per-kernel compile log with shape signatures, the jaxpr
            # audits, and the per-site transfer manifest with its
            # ledger cross-check counters
            from ..ops.compileaudit import (audit_snapshot,
                                            manifest_snapshot)
            out["compileaudit"] = audit_snapshot()
            out["xfer"] = manifest_snapshot()
            out["wal"] = wal_collector()
            out["flight"] = flight_collector()
            # startup recovery report: cumulative replay/salvage/
            # quarantine counters plus the recent per-shard reports
            # ring — what the last restart actually recovered
            out["recovery"] = recovery_summary()
            # p50/p95/p99 summaries of every registered histogram
            # (query/write latency, queue wait, phases, D2H pulls)
            out["latency"] = histogram_summaries()
            out["slow_log"] = list(srv.slow_log)
            self._reply(200, out)
            return
        if path == "/debug/requests":
            # flight-recorder summary: the last N completed traces
            # plus the always-kept slow/error ring (query text is
            # password-redacted before it ever reaches a record)
            self._reply(200, tracing.recorder().summaries())
            return
        if path == "/debug/trace":
            p = self._params()
            tid = p.get("id", "")
            rec = tracing.recorder().get(tid) if tid else None
            if rec is None:
                self._reply(404, {"error": f"no trace {tid!r} in the "
                                  "flight recorder (see "
                                  "/debug/requests)"})
                return
            if p.get("format") == "chrome":
                # Chrome trace-event / Perfetto timeline export
                body = tracing.chrome_json(rec).encode()
                self.send_response(200)
                self.send_header("Content-Type", "application/json")
                self.send_header("Access-Control-Allow-Origin", "*")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)
                return
            out = rec.summary()
            if rec.root is not None:
                out["tree"] = rec.root.render()
                out["spans"] = rec.root.to_dict()
            self._reply(200, out)
            return
        if path == "/debug/device":
            # device resource observatory: HBM ledger (per-tier bytes,
            # high-watermarks, pressure events), exact cross-check
            # against the caches, backend reconciliation, and the
            # utilization timeline ring; ?format=chrome exports the
            # timeline as a Perfetto counter track that lays next to
            # the /debug/trace span export
            from ..ops import hbm as _hbm
            p = self._params()
            smp = _hbm.sampler()
            samples = smp.samples()
            if not samples:
                # sampler disabled or not yet ticked: take one sample
                # on demand so the endpoint is never empty (NOT
                # recorded — a read must not fabricate timeline
                # entries at request times)
                samples = [smp.sample_once(record=False)]
            if p.get("format") == "chrome":
                try:
                    base_ns = int(p["base_ns"]) if "base_ns" in p \
                        else None
                except ValueError:
                    base_ns = None
                body = json.dumps({
                    "traceEvents": _hbm.chrome_counter_events(
                        samples, base_ns=base_ns),
                    "displayTimeUnit": "ms"}).encode()
                self.send_response(200)
                self.send_header("Content-Type", "application/json")
                self.send_header("Access-Control-Allow-Origin", "*")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)
                return
            self._reply(200, {
                "ledger": _hbm.LEDGER.snapshot(),
                "cross_check": _hbm.cross_check(),
                "reconcile": _hbm.reconcile(),
                "timeline": {
                    "sampler_running": smp.running(),
                    "interval_ms": float(knobs.get("OG_DEVUTIL_MS")),
                    "samples": samples}})
            return
        if path == "/debug/scheduler":
            # serving-runtime view: admission counters/gauges plus the
            # cost-model calibration state (per-class learned bias,
            # recent estimate-vs-actual records, error-histogram tails)
            from ..query import scheduler as _qs
            sch = _qs.get_scheduler()
            self._reply(200, {"enabled": _qs.enabled(),
                              "scheduler": sch.snapshot(),
                              "tenants": sch.tenants_snapshot(),
                              "calibration":
                                  sch.calibration_snapshot()})
            return
        if path == "/debug/ctrl":
            if not self._admin_gate(user):
                return
            p = self._params()
            code, payload = srv.sysctrl.handle(p.pop("mod", ""), p)
            self._reply(code, payload)
            return
        if path == "/query":
            meta: dict = {}
            code, payload = srv.handle_query(
                self._params(), user=user, headers=self.headers,
                meta=meta)
            self._reply_query(code, payload,
                              extra_headers=self._trace_headers(meta))
            return
        if self._is_logstore(path):
            code, payload = srv.handle_logstore("GET", path,
                                                self._params(), b"")
            self._reply(code, payload)
            return
        if path.startswith("/api/v1/"):
            code, payload = srv.handle_prom(path, self._params(),
                                            self._params_multi())
            self._reply(code, payload)
            return
        self._reply(404, {"error": f"not found: {path}"})

    @staticmethod
    def _is_logstore(path: str) -> bool:
        return (path.startswith("/api/v1/repository")
                or path.startswith("/api/v1/logstream")
                or path.startswith("/repo/"))

    @staticmethod
    def _trace_headers(meta: dict) -> dict | None:
        """X-OG-Trace-Id response header when the request landed in
        the flight recorder (sampled, or retained as slow/failed)."""
        if meta.get("trace_id"):
            return {"X-OG-Trace-Id": meta["trace_id"]}
        return None

    def _do_POST(self):
        srv = self.server_ref
        path = self._path()
        ok, user = self._auth()
        if not ok:
            return
        if path == "/write":
            try:
                body = self._body()
            except Exception as e:
                self._reply(400, {"error": f"bad body: {e}"})
                return
            wmeta: dict = {}
            code, payload = srv.handle_write(self._params(), body,
                                             user=user,
                                             headers=self.headers,
                                             meta=wmeta)
            self._reply(code, payload if code != 204 else None,
                        headers=self._trace_headers(wmeta))
            return
        if path == "/query":
            try:
                params = self._form_params(self._params())
            except Exception as e:  # bad gzip / non-utf8 form body
                self._reply(400, {"error": f"bad body: {e}"})
                return
            meta: dict = {}
            code, payload = srv.handle_query(params, user=user,
                                             headers=self.headers,
                                             meta=meta)
            self._reply_query(code, payload, params=params,
                              extra_headers=self._trace_headers(meta))
            return
        if path == "/debug/ctrl":
            if not self._admin_gate(user):
                return
            p = self._params()
            code, payload = srv.sysctrl.handle(p.pop("mod", ""), p)
            self._reply(code, payload)
            return
        if path == "/failpoint":
            # direct failpoint toggle endpoint (reference handler.go
            # POST /failpoint) — a JSON front-end over the same
            # syscontrol handler as /debug/ctrl?mod=failpoint, so
            # validation and error text cannot drift between the two
            if not self._admin_gate(user):
                return
            try:
                doc = json.loads(self._body() or b"{}")
            except Exception as e:
                self._reply(400, {"error": f"bad body: {e}"})
                return
            params = {"point": doc.get("name", ""),
                      "switchon": str(doc.get("enable", True)).lower(),
                      "action": doc.get("action", "error")}
            for k in ("arg", "maxhits", "pct"):
                if doc.get(k) is not None:
                    params[k] = doc[k]
            code, payload = srv.sysctrl.handle("failpoint", params)
            if code == 200 and params["point"]:
                from ..utils import failpoint as fp
                payload = dict(payload, ok=True,
                               failpoints=fp.list_points())
            self._reply(code, payload)
            return
        if self._is_logstore(path):
            if self._is_logstore_catalog(path) \
                    and not self._admin_gate(user):
                return
            try:
                body = self._body()
            except Exception as e:
                self._reply(400, {"error": f"bad body: {e}"})
                return
            code, payload = srv.handle_logstore("POST", path,
                                                self._params(), body)
            self._reply(code, payload)
            return
        if path == "/api/v2/query":
            try:
                body = self._body()
            except Exception as e:
                self._reply(400, {"error": f"bad body: {e}"})
                return
            code, payload, csv_text = srv.handle_flux(
                body, self.headers.get("Content-Type", ""), user=user,
                headers=self.headers)
            if csv_text is not None:
                data = csv_text.encode()
                self.send_response(code)
                self.send_header("Content-Type",
                                 "text/csv; charset=utf-8")
                self.send_header("Access-Control-Allow-Origin", "*")
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)
                return
            hdrs = None
            if code in (429, 503) and isinstance(payload, dict) \
                    and "retry_after" in payload:
                # admission sheds mirror the wait hint in the header,
                # same as /query (plain clients back off without
                # parsing the body)
                hdrs = {"Retry-After": str(max(1, int(round(
                    payload["retry_after"]))))}
            self._reply(code, payload, headers=hdrs)
            return
        if path in ("/api/v1/prom/write", "/api/v1/prom/read"):
            try:
                body = self._body()
            except Exception as e:
                self._reply(400, {"error": f"bad body: {e}"})
                return
            code, payload, raw = srv.handle_prom_remote(
                path, self._params(), body, user=user)
            if raw is not None:
                self.send_response(code)
                self.send_header("Content-Type", "application/x-protobuf")
                self.send_header("Content-Encoding", "snappy")
                self.send_header("Content-Length", str(len(raw)))
                self.end_headers()
                self.wfile.write(raw)
                return
            self._reply(code, payload if code != 204 else None)
            return
        if path.startswith("/api/v1/"):
            try:
                params = self._form_params(self._params())
            except Exception as e:
                self._reply(400, {"error": f"bad body: {e}"})
                return
            code, payload = srv.handle_prom(path, params,
                                            self._params_multi())
            self._reply(code, payload)
            return
        self._reply(404, {"error": f"not found: {path}"})

    def do_DELETE(self):
        path = self._path()
        ok, user = self._auth()
        if not ok:
            return
        if self._is_logstore(path):
            if not self._admin_gate(user):
                return
            code, payload = self.server_ref.handle_logstore(
                "DELETE", path, self._params(), b"")
            self._reply(code, payload)
            return
        self._reply(404, {"error": f"not found: {path}"})

    def do_PUT(self):
        path = self._path()
        ok, user = self._auth()
        if not ok:
            return
        if self._is_logstore(path):
            if not self._admin_gate(user):
                return
            try:
                body = self._body()
            except Exception as e:
                self._reply(400, {"error": f"bad body: {e}"})
                return
            code, payload = self.server_ref.handle_logstore(
                "PUT", path, self._params(), body)
            self._reply(code, payload)
            return
        self._reply(404, {"error": f"not found: {path}"})

    def do_HEAD(self):
        if self._path() in ("/ping", "/status"):
            self._reply(204)
        else:
            self._reply(404)

    def do_OPTIONS(self):
        """CORS preflight (reference serveOptions on /query and
        /write)."""
        self.send_response(204)
        self.send_header("Access-Control-Allow-Origin", "*")
        self.send_header("Access-Control-Allow-Methods",
                         "GET, POST, HEAD, OPTIONS, DELETE, PUT")
        self.send_header("Access-Control-Allow-Headers",
                         "Accept, Authorization, Content-Type, "
                         "X-Requested-With")
        self.send_header("Content-Length", "0")
        self.end_headers()


def main():
    import argparse
    from ..storage import Engine, EngineOptions

    ap = argparse.ArgumentParser(description="opengemini-tpu single node")
    ap.add_argument("--data", default="./data")
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=8086)
    ap.add_argument("--wal-sync", action="store_true")
    args = ap.parse_args()
    eng = Engine(args.data, EngineOptions(wal_sync=args.wal_sync))
    srv = HttpServer(eng, args.host, args.port)
    srv.start()
    log.info("ts-server (single node) ready")

    # graceful shutdown: SIGTERM must flush buffered WAL writes before
    # exit (reference app/command.go signal handling) — without this a
    # plain `kill` loses the unsynced WAL tail
    import signal

    def _term(_sig, _frm):
        raise SystemExit(0)

    signal.signal(signal.SIGTERM, _term)
    try:
        while True:
            time.sleep(3600)
    except (KeyboardInterrupt, SystemExit):
        pass
    finally:
        srv.stop()
        eng.close()


if __name__ == "__main__":
    main()
