"""Streaming /query result serialization.

Role of the reference's ResponseWriter emit path
(lib/util/lifted/influx/httpd/response_writer.go): the default JSON
route built ONE giant document string (`json.dumps` of an 11.5M-cell
result is ~380MB and seconds of wall) while the socket sat idle, and
the whole document lived in memory at once. Here the envelope streams
per SERIES ENTRY:

  * ``iter_results_json`` yields byte pieces whose concatenation is
    BYTE-IDENTICAL to ``json.dumps(payload).encode()`` (golden-tested)
    — each piece is at most one series entry plus envelope glue, so
    peak memory is one entry, not the document;
  * ``stream_chunks`` runs the encoder on a background thread behind a
    small bounded queue (OG_STREAM_QUEUE, default 8 pieces), so JSON
    encoding of entry k overlaps the socket write of entry k-1 — and
    when the ``series`` value is a lazy iterable (finalize-pool chunk
    emission), serialization overlaps result finalization itself;
  * ``iter_results_csv`` is the same streaming shape for the CSV
    Accept route (concatenation == formats.results_to_csv).

The HTTP layer gates the route behind OG_STREAM_JSON (default on) and
accounts the wall as the ``serialize`` query phase (ops/devstats), so
BENCH and /debug/vars attribute emit cost separately from finalize.
"""

from __future__ import annotations

import json
import threading
from typing import Iterable, Iterator

from ..utils import knobs

_COALESCE = 256 * 1024          # target piece size handed to the socket


def stream_queue_depth() -> int:
    return max(1, int(knobs.get("OG_STREAM_QUEUE")))


def stream_json_enabled() -> bool:
    return bool(knobs.get("OG_STREAM_JSON"))


# -------------------------------------------------------------- encoder

def _iter_value(o) -> Iterator[bytes]:
    """Stream one JSON value; dicts/lists recurse so a huge ``series``
    list (or any nested row payload) never materializes as one string.
    Scalar leaves and ROWS encode with json.dumps — separators match
    its defaults (", ", ": ") so the concatenation is byte-identical."""
    if isinstance(o, dict):
        if not o or not all(isinstance(k, str) for k in o):
            # non-str keys take json.dumps' coercion rules — rare and
            # small (never the series envelope); emit in one piece
            yield json.dumps(o).encode()
            return
        yield b"{"
        first = True
        for k, v in o.items():
            head = b"" if first else b", "
            first = False
            yield head + json.dumps(k).encode() + b": "
            if isinstance(v, dict) or _is_stream_list(k, v):
                yield from _iter_value(v)
            elif k == "values" and isinstance(v, list):
                yield from _iter_rows(v)
            else:
                yield json.dumps(v).encode()
        yield b"}"
        return
    if isinstance(o, (list, tuple)) or _is_lazy_iter(o):
        yield b"["
        first = True
        for item in o:
            if not first:
                yield b", "
            first = False
            if isinstance(item, dict):
                yield from _iter_value(item)
            else:
                yield json.dumps(item).encode()
        yield b"]"
        return
    yield json.dumps(o).encode()


_ROWS_CHUNK = 4096


def _iter_rows(rows: list) -> Iterator[bytes]:
    """Chunked emit of one entry's row list: json.dumps per ~4K-row
    slice, concatenation byte-identical to json.dumps(rows) (slice
    bodies join with the same ", " separator the C encoder uses). A
    single-series heavy result used to encode as ONE dumps piece — at
    11.5M rows that is a ~380MB resident string, the exact whole-
    document problem the streaming envelope was built to kill, one
    level down. Per-row dumps calls would drown the pipe instead;
    slices keep the C encoder's throughput."""
    if len(rows) <= _ROWS_CHUNK:
        yield json.dumps(rows).encode()
        return
    yield b"["
    first = True
    for lo in range(0, len(rows), _ROWS_CHUNK):
        piece = json.dumps(rows[lo:lo + _ROWS_CHUNK]).encode()
        if not first:
            yield b", "
        first = False
        yield piece[1:-1]
    yield b"]"


def _is_stream_list(key: str, v) -> bool:
    """Container values worth streaming element-wise: the results /
    series envelopes (one series entry per piece). Row lists inside an
    entry stay on json.dumps — per-row pieces would drown the pipe in
    tiny yields."""
    return key in ("results", "series") and (
        isinstance(v, (list, tuple)) or _is_lazy_iter(v))


def _is_lazy_iter(v) -> bool:
    return (not isinstance(v, (str, bytes, dict, list, tuple))
            and hasattr(v, "__iter__"))


def iter_results_json(payload: dict,
                      tail: bytes = b"\n") -> Iterator[bytes]:
    """Byte pieces of the /query JSON body, coalesced to ~256KB for
    the socket; b"".join(...) == json.dumps(payload).encode() + tail.
    A series entry is encoded only when the iterator reaches it, so a
    lazy ``series`` iterable streams as it is produced."""
    buf = bytearray()
    for piece in _iter_value(payload):
        buf += piece
        if len(buf) >= _COALESCE:
            yield bytes(buf)
            buf.clear()
    buf += tail
    if buf:
        yield bytes(buf)


# ------------------------------------------------------------------ csv

def iter_results_csv(payload: dict) -> Iterator[bytes]:
    """Streaming twin of formats.results_to_csv: concatenation is
    byte-identical, pieces are bounded (one row block per series)."""
    from .formats import _csv_escape
    buf = bytearray()
    any_out = False
    for res in payload.get("results", []):
        for s in res.get("series", []):
            any_out = True
            cols = s.get("columns", [])
            buf += (",".join(["name", "tags"]
                             + [_csv_escape(c) for c in cols])
                    + "\n").encode()
            tags = ",".join(f"{k}={v}" for k, v in
                            sorted(s.get("tags", {}).items()))
            head = _csv_escape(s.get("name", "")) + "," \
                + _csv_escape(tags)
            for row in s.get("values", []):
                cells = [head]
                cells += ["" if v is None else
                          (repr(v) if isinstance(v, float)
                           else _csv_escape(v))
                          for v in row]
                buf += (",".join(cells) + "\n").encode()
                if len(buf) >= _COALESCE:
                    yield bytes(buf)
                    buf.clear()
        if "error" in res:
            any_out = True
            buf += (f"error,{_csv_escape(res['error'])}" + "\n").encode()
    if not any_out:
        # results_to_csv returns "" for empty output (no trailing \n)
        if buf:
            yield bytes(buf)
        return
    if buf:
        yield bytes(buf)


# ------------------------------------------------- bounded-queue overlap

_END = object()


def stream_chunks(pieces: Iterable[bytes],
                  depth: int | None = None) -> Iterator[bytes]:
    """Re-yield ``pieces`` produced on a BACKGROUND thread through a
    bounded queue: the producer (JSON/CSV encoding — and, behind a
    lazy series iterable, finalize itself) runs ahead of the consumer
    (socket writes) by at most ``depth`` pieces. An encoder exception
    re-raises in the consumer after the in-flight pieces drain.

    Abandonment-safe: when the consumer drops the generator mid-stream
    (client disconnect → BrokenPipeError in the socket writer), the
    ``finally`` sets the stop flag and drains the queue, so the
    producer's bounded put can never block forever holding the encoded
    document alive (the leak would be one thread + up to the full
    result per aborted request)."""
    import queue
    q: "queue.Queue" = queue.Queue(maxsize=depth or stream_queue_depth())
    err: list[BaseException] = []
    stop = threading.Event()

    def _put(item) -> bool:
        while not stop.is_set():
            try:
                q.put(item, timeout=0.25)
                return True
            except queue.Full:
                continue
        return False

    def produce():
        try:
            for p in pieces:
                if not _put(p):
                    return
        except BaseException as e:   # noqa: BLE001 — re-raised below
            err.append(e)
        finally:
            _put(_END)

    t = threading.Thread(target=produce, daemon=True,
                         name="og-serialize")
    t.start()
    try:
        while True:
            p = q.get()
            if p is _END:
                break
            yield p
    finally:
        stop.set()
        while True:               # release a blocked producer put
            try:
                q.get_nowait()
            except queue.Empty:
                break
        t.join(timeout=5)
    if err:
        raise err[0]
