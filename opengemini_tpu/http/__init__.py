from .server import HttpServer
