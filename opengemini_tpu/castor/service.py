"""Castor service client (role of reference services/castor/service.go:32-343
+ client.go: connection pool over worker addresses, retries with
failover, result dispatch).

With no workers configured the service runs the algorithms in-process
(single-node deployments; the reference requires a worker fleet, we keep
the same flight contract but degrade gracefully).
"""

from __future__ import annotations

import itertools
import json
import threading
import uuid

import numpy as np

from ..utils import get_logger
from ..utils.errors import GeminiError
from . import algorithms

log = get_logger(__name__)


class CastorService:
    def __init__(self, worker_locations: list[str] | None = None,
                 max_retries: int = 2):
        self.locations = list(worker_locations or [])
        self.max_retries = max_retries
        self._clients: dict[str, object] = {}
        self._rr = itertools.count()
        self._lock = threading.Lock()
        self._local_models: dict[str, dict] = {}   # in-proc fallback cache
        self.tasks = 0
        self.failures = 0

    # -------------------------------------------------------------- pool

    def _client(self, loc: str):
        import pyarrow.flight as flight
        with self._lock:
            c = self._clients.get(loc)
            if c is None:
                c = self._clients[loc] = flight.FlightClient(loc)
            return c

    def _pick_locations(self) -> list[str]:
        """Round-robin start point, then failover through the rest."""
        if not self.locations:
            return []
        start = next(self._rr) % len(self.locations)
        return self.locations[start:] + self.locations[:start]

    def close(self) -> None:
        with self._lock:
            for c in self._clients.values():
                c.close()
            self._clients.clear()

    # ---------------------------------------------------------------- api

    def detect(self, times, values, algo: str, config: dict | None = None,
               task: str = "detect", model_id: str | None = None):
        """Returns (times, values, levels) of anomalous points."""
        times = np.asarray(times, dtype=np.int64)
        values = np.asarray(values, dtype=np.float64)
        with self._lock:
            self.tasks += 1
        if not self.locations:
            model = None
            if task == "fit_detect":
                model = algorithms.fit(times, values, algo, config)
                if model_id:
                    with self._lock:
                        self._local_models[model_id] = model
            elif model_id:
                with self._lock:
                    model = self._local_models.get(model_id)
            mask = algorithms.detect(times, values, algo, config, model)
            idx = np.nonzero(mask)[0]
            return times[idx], values[idx], np.ones(len(idx))
        table = self._run_remote(times, values, algo, config, task,
                                 model_id)
        return (table.column("time").to_numpy(zero_copy_only=False),
                table.column(table.column_names[1])
                     .to_numpy(zero_copy_only=False),
                table.column("anomaly_level")
                     .to_numpy(zero_copy_only=False))

    def fit(self, times, values, algo: str, config: dict | None = None,
            model_id: str | None = None) -> dict:
        times = np.asarray(times, dtype=np.int64)
        values = np.asarray(values, dtype=np.float64)
        with self._lock:
            self.tasks += 1
        if not self.locations:
            model = algorithms.fit(times, values, algo, config)
            if model_id:
                with self._lock:
                    self._local_models[model_id] = model
            return model
        table = self._run_remote(times, values, algo, config, "fit",
                                 model_id)
        return json.loads(table.column("model")[0].as_py())

    # ------------------------------------------------------------- remote

    def _run_remote(self, times, values, algo, config, task, model_id):
        import pyarrow as pa
        import pyarrow.flight as flight
        cmd = {"id": uuid.uuid4().hex, "type": task, "algo": algo,
               "config": config or {}}
        if model_id:
            cmd["model_id"] = model_id
        body = pa.table({"time": pa.array(times, type=pa.int64()),
                         "value": pa.array(values, type=pa.float64())})
        last_err: Exception | None = None
        tried = 0
        for loc in self._pick_locations():
            if tried > self.max_retries:
                break
            tried += 1
            try:
                client = self._client(loc)
                desc = flight.FlightDescriptor.for_command(
                    json.dumps(cmd).encode())
                writer, _ = client.do_put(desc, body.schema)
                writer.write_table(body)
                writer.close()
                reader = client.do_get(flight.Ticket(cmd["id"].encode()))
                return reader.read_all()
            except Exception as e:
                last_err = e
                with self._lock:
                    self.failures += 1
                log.warning("castor worker %s failed: %s", loc, e)
                # pop but do NOT close: another thread may be mid-call on
                # the shared client; the dropped reference closes on GC
                with self._lock:
                    self._clients.pop(loc, None)
        raise GeminiError(f"all castor workers failed: {last_err}")

    def stats(self) -> dict[str, int]:
        return {"tasks": self.tasks, "failures": self.failures,
                "workers": len(self.locations)}
