"""Castor Python worker (role of reference python/ts-udf/server/server.py
+ handler.py: a Flight endpoint that receives series data, runs
detect/fit, and hands results back; fitted models are cached in-process
keyed by model id).

Protocol (mirrors the reference's flight usage):
  DoPut  descriptor command = JSON {"id", "type": "detect"|"fit"|
         "fit_detect", "algo", "config"?, "model_id"?}
         body = arrow table with "time" (int64 ns) + one value column.
  DoGet  ticket = the same id → result table:
         detect: rows (time, value, anomaly_level) for flagged points
         fit:    single-row table with the serialized model JSON.
"""

from __future__ import annotations

import json
import threading

import numpy as np

from ..utils import get_logger
from . import algorithms

log = get_logger(__name__)

try:
    import pyarrow as pa
    import pyarrow.flight as flight
    HAVE_FLIGHT = True
except Exception:                                    # pragma: no cover
    pa = flight = None
    HAVE_FLIGHT = False


class CastorWorker((flight.FlightServerBase if HAVE_FLIGHT else object)):
    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 model_cache_size: int = 256,
                 result_buffer_size: int = 4096):
        super().__init__(f"grpc://{host}:{port}")
        self.host = host
        self.results: dict[str, object] = {}
        self.models: dict[str, dict] = {}
        self.model_cache_size = max(1, model_cache_size)
        self.result_buffer_size = max(1, result_buffer_size)
        self.tasks_done = 0
        self._lock = threading.Lock()
        self._serve_thread: threading.Thread | None = None

    @property
    def location(self) -> str:
        return f"grpc://{self.host}:{self.port}"

    # ---------------------------------------------------------- flight rpc

    def do_put(self, context, descriptor, reader, writer):
        cmd = json.loads(descriptor.command.decode())
        table = reader.read_all()
        try:
            result = self._run(cmd, table)
        except Exception as e:
            log.warning("castor task %s failed: %s", cmd.get("id"), e)
            result = e
        with self._lock:
            # bound the result buffer: an orphaned result (client died
            # between DoPut and DoGet, or failed over to another worker)
            # must not leak its arrow table forever
            while len(self.results) >= self.result_buffer_size:
                self.results.pop(next(iter(self.results)))
            self.results[cmd["id"]] = result
            self.tasks_done += 1

    def do_get(self, context, ticket):
        with self._lock:
            result = self.results.pop(ticket.ticket.decode(), None)
        if result is None:
            raise flight.FlightServerError("unknown task id")
        if isinstance(result, Exception):
            raise flight.FlightServerError(f"task failed: {result}")
        return flight.RecordBatchStream(result)

    # ----------------------------------------------------------- task exec

    def _run(self, cmd: dict, table):
        task = cmd.get("type", "detect")
        algo = cmd["algo"]
        config = cmd.get("config") or {}
        names = [n for n in table.column_names if n != "time"]
        if not names:
            raise ValueError("no value column")
        times = table.column("time").to_numpy(zero_copy_only=False)
        values = table.column(names[0]).to_numpy(zero_copy_only=False)

        if task == "fit":
            model = algorithms.fit(times, values, algo, config)
            self._store_model(cmd.get("model_id") or cmd["id"], model)
            return pa.table({"model": pa.array([json.dumps(model)])})

        model = None
        if task == "fit_detect":
            model = algorithms.fit(times, values, algo, config)
            self._store_model(cmd.get("model_id") or cmd["id"], model)
        elif cmd.get("model_id"):
            with self._lock:
                model = self.models.get(cmd["model_id"])
        mask = algorithms.detect(times, values, algo, config, model)
        idx = np.nonzero(mask)[0]
        return pa.table({
            "time": pa.array(times[idx], type=pa.int64()),
            names[0]: pa.array(values[idx], type=pa.float64()),
            "anomaly_level": pa.array(np.ones(len(idx)), type=pa.float64()),
        })

    def _store_model(self, key: str, model: dict) -> None:
        with self._lock:
            if len(self.models) >= self.model_cache_size:
                self.models.pop(next(iter(self.models)))
            self.models[key] = model

    # ----------------------------------------------------------- lifecycle

    def start(self) -> None:
        self._serve_thread = threading.Thread(target=self.serve,
                                              name="castor-worker",
                                              daemon=True)
        self._serve_thread.start()
        log.info("castor worker at %s", self.location)

    def stop(self) -> None:
        self.shutdown()
        if self._serve_thread is not None:
            self._serve_thread.join(timeout=5)
            self._serve_thread = None
