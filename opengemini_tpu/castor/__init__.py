"""Castor AI layer (role of reference services/castor + python/ts-udf):
anomaly detection / model fit via Python workers over Arrow Flight, with
an in-process fallback so single-node deployments need no worker fleet.
"""

from .algorithms import ALGORITHMS, detect, fit
from .service import CastorService
from .worker import CastorWorker

__all__ = ["ALGORITHMS", "detect", "fit", "CastorService", "CastorWorker"]
