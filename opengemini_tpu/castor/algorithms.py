"""Castor detection/fit algorithms (role of reference
python/ts-udf/server/{detect,fit}.py — ThresholdAD / ValueChangeAD /
DIFFERENTIATEAD / IncrementalAD families).

Pure-numpy detectors shared by the flight worker and the in-process
fallback. Each detector maps (times, values, config, model?) → bool
anomaly mask; ``fit`` produces a model dict that ``detect`` can reuse
(the reference caches fitted models in the worker keyed by the query's
model id; same contract here).
"""

from __future__ import annotations

import numpy as np

from ..utils.errors import GeminiError


def _cfg(config: dict | None, key: str, default: float) -> float:
    if not config or key not in config:
        return default
    return float(config[key])


# ------------------------------------------------------------- detectors

def _threshold(times, values, config, model):
    upper = _cfg(config, "upper", np.inf)
    lower = _cfg(config, "lower", -np.inf)
    return (values > upper) | (values < lower)


def _ksigma(times, values, config, model):
    k = _cfg(config, "k", 3.0)
    if model and "mean" in model:
        mean, std = model["mean"], model["std"]
    else:
        mean, std = float(np.mean(values)), float(np.std(values))
    if std == 0.0:
        return np.zeros(len(values), dtype=bool)
    return np.abs(values - mean) > k * std


def _diff(times, values, config, model):
    """ValueChangeAD / DIFFERENTIATEAD analog: anomalous step changes —
    |Δv| beyond k·σ(Δv) (or an absolute delta if configured)."""
    if len(values) < 2:
        return np.zeros(len(values), dtype=bool)
    d = np.diff(values)
    delta = config.get("delta") if config else None
    if delta is not None:
        hit = np.abs(d) > float(delta)
    else:
        k = _cfg(config, "k", 3.0)
        std = model["diff_std"] if model and "diff_std" in model \
            else float(np.std(d))
        if std == 0.0:
            return np.zeros(len(values), dtype=bool)
        hit = np.abs(d) > k * std
    out = np.zeros(len(values), dtype=bool)
    out[1:] = hit
    return out


def _iqr(times, values, config, model):
    k = _cfg(config, "k", 1.5)
    if model and "q1" in model:
        q1, q3 = model["q1"], model["q3"]
    else:
        q1, q3 = np.percentile(values, [25, 75])
    iqr = q3 - q1
    return (values < q1 - k * iqr) | (values > q3 + k * iqr)


def _incremental(times, values, config, model):
    """IncrementalAD analog: rolling-window mean/std, flag points that
    deviate k·σ from the trailing window (no lookahead)."""
    k = _cfg(config, "k", 3.0)
    w = int(_cfg(config, "window", 20))
    n = len(values)
    out = np.zeros(n, dtype=bool)
    if n <= 2:
        return out
    csum = np.concatenate([[0.0], np.cumsum(values)])
    csq = np.concatenate([[0.0], np.cumsum(values * values)])
    idx = np.arange(n)
    lo = np.maximum(idx - w, 0)
    cnt = idx - lo
    ok = cnt >= 2
    s = csum[idx] - csum[lo]
    sq = csq[idx] - csq[lo]
    with np.errstate(invalid="ignore", divide="ignore"):
        mean = s / cnt
        var = np.maximum(sq / cnt - mean * mean, 0.0)
        std = np.sqrt(var)
        dev = np.abs(values - mean)
        out[ok] = dev[ok] > k * np.where(std[ok] > 0, std[ok], np.inf)
    return out


ALGORITHMS = {
    "threshold": _threshold,
    "ksigma": _ksigma,
    "diff": _diff,
    "iqr": _iqr,
    "incremental": _incremental,
}


# ------------------------------------------------------------ public api

def detect(times: np.ndarray, values: np.ndarray, algo: str,
           config: dict | None = None,
           model: dict | None = None) -> np.ndarray:
    fn = ALGORITHMS.get(algo)
    if fn is None:
        raise GeminiError(f"unknown castor algorithm: {algo}")
    values = np.asarray(values, dtype=np.float64)
    if len(values) == 0:
        return np.zeros(0, dtype=bool)
    return fn(np.asarray(times), values, config or {}, model)


def fit(times: np.ndarray, values: np.ndarray, algo: str,
        config: dict | None = None) -> dict:
    """Train a model for later detect calls (reference fit.py)."""
    if algo not in ALGORITHMS:
        raise GeminiError(f"unknown castor algorithm: {algo}")
    values = np.asarray(values, dtype=np.float64)
    model: dict = {"algo": algo, "n": int(len(values))}
    if len(values):
        model.update(mean=float(np.mean(values)),
                     std=float(np.std(values)))
        q1, q3 = np.percentile(values, [25, 75])
        model.update(q1=float(q1), q3=float(q3))
    if len(values) > 1:
        model["diff_std"] = float(np.std(np.diff(values)))
    return model
