"""In-memory write buffer (role of reference engine/mutable/table.go
MemTable + ts_table.go).

Per (measurement, sid) chunked column builders — appends go to python lists
of small numpy chunks, so repeated writes are O(1) amortized (no
concatenate-per-append); finalize() materializes sorted Records per series
for flush or query.
"""

from __future__ import annotations

import threading

import numpy as np

from ..record import ColVal, DataType, Field, Record, Schema
from ..utils.errors import ErrTypeConflict

_FIELD_TYPE = {
    float: DataType.FLOAT,
    int: DataType.INTEGER,
    bool: DataType.BOOLEAN,
    str: DataType.STRING,
}


def field_type_of(v) -> DataType:
    # bool is a subclass of int — check it first
    if isinstance(v, bool):
        return DataType.BOOLEAN
    if isinstance(v, int):
        return DataType.INTEGER
    if isinstance(v, float):
        return DataType.FLOAT
    if isinstance(v, str):
        return DataType.STRING
    raise ErrTypeConflict(f"unsupported field value type {type(v)}")


class _SeriesBuf:
    """Column builders for one series: parallel python lists per field."""

    __slots__ = ("times", "fields")

    def __init__(self):
        self.times: list[int] = []
        self.fields: dict[str, list] = {}

    def append(self, fields: dict, time: int, schema: dict[str, DataType]):
        n = len(self.times)
        self.times.append(time)
        for k, v in fields.items():
            col = self.fields.get(k)
            if col is None:
                col = self.fields[k] = [None] * n
            col.append(v)
        # backfill fields not present in this row
        for k, col in self.fields.items():
            if len(col) < len(self.times):
                col.append(None)

    def extend(self, times: list, fields: dict[str, list]) -> None:
        """Bulk columnar append (record-writer path): every field list
        is row-aligned with `times`."""
        n0 = len(self.times)
        self.times.extend(times)
        total = len(self.times)
        for k, vals in fields.items():
            col = self.fields.get(k)
            if col is None:
                col = self.fields[k] = [None] * n0
            col.extend(vals)
        for k, col in self.fields.items():
            if len(col) < total:
                col.extend([None] * (total - len(col)))


class MemTable:
    """One measurement's in-memory data across its series."""

    def __init__(self, measurement: str):
        self.measurement = measurement
        self.schema: dict[str, DataType] = {}
        self.series: dict[int, _SeriesBuf] = {}
        self.rows = 0
        self.approx_bytes = 0

    def validate(self, fields: dict) -> None:
        """Raise ErrTypeConflict on schema conflict WITHOUT mutating state
        (called before the row is made durable in the WAL)."""
        for k, v in fields.items():
            ft = field_type_of(v)
            cur = self.schema.get(k)
            if cur is not None and cur != ft:
                # int written into float field is coerced (influx semantics)
                if not (cur == DataType.FLOAT and ft == DataType.INTEGER):
                    raise ErrTypeConflict(
                        f"field {k}: {ft.name} conflicts with {cur.name}")

    def write(self, sid: int, fields: dict, time: int) -> None:
        self.validate(fields)
        for k, v in fields.items():
            ft = field_type_of(v)
            if k not in self.schema:
                self.schema[k] = ft
        buf = self.series.get(sid)
        if buf is None:
            buf = self.series[sid] = _SeriesBuf()
        buf.append(fields, time, self.schema)
        self.rows += 1
        self.approx_bytes += 24 + 16 * len(fields)

    def write_columns(self, sid: int, times, fields: dict) -> None:
        """Bulk columnar write: arrays are row-aligned, all-valid.
        Types are validated ONCE per column (the per-row path validates
        per row)."""
        probe = {k: (v[0].item() if hasattr(v[0], "item") else v[0])
                 for k, v in fields.items() if len(v)}
        self.validate(probe)
        for k, v in probe.items():
            if k not in self.schema:
                self.schema[k] = field_type_of(v)
        buf = self.series.get(sid)
        if buf is None:
            buf = self.series[sid] = _SeriesBuf()
        tl = times.tolist() if hasattr(times, "tolist") else list(times)
        buf.extend(tl, {k: (v.tolist() if hasattr(v, "tolist")
                            else list(v))
                        for k, v in fields.items()})
        n = len(tl)
        self.rows += n
        self.approx_bytes += n * (24 + 16 * len(fields))

    def record_schema(self) -> Schema:
        return Schema.from_pairs(sorted(self.schema.items()))

    def series_record(self, sid: int) -> Record | None:
        """Materialize one series as a time-sorted Record over the full
        measurement schema (missing fields → null)."""
        buf = self.series.get(sid)
        if buf is None or not buf.times:
            return None
        n = len(buf.times)
        schema = self.record_schema()
        cols = []
        for f in schema:
            if f.name == "time":
                cols.append(ColVal(DataType.TIME,
                                   np.array(buf.times, dtype=np.int64)))
                continue
            raw = buf.fields.get(f.name)
            if raw is None:
                cols.append(ColVal.nulls(f.type, n))
                continue
            valid = np.array([x is not None for x in raw], dtype=np.bool_)
            if f.type.is_numeric:
                vals = np.array(
                    [x if x is not None else 0 for x in raw],
                    dtype=f.type.numpy_dtype)
                cols.append(ColVal(f.type, vals, valid))
            else:
                cols.append(ColVal.from_strings(
                    [x if x is not None else None for x in raw], f.type))
        return Record(schema, cols).sort_by_time()

    def sids(self) -> list[int]:
        return sorted(self.series)


class MemTables:
    """All measurements' memtables for one shard, with a snapshot swap for
    flush (reference shard.go snapshotTbl protocol: writes go to a fresh
    active table while the snapshot flushes)."""

    def __init__(self):
        self._lock = threading.RLock()
        self.active: dict[str, MemTable] = {}
        self.snapshot: dict[str, MemTable] | None = None
        # monotonically bumped on any visible change; consumed by the
        # executor's scan-plan cache key (plans are pure functions of
        # file set + memtable contents)
        self.mutations = 0

    def write(self, measurement: str, sid: int, fields: dict,
              time: int) -> None:
        with self._lock:
            mt = self.active.get(measurement)
            if mt is None:
                mt = self.active[measurement] = MemTable(measurement)
            mt.write(sid, fields, time)
            self.mutations += 1

    def write_columns(self, measurement: str, sid: int, times,
                      fields: dict) -> None:
        with self._lock:
            mt = self.active.get(measurement)
            if mt is None:
                mt = self.active[measurement] = MemTable(measurement)
            mt.write_columns(sid, times, fields)
            self.mutations += 1

    def validate(self, measurement: str, fields: dict) -> None:
        with self._lock:
            mt = self.active.get(measurement)
            if mt is not None:
                mt.validate(fields)

    @property
    def approx_bytes(self) -> int:
        with self._lock:
            return sum(m.approx_bytes for m in self.active.values())

    def begin_snapshot(self) -> dict[str, MemTable]:
        with self._lock:
            if self.snapshot is not None:
                raise RuntimeError("snapshot already in progress")
            self.snapshot = self.active
            self.active = {}
            self.mutations += 1
            return self.snapshot

    def commit_snapshot(self) -> None:
        with self._lock:
            self.snapshot = None
            self.mutations += 1

    def abort_snapshot(self) -> None:
        """Put the snapshot back (flush failed); merges with writes that
        arrived meanwhile by replaying the newer data on top."""
        with self._lock:
            snap, self.snapshot = self.snapshot, None
            self.mutations += 1
            if not snap:
                return
            newer = self.active
            self.active = snap
            for mst, mt in newer.items():
                for sid, buf in mt.series.items():
                    for i, t in enumerate(buf.times):
                        fields = {k: col[i] for k, col in buf.fields.items()
                                  if col[i] is not None}
                        self.write(mst, sid, fields, t)

    def tables_for_read(self) -> list[dict[str, MemTable]]:
        """Active + in-flight snapshot (reads must see both)."""
        with self._lock:
            out = [self.active]
            if self.snapshot is not None:
                out.append(self.snapshot)
            return out
