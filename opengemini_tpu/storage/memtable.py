"""In-memory write buffer (role of reference engine/mutable/table.go
MemTable + ts_table.go).

Per (measurement, sid) chunked column builders — appends go to python lists
of small numpy chunks, so repeated writes are O(1) amortized (no
concatenate-per-append); finalize() materializes sorted Records per series
for flush or query.
"""

from __future__ import annotations

import threading

import numpy as np

from ..record import ColVal, DataType, Field, Record, Schema
from ..utils.errors import ErrTypeConflict

_FIELD_TYPE = {
    float: DataType.FLOAT,
    int: DataType.INTEGER,
    bool: DataType.BOOLEAN,
    str: DataType.STRING,
}


def field_type_of(v) -> DataType:
    # bool is a subclass of int — check it first
    if isinstance(v, bool):
        return DataType.BOOLEAN
    if isinstance(v, int):
        return DataType.INTEGER
    if isinstance(v, float):
        return DataType.FLOAT
    if isinstance(v, str):
        return DataType.STRING
    raise ErrTypeConflict(f"unsupported field value type {type(v)}")


class _SeriesBuf:
    """Column builders for one series, stored as CHUNK ENTRIES so the
    bulk columnar path keeps its numpy arrays untouched (the previous
    list-based builders converted every value through .tolist() —
    measured as the ingest floor once the WAL/index syncs were
    amortized). Two entry kinds interleave freely:

      ["list", start_row, times_list, {field: list-with-None-backfill}]
          — per-row appends accumulate in the trailing list entry
      ["np",   start_row, times_i64,  {field: ndarray}]
          — bulk appends land as-is, all rows valid

    Rows align by global row index; series_record() sorts by time at
    materialization, so entry order never matters semantically."""

    __slots__ = ("n", "entries")

    def __init__(self):
        self.n = 0
        self.entries: list = []

    def append(self, fields: dict, time: int, schema: dict[str, DataType]):
        e = self.entries[-1] if self.entries else None
        if e is None or e[0] != "list":
            e = ["list", self.n, [], {}]
            self.entries.append(e)
        tl, fd = e[2], e[3]
        k = len(tl)
        tl.append(time)
        for key, v in fields.items():
            col = fd.get(key)
            if col is None:
                col = fd[key] = [None] * k
            col.append(v)
        # backfill fields not present in this row
        for col in fd.values():
            if len(col) < len(tl):
                col.append(None)
        self.n += 1

    def extend_arrays(self, times: np.ndarray,
                      fields: dict[str, np.ndarray]) -> None:
        """Bulk columnar append: row-aligned, all-valid arrays stored
        with zero per-value conversion."""
        self.entries.append(["np", self.n, times, fields])
        self.n += len(times)

    def entry_views(self):
        """Consistent-prefix snapshot of the entries: rows beyond the
        n captured HERE are excluded, so a concurrent append to the
        trailing list entry cannot misalign or overflow a reader's
        arrays (lock-free read contract of tables_for_read)."""
        n = self.n
        out = []
        for e in self.entries[:]:
            kind, start, tl, fd = e
            if start >= n:
                break
            ln = min(len(tl), n - start)
            out.append((kind, start, tl, fd, ln))
        return n, out


class MemTable:
    """One measurement's in-memory data across its series."""

    def __init__(self, measurement: str):
        self.measurement = measurement
        self.schema: dict[str, DataType] = {}
        self.series: dict[int, _SeriesBuf] = {}
        # bulk ingest frames: (sids, offsets, times_cat, {field: cat})
        self.bulk_frames: list = []
        # (frames_indexed, {sid: [(frame, lo, hi)]}) published as ONE
        # tuple: lock-free readers must never observe a fresh index
        # with a stale counter (re-appending duplicates rows)
        self._bulk_index: tuple | None = None
        self.rows = 0
        self.approx_bytes = 0

    def validate(self, fields: dict) -> None:
        """Raise ErrTypeConflict on schema conflict WITHOUT mutating state
        (called before the row is made durable in the WAL)."""
        for k, v in fields.items():
            ft = field_type_of(v)
            cur = self.schema.get(k)
            if cur is not None and cur != ft:
                # int written into float field is coerced (influx semantics)
                if not (cur == DataType.FLOAT and ft == DataType.INTEGER):
                    raise ErrTypeConflict(
                        f"field {k}: {ft.name} conflicts with {cur.name}")

    def write(self, sid: int, fields: dict, time: int) -> None:
        self.validate(fields)
        for k, v in fields.items():
            ft = field_type_of(v)
            if k not in self.schema:
                self.schema[k] = ft
        buf = self.series.get(sid)
        if buf is None:
            buf = self.series[sid] = _SeriesBuf()
        buf.append(fields, time, self.schema)
        self.rows += 1
        self.approx_bytes += 24 + 16 * len(fields)

    def write_columns(self, sid: int, times, fields: dict) -> None:
        """Bulk columnar write: arrays are row-aligned, all-valid.
        Types are validated ONCE per column (the per-row path validates
        per row); the arrays land in the buffer untouched."""
        probe = {k: (v[0].item() if hasattr(v[0], "item") else v[0])
                 for k, v in fields.items() if len(v)}
        self.validate(probe)
        for k, v in probe.items():
            if k not in self.schema:
                self.schema[k] = field_type_of(v)
        buf = self.series.get(sid)
        if buf is None:
            buf = self.series[sid] = _SeriesBuf()
        buf.extend_arrays(
            np.ascontiguousarray(times, dtype=np.int64),
            {k: np.asarray(v) for k, v in fields.items()})
        n = len(times)
        self.rows += n
        self.approx_bytes += n * (24 + 16 * len(fields))

    def write_columns_bulk(self, sids: np.ndarray, offsets: np.ndarray,
                           times_cat: np.ndarray,
                           fields_cat: dict[str, np.ndarray]) -> None:
        """Multi-series bulk append: the WHOLE batch lands as ONE frame
        (sids, offsets, concatenated columns) — zero per-series Python.
        Series i owns rows [offsets[i], offsets[i+1]). Reads reach the
        frames through a lazily-built sid index (series_record); flush
        consolidates all frames with one vectorized lexsort."""
        probe = {k: (v[0].item() if hasattr(v[0], "item") else v[0])
                 for k, v in fields_cat.items() if len(v)}
        self.validate(probe)
        for k, v in probe.items():
            if k not in self.schema:
                self.schema[k] = field_type_of(v)
        self.bulk_frames.append((np.asarray(sids, dtype=np.int64),
                                 np.asarray(offsets, dtype=np.int64),
                                 times_cat, fields_cat))
        n = len(times_cat)
        self.rows += n
        self.approx_bytes += n * (24 + 16 * len(fields_cat))

    def _bulk_lookup(self, sid: int):
        """[(frame_idx, lo, hi)] for one sid across bulk frames."""
        if not self.bulk_frames:
            return ()
        ent = self._bulk_index
        if ent is None or ent[0] < len(self.bulk_frames):
            frames = self.bulk_frames[:]
            if ent is None:
                ix, start = {}, 0
            else:
                # deep-copy the per-sid lists: the read path is lock-
                # free, so two concurrent rebuilds must never append
                # into a shared list (duplicated rows)
                ix = {k: v[:] for k, v in ent[1].items()}
                start = ent[0]
            for fi in range(start, len(frames)):
                sids, offs, _t, _f = frames[fi]
                for j, s in enumerate(sids.tolist()):
                    lo, hi = int(offs[j]), int(offs[j + 1])
                    if hi > lo:
                        ix.setdefault(s, []).append((fi, lo, hi))
            self._bulk_index = ent = (len(frames), ix)
        return ent[1].get(sid, ())

    def consolidate_bulk(self):
        """All bulk frames → (sids ascending, offsets, times_cat
        sorted per series, {field: cat}) with one vectorized lexsort —
        the writer's bulk flush input. None when frames disagree on
        field names (fall back to per-series materialization)."""
        frames = self.bulk_frames
        if not frames:
            return None
        names = sorted(frames[0][3])
        for _s, _o, _t, f in frames[1:]:
            if sorted(f) != names:
                return None
        row_sids = np.concatenate([
            np.repeat(s, np.diff(o)) for s, o, _t, _f in frames])
        times = np.concatenate([t for _s, _o, t, _f in frames])
        order = np.lexsort((times, row_sids))
        row_sids = row_sids[order]
        times = times[order]
        cols = {k: np.concatenate([f[k] for _s, _o, _t, f in frames]
                                  )[order] for k in names}
        bounds = np.flatnonzero(np.diff(row_sids, prepend=-1))
        sids_u = row_sids[bounds]
        offsets = np.append(bounds, len(row_sids))
        return sids_u, offsets, times, cols

    def record_schema(self) -> Schema:
        return Schema.from_pairs(sorted(self.schema.items()))

    def series_record(self, sid: int) -> Record | None:
        """Materialize one series as a time-sorted Record over the full
        measurement schema (missing fields → null). Combines per-row
        buffers and bulk-frame slices."""
        buf = self.series.get(sid)
        if buf is None or buf.n == 0:
            n, views = 0, []
        else:
            n, views = buf.entry_views()
        frames = self.bulk_frames
        for fi, lo, hi in self._bulk_lookup(sid):
            _s, _o, t_cat, f_cat = frames[fi]
            views.append(("np", n, t_cat[lo:hi],
                          {k: v[lo:hi] for k, v in f_cat.items()},
                          hi - lo))
            n += hi - lo
        if n == 0:
            return None
        schema = self.record_schema()
        times = np.empty(n, dtype=np.int64)
        for _kind, start, tl, _fd, ln in views:
            times[start:start + ln] = tl[:ln]
        cols = []
        for f in schema:
            if f.name == "time":
                cols.append(ColVal(DataType.TIME, times))
                continue
            if f.type.is_numeric:
                vals = np.zeros(n, dtype=f.type.numpy_dtype)
                valid = np.zeros(n, dtype=np.bool_)
                seen = False
                for kind, start, tl, fd, ln in views:
                    raw = fd.get(f.name)
                    if raw is None:
                        continue
                    seen = True
                    if kind == "np":
                        vals[start:start + ln] = raw[:ln]
                        valid[start:start + ln] = True
                    else:
                        # a concurrent row append fills tl before the
                        # field columns — pad the not-yet-backfilled
                        # tail as null
                        sub = [raw[i] if i < len(raw) else None
                               for i in range(ln)]
                        vals[start:start + ln] = [
                            x if x is not None else 0 for x in sub]
                        valid[start:start + ln] = [
                            x is not None for x in sub]
                cols.append(ColVal(f.type, vals, valid)
                            if seen else ColVal.nulls(f.type, n))
                continue
            # strings: assemble a python list view
            raw_all: list = [None] * n
            seen = False
            for _kind, start, tl, fd, ln in views:
                raw = fd.get(f.name)
                if raw is None:
                    continue
                seen = True
                for i in range(min(ln, len(raw))):
                    raw_all[start + i] = raw[i]
            cols.append(ColVal.from_strings(raw_all, f.type)
                        if seen else ColVal.nulls(f.type, n))
        return Record(schema, cols).sort_by_time()

    def sids(self) -> list[int]:
        if not self.bulk_frames:
            return sorted(self.series)
        bulk = np.unique(np.concatenate(
            [s for s, _o, _t, _f in self.bulk_frames]))
        return sorted(set(self.series) | set(bulk.tolist()))


class MemTables:
    """All measurements' memtables for one shard, with a snapshot swap for
    flush (reference shard.go snapshotTbl protocol: writes go to a fresh
    active table while the snapshot flushes)."""

    def __init__(self):
        self._lock = threading.RLock()
        self.active: dict[str, MemTable] = {}
        self.snapshot: dict[str, MemTable] | None = None
        # monotonically bumped on any visible change; consumed by the
        # executor's scan-plan cache key (plans are pure functions of
        # file set + memtable contents)
        self.mutations = 0

    def write(self, measurement: str, sid: int, fields: dict,
              time: int) -> None:
        with self._lock:
            mt = self.active.get(measurement)
            if mt is None:
                mt = self.active[measurement] = MemTable(measurement)
            mt.write(sid, fields, time)
            self.mutations += 1

    def write_columns(self, measurement: str, sid: int, times,
                      fields: dict) -> None:
        with self._lock:
            mt = self.active.get(measurement)
            if mt is None:
                mt = self.active[measurement] = MemTable(measurement)
            mt.write_columns(sid, times, fields)
            self.mutations += 1

    def write_columns_bulk(self, measurement: str, sids, offsets,
                           times_cat, fields_cat) -> None:
        with self._lock:
            mt = self.active.get(measurement)
            if mt is None:
                mt = self.active[measurement] = MemTable(measurement)
            mt.write_columns_bulk(sids, offsets, times_cat, fields_cat)
            self.mutations += 1

    def validate(self, measurement: str, fields: dict) -> None:
        with self._lock:
            mt = self.active.get(measurement)
            if mt is not None:
                mt.validate(fields)

    @property
    def approx_bytes(self) -> int:
        with self._lock:
            return sum(m.approx_bytes for m in self.active.values())

    def begin_snapshot(self) -> dict[str, MemTable]:
        with self._lock:
            if self.snapshot is not None:
                raise RuntimeError("snapshot already in progress")
            self.snapshot = self.active
            self.active = {}
            self.mutations += 1
            return self.snapshot

    def commit_snapshot(self) -> None:
        with self._lock:
            self.snapshot = None
            self.mutations += 1

    def abort_snapshot(self) -> None:
        """Put the snapshot back (flush failed); merges with writes that
        arrived meanwhile by replaying the newer data on top."""
        with self._lock:
            snap, self.snapshot = self.snapshot, None
            self.mutations += 1
            if not snap:
                return
            newer = self.active
            self.active = snap
            for mst, mt in newer.items():
                for frame in mt.bulk_frames:
                    self.write_columns_bulk(mst, *frame)
                for sid, buf in mt.series.items():
                    # bulk chunks re-extend wholesale (replaying a
                    # 1M-row burst per value would hold the lock for
                    # the exact conversion this layout avoids)
                    for kind, _start, tl, fd in buf.entries:
                        if kind == "np":
                            self.write_columns(mst, sid, tl, fd)
                            continue
                        for i in range(len(tl)):
                            fields = {k: col[i]
                                      for k, col in fd.items()
                                      if i < len(col)
                                      and col[i] is not None}
                            self.write(mst, sid, fields, tl[i])

    def tables_for_read(self) -> list[dict[str, MemTable]]:
        """Active + in-flight snapshot (reads must see both)."""
        with self._lock:
            out = [self.active]
            if self.snapshot is not None:
                out.append(self.snapshot)
            return out
