"""Write-ahead log (role of reference engine/wal.go:111 — compressed
records, rotation via Switch, replay on open).

Frame format: [u32 len][u32 crc32 of payload][payload], where payload is
[codec u8][u32 raw size][compressed batch]. Codecs: zstd (default) or the
native LZ4 block codec (the reference's WAL offers lz4/snappy,
engine/wal.go:236 — lz4 here rides the C++ codec in native/lz4.cpp).
Payload is a batch of rows serialized compactly (measurement, sid, time,
fields). Replay validates crc and stops at the first torn frame.
"""

from __future__ import annotations

import os
import struct
import threading
import zlib

from ..native import lz4_compress, lz4_decompress
from ..utils.zstd_compat import zstandard
from ..utils import failpoint, get_logger

log = get_logger(__name__)

_HDR = struct.Struct("<II")
_ZSTD, _LZ4 = 1, 2
# columnar frames (bulk record writes — reference record_writer.go path)
_ZSTD_COLS, _LZ4_COLS = 3, 4
# multi-series bulk frame: one measurement, concatenated column arrays
# with per-series row offsets (the per-entry cols frame costs ~5.6µs
# of pack per tiny series; this packs the batch in O(fields))
_ZSTD_COLSB, _LZ4_COLSB = 5, 6


def _pack_batch(rows: list[tuple[str, int, dict, int]]) -> bytes:
    """rows: (measurement, sid, fields, time)"""
    out = [struct.pack("<I", len(rows))]
    for mst, sid, fields, t in rows:
        mb = mst.encode()
        out.append(struct.pack("<HQqH", len(mb), sid, t, len(fields)))
        out.append(mb)
        for k, v in fields.items():
            kb = k.encode()
            if isinstance(v, bool):
                ty, vb = 3, struct.pack("<?", v)
            elif isinstance(v, int):
                ty, vb = 1, struct.pack("<q", v)
            elif isinstance(v, float):
                ty, vb = 2, struct.pack("<d", v)
            else:
                ty, vb = 4, str(v).encode()
            out.append(struct.pack("<HBI", len(kb), ty, len(vb)))
            out.append(kb)
            out.append(vb)
    return b"".join(out)


def _unpack_batch(buf: bytes) -> list[tuple[str, int, dict, int]]:
    (n,) = struct.unpack_from("<I", buf, 0)
    pos = 4
    rows = []
    for _ in range(n):
        mlen, sid, t, nf = struct.unpack_from("<HQqH", buf, pos)
        pos += struct.calcsize("<HQqH")
        mst = buf[pos:pos + mlen].decode()
        pos += mlen
        fields = {}
        for _ in range(nf):
            klen, ty, vlen = struct.unpack_from("<HBI", buf, pos)
            pos += struct.calcsize("<HBI")
            k = buf[pos:pos + klen].decode()
            pos += klen
            vb = buf[pos:pos + vlen]
            pos += vlen
            if ty == 1:
                v = struct.unpack("<q", vb)[0]
            elif ty == 2:
                v = struct.unpack("<d", vb)[0]
            elif ty == 3:
                v = struct.unpack("<?", vb)[0]
            else:
                v = vb.decode()
            fields[k] = v
        rows.append((mst, sid, fields, t))
    return rows


def _pack_cols(entries) -> bytes:
    """Columnar batch: [(mst, sid, times i64 array, {field: array})…] —
    numpy buffers serialized whole, no per-row Python."""
    import numpy as np
    out = [struct.pack("<I", len(entries))]
    for mst, sid, times, fields in entries:
        mb = mst.encode()
        t = np.ascontiguousarray(times, dtype="<i8")
        out.append(struct.pack("<HQIH", len(mb), sid, len(t),
                               len(fields)))
        out.append(mb)
        out.append(t.tobytes())
        for k, arr in fields.items():
            kb = k.encode()
            a = np.ascontiguousarray(arr)
            if a.dtype.byteorder == ">":
                a = a.astype(a.dtype.newbyteorder("<"))
            dtb = a.dtype.str.encode()
            out.append(struct.pack("<HB", len(kb), len(dtb)))
            out.append(kb)
            out.append(dtb)
            out.append(a.tobytes())
    return b"".join(out)


def _pack_cols_bulk(mst: str, sids, offsets, times_cat,
                    fields_cat) -> bytes:
    import numpy as np
    mb = mst.encode()
    out = [struct.pack("<HIQH", len(mb), len(sids), len(times_cat),
                       len(fields_cat)),
           mb,
           np.ascontiguousarray(sids, dtype="<i8").tobytes(),
           np.ascontiguousarray(offsets, dtype="<i8").tobytes(),
           np.ascontiguousarray(times_cat, dtype="<i8").tobytes()]
    for k, arr in fields_cat.items():
        kb = k.encode()
        a = np.ascontiguousarray(arr)
        if a.dtype.byteorder == ">":
            a = a.astype(a.dtype.newbyteorder("<"))
        dtb = a.dtype.str.encode()
        out.append(struct.pack("<HB", len(kb), len(dtb)))
        out.append(kb)
        out.append(dtb)
        out.append(a.tobytes())
    return b"".join(out)


def _unpack_cols_bulk(buf: bytes):
    import numpy as np
    mlen, ns, rows, nf = struct.unpack_from("<HIQH", buf, 0)
    pos = struct.calcsize("<HIQH")
    mst = buf[pos:pos + mlen].decode()
    pos += mlen
    sids = np.frombuffer(buf, dtype="<i8", count=ns, offset=pos).copy()
    pos += ns * 8
    offsets = np.frombuffer(buf, dtype="<i8", count=ns + 1,
                            offset=pos).copy()
    pos += (ns + 1) * 8
    times_cat = np.frombuffer(buf, dtype="<i8", count=rows,
                              offset=pos).copy()
    pos += rows * 8
    fields = {}
    for _ in range(nf):
        klen, dlen = struct.unpack_from("<HB", buf, pos)
        pos += struct.calcsize("<HB")
        k = buf[pos:pos + klen].decode()
        pos += klen
        dt = np.dtype(buf[pos:pos + dlen].decode())
        pos += dlen
        fields[k] = np.frombuffer(buf, dtype=dt, count=rows,
                                  offset=pos).copy()
        pos += rows * dt.itemsize
    return mst, sids, offsets, times_cat, fields


def _unpack_cols(buf: bytes):
    import numpy as np
    (n,) = struct.unpack_from("<I", buf, 0)
    pos = 4
    entries = []
    for _ in range(n):
        mlen, sid, rows, nf = struct.unpack_from("<HQIH", buf, pos)
        pos += struct.calcsize("<HQIH")
        mst = buf[pos:pos + mlen].decode()
        pos += mlen
        times = np.frombuffer(buf, dtype="<i8", count=rows,
                              offset=pos).copy()
        pos += rows * 8
        fields = {}
        for _ in range(nf):
            klen, dlen = struct.unpack_from("<HB", buf, pos)
            pos += struct.calcsize("<HB")
            k = buf[pos:pos + klen].decode()
            pos += klen
            dt = np.dtype(buf[pos:pos + dlen].decode())
            pos += dlen
            fields[k] = np.frombuffer(buf, dtype=dt, count=rows,
                                      offset=pos).copy()
            pos += rows * dt.itemsize
        entries.append((mst, sid, times, fields))
    return entries


# cumulative metrics for the statistics pusher (reference
# statistics/wal.go analog)
from ..utils.stats import register_counters

WAL_STATS = register_counters("wal", {
    "writes": 0, "bytes_written": 0, "switches": 0,
    "replayed_batches": 0})


class WAL:
    def __init__(self, dir_path: str, sync: bool = False,
                 compression: str = "zstd"):
        self.dir = dir_path
        self.sync = sync
        if compression not in ("zstd", "lz4"):
            raise ValueError(f"unknown wal compression {compression!r}")
        self.compression = compression
        os.makedirs(dir_path, exist_ok=True)
        self._lock = threading.Lock()
        self._seq = self._max_seq() + 1
        self._f = open(self._path(self._seq), "ab")
        self._zc = zstandard.ZstdCompressor(level=1)

    def _path(self, seq: int) -> str:
        return os.path.join(self.dir, f"{seq:06d}.wal")

    def _max_seq(self) -> int:
        mx = 0
        for fn in os.listdir(self.dir):
            if fn.endswith(".wal"):
                try:
                    mx = max(mx, int(fn[:-4]))
                except ValueError:
                    pass
        return mx

    def write(self, rows: list[tuple[str, int, dict, int]]) -> None:
        failpoint.inject("wal.write.err")
        raw = _pack_batch(rows)
        if self.compression == "lz4":
            codec, body = _LZ4, lz4_compress(raw)
        else:
            codec, body = _ZSTD, self._zc.compress(raw)
        payload = struct.pack("<BI", codec, len(raw)) + body
        frame = _HDR.pack(len(payload), zlib.crc32(payload)) + payload
        with self._lock:
            self._f.write(frame)
            if self.sync:
                self._f.flush()
                os.fsync(self._f.fileno())
        from ..utils.stats import bump as _bump
        _bump(WAL_STATS, "writes")
        _bump(WAL_STATS, "bytes_written", len(frame))

    def write_cols(self, entries) -> None:
        """Columnar frame (bulk record write path)."""
        failpoint.inject("wal.write.err")
        raw = _pack_cols(entries)
        if self.compression == "lz4":
            codec, body = _LZ4_COLS, lz4_compress(raw)
        else:
            codec, body = _ZSTD_COLS, self._zc.compress(raw)
        payload = struct.pack("<BI", codec, len(raw)) + body
        frame = _HDR.pack(len(payload), zlib.crc32(payload)) + payload
        with self._lock:
            self._f.write(frame)
            if self.sync:
                self._f.flush()
                os.fsync(self._f.fileno())
        from ..utils.stats import bump as _bump
        _bump(WAL_STATS, "writes")
        _bump(WAL_STATS, "bytes_written", len(frame))

    def write_cols_bulk(self, mst: str, sids, offsets, times_cat,
                        fields_cat) -> None:
        """Multi-series concatenated columnar frame (bulk ingest)."""
        failpoint.inject("wal.write.err")
        raw = _pack_cols_bulk(mst, sids, offsets, times_cat, fields_cat)
        if self.compression == "lz4":
            codec, body = _LZ4_COLSB, lz4_compress(raw)
        else:
            codec, body = _ZSTD_COLSB, self._zc.compress(raw)
        payload = struct.pack("<BI", codec, len(raw)) + body
        frame = _HDR.pack(len(payload), zlib.crc32(payload)) + payload
        with self._lock:
            self._f.write(frame)
            if self.sync:
                self._f.flush()
                os.fsync(self._f.fileno())
        from ..utils.stats import bump as _bump
        _bump(WAL_STATS, "writes")
        _bump(WAL_STATS, "bytes_written", len(frame))

    def switch(self) -> int:
        """Rotate to a new segment; returns the sealed segment's seq
        (reference WAL.Switch). The sealed file is removed by
        remove_sealed() after the matching memtable flush commits."""
        with self._lock:
            self._f.flush()
            os.fsync(self._f.fileno())
            self._f.close()
            sealed = self._seq
            self._seq += 1
            self._f = open(self._path(self._seq), "ab")
        from ..utils.stats import bump as _bump
        _bump(WAL_STATS, "switches")
        return sealed

    def remove_upto(self, seq: int) -> None:
        for fn in sorted(os.listdir(self.dir)):
            if fn.endswith(".wal"):
                try:
                    s = int(fn[:-4])
                except ValueError:
                    continue
                if s <= seq:
                    os.unlink(os.path.join(self.dir, fn))

    def replay(self):
        """Yield row batches from all segments in order; stops at torn/corrupt
        frames (reference engine/wal.go:562 parallel replay — ours is
        sequential, one core)."""
        zd = zstandard.ZstdDecompressor()
        with self._lock:
            seqs = sorted(
                int(fn[:-4]) for fn in os.listdir(self.dir)
                if fn.endswith(".wal") and fn[:-4].isdigit())
        for seq in seqs:
            try:
                with open(self._path(seq), "rb") as f:
                    data = f.read()
            except FileNotFoundError:
                continue
            pos = 0
            while pos + _HDR.size <= len(data):
                ln, crc = _HDR.unpack_from(data, pos)
                if pos + _HDR.size + ln > len(data):
                    log.warning("wal %06d: torn frame at %d", seq, pos)
                    break
                payload = data[pos + _HDR.size:pos + _HDR.size + ln]
                if zlib.crc32(payload) != crc:
                    log.warning("wal %06d: bad crc at %d", seq, pos)
                    break
                if len(payload) >= 5 and payload[0] in (
                        _ZSTD, _LZ4, _ZSTD_COLS, _LZ4_COLS,
                        _ZSTD_COLSB, _LZ4_COLSB):
                    codec, rawlen = struct.unpack_from("<BI", payload, 0)
                    body = payload[5:]
                    if codec in (_LZ4, _LZ4_COLS, _LZ4_COLSB):
                        raw = lz4_decompress(body, rawlen)
                    else:
                        raw = zd.decompress(body)
                    if codec in (_ZSTD_COLS, _LZ4_COLS):
                        yield ("cols", _unpack_cols(raw))
                        pos += _HDR.size + ln
                        continue
                    if codec in (_ZSTD_COLSB, _LZ4_COLSB):
                        yield ("colsb", _unpack_cols_bulk(raw))
                        pos += _HDR.size + ln
                        continue
                else:
                    # legacy frame: bare zstd payload (zstd magic first byte
                    # 0x28 cannot collide with the codec ids)
                    raw = zd.decompress(payload)
                yield _unpack_batch(raw)
                pos += _HDR.size + ln

    def close(self) -> None:
        with self._lock:
            if self._f.closed:
                return
            self._f.flush()
            os.fsync(self._f.fileno())
            self._f.close()
