"""Write-ahead log (role of reference engine/wal.go:111 — compressed
records, rotation via Switch, replay on open).

Frame format: [u32 len][u32 crc32 of payload][payload], where payload is
[codec u8][u32 raw size][compressed batch]. Codecs: zstd (default) or the
native LZ4 block codec (the reference's WAL offers lz4/snappy,
engine/wal.go:236 — lz4 here rides the C++ codec in native/lz4.cpp).
Payload is a batch of rows serialized compactly (measurement, sid, time,
fields). Replay validates crc and stops at the first torn frame.
"""

from __future__ import annotations

import os
import struct
import threading
import zlib

from ..native import lz4_compress, lz4_decompress
from ..utils.zstd_compat import zstandard
from ..utils import failpoint, fileops, get_logger, knobs

log = get_logger(__name__)

_HDR = struct.Struct("<II")
_ZSTD, _LZ4 = 1, 2
# columnar frames (bulk record writes — reference record_writer.go path)
_ZSTD_COLS, _LZ4_COLS = 3, 4
# multi-series bulk frame: one measurement, concatenated column arrays
# with per-series row offsets (the per-entry cols frame costs ~5.6µs
# of pack per tiny series; this packs the batch in O(fields))
_ZSTD_COLSB, _LZ4_COLSB = 5, 6
# uncompressed frames (wal_compression="none" — the ingest line-rate
# lane: LZ4 of a 2MB columnar frame costs more CPU than everything
# else on the acknowledge path combined; crash safety is the CRC +
# fsync contract, compression was only ever a disk-space trade)
_NONE, _NONE_COLS, _NONE_COLSB = 7, 8, 9


def _pack_batch(rows: list[tuple[str, int, dict, int]]) -> bytes:
    """rows: (measurement, sid, fields, time)"""
    out = [struct.pack("<I", len(rows))]
    for mst, sid, fields, t in rows:
        mb = mst.encode()
        out.append(struct.pack("<HQqH", len(mb), sid, t, len(fields)))
        out.append(mb)
        for k, v in fields.items():
            kb = k.encode()
            if isinstance(v, bool):
                ty, vb = 3, struct.pack("<?", v)
            elif isinstance(v, int):
                ty, vb = 1, struct.pack("<q", v)
            elif isinstance(v, float):
                ty, vb = 2, struct.pack("<d", v)
            else:
                ty, vb = 4, str(v).encode()
            out.append(struct.pack("<HBI", len(kb), ty, len(vb)))
            out.append(kb)
            out.append(vb)
    return b"".join(out)


def _unpack_batch(buf: bytes) -> list[tuple[str, int, dict, int]]:
    (n,) = struct.unpack_from("<I", buf, 0)
    pos = 4
    rows = []
    for _ in range(n):
        mlen, sid, t, nf = struct.unpack_from("<HQqH", buf, pos)
        pos += struct.calcsize("<HQqH")
        mst = buf[pos:pos + mlen].decode()
        pos += mlen
        fields = {}
        for _ in range(nf):
            klen, ty, vlen = struct.unpack_from("<HBI", buf, pos)
            pos += struct.calcsize("<HBI")
            k = buf[pos:pos + klen].decode()
            pos += klen
            vb = buf[pos:pos + vlen]
            pos += vlen
            if ty == 1:
                v = struct.unpack("<q", vb)[0]
            elif ty == 2:
                v = struct.unpack("<d", vb)[0]
            elif ty == 3:
                v = struct.unpack("<?", vb)[0]
            else:
                v = vb.decode()
            fields[k] = v
        rows.append((mst, sid, fields, t))
    return rows


def _pack_cols(entries) -> bytes:
    """Columnar batch: [(mst, sid, times i64 array, {field: array})…] —
    numpy buffers serialized whole, no per-row Python."""
    import numpy as np
    out = [struct.pack("<I", len(entries))]
    for mst, sid, times, fields in entries:
        mb = mst.encode()
        t = np.ascontiguousarray(times, dtype="<i8")
        out.append(struct.pack("<HQIH", len(mb), sid, len(t),
                               len(fields)))
        out.append(mb)
        out.append(t.tobytes())
        for k, arr in fields.items():
            kb = k.encode()
            a = np.ascontiguousarray(arr)
            if a.dtype.byteorder == ">":
                a = a.astype(a.dtype.newbyteorder("<"))
            dtb = a.dtype.str.encode()
            out.append(struct.pack("<HB", len(kb), len(dtb)))
            out.append(kb)
            out.append(dtb)
            out.append(a.tobytes())
    return b"".join(out)


def _pack_cols_bulk_parts(mst: str, sids, offsets, times_cat,
                          fields_cat) -> list:
    """The bulk frame as a scatter-gather parts list: numpy payloads
    stay zero-copy buffer views (`.data.cast("B")`), so the
    uncompressed codec can CRC + write them without ever joining —
    three full-payload memcpys gone from the line-rate lane."""
    import numpy as np

    def _buf(a):
        return a.data.cast("B")

    mb = mst.encode()
    out = [struct.pack("<HIQH", len(mb), len(sids), len(times_cat),
                       len(fields_cat)),
           mb,
           _buf(np.ascontiguousarray(sids, dtype="<i8")),
           _buf(np.ascontiguousarray(offsets, dtype="<i8")),
           _buf(np.ascontiguousarray(times_cat, dtype="<i8"))]
    for k, arr in fields_cat.items():
        kb = k.encode()
        a = np.ascontiguousarray(arr)
        if a.dtype.byteorder == ">":
            a = a.astype(a.dtype.newbyteorder("<"))
        dtb = a.dtype.str.encode()
        out.append(struct.pack("<HB", len(kb), len(dtb)))
        out.append(kb)
        out.append(dtb)
        out.append(_buf(a))
    return out


def _pack_cols_bulk(mst: str, sids, offsets, times_cat,
                    fields_cat) -> bytes:
    return b"".join(_pack_cols_bulk_parts(mst, sids, offsets,
                                          times_cat, fields_cat))


def _unpack_cols_bulk(buf: bytes):
    import numpy as np
    mlen, ns, rows, nf = struct.unpack_from("<HIQH", buf, 0)
    pos = struct.calcsize("<HIQH")
    mst = buf[pos:pos + mlen].decode()
    pos += mlen
    sids = np.frombuffer(buf, dtype="<i8", count=ns, offset=pos).copy()
    pos += ns * 8
    offsets = np.frombuffer(buf, dtype="<i8", count=ns + 1,
                            offset=pos).copy()
    pos += (ns + 1) * 8
    times_cat = np.frombuffer(buf, dtype="<i8", count=rows,
                              offset=pos).copy()
    pos += rows * 8
    fields = {}
    for _ in range(nf):
        klen, dlen = struct.unpack_from("<HB", buf, pos)
        pos += struct.calcsize("<HB")
        k = buf[pos:pos + klen].decode()
        pos += klen
        dt = np.dtype(buf[pos:pos + dlen].decode())
        pos += dlen
        fields[k] = np.frombuffer(buf, dtype=dt, count=rows,
                                  offset=pos).copy()
        pos += rows * dt.itemsize
    return mst, sids, offsets, times_cat, fields


def _unpack_cols(buf: bytes):
    import numpy as np
    (n,) = struct.unpack_from("<I", buf, 0)
    pos = 4
    entries = []
    for _ in range(n):
        mlen, sid, rows, nf = struct.unpack_from("<HQIH", buf, pos)
        pos += struct.calcsize("<HQIH")
        mst = buf[pos:pos + mlen].decode()
        pos += mlen
        times = np.frombuffer(buf, dtype="<i8", count=rows,
                              offset=pos).copy()
        pos += rows * 8
        fields = {}
        for _ in range(nf):
            klen, dlen = struct.unpack_from("<HB", buf, pos)
            pos += struct.calcsize("<HB")
            k = buf[pos:pos + klen].decode()
            pos += klen
            dt = np.dtype(buf[pos:pos + dlen].decode())
            pos += dlen
            fields[k] = np.frombuffer(buf, dtype=dt, count=rows,
                                      offset=pos).copy()
            pos += rows * dt.itemsize
        entries.append((mst, sid, times, fields))
    return entries


# cumulative metrics for the statistics pusher (reference
# statistics/wal.go analog). The recovery counters are the /metrics
# face of the structured recovery report below: every restart's replay
# adds its frame/torn/salvage/quarantine tallies here.
from ..utils.stats import register_counters

WAL_STATS = register_counters("wal", {
    "writes": 0, "bytes_written": 0, "switches": 0,
    "group_commits": 0, "group_commit_frames": 0,
    "replayed_batches": 0, "replayed_frames": 0,
    "torn_frames": 0, "bad_crc_frames": 0, "decode_error_frames": 0,
    "salvaged_frames": 0, "quarantined_files": 0,
    "quarantined_bytes": 0, "truncated_segments": 0,
    "orphans_removed": 0, "recovery_ms": 0})


# ---------------------------------------------------- recovery report
#
# Structured startup-recovery summaries (reference engine/wal.go:562
# replay bookkeeping): each shard's replay appends one report; the
# bounded ring plus the process-wide totals surface through
# /debug/vars ("recovery"), /metrics (WAL_STATS counters) and the
# stats pusher. A report says what a restart actually did — frames
# replayed, bytes salvaged, files quarantined, recovery_ms — which is
# the difference between "it came back" and "it came back WITH the
# acknowledged data".

from collections import deque as _deque

_RECOVERY_LOCK = threading.Lock()
_RECOVERY_REPORTS: "_deque[dict]" = _deque(maxlen=32)


def record_recovery(report: dict) -> None:
    with _RECOVERY_LOCK:
        _RECOVERY_REPORTS.append(dict(report))


def recovery_reports() -> list[dict]:
    with _RECOVERY_LOCK:
        return [dict(r) for r in _RECOVERY_REPORTS]


def recovery_summary() -> dict:
    """Process-wide recovery view for /debug/vars: cumulative counters
    plus the recent per-shard reports ring."""
    # replayed_batches (the pre-PR-10 pusher counter, kept for
    # dashboard compat) is a synonym of replayed_frames here — the
    # report exports one name only
    keys = ("replayed_frames", "torn_frames",
            "bad_crc_frames", "decode_error_frames", "salvaged_frames",
            "quarantined_files", "quarantined_bytes",
            "truncated_segments", "orphans_removed", "recovery_ms")
    return {**{k: WAL_STATS.get(k, 0) for k in keys},
            "shards": recovery_reports()}


class WAL:
    def __init__(self, dir_path: str, sync: bool = False,
                 compression: str = "zstd"):
        self.dir = dir_path
        self.sync = sync
        if compression not in ("zstd", "lz4", "none"):
            raise ValueError(f"unknown wal compression {compression!r}")
        self.compression = compression
        os.makedirs(dir_path, exist_ok=True)
        self._lock = threading.Lock()
        # group commit (OG_WAL_GROUP_COMMIT_US): tickets are frame
        # sequence numbers; a write is DURABLE once a completed fsync
        # covers its ticket. One leader per group holds the window
        # open (cv.wait releases _lock so followers keep appending),
        # then syncs once for every frame written so far.
        self._gc_cv = threading.Condition(self._lock)
        self._gc_writes = 0      # tickets issued (frames appended)
        self._gc_synced = 0      # highest ticket a finished fsync covers
        self._gc_syncing = False  # a leader is inside its window/fsync
        self._seq = self._max_seq() + 1
        self._f = open(self._path(self._seq), "ab")
        # the segment's DIRECTORY ENTRY must survive a crash, or every
        # fsynced frame in it is unreachable after restart (file fsync
        # persists bytes, not the name)
        fileops.fsync_dir(self.dir)
        self._zc = zstandard.ZstdCompressor(level=1)

    def _path(self, seq: int) -> str:
        return os.path.join(self.dir, f"{seq:06d}.wal")

    def _max_seq(self) -> int:
        mx = 0
        for fn in os.listdir(self.dir):
            if fn.endswith(".wal"):
                try:
                    mx = max(mx, int(fn[:-4]))
                except ValueError:
                    pass
        return mx

    def _emit(self, payload: bytes, defer_sync: bool = False) -> int:
        """Append one framed payload; returns the frame's durability
        TICKET. Crash points bracket the fsync — the durability
        boundary the crash harness proves: a kill at ``pre_sync`` may
        tear the frame (the write is unacknowledged, replay must drop
        it whole); a kill at ``post_sync`` leaves a durable frame the
        caller never acked (replay must surface it, idempotently).

        With OG_WAL_GROUP_COMMIT_US > 0 the fsync moves to
        wait_durable(): concurrent emitters coalesce into one sync.
        ``defer_sync`` callers get the ticket back immediately and MUST
        call wait_durable(ticket) before acknowledging the write (the
        shard releases its own lock first, so concurrent shards join
        the same group)."""
        return self._emit_parts([payload], defer_sync)

    def _emit_parts(self, parts: list, defer_sync: bool = False) -> int:
        """Scatter-gather _emit: frame a PARTS LIST without joining it.
        The CRC is folded incrementally and the parts are written
        back-to-back behind the 8-byte header, so the uncompressed
        bulk-columnar lane never materializes the 2MB payload as one
        contiguous bytes object (the join + frame-concat memcpys were
        a top-3 cost at line rate). Byte layout on disk is identical
        to _emit(b"".join(parts))."""
        total = 0
        crc = 0
        for p in parts:
            total += len(p)
            crc = zlib.crc32(p, crc)
        hdr = _HDR.pack(total, crc)
        gc_us = int(knobs.get("OG_WAL_GROUP_COMMIT_US")) \
            if self.sync else 0
        if gc_us <= 0:
            with self._lock:
                w = self._f.write
                w(hdr)
                for p in parts:
                    w(p)
                failpoint.inject("wal.append.crash_pre_sync")
                if self.sync:
                    self._f.flush()
                    os.fsync(self._f.fileno())
                failpoint.inject("wal.append.crash_post_sync")
                self._gc_writes += 1
                ticket = self._gc_synced = self._gc_writes
        else:
            with self._lock:
                w = self._f.write
                w(hdr)
                for p in parts:
                    w(p)
                failpoint.inject("wal.append.crash_pre_sync")
                self._gc_writes += 1
                ticket = self._gc_writes
        from ..utils.stats import bump as _bump
        _bump(WAL_STATS, "writes")
        _bump(WAL_STATS, "bytes_written", total + _HDR.size)
        if gc_us > 0 and not defer_sync:
            self.wait_durable(ticket)
        return ticket

    def wait_durable(self, ticket: int) -> None:
        """Block until an fsync covering ``ticket`` has completed
        (group commit). The first uncovered waiter becomes the group
        LEADER: it holds the commit window open for up to
        OG_WAL_GROUP_COMMIT_US (cv.wait releases the lock, so follower
        frames keep landing), then syncs once for everything appended.
        A leader whose fsync raises surfaces the error to its own
        caller; uncovered followers retry as the next leader, so a
        transient sync failure never wedges the queue. No-op when the
        ticket is already durable (non-grouped mode syncs in _emit)."""
        from ..utils.stats import bump as _bump
        with self._gc_cv:
            # post_sync fires only when THIS call observed the sync
            # (non-grouped mode already injected it inside _emit)
            waited = self._gc_synced < ticket
            while self._gc_synced < ticket:
                if self._gc_syncing:
                    self._gc_cv.wait(0.05)
                    continue
                self._gc_syncing = True
                try:
                    gc_us = int(knobs.get("OG_WAL_GROUP_COMMIT_US"))
                    if gc_us > 0 and self._gc_writes <= ticket:
                        # window: collect followers before paying the
                        # sync (wait drops the lock; notify on a
                        # completed competing sync ends it early)
                        self._gc_cv.wait(gc_us / 1e6)
                    high = self._gc_writes
                    # crash here: the whole group's frames are
                    # appended but NOT fsynced — none are acked, so
                    # replay may serve all, some (OS made progress),
                    # or none, each batch whole-or-absent (C2)
                    failpoint.inject("wal.group_commit.crash")
                    self._f.flush()
                    os.fsync(self._f.fileno())
                    self._gc_synced = max(self._gc_synced, high)
                    _bump(WAL_STATS, "group_commits")
                    _bump(WAL_STATS, "group_commit_frames",
                          high - ticket + 1)
                finally:
                    self._gc_syncing = False
                    self._gc_cv.notify_all()
            if waited:
                failpoint.inject("wal.append.crash_post_sync")

    def write(self, rows: list[tuple[str, int, dict, int]],
              defer_sync: bool = False) -> int:
        failpoint.inject("wal.write.err")
        raw = _pack_batch(rows)
        if self.compression == "lz4":
            codec, body = _LZ4, lz4_compress(raw)
        elif self.compression == "none":
            codec, body = _NONE, raw
        else:
            codec, body = _ZSTD, self._zc.compress(raw)
        return self._emit(struct.pack("<BI", codec, len(raw)) + body,
                          defer_sync)

    def write_cols(self, entries, defer_sync: bool = False) -> int:
        """Columnar frame (bulk record write path)."""
        failpoint.inject("wal.write.err")
        raw = _pack_cols(entries)
        if self.compression == "lz4":
            codec, body = _LZ4_COLS, lz4_compress(raw)
        elif self.compression == "none":
            codec, body = _NONE_COLS, raw
        else:
            codec, body = _ZSTD_COLS, self._zc.compress(raw)
        return self._emit(struct.pack("<BI", codec, len(raw)) + body,
                          defer_sync)

    def write_cols_bulk(self, mst: str, sids, offsets, times_cat,
                        fields_cat, defer_sync: bool = False) -> int:
        """Multi-series concatenated columnar frame (bulk ingest)."""
        failpoint.inject("wal.write.err")
        parts = _pack_cols_bulk_parts(mst, sids, offsets, times_cat,
                                      fields_cat)
        if self.compression == "none":
            rawlen = sum(len(p) for p in parts)
            return self._emit_parts(
                [struct.pack("<BI", _NONE_COLSB, rawlen)] + parts,
                defer_sync)
        raw = b"".join(parts)
        if self.compression == "lz4":
            codec, body = _LZ4_COLSB, lz4_compress(raw)
        else:
            codec, body = _ZSTD_COLSB, self._zc.compress(raw)
        return self._emit(struct.pack("<BI", codec, len(raw)) + body,
                          defer_sync)

    def switch(self) -> int:
        """Rotate to a new segment; returns the sealed segment's seq
        (reference WAL.Switch). The sealed file is removed by
        remove_upto() after the matching memtable flush commits."""
        with self._lock:
            self._f.flush()
            os.fsync(self._f.fileno())
            # the seal's fsync covers every appended frame: release
            # any group-commit waiters parked on the sealed segment
            self._gc_synced = self._gc_writes
            self._gc_cv.notify_all()
            # crash here: sealed segment durable, successor not yet
            # created — restart replays the sealed segment and opens a
            # fresh one (same seq the successor would have taken).
            # BEFORE the close(): the admin plane can arm this site
            # with a non-crash action (error), and raising after the
            # close would leave _f unusable for every later write
            failpoint.inject("wal.switch.crash")
            self._f.close()
            sealed = self._seq
            self._seq += 1
            self._f = open(self._path(self._seq), "ab")
            fileops.fsync_dir(self.dir)
        from ..utils.stats import bump as _bump
        _bump(WAL_STATS, "switches")
        return sealed

    def remove_upto(self, seq: int) -> None:
        removed = False
        for fn in sorted(os.listdir(self.dir)):
            if fn.endswith(".wal"):
                try:
                    s = int(fn[:-4])
                except ValueError:
                    continue
                if s <= seq:
                    os.unlink(os.path.join(self.dir, fn))
                    if not removed:
                        removed = True
                        # crash window: some retired segments gone,
                        # some surviving — replay of a survivor whose
                        # rows already live in TSSP files must be
                        # idempotent (last-wins merge on identical
                        # rows), which the crash harness proves
                        failpoint.inject("wal.remove_upto.crash")
        if removed:
            fileops.fsync_dir(self.dir)

    @staticmethod
    def _scan_next_frame(data: bytes, start: int) -> int | None:
        """Salvage scan: first offset > ``start`` where a whole frame
        parses (plausible length + CRC match). A CRC over the actual
        payload makes false positives ~2^-32; recovery-path cost only
        — and BOUNDED: candidate offsets whose random length field
        happens to land in-bounds each cost a CRC over up to the
        remaining segment, so a multi-MB garbage region could
        otherwise turn one restart into hours of checksumming. A
        fixed work budget (bytes CRC'd) degrades to the no-salvage
        behavior (quarantine the tail) instead of hanging recovery."""
        n = len(data)
        q = start + 1
        budget = 1 << 28                  # ~256MB of CRC work
        while q + _HDR.size <= n:
            ln, crc = _HDR.unpack_from(data, q)
            end = q + _HDR.size + ln
            if 0 < ln <= n - q - _HDR.size:
                if zlib.crc32(data[q + _HDR.size:end]) == crc:
                    return q
                budget -= ln
                if budget <= 0:
                    log.warning(
                        "wal salvage scan exhausted its work budget "
                        "at offset %d; treating the tail as "
                        "unsalvageable", q)
                    return None
            q += 1
        return None

    def _quarantine(self, path: str, data: bytes, regions: list,
                    seg_rep: dict) -> None:
        """Preserve the bad byte regions of one segment to
        ``<seg>.corrupt`` (create-once — a second restart re-scanning
        the same damage must not rewrite it) and truncate the segment
        to its valid prefix when the damage reaches EOF, so the NEXT
        restart replays a clean file instead of re-tripping."""
        from ..utils.stats import bump as _bump
        if not knobs.get("OG_STORAGE_QUARANTINE") or not regions:
            return
        cpath = path + ".corrupt"
        blob = b"".join(data[a:b] for a, b in regions)
        if not os.path.exists(cpath):
            fileops.durable_write(cpath, blob)
            _bump(WAL_STATS, "quarantined_files")
            _bump(WAL_STATS, "quarantined_bytes", len(blob))
            seg_rep["quarantined_bytes"] = len(blob)
        if regions[-1][1] >= len(data) and regions[-1][0] < len(data):
            with open(path, "r+b") as tf:
                tf.truncate(regions[-1][0])
                tf.flush()
                os.fsync(tf.fileno())
            _bump(WAL_STATS, "truncated_segments")
            seg_rep["truncated_at"] = regions[-1][0]

    def replay(self, report: dict | None = None):
        """Yield row batches from all segments in order, recovering
        past damage instead of silently dropping it (reference
        engine/wal.go:562 replay + torn-frame handling):

        - a torn/bad-CRC frame stops the segment at its valid prefix;
          the corrupt tail is preserved to ``<seg>.corrupt`` and the
          segment truncated (OG_STORAGE_QUARANTINE), so restart #2
          replays clean;
        - with OG_WAL_SALVAGE=1 the scan continues past the bad region
          to the next CRC-valid frame and keeps replaying (counted as
          salvaged);
        - a frame whose boundary is sound but whose payload fails to
          decompress/unpack is skipped individually (boundary is
          CRC-proven, so later frames are safe) and quarantined.

        Every anomaly lands in WAL_STATS and, when ``report`` is
        given, in ``report["segments"]`` — the structured recovery
        report /debug/vars serves."""
        from ..utils.stats import bump as _bump
        zd = zstandard.ZstdDecompressor()
        salvage = bool(knobs.get("OG_WAL_SALVAGE"))
        with self._lock:
            seqs = sorted(
                int(fn[:-4]) for fn in os.listdir(self.dir)
                if fn.endswith(".wal") and fn[:-4].isdigit())
        for seq in seqs:
            path = self._path(seq)
            try:
                with open(path, "rb") as f:
                    data = f.read()
            except FileNotFoundError:
                continue
            seg_rep = {"seq": seq, "frames": 0, "torn": 0,
                       "bad_crc": 0, "decode_errors": 0, "salvaged": 0}
            bad_regions: list[tuple[int, int]] = []
            pos = 0
            salvaged_run = False
            while pos + _HDR.size <= len(data):
                ln, crc = _HDR.unpack_from(data, pos)
                end = pos + _HDR.size + ln
                bad_kind = None
                if end > len(data):
                    bad_kind = "torn"
                elif zlib.crc32(data[pos + _HDR.size:end]) != crc:
                    bad_kind = "bad_crc"
                if bad_kind is not None:
                    key = "torn_frames" if bad_kind == "torn" \
                        else "bad_crc_frames"
                    _bump(WAL_STATS, key)
                    seg_rep["torn" if bad_kind == "torn"
                            else "bad_crc"] += 1
                    nxt = self._scan_next_frame(data, pos) \
                        if salvage else None
                    if nxt is None:
                        log.warning(
                            "wal %06d: %s frame at %d; quarantining "
                            "%d tail bytes", seq, bad_kind, pos,
                            len(data) - pos)
                        bad_regions.append((pos, len(data)))
                        pos = len(data)
                        break
                    log.warning(
                        "wal %06d: %s frame at %d; salvage resumes "
                        "at %d", seq, bad_kind, pos, nxt)
                    bad_regions.append((pos, nxt))
                    pos = nxt
                    salvaged_run = True
                    continue
                payload = data[pos + _HDR.size:end]
                parsed = None
                try:
                    if len(payload) >= 5 and payload[0] in (
                            _ZSTD, _LZ4, _ZSTD_COLS, _LZ4_COLS,
                            _ZSTD_COLSB, _LZ4_COLSB,
                            _NONE, _NONE_COLS, _NONE_COLSB):
                        codec, rawlen = struct.unpack_from(
                            "<BI", payload, 0)
                        body = payload[5:]
                        if codec in (_LZ4, _LZ4_COLS, _LZ4_COLSB):
                            raw = lz4_decompress(body, rawlen)
                        elif codec in (_NONE, _NONE_COLS,
                                       _NONE_COLSB):
                            raw = bytes(body)
                        else:
                            raw = zd.decompress(body)
                        if codec in (_ZSTD_COLS, _LZ4_COLS,
                                     _NONE_COLS):
                            parsed = ("cols", _unpack_cols(raw))
                        elif codec in (_ZSTD_COLSB, _LZ4_COLSB,
                                       _NONE_COLSB):
                            parsed = ("colsb", _unpack_cols_bulk(raw))
                        else:
                            parsed = _unpack_batch(raw)
                    else:
                        # legacy frame: bare zstd payload (zstd magic
                        # first byte 0x28 cannot collide with the
                        # codec ids)
                        parsed = _unpack_batch(zd.decompress(payload))
                except Exception as e:
                    # boundary is CRC-proven: skip exactly this frame,
                    # keep the later ones (no salvage scan needed)
                    log.warning("wal %06d: frame at %d fails to "
                                "decode (%s); quarantined", seq, pos, e)
                    _bump(WAL_STATS, "decode_error_frames")
                    seg_rep["decode_errors"] += 1
                    bad_regions.append((pos, end))
                    pos = end
                    continue
                if salvaged_run:
                    _bump(WAL_STATS, "salvaged_frames")
                    seg_rep["salvaged"] += 1
                _bump(WAL_STATS, "replayed_frames")
                _bump(WAL_STATS, "replayed_batches")
                seg_rep["frames"] += 1
                yield parsed
                pos = end
            self._quarantine(path, data, bad_regions, seg_rep)
            if report is not None and (
                    seg_rep["frames"] or bad_regions):
                report.setdefault("segments", []).append(seg_rep)

    def close(self) -> None:
        with self._lock:
            if self._f.closed:
                return
            self._f.flush()
            os.fsync(self._f.fileno())
            self._gc_synced = self._gc_writes
            self._gc_cv.notify_all()
            self._f.close()
