from .tssp import (SEGMENT_SIZE, ColumnMeta, ChunkMeta, PreAgg, Segment,
                   TSSPReader, TSSPWriter)
from .rows import PointRow
from .memtable import MemTable, MemTables
from .wal import WAL
from .shard import Shard
from .engine import Engine, EngineOptions
from .backup import (BackupError, create_backup, restore_backup,
                     verify_backup)
