"""Ingest row model (role of the reference's influx.Row from the line
protocol parser, lib/util/lifted/vm/protoparser/influx)."""

from __future__ import annotations

from dataclasses import dataclass, field


@dataclass
class PointRow:
    measurement: str
    tags: dict[str, str] = field(default_factory=dict)
    fields: dict[str, float | int | bool | str] = field(default_factory=dict)
    time: int = 0  # ns since epoch
