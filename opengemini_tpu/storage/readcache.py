"""Decoded-block LRU read cache (role of reference lib/readcache:
blockcache.go + maplru.go — a byte-budgeted LRU over TSSP block reads).

TPU-first deviation: the reference caches *compressed* file blocks; here
the cache holds *decoded* ColVal segments, because the expensive step on
this stack is decode (the mmap page cache already serves raw bytes) and
decoded columns are what get shipped to the device. Keys are
(file path, segment offset) — a file is immutable once written, and
compaction produces new paths, so entries never go stale; dropped files
just age out.
"""

from __future__ import annotations

import threading
from collections import OrderedDict


class BlockCache:
    """Byte-accounted LRU. get/put are O(1); eviction pops oldest."""

    def __init__(self, capacity_bytes: int = 256 * 1024 * 1024):
        self.capacity = capacity_bytes
        self._lock = threading.Lock()
        self._map: OrderedDict[tuple, tuple[object, int]] = OrderedDict()
        self._bytes = 0
        self.hits = 0
        self.misses = 0
        self.evictions = 0

    def get(self, key: tuple):
        with self._lock:
            ent = self._map.get(key)
            if ent is None:
                self.misses += 1
                return None
            self._map.move_to_end(key)
            self.hits += 1
            return ent[0]

    def put(self, key: tuple, value, nbytes: int) -> None:
        if nbytes > self.capacity:
            return
        with self._lock:
            old = self._map.pop(key, None)
            if old is not None:
                self._bytes -= old[1]
            self._map[key] = (value, nbytes)
            self._bytes += nbytes
            while self._bytes > self.capacity and self._map:
                _k, (_v, nb) = self._map.popitem(last=False)
                self._bytes -= nb
                self.evictions += 1

    def purge(self) -> None:
        with self._lock:
            self._map.clear()
            self._bytes = 0

    def stats(self) -> dict:
        with self._lock:
            return {"bytes": self._bytes, "capacity": self.capacity,
                    "entries": len(self._map), "hits": self.hits,
                    "misses": self.misses, "evictions": self.evictions}


# process-wide cache; capacity reconfigured from DataConfig at startup
_cache = BlockCache()
_enabled = True


def global_cache() -> BlockCache:
    return _cache


def configure(capacity_bytes: int) -> None:
    global _cache, _enabled
    _enabled = capacity_bytes > 0
    _cache = BlockCache(max(capacity_bytes, 1))


def enabled() -> bool:
    return _enabled
