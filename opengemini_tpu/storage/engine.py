"""Storage engine: databases → retention policies → time-partitioned shard
groups → shards (role of reference engine/engine.go:74 Engine →
DBPTInfo → Shard, plus the meta shard-group model from
lib/util/lifted/influx/meta/shardinfo.go).

Single-node scope: one partition per database; shard groups cut by time
duration (time partitioning = the framework's first distribution axis,
SURVEY §2.6.1). Multi-partition hash distribution lives in parallel/.
"""

from __future__ import annotations

import os
import re
import threading
from dataclasses import dataclass

import numpy as np

from ..index import TagFilter
from ..record import Record
from ..utils import epochs as _epochs
from ..utils import fileops, get_logger
from ..utils.errors import ErrDatabaseNotFound, ErrQueryError
from .rows import PointRow
from .shard import Shard
from .tssp import SEGMENT_SIZE

log = get_logger(__name__)

NS_PER_HOUR = 3600 * 10**9
DEFAULT_SHARD_DURATION = 24 * 7 * NS_PER_HOUR  # 7d, influx default for inf RP


@dataclass
class EngineOptions:
    shard_duration: int = DEFAULT_SHARD_DURATION
    flush_bytes: int = 256 * 1024 * 1024
    wal_sync: bool = False
    wal_compression: str = "zstd"     # "zstd" | "lz4" | "none"
    segment_size: int = SEGMENT_SIZE
    obs_store: object | None = None   # hierarchical cold tier (obs.py)
    # lazy shard open (reference engine.go:780 openShardLazy): startup
    # discovers shard dirs without replaying their WALs / loading their
    # indexes; a shard materializes on first access. The NEWEST
    # `preload_shards` open eagerly — the warm tier dashboards hit
    lazy_shard_open: bool = True
    preload_shards: int = 2


class Database:
    def __init__(self, name: str, path: str, opts: EngineOptions):
        self.name = name
        self.path = path
        self.opts = opts
        self.shards: dict[int, Shard] = {}  # key: shard-group index
        self._lock = threading.RLock()
        os.makedirs(path, exist_ok=True)
        # column-store measurement declarations, shared (by reference)
        # with every shard of this db; persisted so reopen keeps the
        # engine type (reference: measurement EngineType in ts-meta)
        self._cs_path = os.path.join(path, "colstore.json")
        self.cs_options: dict[str, dict] = {}
        if os.path.exists(self._cs_path):
            import json
            with open(self._cs_path) as f:
                self.cs_options.update(json.load(f))
        self._load()

    def set_columnstore(self, mst: str, primary_key: list[str],
                        indexes: dict[str, str] | None = None,
                        fragment_rows: int = 4096) -> None:
        """Declare a measurement column-store. Must happen before its
        first flush: existing TSSP data is not converted, so the DDL is
        rejected once row-store files exist (they would become invisible
        to the column-store query path)."""
        import json
        with self._lock:
            if mst not in self.cs_options:
                for s in self.all_shards():
                    if s._files.get(mst):
                        raise ErrQueryError(
                            f"measurement {mst!r} already has row-store "
                            "data; cannot convert to columnstore")
            self.cs_options[mst] = {
                "primary_key": list(primary_key),
                "indexes": dict(indexes or {}),
                "fragment_rows": fragment_rows,
            }
            tmp = self._cs_path + ".tmp"
            with open(tmp, "w") as f:
                json.dump(self.cs_options, f)
                f.flush()
                os.fsync(f.fileno())
            fileops.durable_replace(tmp, self._cs_path)

    def is_columnstore(self, mst: str) -> bool:
        return mst in self.cs_options

    def _load(self) -> None:
        swept = 0
        for fn in sorted(os.listdir(self.path)):
            # crash leftovers at the db level (colstore.json.tmp):
            # unpublished by construction — sweep before anything opens
            if fn.endswith(".tmp"):
                try:
                    os.unlink(os.path.join(self.path, fn))
                    swept += 1
                except OSError:
                    pass
                continue
            m = re.fullmatch(r"shard_(-?\d+)", fn)
            if m:
                gi = int(m.group(1))
                # placeholder: WAL replay + index load deferred to
                # first access (lazy open, engine.go:780 role)
                self.shards[gi] = None
        if swept:
            # the unlinks themselves must survive a crash, or the
            # orphan reappears on the next restart (same discipline
            # as Shard._sweep_orphans)
            fileops.fsync_dir(self.path)
        if not self.opts.lazy_shard_open:
            for gi in list(self.shards):
                self.shards[gi] = self._open_shard(gi)
            return
        # warm tier: the newest shards preload eagerly
        n_pre = max(self.opts.preload_shards, 0)
        if n_pre:
            for gi in sorted(self.shards)[-n_pre:]:
                self.shards[gi] = self._open_shard(gi)

    def _shard(self, gi: int) -> Shard:
        """Materialize a lazily-discovered shard (idempotent)."""
        s = self.shards.get(gi)
        if s is None:
            s = self.shards[gi] = self._open_shard(gi)
        return s

    def _open_shard(self, gi: int) -> Shard:
        sd = self.opts.shard_duration
        return Shard(os.path.join(self.path, f"shard_{gi}"),
                     shard_id=gi, start_time=gi * sd,
                     end_time=(gi + 1) * sd,
                     flush_bytes=self.opts.flush_bytes,
                     wal_sync=self.opts.wal_sync,
                     wal_compression=self.opts.wal_compression,
                     segment_size=self.opts.segment_size,
                     cs_options=self.cs_options,
                     obs_store=self.opts.obs_store)

    def shard_for_time(self, t: int, create: bool = True) -> Shard | None:
        gi = t // self.opts.shard_duration
        with self._lock:
            if gi in self.shards:
                return self._shard(gi)
            if not create:
                return None
            s = self.shards[gi] = self._open_shard(gi)
            return s

    def drop_shard(self, gi: int) -> None:
        import shutil
        # retention drop destroys data non-append-wise: the result
        # cache must never serve the dropped range — db-wide wipe
        # generation bump (epochs has no per-mst view of a shard).
        # Bumped BEFORE and AFTER the removal: a scan racing the
        # delete could stamp the pre-bump epoch while reading
        # partially-deleted state; the post-bump invalidates it
        _epochs.note_wipe(self.name)
        with self._lock:
            # pop + rmtree under the lock so shard_for_time cannot recreate
            # the directory mid-delete (a later write re-creates it fresh)
            present = gi in self.shards
            s = self.shards.pop(gi, None)
            if s is not None:
                # keep TSSP mmaps open: in-flight queries may still hold the
                # readers; they close via GC (unlinked data stays readable)
                s.close(close_files=False)
                shutil.rmtree(s.path, ignore_errors=True)
            elif present:
                # lazily-discovered, never materialized: remove the dir
                shutil.rmtree(os.path.join(self.path, f"shard_{gi}"),
                              ignore_errors=True)
        _epochs.note_wipe(self.name)

    def shards_overlapping(self, t_min: int, t_max: int) -> list[Shard]:
        """Time-pruned shard selection (reference shard_mapper.go:74-117)."""
        sd = self.opts.shard_duration
        lo = t_min // sd
        hi = t_max // sd
        with self._lock:
            gis = [gi for gi in sorted(self.shards) if lo <= gi <= hi]
        out = []
        for gi in gis:                    # per-shard lock granularity
            with self._lock:
                if gi in self.shards:
                    out.append(self._shard(gi))
        return out

    def all_shards(self) -> list[Shard]:
        # snapshot ids under the lock, materialize per shard so each
        # cold open (WAL replay + index load) holds the lock alone —
        # concurrent writes/queries interleave between opens
        with self._lock:
            gis = sorted(self.shards)
        out = []
        for gi in gis:
            with self._lock:
                if gi in self.shards:      # racing drop_shard
                    out.append(self._shard(gi))
        return out

    def opened_shards(self) -> list[Shard]:
        """Materialized shards only — for periodic services and stats
        that must not defeat lazy open by touching cold shards."""
        with self._lock:
            return [s for _gi, s in sorted(self.shards.items())
                    if s is not None]

    def discovered_shards(self) -> list[tuple[int, bool]]:
        """(shard group index, opened) without materializing anything —
        observability for the lazy tier."""
        with self._lock:
            return [(gi, self.shards[gi] is not None)
                    for gi in sorted(self.shards)]


class Engine:
    """Top storage object (reference Engine engine/engine.go:74)."""

    def __init__(self, data_path: str, opts: EngineOptions | None = None):
        self.path = data_path
        self.opts = opts or EngineOptions()
        self.databases: dict[str, Database] = {}
        self._lock = threading.RLock()
        # post-write hooks: fn(db_name, rows) after a successful write
        # (stream engine, subscribers — reference hooks these in the
        # coordinator PointsWriter, points_writer.go:525)
        self.write_hooks: list = []
        os.makedirs(data_path, exist_ok=True)
        for fn in sorted(os.listdir(data_path)):
            if os.path.isdir(os.path.join(data_path, fn)):
                self.databases[fn] = Database(
                    fn, os.path.join(data_path, fn), self.opts)

    # ---- DDL -------------------------------------------------------------

    def create_database(self, name: str) -> Database:
        with self._lock:
            db = self.databases.get(name)
            if db is None:
                db = self.databases[name] = Database(
                    name, os.path.join(self.path, name), self.opts)
            return db

    def drop_database(self, name: str) -> None:
        import shutil
        # wipe-generation bump BEFORE and AFTER: a scan racing the
        # drop could stamp the pre-bump generation while reading
        # half-removed state; the post-bump invalidates that entry
        _epochs.note_wipe(name)
        with self._lock:
            db = self.databases.pop(name, None)
        if db is not None:
            for s in db.all_shards():
                s.close()
            shutil.rmtree(db.path, ignore_errors=True)
        _epochs.note_wipe(name)

    def database(self, name: str) -> Database:
        db = self.databases.get(name)
        if db is None:
            raise ErrDatabaseNotFound(f"database not found: {name}")
        return db

    def create_columnstore(self, db_name: str, mst: str,
                           primary_key: list[str],
                           indexes: dict[str, str] | None = None,
                           fragment_rows: int = 4096) -> None:
        """CREATE MEASUREMENT ... ENGINETYPE columnstore (reference DDL:
        column-store measurements with PRIMARYKEY + INDEXES)."""
        self.create_database(db_name).set_columnstore(
            mst, primary_key, indexes, fragment_rows)

    # ---- writes (reference Engine.WriteRows engine/engine.go:881) --------

    def write_points(self, db_name: str, rows: list[PointRow],
                     create_db: bool = True) -> int:
        db = (self.create_database(db_name) if create_db
              else self.database(db_name))
        # group by target shard
        by_shard: dict[int, list[PointRow]] = {}
        sd = db.opts.shard_duration
        for r in rows:
            by_shard.setdefault(r.time // sd, []).append(r)
        n = 0
        written: list[PointRow] = []
        err: Exception | None = None
        for gi, batch in by_shard.items():
            try:
                shard = db.shard_for_time(gi * sd)
                n += shard.write_rows(batch)
                written.extend(batch)
            except Exception as e:
                err = e
        # result-cache invalidation: exact per-measurement write
        # extents over ALL attempted rows — a shard write that raised
        # may still have persisted rows before the error, so the bump
        # must cover them (over-invalidation on the failed remainder
        # is safe; a skipped bump would serve them stale). Bumped
        # AFTER the writes so a scan racing the batch stamps a
        # pre-bump epoch and invalidates.
        if rows:
            ext: dict[str, list] = {}
            for r in rows:
                e = ext.get(r.measurement)
                if e is None:
                    ext[r.measurement] = [r.time, r.time]
                else:
                    if r.time < e[0]:
                        e[0] = r.time
                    if r.time > e[1]:
                        e[1] = r.time
            for mst, (lo, hi) in ext.items():
                _epochs.note_write(db_name, mst, lo, hi)
        # hooks see only rows that were actually stored — derived data
        # (streams, subscribers) must not diverge from the source
        if written:
            for hook in self.write_hooks:
                try:
                    hook(db_name, written)
                except Exception:
                    log.exception("write hook failed")
        if err is not None:
            raise err
        return n

    def write_record(self, db_name: str, mst: str, tags: dict,
                     times, fields: dict, create_db: bool = True) -> int:
        """Bulk columnar write of one series (reference RecordWriter,
        coordinator/record_writer.go:79 — the arrow-flight/high-
        throughput ingest path): numpy time/value arrays, routed to
        shards by time slice, no per-row Python objects. Write hooks
        (streams, subscribers) are fed materialized rows only when any
        are registered."""
        return self.write_record_batch(
            db_name, [(mst, tags, times, fields)], create_db=create_db)

    def write_record_batch(self, db_name: str, batches,
                           create_db: bool = True) -> int:
        """Multi-series bulk ingest: [(mst, tags, times, fields)] —
        one index fsync + one WAL frame per shard for the WHOLE batch
        (shard.write_columns_batch; the per-series write_record path
        pays an index fsync per new series)."""
        import numpy as np
        db = (self.create_database(db_name) if create_db
              else self.database(db_name))
        sd = db.opts.shard_duration
        per_shard: dict[int, list] = {}
        # single-shard entries group by (shard, mst, field names) for
        # the many-tiny-series bulk path (one index insert + one WAL
        # frame + one memtable pass per GROUP — prom remote-write at
        # 1M-series cardinality is ~9x faster through it)
        bulk_groups: dict[tuple, list] = {}
        for mst, tags, times, fields in batches:
            times = np.ascontiguousarray(times, dtype=np.int64)
            if len(times) == 0:
                continue
            if len(times) <= 64:       # tiny series: numpy reduction
                tl = times.tolist()    # overhead dwarfs the work
                lo, hi = min(tl) // sd, max(tl) // sd
            else:
                lo = int(times.min()) // sd
                hi = int(times.max()) // sd
            if lo == hi:
                bulk_groups.setdefault(
                    (lo, mst, tuple(sorted(fields))), []).append(
                        (tags, times, fields))
                continue
            slots = times // sd
            for gi in np.unique(slots):
                m = slots == gi
                per_shard.setdefault(int(gi), []).append(
                    (mst, tags, times[m],
                     {k: np.asarray(v)[m] for k, v in fields.items()}))
        n = 0
        written: list = []
        err: Exception | None = None
        # result-cache invalidation extents, shard-granular: the bulk
        # path must not pay per-series numpy min/max — coarser ranges
        # only over-invalidate, never serve stale
        w_ext: dict[str, list] = {}

        def _note_gi(mst: str, gi: int) -> None:
            e = w_ext.get(mst)
            if e is None:
                w_ext[mst] = [gi, gi]
            else:
                if gi < e[0]:
                    e[0] = gi
                if gi > e[1]:
                    e[1] = gi

        for (gi, mst, _names), ents in sorted(bulk_groups.items(),
                                              key=lambda kv: kv[0][:2]):
            if len(ents) < 8:
                per_shard.setdefault(gi, []).extend(
                    (mst, tg, tm, f) for tg, tm, f in ents)
                continue
            # extent noted whether or not the write below succeeds: a
            # raising shard may have persisted part of the group, and
            # over-invalidating the failed remainder is safe while a
            # skipped bump would serve persisted rows stale (the
            # note_write bump itself lands after ALL shard writes)
            _note_gi(mst, gi)
            try:
                shard = db.shard_for_time(gi * sd)
                n += shard.write_columns_bulk(
                    mst, [tg for tg, _t, _f in ents],
                    [tm for _g, tm, _f in ents],
                    [f for _g, _t, f in ents])
                written.extend((mst, tg, tm, f) for tg, tm, f in ents)
            except Exception as e:
                err = e
        for gi, ents in sorted(per_shard.items()):
            for mst, _tg, _tm, _f in ents:
                _note_gi(mst, gi)
            try:
                shard = db.shard_for_time(gi * sd)
                n += shard.write_columns_batch(ents)
                written.extend(ents)
            except Exception as e:
                # keep going like write_points: hooks must see every
                # row that WAS stored even when a later shard fails
                err = e
        for mst, (lo_gi, hi_gi) in w_ext.items():
            _epochs.note_write(db_name, mst, lo_gi * sd,
                               min((hi_gi + 1) * sd - 1, 1 << 62))
        if written and self.write_hooks:
            self._fanout_hooks(db_name, written)
        if err is not None:
            raise err
        return n

    # bound on PointRows materialized at once for row-wise write hooks:
    # the bulk ingest path must not allocate a million-row list just
    # because a stream task is registered
    _HOOK_CHUNK = 65536

    def _fanout_hooks(self, db_name: str, written: list) -> None:
        """Write-hook fan-out for the bulk columnar paths. ``written``
        is [(mst, tags, times, field arrays)] batches. Hooks that set
        ``wants_columnar = True`` receive those batches directly (no
        row materialization at all); row-wise hooks get PointRows
        built in bounded chunks from ONE per-column tolist() each —
        no per-value ndarray .item() calls."""
        import numpy as np
        from .rows import PointRow
        row_hooks, col_hooks = [], []
        for h in self.write_hooks:
            (col_hooks if getattr(h, "wants_columnar", False)
             else row_hooks).append(h)
        for hook in col_hooks:
            try:
                hook(db_name, written)
            except Exception:
                log.exception("write hook failed")
        if not row_hooks:
            return
        chunk: list = []

        def _flush() -> None:
            # hooks may keep the list past this call (the subscriber
            # encodes its batch lazily on a worker thread) — hand over
            # ownership and start a fresh chunk instead of clearing
            nonlocal chunk
            if not chunk:
                return
            rows, chunk = chunk, []
            for hook in row_hooks:
                try:
                    hook(db_name, rows)
                except Exception:
                    log.exception("write hook failed")

        C = self._HOOK_CHUNK
        for mst, tags, times, fields in written:
            names = list(fields)
            cols = [np.asarray(v).tolist() for v in fields.values()]
            tl = np.asarray(times).tolist()
            for i0 in range(0, len(tl), C):
                i1 = min(i0 + C, len(tl))
                chunk.extend(
                    PointRow(mst, tags, dict(zip(names, vals)), t)
                    for t, vals in zip(
                        tl[i0:i1],
                        zip(*(c[i0:i1] for c in cols))))
                if len(chunk) >= C:
                    _flush()
        _flush()

    def write_series_matrix(self, db_name: str, mst: str, keys: list,
                            tag_cols: list, times, fields: dict,
                            create_db: bool = True) -> int:
        """Aligned-series matrix ingest: S series × one (P,) timestamp
        vector, fields as (S, P) matrices (the scrape / prom
        remote-write shape — every per-series cost is a numpy slice;
        see Shard.write_series_matrix). Rows split across shard groups
        by TIME COLUMN only (all series share it)."""
        db = (self.create_database(db_name) if create_db
              else self.database(db_name))
        sd = db.opts.shard_duration
        times = np.ascontiguousarray(times, dtype=np.int64)
        slots = times // sd
        n = 0
        try:
            for gi in np.unique(slots):
                m = slots == gi
                shard = db.shard_for_time(int(gi) * sd)
                n += shard.write_series_matrix(
                    mst, keys, tag_cols, times[m],
                    {k: np.asarray(v)[:, m] for k, v in fields.items()})
        finally:
            if len(times):
                # one exact extent per call (all series share the time
                # column) — result-cache invalidation. In a finally:
                # a raising shard may have persisted earlier slices,
                # and those must never be served stale
                _epochs.note_write(db_name, mst, int(times.min()),
                                   int(times.max()))
        if self.write_hooks:
            # reshape the matrix into the bulk `written` batch form
            # (per-series numpy row VIEWS — no copies) and share the
            # chunked/columnar fan-out with write_record_batch
            mats = {k: np.asarray(v) for k, v in fields.items()}
            written = [(mst, dict(zip(keys, vals)), times,
                        {k: m[si] for k, m in mats.items()})
                       for si, vals in enumerate(zip(*tag_cols))]
            self._fanout_hooks(db_name, written)
        return n

    # ---- reads -----------------------------------------------------------

    def measurements(self, db_name: str) -> list[str]:
        db = self.database(db_name)
        out: set[str] = set()
        for s in db.all_shards():
            out.update(s.measurements())
        return sorted(out)

    def scan_series(self, db_name: str, measurement: str,
                    filters: list[TagFilter] | None = None,
                    columns: list[str] | None = None,
                    t_min: int | None = None, t_max: int | None = None,
                    ) -> list[tuple[Shard, int, Record]]:
        """Flat scan: (shard, sid, record) per matching series with data.
        Query layers above turn this into device arrays."""
        db = self.database(db_name)
        shards = (db.shards_overlapping(t_min, t_max)
                  if t_min is not None and t_max is not None
                  else db.all_shards())
        out = []
        for s in shards:
            for sid in s.series_ids(measurement, filters).tolist():
                rec = s.read_series(measurement, sid, columns, t_min, t_max)
                if rec is not None:
                    out.append((s, sid, rec))
        return out

    def flush_all(self) -> None:
        for db in list(self.databases.values()):
            for s in db.all_shards():
                s.flush()

    def drop_measurement(self, db_name: str, mst: str) -> None:
        """DROP MEASUREMENT across all shards (reference
        Engine.DropMeasurement). Flush first: WAL replay must not
        resurrect the dropped rows."""
        # epoch bump BEFORE and AFTER the removal: a scan racing the
        # drop could stamp the pre-bump epoch while still seeing the
        # rows; the post-bump invalidates that entry (the append path
        # needs only the after-bump — rows there APPEAR rather than
        # vanish, and a scan cannot cache what it never saw)
        _epochs.note_wipe(db_name, mst)
        db = self.database(db_name)
        for s in db.all_shards():
            s.flush()
            s.drop_measurement(mst)
        _epochs.note_wipe(db_name, mst)

    def delete_rows(self, db_name: str, mst: str,
                    t_min: int | None = None, t_max: int | None = None,
                    tag_filters=None, tag_exprs=None,
                    drop_series: bool = False) -> int:
        """DELETE FROM mst [WHERE time/tag predicates] (reference
        Engine delete path). tag_exprs are pure-tag and/or predicate
        trees (h = 'a' OR h = 'b'). Returns rows removed.

        drop_series=True additionally removes the matched series from
        each shard's tsi index (DROP SERIES semantics — DELETE keeps
        the series key visible, DROP SERIES does not)."""
        _epochs.note_wipe(db_name, mst)
        db = self.database(db_name)
        removed = 0
        for s in db.all_shards():
            s.flush()
            sids = None
            if tag_filters or tag_exprs:
                sids = s.index.series_ids(mst, tag_filters, tag_exprs)
                if len(sids) == 0:
                    continue
            removed += s.delete_rows(mst, t_min, t_max, sids)
            if drop_series:
                if sids is None:
                    s.index.drop_measurement(mst)
                else:
                    s.index.drop_series(mst, sids)
        # post-removal bump: invalidates any entry a racing scan
        # stamped with the pre-bump epoch while the rows still existed
        _epochs.note_wipe(db_name, mst)
        return removed

    def close(self) -> None:
        # drop this engine's result-cache entries (keyed by engine
        # token — they can never be served again). sys.modules guard:
        # storage-only contexts (crash-harness children) must not pull
        # the query stack — and jax — in just to close
        import sys as _sys
        _rc = _sys.modules.get("opengemini_tpu.query.resultcache")
        if _rc is not None:
            try:
                _rc.note_engine_closed(self)
            except Exception:
                log.exception("result-cache purge on close failed")
        for db in list(self.databases.values()):
            with db._lock:
                opened = [s for s in db.shards.values()
                          if s is not None]
            for s in opened:     # never materialize a shard to close it
                s.close()
