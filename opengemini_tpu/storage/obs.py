"""Object-storage tier (role of reference lib/obs/obs_options.go +
lib/fileops/obs_fs.go: an OBS/S3-style store mounted as a filesystem, and
engine/immutable/detached_*.go: TSSP files queried "detached" — metadata
and data fetched lazily by byte range instead of a local mmap).

``ObjectStore`` is the provider interface; ``LocalObjectStore`` is the
bundled directory-backed implementation (the test/on-prem emulation —
a real S3/OBS client plugs in by implementing the same five methods).
``DetachedSource`` adapts a stored object to the byte-slice protocol the
TSSP reader uses, with block-aligned range fetches and a small LRU so
meta/bloom/trailer reads don't re-fetch per access.
"""

from __future__ import annotations

import os
import shutil
import threading
from collections import OrderedDict

from ..utils import fileops, get_logger

log = get_logger(__name__)

DEFAULT_BLOCK = 256 * 1024


class ObjectStore:
    """Minimal blob-store interface (put/get_range/size/delete/list)."""

    def put_file(self, key: str, path: str) -> None:
        raise NotImplementedError

    def get_range(self, key: str, offset: int, length: int) -> bytes:
        raise NotImplementedError

    def size(self, key: str) -> int:
        raise NotImplementedError

    def delete(self, key: str) -> None:
        raise NotImplementedError

    def list(self, prefix: str = "") -> list[str]:
        raise NotImplementedError


class LocalObjectStore(ObjectStore):
    """Directory-backed object store. Keys are '/'-separated; objects are
    immutable once put (TSSP files are immutable, so overwrite = error)."""

    def __init__(self, root: str):
        self.root = root
        os.makedirs(root, exist_ok=True)

    def _path(self, key: str) -> str:
        p = os.path.normpath(os.path.join(self.root, key))
        if not p.startswith(os.path.abspath(self.root) + os.sep) \
                and p != os.path.abspath(self.root):
            p2 = os.path.abspath(p)
            if not p2.startswith(os.path.abspath(self.root) + os.sep):
                raise ValueError(f"key escapes store root: {key}")
        return p

    def put_file(self, key: str, path: str) -> None:
        dst = self._path(key)
        os.makedirs(os.path.dirname(dst), exist_ok=True)
        tmp = dst + ".uploading"
        shutil.copy2(path, tmp)       # copy2 never fsyncs
        fileops.durable_replace(tmp, dst, sync_src=True)

    def get_range(self, key: str, offset: int, length: int) -> bytes:
        with open(self._path(key), "rb") as f:
            f.seek(offset)
            return f.read(length)

    def size(self, key: str) -> int:
        return os.path.getsize(self._path(key))

    def delete(self, key: str) -> None:
        try:
            os.unlink(self._path(key))
        except FileNotFoundError:
            pass

    def list(self, prefix: str = "") -> list[str]:
        out = []
        for r, _d, files in os.walk(self.root):
            for f in files:
                key = os.path.relpath(os.path.join(r, f), self.root)
                key = key.replace(os.sep, "/")
                if key.startswith(prefix):
                    out.append(key)
        return sorted(out)


class DetachedSource:
    """Byte-slice view over a stored object (the lazy-load half of
    detached_lazy_load_index_reader.go): ``src[a:b]`` fetches only the
    blocks covering [a, b), caching them in a per-source LRU."""

    def __init__(self, store: ObjectStore, key: str,
                 block_size: int = DEFAULT_BLOCK, cache_blocks: int = 64):
        self.store = store
        self.key = key
        self.block_size = block_size
        self.cache_blocks = cache_blocks
        self._len = store.size(key)
        self._cache: OrderedDict[int, bytes] = OrderedDict()
        self._lock = threading.Lock()
        self.closed = False
        self.fetches = 0           # range GETs issued (ops visibility)

    def __len__(self) -> int:
        return self._len

    def _block(self, bi: int) -> bytes:
        with self._lock:
            b = self._cache.get(bi)
            if b is not None:
                self._cache.move_to_end(bi)
                return b
        off = bi * self.block_size
        data = self.store.get_range(self.key, off,
                                    min(self.block_size, self._len - off))
        with self._lock:
            self.fetches += 1
            self._cache[bi] = data
            while len(self._cache) > self.cache_blocks:
                self._cache.popitem(last=False)
        return data

    def __getitem__(self, sl: slice) -> bytes:
        start, stop, step = sl.indices(self._len)
        if step != 1 or stop <= start:
            return b""
        bs = self.block_size
        first, last = start // bs, (stop - 1) // bs
        parts = []
        for bi in range(first, last + 1):
            blk = self._block(bi)
            lo = start - bi * bs if bi == first else 0
            hi = stop - bi * bs if bi == last else len(blk)
            parts.append(blk[lo:hi])
        return b"".join(parts)

    def close(self) -> None:
        self.closed = True
        with self._lock:
            self._cache.clear()
