"""Backup & restore of an engine data directory.

Role of the reference's backup stack: lib/backup/backup.go (backup sets
with full + incremental modes), engine/backup.go (engine-side hooks),
app/ts-recover/recover/recover.go (restore binary). The unit here is the
whole engine data tree (db → shard → {tssp, wal, index files}): a backup
is a content-addressed snapshot with a manifest; incrementals reference a
base backup and only materialize files whose content changed (TSSP files
are immutable, so incrementals are naturally small).

Restore resolves each file through the base chain (nearest backup that
materialized it), verifies checksums, and rebuilds a data dir an Engine
can open directly (WAL replay included, §5 checkpoint/resume).
"""

from __future__ import annotations

import hashlib
import json
import os
import shutil
import time

from ..utils import failpoint, fileops, get_logger

log = get_logger(__name__)

MANIFEST = "manifest.json"
DATA_SUBDIR = "data"


class BackupError(Exception):
    pass


def _sha256(path: str) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest()


def _walk_files(root: str) -> list[str]:
    out = []
    for r, _dirs, files in os.walk(root):
        for f in files:
            out.append(os.path.relpath(os.path.join(r, f), root))
    return sorted(out)


def _load_manifest(backup_dir: str) -> dict:
    p = os.path.join(backup_dir, MANIFEST)
    if not os.path.exists(p):
        raise BackupError(f"not a backup dir (no {MANIFEST}): {backup_dir}")
    with open(p) as f:
        return json.load(f)


def _chain(backup_dir: str) -> list[str]:
    """Backup dir + its base ancestry, newest first."""
    chain = []
    cur: str | None = os.path.abspath(backup_dir)
    while cur is not None:
        if cur in chain:
            raise BackupError(f"backup base cycle at {cur}")
        chain.append(cur)
        base = _load_manifest(cur).get("base")
        if base is not None and not os.path.isabs(base):
            base = os.path.normpath(os.path.join(cur, base))
        cur = base
    return chain


def create_backup(engine, backup_dir: str, base_dir: str | None = None,
                  flush: bool = True) -> dict:
    """Snapshot the engine's data tree into backup_dir. base_dir: a prior
    backup — files whose sha256 matches are recorded but not re-copied
    (incremental). flush=True persists memtables first so the snapshot is
    self-contained without live WAL tails."""
    if os.path.exists(os.path.join(backup_dir, MANIFEST)):
        raise BackupError(f"backup dir already used: {backup_dir}")
    eng_abs = os.path.abspath(engine.path)
    bk_abs = os.path.abspath(backup_dir)
    if os.path.commonpath([eng_abs, bk_abs]) in (eng_abs, bk_abs):
        # a backup inside the data dir would be snapshotted as a database
        # (and vice versa)
        raise BackupError(
            f"backup dir must be outside the data dir: {backup_dir}")
    if flush:
        engine.flush_all()
    base_files: dict[str, dict] = {}
    if base_dir is not None:
        # chain-resolved: an incremental can base on an incremental
        for d in _chain(base_dir):
            for rel, meta in _load_manifest(d)["files"].items():
                base_files.setdefault(rel, meta)
    os.makedirs(os.path.join(backup_dir, DATA_SUBDIR), exist_ok=True)
    files: dict[str, dict] = {}
    copied = 0
    # background compaction unlinks merged TSSP inputs while we walk; a
    # vanished file's data lives in a successor file, so re-walk until a
    # pass completes with no surprises (reference quiesces compaction;
    # retrying is lock-free and converges because merges are finite)
    for _attempt in range(8):
        vanished = False
        todo = [r for r in _walk_files(engine.path) if r not in files]
        files = {r: m for r, m in files.items()
                 if os.path.exists(os.path.join(engine.path, r))}
        for rel in todo:
            src = os.path.join(engine.path, rel)
            dst = os.path.join(backup_dir, DATA_SUBDIR, rel)
            try:
                prior = base_files.get(rel)
                if prior is not None and _sha256(src) == prior["sha256"]:
                    # content lives in the base chain
                    files[rel] = {"size": prior["size"],
                                  "sha256": prior["sha256"], "ref": True}
                    continue
                os.makedirs(os.path.dirname(dst), exist_ok=True)
                shutil.copy2(src, dst)
            except FileNotFoundError:
                vanished = True
                continue
            # hash the COPY: it is what restore reads, and the source may
            # be concurrently appended (live WAL tail)
            files[rel] = {"size": os.path.getsize(dst),
                          "sha256": _sha256(dst)}
            copied += 1
        if not vanished and not [r for r in _walk_files(engine.path)
                                 if r not in files]:
            break
    else:
        raise BackupError("data dir would not quiesce (files kept "
                          "appearing/vanishing); stop compaction and retry")
    manifest = {
        "created_unix": time.time(),
        "base": (os.path.relpath(os.path.abspath(base_dir), backup_dir)
                 if base_dir is not None else None),
        "files": files,
    }
    tmp = os.path.join(backup_dir, MANIFEST + ".tmp")
    with open(tmp, "w") as f:
        json.dump(manifest, f, indent=1)
        f.flush()
        os.fsync(f.fileno())
    # crash here: copied data files but no manifest — the dir is "not
    # a backup" to restore/verify (loud BackupError), never a silently
    # short one; the manifest rename IS the backup's commit point
    failpoint.inject("backup.manifest.crash")
    fileops.durable_replace(tmp, os.path.join(backup_dir, MANIFEST))
    log.info("backup %s: %d files (%d copied, %d referenced)",
             backup_dir, len(files), copied, len(files) - copied)
    return {"files": len(files), "copied": copied}


def restore_backup(backup_dir: str, target_data_dir: str) -> dict:
    """Rebuild a data dir from a backup (and its base chain). The target
    must not already contain data. Every file is checksum-verified."""
    if os.path.exists(target_data_dir) and os.listdir(target_data_dir):
        raise BackupError(f"restore target not empty: {target_data_dir}")
    chain = _chain(backup_dir)
    manifest = _load_manifest(backup_dir)
    os.makedirs(target_data_dir, exist_ok=True)
    restored = 0
    for rel, meta in manifest["files"].items():
        src = None
        for d in chain:
            cand = os.path.join(d, DATA_SUBDIR, rel)
            if os.path.exists(cand):
                src = cand
                break
        if src is None:
            raise BackupError(f"file missing from backup chain: {rel}")
        if _sha256(src) != meta["sha256"]:
            raise BackupError(f"checksum mismatch: {rel} (from {src})")
        dst = os.path.join(target_data_dir, rel)
        os.makedirs(os.path.dirname(dst), exist_ok=True)
        shutil.copy2(src, dst)
        restored += 1
    log.info("restore %s → %s: %d files", backup_dir, target_data_dir,
             restored)
    return {"files": restored}


def verify_backup(backup_dir: str) -> list[str]:
    """Integrity check: returns the list of problems ([] = healthy).
    Checks every manifest entry resolves through the chain and matches
    its checksum."""
    problems = []
    try:
        chain = _chain(backup_dir)
        manifest = _load_manifest(backup_dir)
    except BackupError as e:
        return [str(e)]
    for rel, meta in manifest["files"].items():
        src = None
        for d in chain:
            cand = os.path.join(d, DATA_SUBDIR, rel)
            if os.path.exists(cand):
                src = cand
                break
        if src is None:
            problems.append(f"missing: {rel}")
        elif _sha256(src) != meta["sha256"]:
            problems.append(f"corrupt: {rel} (at {src})")
    return problems
