"""S3-compatible ObjectStore client (role of reference
lib/fileops/obs_fs.go — the OBS/S3 backend behind the detached/
hierarchical tier; lib/obs/obs_options.go holds the endpoint/ak/sk
config).

Pure-stdlib implementation: AWS Signature V4 over urllib, path-style
addressing (works against AWS, MinIO, Huawei OBS and the bundled mock
server in tests). Plugs into storage/obs.py's five-method interface, so
`services/hierarchical.py` and detached TSSP reads work unchanged on a
real bucket.

Credentials resolve from arguments or the standard environment
(AWS_ACCESS_KEY_ID / AWS_SECRET_ACCESS_KEY / AWS_REGION).
"""

from __future__ import annotations

import datetime
import hashlib
import hmac
import os
import urllib.error
import urllib.parse
import urllib.request
import xml.etree.ElementTree as ET

from ..utils import get_logger
from ..utils.errors import GeminiError
from .obs import ObjectStore

log = get_logger(__name__)

_EMPTY_SHA = hashlib.sha256(b"").hexdigest()


class S3Error(GeminiError):
    """Cold-tier failure: surfaces as a query error (the executor's
    GeminiError boundary), not a connection-killing exception."""


def _sign(key: bytes, msg: str) -> bytes:
    return hmac.new(key, msg.encode(), hashlib.sha256).digest()


class S3ObjectStore(ObjectStore):
    """put/get_range/size/delete/list against one bucket (+ optional key
    prefix) on any S3-compatible endpoint."""

    def __init__(self, endpoint: str, bucket: str,
                 access_key: str | None = None,
                 secret_key: str | None = None,
                 region: str | None = None,
                 prefix: str = "", timeout_s: float = 30.0):
        self.endpoint = endpoint.rstrip("/")
        self.bucket = bucket
        self.prefix = prefix.strip("/")
        self.access_key = access_key \
            or os.environ.get("AWS_ACCESS_KEY_ID", "")
        self.secret_key = secret_key \
            or os.environ.get("AWS_SECRET_ACCESS_KEY", "")
        self.region = region or os.environ.get("AWS_REGION", "us-east-1")
        self.timeout_s = timeout_s
        u = urllib.parse.urlparse(self.endpoint)
        self._host = u.netloc

    # ---- SigV4 -----------------------------------------------------------

    def _auth_headers(self, method: str, canon_uri: str,
                      canon_query: str, payload_sha: str) -> dict:
        now = datetime.datetime.now(datetime.timezone.utc)
        amz_date = now.strftime("%Y%m%dT%H%M%SZ")
        datestamp = now.strftime("%Y%m%d")
        headers = {"host": self._host, "x-amz-date": amz_date,
                   "x-amz-content-sha256": payload_sha}
        signed = ";".join(sorted(headers))
        canon_headers = "".join(f"{k}:{headers[k]}\n"
                                for k in sorted(headers))
        creq = "\n".join([method, canon_uri, canon_query, canon_headers,
                          signed, payload_sha])
        scope = f"{datestamp}/{self.region}/s3/aws4_request"
        sts = "\n".join(["AWS4-HMAC-SHA256", amz_date, scope,
                         hashlib.sha256(creq.encode()).hexdigest()])
        k = _sign(("AWS4" + self.secret_key).encode(), datestamp)
        k = _sign(k, self.region)
        k = _sign(k, "s3")
        k = _sign(k, "aws4_request")
        sig = hmac.new(k, sts.encode(), hashlib.sha256).hexdigest()
        out = {"x-amz-date": amz_date,
               "x-amz-content-sha256": payload_sha,
               "Authorization":
                   f"AWS4-HMAC-SHA256 Credential={self.access_key}/"
                   f"{scope}, SignedHeaders={signed}, Signature={sig}"}
        return out

    def _key(self, key: str) -> str:
        key = key.lstrip("/")
        return f"{self.prefix}/{key}" if self.prefix else key

    def _request(self, method: str, key: str | None,
                 query: dict | None = None, body: bytes = b"",
                 extra_headers: dict | None = None,
                 ok=(200, 204, 206)):
        canon_uri = "/" + urllib.parse.quote(self.bucket, safe="")
        if key is not None:
            canon_uri += "/" + urllib.parse.quote(self._key(key),
                                                  safe="/~")
        qitems = sorted((query or {}).items())
        canon_query = "&".join(
            f"{urllib.parse.quote(str(k), safe='~')}="
            f"{urllib.parse.quote(str(v), safe='~')}"
            for k, v in qitems)
        payload_sha = hashlib.sha256(body).hexdigest() if body \
            else _EMPTY_SHA
        url = self.endpoint + canon_uri
        if canon_query:
            url += "?" + canon_query
        headers = self._auth_headers(method, canon_uri, canon_query,
                                     payload_sha)
        headers.update(extra_headers or {})
        req = urllib.request.Request(url, data=body or None,
                                     method=method, headers=headers)
        try:
            resp = urllib.request.urlopen(req, timeout=self.timeout_s)
        except urllib.error.HTTPError as e:
            if e.code in ok:
                return e
            detail = e.read(512).decode(errors="replace")
            raise S3Error(f"{method} {key or ''}: HTTP {e.code} "
                          f"{detail}") from None
        except urllib.error.URLError as e:
            raise S3Error(f"{method} {key or ''}: {e}") from None
        if resp.status not in ok:
            raise S3Error(f"{method} {key or ''}: HTTP {resp.status}")
        return resp

    # ---- ObjectStore interface ------------------------------------------

    def put_file(self, key: str, path: str) -> None:
        with open(path, "rb") as f:
            body = f.read()
        self._request("PUT", key, body=body)

    def get_range(self, key: str, offset: int, length: int) -> bytes:
        resp = self._request(
            "GET", key,
            extra_headers={"Range":
                           f"bytes={offset}-{offset + length - 1}"})
        data = resp.read()
        if resp.status == 200 and (offset or len(data) > length):
            # endpoint/proxy ignored the Range header and sent the
            # whole object: slice locally rather than decode bytes
            # from the wrong offset
            return data[offset:offset + length]
        return data

    def size(self, key: str) -> int:
        resp = self._request("HEAD", key)
        cl = resp.headers.get("Content-Length")
        if cl is None:
            raise S3Error(f"HEAD {key}: no Content-Length")
        return int(cl)

    def delete(self, key: str) -> None:
        self._request("DELETE", key, ok=(200, 204, 404))

    def list(self, prefix: str = "") -> list[str]:
        """ListObjectsV2 with continuation; returns keys relative to the
        store prefix."""
        out: list[str] = []
        token = None
        strip = (self.prefix + "/") if self.prefix else ""
        while True:
            q = {"list-type": "2", "prefix": self._key(prefix)}
            if token:
                q["continuation-token"] = token
            resp = self._request("GET", None, query=q)
            root = ET.fromstring(resp.read())
            ns = ""
            if root.tag.startswith("{"):
                ns = root.tag.split("}")[0] + "}"
            for c in root.findall(f"{ns}Contents"):
                k = c.find(f"{ns}Key").text or ""
                if strip and k.startswith(strip):
                    k = k[len(strip):]
                out.append(k)
            trunc = root.find(f"{ns}IsTruncated")
            if trunc is None or trunc.text != "true":
                break
            nt = root.find(f"{ns}NextContinuationToken")
            if nt is None:
                break
            token = nt.text
        return sorted(out)


class MockS3Server:
    """In-process S3-compatible HTTP server (tests / local dev): PUT,
    GET (with Range), HEAD, DELETE, ListObjectsV2 with path-style
    addressing. Verifies nothing about signatures — it stands in for a
    bucket, not for IAM."""

    def __init__(self, port: int = 0, fail_get_ranges: bool = False):
        import http.server
        import threading

        store: dict[str, bytes] = {}
        self.objects = store
        self.fail_get_ranges = fail_get_ranges
        outer = self

        class H(http.server.BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def _key(self):
                path = urllib.parse.urlparse(self.path)
                return urllib.parse.unquote(path.path.lstrip("/")), \
                    urllib.parse.parse_qs(path.query)

            def do_PUT(self):
                key, _q = self._key()
                ln = int(self.headers.get("Content-Length", 0))
                store[key] = self.rfile.read(ln)
                self.send_response(200)
                self.send_header("ETag", '"x"')
                self.end_headers()

            def do_HEAD(self):
                key, _q = self._key()
                if key not in store:
                    self.send_response(404)
                    self.end_headers()
                    return
                self.send_response(200)
                self.send_header("Content-Length", str(len(store[key])))
                self.end_headers()

            def do_GET(self):
                key, q = self._key()
                if "list-type" in q:
                    prefix = q.get("prefix", [""])[0]
                    bucket = key.split("/")[0]
                    keys = sorted(
                        k for k in store
                        if k.startswith(bucket + "/")
                        and k[len(bucket) + 1:].startswith(prefix))
                    body = ["<ListBucketResult>"]
                    for k in keys:
                        body.append(
                            f"<Contents><Key>{k[len(bucket) + 1:]}"
                            f"</Key></Contents>")
                    body.append("<IsTruncated>false</IsTruncated>"
                                "</ListBucketResult>")
                    data = "".join(body).encode()
                    self.send_response(200)
                    self.send_header("Content-Length", str(len(data)))
                    self.end_headers()
                    self.wfile.write(data)
                    return
                if key not in store:
                    self.send_response(404)
                    self.end_headers()
                    return
                data = store[key]
                rng = self.headers.get("Range")
                code = 200
                if rng and rng.startswith("bytes="):
                    if outer.fail_get_ranges:
                        self.send_response(500)
                        self.end_headers()
                        return
                    a, b = rng[6:].split("-")
                    data = data[int(a):int(b) + 1]
                    code = 206
                self.send_response(code)
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

            def do_DELETE(self):
                key, _q = self._key()
                store.pop(key, None)
                self.send_response(204)
                self.end_headers()

        self._httpd = http.server.ThreadingHTTPServer(
            ("127.0.0.1", port), H)
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True)

    @property
    def endpoint(self) -> str:
        return f"http://127.0.0.1:{self.port}"

    def start(self) -> "MockS3Server":
        self._thread.start()
        return self

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
