"""Parquet export (role of reference lib/parquet/writer.go +
engine/immutable/task_parquet.go: write stored time-series data out as
parquet files for sharing with external analytics stacks).

Exports one measurement per parquet file: tag columns as dictionary-
encoded strings, field columns in their native types, time as
timestamp[ns]. Field nulls follow the stored validity masks.
"""

from __future__ import annotations

import os

import numpy as np

from ..record import DataType
from ..utils import get_logger

log = get_logger(__name__)


def _col_arrays(recs_with_tags):
    """(tags, Record) list → column name → list of per-series numpy/py
    arrays, padded with None where a series lacks the column."""
    import pyarrow as pa

    all_fields: dict[str, DataType] = {}
    all_tags: list[str] = []
    for tags, rec in recs_with_tags:
        for k in tags:
            if k not in all_tags:
                all_tags.append(k)
        for f in rec.schema:
            if f.name != "time":
                all_fields.setdefault(f.name, f.type)

    arrays: dict[str, list] = {"time": []}
    for k in all_tags:
        arrays[k] = []
    for name in all_fields:
        arrays[name] = []

    for tags, rec in recs_with_tags:
        n = rec.num_rows
        arrays["time"].append(pa.array(rec.times, type=pa.int64()))
        for k in all_tags:
            # explicit string type: an all-None chunk (series missing the
            # tag) must not infer the null type or chunked_array fails
            arrays[k].append(pa.array([tags.get(k)] * n,
                                      type=pa.string()))
        for name, ty in all_fields.items():
            col = rec.column(name)
            if col is None:
                arrays[name].append(pa.nulls(n, _pa_type(ty)))
                continue
            if col.is_string_like():
                arrays[name].append(pa.array(col.to_strings(),
                                             type=pa.string()))
            else:
                vals = col.values
                mask = ~col.valid
                arrays[name].append(
                    pa.array(vals, type=_pa_type(ty),
                             mask=mask if mask.any() else None))
    return all_tags, arrays


def _pa_type(ty: DataType):
    import pyarrow as pa
    return {DataType.FLOAT: pa.float64(), DataType.INTEGER: pa.int64(),
            DataType.BOOLEAN: pa.bool_(), DataType.STRING: pa.string(),
            DataType.TAG: pa.string(), DataType.TIME: pa.int64()}[ty]


def export_measurement(engine, db: str, measurement: str, path: str,
                       t_min: int | None = None, t_max: int | None = None,
                       compression: str = "zstd") -> int:
    """Write one measurement to a parquet file; returns rows written.
    Docstring refs: reference lib/parquet/writer.go builds the same
    (tags..., fields..., time) schema per measurement."""
    import pyarrow as pa
    import pyarrow.parquet as pq

    recs = []
    for shard, sid, rec in engine.scan_series(db, measurement,
                                              t_min=t_min, t_max=t_max):
        recs.append((shard.index.tags_of(sid), rec))
    if not recs:
        return 0
    tag_keys, arrays = _col_arrays(recs)

    cols = {}
    for name, chunks in arrays.items():
        arr = pa.chunked_array(chunks)
        if name in tag_keys:
            arr = arr.combine_chunks().dictionary_encode()
        elif name == "time":
            arr = arr.cast(pa.timestamp("ns"))
        cols[name] = arr
    table = pa.table(cols)
    # global time order, as the reference's parquet task emits
    table = table.sort_by("time")
    os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
    pq.write_table(table, path, compression=compression)
    log.info("exported %s.%s: %d rows → %s", db, measurement,
             table.num_rows, path)
    return table.num_rows


def export_database(engine, db: str, out_dir: str,
                    t_min: int | None = None,
                    t_max: int | None = None) -> dict[str, int]:
    """Export every measurement of a database; returns rows per
    measurement (engine/immutable/task_parquet.go batch behavior)."""
    out = {}
    for mst in engine.measurements(db):
        path = os.path.join(out_dir, f"{mst}.parquet")
        out[mst] = export_measurement(engine, db, mst, path, t_min, t_max)
    return out
